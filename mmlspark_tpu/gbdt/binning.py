"""Quantile feature binning (host side).

Analog of LightGBM's BinMapper construction, which the reference drives
through ``LGBM_DatasetCreateFromMat`` (ref: src/lightgbm/src/main/scala/
LightGBMUtils.scala:283-351): continuous features are discretized into at
most ``max_bin`` equal-frequency bins; the binned matrix is what the
histogram kernels consume on device.

Host/numpy by design: binning is a one-time O(N·F) preprocessing pass
(sort-based), exactly the part LightGBM also keeps on CPU. The output is a
small int matrix that ships to HBM once.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np


class BinMapper:
    """Per-feature quantile bin boundaries.

    ``upper_bounds[f]`` holds ascending split values; value ``v`` maps to
    bin ``searchsorted(upper_bounds[f], v, side='left')``. NaNs map to bin
    0 (treated as smallest — the reference's zero_as_missing=false default
    folds missing into the lowest bin).
    """

    def __init__(self, upper_bounds: List[np.ndarray], max_bin: int,
                 f32_values_safe: bool = False):
        self.upper_bounds = [np.asarray(u, dtype=np.float64)
                             for u in upper_bounds]
        self.max_bin = int(max_bin)
        # computed at fit time from TRUE data gaps (see _feature_bounds);
        # conservative False for mappers restored without the flag
        self.f32_values_safe = bool(f32_values_safe)

    @property
    def num_features(self) -> int:
        return len(self.upper_bounds)

    @property
    def num_bins(self) -> np.ndarray:
        """Actual bin count per feature (<= max_bin)."""
        return np.asarray([len(u) + 1 for u in self.upper_bounds])

    @staticmethod
    def fit(X: np.ndarray, max_bin: int = 255,
            sample_cnt: int = 200_000, seed: int = 2) -> "BinMapper":
        # sample BEFORE the f64 conversion: converting f32->f64 is exact
        # per value, so boundaries are identical to converting the full
        # matrix first — without materializing a second full-size copy
        X_full = np.asarray(X)
        n, f = X_full.shape
        sampled_idx = None
        if n > sample_cnt:
            rng = np.random.default_rng(seed)
            sampled_idx = rng.choice(n, size=sample_cnt, replace=False)
            X = np.asarray(X_full[sampled_idx], dtype=np.float64)
        else:
            X = np.asarray(X_full, dtype=np.float64)
        results = [_feature_bounds(X[:, j], max_bin) for j in range(f)]
        bounds = [b for b, _ in results]
        safe = all(ok for _, ok in results)
        if safe and sampled_idx is not None:
            # the gap-based safety above is certified on the SAMPLE only;
            # unsampled rows inside a cut's f32 rounding band could still
            # flip one bin on the f32 device path. Spot-check a holdout of
            # unsampled rows: if any bins differently in f32, drop to f64.
            rest = _holdout_rows(n, sampled_idx, rng)
            hold = X_full[rest]
            safe = _holdout_f32_agrees(
                bounds, ((j, hold[:, j]) for j in range(f)))
        return BinMapper(bounds, max_bin, f32_values_safe=safe)

    @staticmethod
    def fit_sparse(csr, max_bin: int = 255, sample_cnt: int = 200_000,
                   seed: int = 2) -> "BinMapper":
        """Fit boundaries directly from a CSRMatrix — per-feature
        nonzeros come from a one-shot CSC view and the implicit zeros
        enter the frequency histogram analytically, so no dense float
        matrix ever exists (the LGBM_DatasetCreateFromCSR analog,
        ref: LightGBMUtils.scala:283-351).

        f32 safety mirrors the dense fit: the gap check runs on the
        sample, and when sampling occurred a holdout of UNSAMPLED rows
        is spot-checked (f32 vs f64 binning) before the f32 inference
        walk is allowed."""
        full = csr
        n_full = csr.shape[0]
        n = n_full
        sampled_idx = None
        if n > sample_cnt:
            rng = np.random.default_rng(seed)
            sampled_idx = rng.choice(n, size=sample_cnt, replace=False)
            csr = csr.take(sampled_idx)
            n = sample_cnt
        col_ptr, _, vals = csr.csc()
        bounds: List[np.ndarray] = []
        safe = True
        for j in range(csr.shape[1]):
            v = vals[col_ptr[j]:col_ptr[j + 1]]
            v = v[np.isfinite(v)]
            distinct, counts = np.unique(v, return_counts=True)
            counts = counts.astype(np.int64)
            zeros = n - (int(col_ptr[j + 1]) - int(col_ptr[j]))
            if zeros > 0:
                pos = int(np.searchsorted(distinct, 0.0))
                if pos < len(distinct) and distinct[pos] == 0.0:
                    counts[pos] += zeros
                else:
                    distinct = np.insert(distinct, pos, 0.0)
                    counts = np.insert(counts, pos, zeros)
            b, ok = _bounds_from_counts(np.asarray(distinct, np.float64),
                                        counts, max_bin)
            bounds.append(b)
            safe = safe and ok
        if safe and sampled_idx is not None:
            # same unsampled-row holdout discipline as the dense fit:
            # values inside a cut's f32 rounding band flip one bin on
            # the f32 device path — verify none exist before claiming
            # f32 safety (fall back to the f64 walk otherwise)
            rest = _holdout_rows(n_full, sampled_idx, rng)
            hold_ptr, _, hold_vals = full.take(rest).csc()
            safe = _holdout_f32_agrees(
                bounds, ((j, hold_vals[hold_ptr[j]:hold_ptr[j + 1]])
                         for j in range(csr.shape[1])))
        return BinMapper(bounds, max_bin, f32_values_safe=safe)

    def transform_sparse(self, csr) -> np.ndarray:
        """CSRMatrix -> FEATURES-MAJOR (F, N) int32 bins without a dense
        float matrix: every row starts in its feature's zero bin, then
        only the nonzeros are re-binned via searchsorted."""
        n, f = csr.shape
        out = np.empty((f, n), np.int32)
        col_ptr, rows, vals = csr.csc()
        for j in range(f):
            ub = self.upper_bounds[j]
            out[j, :] = np.searchsorted(ub, 0.0, side="left")
            lo, hi = int(col_ptr[j]), int(col_ptr[j + 1])
            if hi > lo:
                b = np.searchsorted(ub, vals[lo:hi], side="left"
                                    ).astype(np.int32)
                b[np.isnan(vals[lo:hi])] = 0
                out[j, rows[lo:hi]] = b
        return out

    def transform(self, X: np.ndarray) -> np.ndarray:
        """Raw features -> int32 bin indices, shape (N, F).

        Uses the native OpenMP binning kernel when available (the
        LightGBM dataset-construction analog, native/mml_native.cpp
        mml_apply_bins), falling back to vectorized numpy."""
        X = np.asarray(X, dtype=np.float64)
        try:
            from mmlspark_tpu.native import loader as native
            if native.available():
                out = native.apply_bins(X, self.upper_bounds)
                if out is not None:
                    return out
        except Exception:  # noqa: BLE001 — native is only an accelerator
            pass
        out = np.empty(X.shape, dtype=np.int32)
        for j, ub in enumerate(self.upper_bounds):
            col = X[:, j]
            binned = np.searchsorted(ub, col, side="left")
            binned[np.isnan(col)] = 0
            out[:, j] = binned
        return out

    def transform_fm(self, X: np.ndarray) -> np.ndarray:
        """Raw features -> FEATURES-MAJOR (F, N) bins, the GBDT engine's
        ship layout. Fast path: the fused native kernel bins f32/f64
        input straight into transposed uint8 (one pass instead of
        transform + transpose + narrow — three full sweeps at HIGGS
        scale). Falls back to transform(X).T (int32) when the native
        kernel or the <=256-bin precondition is unavailable. f32 input
        widens per-value to f64 before the boundary compare, so results
        are bit-identical to the f64 path."""
        try:
            from mmlspark_tpu.native import loader as native
            if native.available():
                out = native.apply_bins_t_u8(X, self.upper_bounds)
                if out is not None:
                    return out
        except Exception:  # noqa: BLE001 — native is only an accelerator
            pass
        return np.ascontiguousarray(
            self.transform(np.asarray(X, dtype=np.float64)).T)

    def transform_fm_range(self, X: np.ndarray, j0: int,
                           j1: int) -> np.ndarray:
        """Bin features [j0, j1) straight into the (j1-j0, N)
        features-major ship layout — the chunk primitive behind the
        booster's pipelined bin+ship (one chunk bins on host while the
        previous chunk's host->device DMA is in flight). Native fused
        kernel (uint8) when available; numpy per-column searchsorted
        (int32) otherwise, widened per column to f64 so results are
        bit-identical to transform()."""
        try:
            from mmlspark_tpu.native import loader as native
            if native.available():
                out = native.apply_bins_t_u8(X, self.upper_bounds,
                                             feature_range=(j0, j1))
                if out is not None:
                    return out
        except Exception:  # noqa: BLE001 — native is only an accelerator
            pass
        n = X.shape[0]
        out = np.empty((j1 - j0, n), np.int32)
        for j in range(j0, j1):
            col = np.asarray(X[:, j], dtype=np.float64)
            binned = np.searchsorted(self.upper_bounds[j], col,
                                     side="left").astype(np.int32)
            binned[np.isnan(col)] = 0
            out[j - j0] = binned
        return out

    def bin_threshold_value(self, feature: int, bin_idx: int) -> float:
        """The raw-value threshold for 'go left if bin <= bin_idx':
        the upper boundary of that bin. Rows with value <= this boundary
        land in bins [0..bin_idx]."""
        ub = self.upper_bounds[feature]
        if len(ub) == 0 or int(bin_idx) >= len(ub):
            # Split at (or past) a feature's top bin: every value goes left
            # during binned training, so the raw-value threshold must be +inf
            # to keep train/predict consistent (a finite ub[-1] would send
            # values > ub[-1] right at inference only).
            return np.inf
        return float(ub[int(bin_idx)])

    def f32_safe(self) -> bool:
        """True when binning/threshold comparison can run in float32
        without changing assignments: every boundary's distance to the
        data values it separates (measured on the fit SAMPLE — up to
        sample_cnt rows, so unsampled rows inside a cut's f32 band can
        still flip by one bin; the 8x-eps margin keeps that band narrow)
        dominates the f32 rounding band around it. Timestamps/IDs
        (>24-bit mantissa) and features with sub-f32-resolution
        distinctions both fail and stay in f64."""
        return self.f32_values_safe

    def threshold_matrix(self, num_bins: int) -> np.ndarray:
        """(F, num_bins) lookup of bin_threshold_value for every (feature,
        bin) pair — lets the booster convert a whole stacked forest's bin
        thresholds to raw-value thresholds in one vectorized gather instead
        of a per-node Python loop."""
        out = np.full((self.num_features, num_bins), np.inf)
        for j, ub in enumerate(self.upper_bounds):
            k = min(len(ub), num_bins)
            out[j, :k] = ub[:k]
        return out

    # -- persistence --------------------------------------------------------

    def to_json(self) -> dict:
        return {"max_bin": self.max_bin,
                "f32_values_safe": self.f32_values_safe,
                "upper_bounds": [u.tolist() for u in self.upper_bounds]}

    @staticmethod
    def from_json(d: dict) -> "BinMapper":
        return BinMapper([np.asarray(u) for u in d["upper_bounds"]],
                         d["max_bin"],
                         f32_values_safe=d.get("f32_values_safe", False))


def _holdout_rows(n: int, sampled_idx: np.ndarray, rng) -> np.ndarray:
    """Up to 50k row indices that the fit sample did NOT cover."""
    mask = np.ones(n, dtype=bool)
    mask[sampled_idx] = False
    rest = np.flatnonzero(mask)
    if len(rest) > 50_000:
        rest = rng.choice(rest, size=50_000, replace=False)
    return rest


def _holdout_f32_agrees(bounds, feature_values) -> bool:
    """Shared f32-safety spot check (dense and sparse fit paths):
    ``feature_values`` yields (feature_idx, holdout values); True when
    every value bins identically under f64 and f32 boundaries (NaN is
    excluded — it maps to bin 0 in either dtype)."""
    for j, col in feature_values:
        ub = bounds[j]
        if not len(ub):
            continue
        v = np.asarray(col)
        v = v[~np.isnan(v)]
        b64 = np.searchsorted(ub, v, side="left")
        b32 = np.searchsorted(ub.astype(np.float32),
                              v.astype(np.float32), side="left")
        if not np.array_equal(b64, b32):
            import logging
            logging.getLogger("mmlspark_tpu.gbdt").info(
                "feature %d: unsampled rows bin differently in f32; "
                "using the f64 binning path", j)
            return False
    return True


_EPS32 = float(np.finfo(np.float32).eps)


def _cut_f32_ok(lo: float, hi: float) -> bool:
    """A boundary at (lo+hi)/2 separates lo from hi under f32 compares
    iff the half-gap dominates the f32 rounding band at that magnitude."""
    return (hi - lo) / 2.0 > 8.0 * _EPS32 * max(abs(lo), abs(hi))


def _feature_bounds(col: np.ndarray, max_bin: int):
    """Equal-frequency boundaries for one feature column.
    Returns (bounds, f32_ok) — f32_ok is False when any cut sits closer
    to its neighboring data values than float32 can resolve."""
    col = col[np.isfinite(col)]
    if col.size == 0:
        return np.empty(0), True
    distinct, counts = np.unique(col, return_counts=True)
    return _bounds_from_counts(distinct, counts, max_bin)


def _bounds_from_counts(distinct: np.ndarray, counts: np.ndarray,
                        max_bin: int):
    """Equal-frequency cuts from a (sorted distinct values, counts)
    histogram — shared by the dense column path and the sparse path
    (which merges the implicit-zeros count in without materializing)."""
    if len(distinct) <= 1:
        return np.empty(0), True
    if len(distinct) <= max_bin:
        # one bin per distinct value; boundaries at midpoints
        ok = all(_cut_f32_ok(a, b)
                 for a, b in zip(distinct[:-1], distinct[1:]))
        return (distinct[:-1] + distinct[1:]) / 2.0, ok
    # equal-frequency: cut where the cumulative count fills a bin's
    # quota. O(max_bin·log d) — one searchsorted per CUT, not a Python
    # walk over every distinct value (same arithmetic: cum[i] is exactly
    # the f64 the old accumulating loop held, counts being integers)
    cum = np.cumsum(counts)
    per_bin = cum[-1] / max_bin
    bounds = []
    ok = True
    last = len(distinct) - 1
    target = per_bin
    while len(bounds) < max_bin - 1:
        i = int(np.searchsorted(cum, target, side="left"))
        if i >= last:
            break
        bounds.append((distinct[i] + distinct[i + 1]) / 2.0)
        ok = ok and _cut_f32_ok(distinct[i], distinct[i + 1])
        target = cum[i] + per_bin
    return np.asarray(bounds), ok
