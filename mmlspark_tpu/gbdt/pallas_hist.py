"""Pallas TPU kernel for GBDT histogram building.

The histogram is the GBDT hot op (the reference spends its training time
inside LightGBM's native C++ histogram loop, ref: TrainUtils.scala:82-89).
On TPU the scatter-free formulation is histogram-by-matmul: for a chunk
of rows, build the bin one-hot in VMEM and contract it against the
per-row stats with one MXU matmul, accumulating all (feature, bin, leaf)
cells of the chunk at once. Scatter/segment_sum is hundreds of times
slower on TPU (serialized scatter units), and the XLA onehot path
round-trips the one-hot through HBM; this kernel keeps it in VMEM.

Two layout decisions carry the performance:
  - the matmul runs as (3L, C) @ (C, fc*B): the tiny stats dimension
    (3 for the single-leaf histograms the tree grower builds) lands in
    the MXU sublane axis where it pads 3->8, not the lane axis where it
    would pad 3->128 — a 16x difference in matmul work;
  - block shapes obey Mosaic's tiling rules ((8, 128)-divisible or
    full-dimension): bins arrive features-major (F, N) — the layout the
    whole GBDT engine stores — blocked (fc, C); num_bins is padded to a
    multiple of 32 so fc*B is always 128-divisible.

Row-chunk grid steps accumulate into the same output block, which is
safe because TPU grid iterations execute sequentially on a core.

Numerics match the scatter/segment-sum path to float32 tolerance; on
non-TPU backends the kernel runs in interpret mode (tests) and the
booster defaults to the scatter path instead.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

ROW_CHUNK = 512            # multiple of 128 (lane dim of the bins block)
ROW_CHUNK_SINGLE = 2048    # L==1 hot path: fewer grid steps (the per-
                           # step overhead dominates at C=512), bigger
                           # VMEM onehot block is affordable without the
                           # (3L, C) leaf-weighted lhs
VMEM_ONEHOT_BYTES = 8 << 20   # onehot block budget: c*fc*B*4 bytes


def _nibble_hl(b_pad: int):
    """Split B into hi*lo digits minimizing VPU work per row:
    hoh compares (h) + loh compares (l) + lhs multiplies (3h) = 4h + l,
    subject to h*l = B. Powers of two keep // and % cheap; l must be a
    multiple of 16 so the output block's lane dim (fc*l, fc=8) stays
    128-divisible — Mosaic rejects partial lane blocks. Returns None
    when no legal factorization exists (caller falls back to the
    direct one-hot kernel)."""
    best = None
    h = 2
    while h * 2 <= b_pad:
        l = b_pad // h
        if h * l == b_pad and l % 16 == 0:
            cost = 4 * h + l
            if best is None or cost < best[0]:
                best = (cost, h, l)
        h *= 2
    return (best[1], best[2]) if best else None


def _hist_kernel_nibble(bins_ref, stats_ref, out_ref, *, h: int, l: int,
                        acc_dtype=jnp.float32):
    """Single-leaf histogram via digit decomposition: bin = hi*l + lo,
    so 1[bin==b] = 1[hi==b_hi]*1[lo==b_lo] and the (3, B) histogram of
    one feature is the (3h, C) x (C, l) matmul of the stats-weighted
    hi-onehot against the lo-onehot — O(h + l) one-hot lanes per row
    instead of O(B), which is what bounds the kernel (the one-hot build
    is VPU-compare work; the matmuls are almost free on the MXU).

    Quantized stats (int8/int16) keep the one-hots in the SAME narrow
    dtype and ask the MXU for an int32 accumulator via
    ``preferred_element_type`` — the i8->i32 lowering the quantized
    inference kernels use (core/quantize.py), giving exact integer
    histogram sums.

    Output layout is (3h, fc*l) — feature j's (3h, l) block at columns
    [j*l, (j+1)*l) — because collapsing (h, l) into the lane axis is
    not a Mosaic-legal reshape; hist_pallas untangles it with one tiny
    XLA transpose on the final (3h, F*l) array."""
    r = pl.program_id(1)
    bins_blk = bins_ref[:]                         # (fc, C) int32
    stats_blk = stats_ref[:]                       # (3, C) f32|int
    fc, c = bins_blk.shape

    hi = bins_blk // l                             # (fc, C)
    lo = bins_blk - hi * l
    hi_ids = lax.broadcasted_iota(jnp.int32, (h, c), 0)
    lo_ids = lax.broadcasted_iota(jnp.int32, (l, c), 0)

    oh_dtype = stats_blk.dtype
    parts = []
    for j in range(fc):                            # static unroll
        hoh = (hi[j][None, :] == hi_ids).astype(oh_dtype)       # (h, C)
        loh = (lo[j][None, :] == lo_ids).astype(oh_dtype)       # (l, C)
        lhs = (stats_blk[:, None, :] * hoh[None, :, :]) \
            .reshape(3 * h, c)                     # (3h, C)
        parts.append(lax.dot_general(
            lhs, loh, (((1,), (1,)), ((), ())),
            preferred_element_type=acc_dtype))     # (3h, l)
    contrib = jnp.concatenate(parts, axis=1)       # (3h, fc*l)

    @pl.when(r == 0)
    def _():
        out_ref[:] = contrib

    @pl.when(r > 0)
    def _():
        out_ref[:] = out_ref[:] + contrib


def _hist_kernel(bins_ref, stats_ref, leaf_ref, out_ref, *,
                 num_leaves: int, num_bins: int,
                 acc_dtype=jnp.float32):
    r = pl.program_id(1)

    bins_blk = bins_ref[:]                         # (fc, C) int32
    stats_blk = stats_ref[:]                       # (3, C) f32|int
    fc, c = bins_blk.shape
    oh_dtype = stats_blk.dtype

    # one-hot (fc*B, C): leading-dims collapse only (Mosaic cannot
    # reshape trailing dims into the lane axis). Quantized stats keep
    # the one-hot in the same narrow int dtype and accumulate int32
    # via preferred_element_type (i8->i32, cf. core/quantize.py).
    bin_ids = lax.broadcasted_iota(jnp.int32, (num_bins, c), 0)
    onehot = (bins_blk[:, None, :] == bin_ids[None, :, :]) \
        .astype(oh_dtype).reshape(fc * num_bins, c)

    if num_leaves == 1:
        lhs = stats_blk                            # (3, C)
    else:
        leaf_blk = leaf_ref[:]                     # (1, C) int32
        leaf_ids = lax.broadcasted_iota(jnp.int32, (num_leaves, c), 0)
        leaf_oh = (leaf_blk == leaf_ids).astype(oh_dtype)      # (L, C)
        lhs = (stats_blk[:, None, :] * leaf_oh[None, :, :]) \
            .reshape(3 * num_leaves, c)            # (3L, C)

    # NT matmul (contract the shared C axis): (3L, C) x (fc*B, C)^T.
    # The tiny 3L dim sits in the MXU sublane axis (pads 3->8), not the
    # lane axis (which would pad 3->128) — 16x less matmul work.
    contrib = lax.dot_general(
        lhs, onehot, (((1,), (1,)), ((), ())),
        preferred_element_type=acc_dtype)          # (3L, fc*B)

    @pl.when(r == 0)
    def _():
        out_ref[:] = contrib

    @pl.when(r > 0)
    def _():
        out_ref[:] = out_ref[:] + contrib


def _block_plan(f: int, n: int, num_bins: int, num_leaves: int):
    """The kernel's block geometry for TRUE input shape (f, n):
    returns (nibble, c, fc, b_pad, f_target, n_target). Both
    hist_pallas's internal padding and grow_tree's once-per-tree
    pre-padding (padded_bins_shape) derive from this single function,
    so they cannot drift.

    Routing: the single-leaf hot path (the tree grower only ever
    builds these) at B >= 128 takes the digit-decomposition kernel —
    VPU one-hot work per row drops from O(B) to O(4h + l), h*l = B
    (measured on v5e at HIGGS shape: 255-bin boost loop 16.4s -> 5.0s).
    At B < 128 the direct one-hot kernel is still faster (fewer,
    larger matmuls)."""
    b_pad = -(-num_bins // 32) * 32
    if num_leaves == 1 and b_pad >= 128 and _nibble_hl(b_pad):
        fc = min(8, f + ((-f) % 8))
        c = min(8192, max(512, n + ((-n) % 512)))
        return (True, c, fc, b_pad,
                f + ((-f) % fc), n + ((-n) % c))
    row_chunk = ROW_CHUNK_SINGLE if num_leaves == 1 else ROW_CHUNK
    row_cap = max(128, (VMEM_ONEHOT_BYTES // 4 // (8 * b_pad))
                  // 128 * 128)
    row_chunk = min(row_chunk, row_cap)
    if n >= row_chunk:
        c = row_chunk
    else:
        c = n + ((-n) % 8)          # single chunk, sublane-aligned
    elems = VMEM_ONEHOT_BYTES // 4 // c
    fc = max(8, (elems // b_pad) // 8 * 8)
    fc = min(fc, f + ((-f) % 8))
    if c * fc * b_pad * 4 > 2 * VMEM_ONEHOT_BYTES:
        # the fc/row floors could not respect the budget (huge num_bins)
        # — fail loudly rather than letting Mosaic's allocator throw a
        # cryptic compile error (booster routes such configs to onehot)
        raise ValueError(
            f"num_bins={num_bins} is beyond the Pallas histogram's VMEM "
            f"tiling range (block {c}x{fc}x{b_pad}); use "
            f"hist_method='onehot'")
    return (False, c, fc, b_pad,
            f + ((-f) % fc), n + ((-n) % c))


def padded_bins_shape(f: int, n: int, num_bins: int,
                      num_leaves: int = 1):
    """(f_target, n_target) the kernel will pad a TRUE (f, n) bins
    matrix to. Callers that invoke the histogram many times on the same
    bins (grow_tree: once per split) pre-pad ONCE to this shape and
    pass ``true_shape`` — profiling showed the per-call pad of the full
    (F, N) matrix was 17% of the boost loop."""
    _, _, _, _, f_t, n_t = _block_plan(f, n, num_bins, num_leaves)
    return f_t, n_t


@functools.partial(jax.jit,
                   static_argnames=("num_leaves", "num_bins",
                                    "interpret", "true_shape"))
def hist_pallas(bins: jnp.ndarray, grad: jnp.ndarray, hess: jnp.ndarray,
                weight: jnp.ndarray, leaf_of_row: jnp.ndarray,
                num_leaves: int, num_bins: int,
                interpret: bool = False,
                true_shape=None,
                count_values=None) -> jnp.ndarray:
    """(3, L, F, B) histogram via the Pallas MXU kernel.

    ``bins`` is features-major (F, N) — consumed directly, no transpose.
    Same contract as histogram.build_histogram's other methods; rows
    with weight 0 (padding/bagging) contribute nothing.

    Float32 by default. Quantized mode (integer grad/hess from
    tree.py's hist_bits < 32 rounding): the stats block and the bin
    one-hot stay in the NARROW int dtype and the MXU accumulates int32
    via ``preferred_element_type`` — the same i8->i32 lowering the
    quantized inference kernels use — returning an exact (3, L, F, B)
    int32 histogram. ``count_values`` then carries the quantized
    per-row weight for the count channel (None keeps c = sum(weight)).

    ``true_shape=(f, n)`` marks ``bins`` as ALREADY padded to
    padded_bins_shape(f, n, ...): the per-call full-matrix pad is then
    a no-op (profiled at 17% of the boost loop when left inside the
    split loop); grad/hess/weight/leaf_of_row stay true-n sized and
    are padded here (cheap (N,) pads). The returned histogram is
    always sliced to the TRUE f."""
    f, n = true_shape if true_shape is not None else bins.shape

    nibble, c, fc, b_pad, f_tgt, n_tgt = _block_plan(
        f, n, num_bins, num_leaves)
    if bins.shape[0] > f_tgt or bins.shape[1] > n_tgt:
        raise ValueError(
            f"bins {bins.shape} exceed the kernel target "
            f"({f_tgt}, {n_tgt}) for true_shape ({f}, {n})")

    # ONE padding block for both kernel paths, keyed off the plan's
    # targets (pre-padded bins make these no-ops — see true_shape)
    pad_rows = n_tgt - bins.shape[1]
    pad_feats = f_tgt - bins.shape[0]
    stat_pad = n_tgt - n
    if pad_rows or pad_feats:
        bins = jnp.pad(bins, ((0, pad_feats), (0, pad_rows)))
    if stat_pad:
        grad = jnp.pad(grad, (0, stat_pad))
        hess = jnp.pad(hess, (0, stat_pad))
        weight = jnp.pad(weight, (0, stat_pad))   # 0-weight padding
        if count_values is not None:
            count_values = jnp.pad(count_values, (0, stat_pad))
        if not nibble:                 # nibble kernel is single-leaf
            leaf_of_row = jnp.pad(leaf_of_row, (0, stat_pad))

    if nibble:
        return _hist_pallas_nibble(bins, grad, hess, weight, f, n,
                                   num_bins, b_pad, c, fc, interpret,
                                   count_values=count_values)
    f_p, n_p = bins.shape

    stats, acc_dtype = _stats_block(grad, hess, weight, count_values)
    leaf2 = leaf_of_row.astype(jnp.int32)[None, :]       # (1, N_p)

    grid = (f_p // fc, n_p // c)
    out = pl.pallas_call(
        functools.partial(_hist_kernel, num_leaves=num_leaves,
                          num_bins=b_pad, acc_dtype=acc_dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((fc, c), lambda fi, ri: (fi, ri)),
            pl.BlockSpec((3, c), lambda fi, ri: (0, ri)),
            pl.BlockSpec((1, c), lambda fi, ri: (0, ri)),
        ],
        out_specs=pl.BlockSpec((3 * num_leaves, fc * b_pad),
                               lambda fi, ri: (0, fi)),
        out_shape=jax.ShapeDtypeStruct(
            (3 * num_leaves, f_p * b_pad), acc_dtype),
        interpret=interpret,
    )(bins, stats, leaf2)

    # (3L, F_p*B_pad) -> (3, L, F, B)
    hist = out.reshape(3, num_leaves, f_p, b_pad)
    if f_p != f or b_pad != num_bins:
        hist = hist[:, :, :f, :num_bins]
    return hist


def _stats_block(grad, hess, weight, count_values):
    """(3, N) stats block + MXU accumulator dtype. Float32 inputs take
    the classic path (bit-identical to HEAD). Integer grad/hess
    (quantized training) keep the block in the narrow wire dtype —
    weight is then the 0/1 row mask and count_values the quantized
    per-row weight — and accumulate exactly in int32."""
    if jnp.issubdtype(grad.dtype, jnp.integer):
        sdt = grad.dtype
        w = weight.astype(sdt)
        cv = w if count_values is None \
            else count_values.astype(sdt) * w
        stats = jnp.stack([grad * w, hess.astype(sdt) * w, cv], axis=0)
        return stats, jnp.int32
    cw = weight if count_values is None else count_values * weight
    stats = jnp.stack([grad * weight, hess * weight, cw],
                      axis=0).astype(jnp.float32)
    return stats, jnp.float32


def _hist_pallas_nibble(bins, grad, hess, weight, f, n, num_bins,
                        b_pad, c, fc, interpret, count_values=None):
    """Single-leaf histogram through the digit-decomposition kernel.
    The tiny per-step VMEM footprint (no (fc*B, C) one-hot block) lets
    row chunks grow to 8192, cutting grid-step count ~8x as well.
    Block geometry comes from _block_plan; inputs arrive already padded
    to the plan's targets by hist_pallas."""
    h, l = _nibble_hl(b_pad)
    f_p, n_p = bins.shape

    stats, acc_dtype = _stats_block(grad, hess, weight, count_values)

    grid = (f_p // fc, n_p // c)
    out = pl.pallas_call(
        functools.partial(_hist_kernel_nibble, h=h, l=l,
                          acc_dtype=acc_dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((fc, c), lambda fi, ri: (fi, ri)),
            pl.BlockSpec((3, c), lambda fi, ri: (0, ri)),
        ],
        out_specs=pl.BlockSpec((3 * h, fc * l), lambda fi, ri: (0, fi)),
        out_shape=jax.ShapeDtypeStruct((3 * h, f_p * l), acc_dtype),
        interpret=interpret,
    )(bins, stats)

    # (3h, F_p*l): feature j's bins live at rows (s*h + hi), cols
    # (j*l + lo); bin = hi*l + lo -> one small XLA transpose rebuilds
    # the (3, 1, F, B) contract
    hist = out.reshape(3, h, f_p, l).transpose(0, 2, 1, 3) \
        .reshape(3, 1, f_p, b_pad)
    if f_p != f or b_pad != num_bins:
        hist = hist[:, :, :f, :num_bins]
    return hist
