"""Pallas TPU kernel for GBDT histogram building.

The histogram is the GBDT hot op (the reference spends its training time
inside LightGBM's native C++ histogram loop, ref: TrainUtils.scala:82-89).
On TPU the scatter-free formulation is histogram-by-matmul: for a chunk
of rows, build the bin one-hot (C, Fc*B) and the leaf-weighted stats
matrix (3L, C) in VMEM, then one MXU matmul accumulates all (leaf,
feature, bin) cells of the chunk at once. The grid tiles (feature-chunk,
row-chunk); row-chunks accumulate into the same output block, which is
safe because TPU grid iterations execute sequentially on a core.

Numerics match the scatter/segment-sum path to float32 tolerance; on
non-TPU backends the kernel runs in interpret mode (tests) and the
booster defaults to the scatter path instead.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

# conservative defaults: VMEM per block ~ C*Fc*B*4 bytes (1 MB at
# 256*16*256) plus the (3L, Fc*B) accumulator
ROW_CHUNK = 256
FEAT_CHUNK = 16


def _hist_kernel(bins_ref, stats_ref, leaf_ref, out_ref, *,
                 num_leaves: int, num_bins: int):
    r = pl.program_id(1)

    bins_blk = bins_ref[:]                         # (C, Fc) int32
    stats_blk = stats_ref[:]                       # (C, 3) f32
    leaf_blk = leaf_ref[:]                         # (C, 1) int32
    c, fc = bins_blk.shape

    # bin one-hot: (C, Fc, B) -> (C, Fc*B)
    bin_ids = lax.broadcasted_iota(jnp.int32, (c, fc, num_bins), 2)
    onehot = (bins_blk[:, :, None] == bin_ids).astype(jnp.float32)
    onehot = onehot.reshape(c, fc * num_bins)

    # leaf-weighted stats: (3L, C)
    leaf_ids = lax.broadcasted_iota(jnp.int32, (c, num_leaves), 1)
    leaf_oh = (leaf_blk == leaf_ids).astype(jnp.float32)   # (C, L)
    lhs = (stats_blk.T[:, None, :] * leaf_oh.T[None, :, :])  # (3, L, C)
    lhs = lhs.reshape(3 * num_leaves, c)

    contrib = jnp.dot(lhs, onehot,
                      preferred_element_type=jnp.float32)  # (3L, Fc*B)

    @pl.when(r == 0)
    def _():
        out_ref[:] = contrib

    @pl.when(r > 0)
    def _():
        out_ref[:] = out_ref[:] + contrib


@functools.partial(jax.jit,
                   static_argnames=("num_leaves", "num_bins", "interpret"))
def hist_pallas(bins: jnp.ndarray, grad: jnp.ndarray, hess: jnp.ndarray,
                weight: jnp.ndarray, leaf_of_row: jnp.ndarray,
                num_leaves: int, num_bins: int,
                interpret: bool = False) -> jnp.ndarray:
    """(3, L, F, B) float32 histogram via the Pallas MXU kernel.

    Same contract as histogram.build_histogram's other methods; rows
    with weight 0 (padding/bagging) contribute nothing.
    """
    n, f = bins.shape
    c = min(ROW_CHUNK, max(8, n))
    fc = min(FEAT_CHUNK, f)

    pad_rows = (-n) % c
    pad_feats = (-f) % fc
    if pad_rows:
        bins = jnp.pad(bins, ((0, pad_rows), (0, 0)))
        grad = jnp.pad(grad, (0, pad_rows))
        hess = jnp.pad(hess, (0, pad_rows))
        weight = jnp.pad(weight, (0, pad_rows))   # 0-weight padding
        leaf_of_row = jnp.pad(leaf_of_row, (0, pad_rows))
    if pad_feats:
        bins = jnp.pad(bins, ((0, 0), (0, pad_feats)))
    n_p, f_p = bins.shape

    stats = jnp.stack([grad * weight, hess * weight, weight],
                      axis=1).astype(jnp.float32)       # (N, 3)
    leaf2 = leaf_of_row.astype(jnp.int32)[:, None]       # (N, 1)

    grid = (f_p // fc, n_p // c)
    out = pl.pallas_call(
        functools.partial(_hist_kernel, num_leaves=num_leaves,
                          num_bins=num_bins),
        grid=grid,
        in_specs=[
            pl.BlockSpec((c, fc), lambda fi, ri: (ri, fi)),
            pl.BlockSpec((c, 3), lambda fi, ri: (ri, 0)),
            pl.BlockSpec((c, 1), lambda fi, ri: (ri, 0)),
        ],
        out_specs=pl.BlockSpec((3 * num_leaves, fc * num_bins),
                               lambda fi, ri: (0, fi)),
        out_shape=jax.ShapeDtypeStruct(
            (3 * num_leaves, f_p * num_bins), jnp.float32),
        interpret=interpret,
    )(bins, stats, leaf2)

    hist = out.reshape(3, num_leaves, f_p, num_bins)
    if pad_feats:
        hist = hist[:, :, :f, :]
    return hist
