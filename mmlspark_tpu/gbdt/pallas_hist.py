"""Pallas TPU kernel for GBDT histogram building.

The histogram is the GBDT hot op (the reference spends its training time
inside LightGBM's native C++ histogram loop, ref: TrainUtils.scala:82-89).
On TPU the scatter-free formulation is histogram-by-matmul: for a chunk
of rows, build the bin one-hot in VMEM and contract it against the
per-row stats with one MXU matmul, accumulating all (feature, bin, leaf)
cells of the chunk at once. Scatter/segment_sum is hundreds of times
slower on TPU (serialized scatter units), and the XLA onehot path
round-trips the one-hot through HBM; this kernel keeps it in VMEM.

Memory layout is chosen for Mosaic's tiling rules (last two block dims
divisible by (8, 128) or equal to the full array dims):
  - bins are passed transposed, (F_p, N_p) int32, blocked (fc, C);
  - per-row stats [g*w, h*w, w] are (N_p, 3), blocked (C, 3) — the last
    dim spans the full array;
  - the output is (F_p*B, 3L), blocked (fc*B, 3L): row-chunk grid steps
    accumulate into the same block, which is safe because TPU grid
    iterations execute sequentially on a core.

Numerics match the scatter/segment-sum path to float32 tolerance; on
non-TPU backends the kernel runs in interpret mode (tests) and the
booster defaults to the scatter path instead.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

ROW_CHUNK = 512           # multiple of 128 (lane dim of the bins block)
VMEM_ONEHOT_ELEMS = 2048  # fc*B budget: onehot block = fc*B*C*4 bytes


def _hist_kernel(bins_ref, stats_ref, leaf_ref, out_ref, *,
                 num_leaves: int, num_bins: int):
    r = pl.program_id(1)

    bins_blk = bins_ref[:]                         # (fc, C) int32
    stats_blk = stats_ref[:]                       # (C, 3) f32
    fc, c = bins_blk.shape

    # bin one-hot, features-major: (fc, B, C) -> (fc*B, C)
    bin_ids = lax.broadcasted_iota(jnp.int32, (num_bins, c), 0)
    onehot = (bins_blk[:, None, :] == bin_ids[None, :, :]) \
        .astype(jnp.float32).reshape(fc * num_bins, c)

    if num_leaves == 1:
        rhs = stats_blk                            # (C, 3)
    else:
        leaf_blk = leaf_ref[:]                     # (C, 1) int32
        leaf_ids = lax.broadcasted_iota(jnp.int32, (c, num_leaves), 1)
        leaf_oh = (leaf_blk == leaf_ids).astype(jnp.float32)   # (C, L)
        rhs = (leaf_oh[:, :, None] * stats_blk[:, None, :]) \
            .reshape(c, num_leaves * 3)            # (C, 3L)

    contrib = jnp.dot(onehot, rhs,
                      preferred_element_type=jnp.float32)  # (fc*B, 3L)

    @pl.when(r == 0)
    def _():
        out_ref[:] = contrib

    @pl.when(r > 0)
    def _():
        out_ref[:] = out_ref[:] + contrib


@functools.partial(jax.jit,
                   static_argnames=("num_leaves", "num_bins", "interpret"))
def hist_pallas(bins: jnp.ndarray, grad: jnp.ndarray, hess: jnp.ndarray,
                weight: jnp.ndarray, leaf_of_row: jnp.ndarray,
                num_leaves: int, num_bins: int,
                interpret: bool = False) -> jnp.ndarray:
    """(3, L, F, B) float32 histogram via the Pallas MXU kernel.

    Same contract as histogram.build_histogram's other methods; rows
    with weight 0 (padding/bagging) contribute nothing.
    """
    n, f = bins.shape

    # row chunk: one full chunk for small inputs, else ROW_CHUNK slices
    if n >= ROW_CHUNK:
        c = ROW_CHUNK
    else:
        c = n + ((-n) % 8)          # single chunk, sublane-aligned
    pad_rows = (-n) % c

    # feature chunk: bounded so the VMEM one-hot block stays ~4 MB
    fc = max(8, (VMEM_ONEHOT_ELEMS // max(num_bins, 1)) // 8 * 8)
    fc = min(fc, f + ((-f) % 8))
    pad_feats = (-f) % fc

    if pad_rows:
        bins = jnp.pad(bins, ((0, pad_rows), (0, 0)))
        grad = jnp.pad(grad, (0, pad_rows))
        hess = jnp.pad(hess, (0, pad_rows))
        weight = jnp.pad(weight, (0, pad_rows))   # 0-weight padding
        leaf_of_row = jnp.pad(leaf_of_row, (0, pad_rows))
    if pad_feats:
        bins = jnp.pad(bins, ((0, 0), (0, pad_feats)))
    n_p, f_p = bins.shape

    bins_t = bins.T                                      # (F_p, N_p)
    stats = jnp.stack([grad * weight, hess * weight, weight],
                      axis=1).astype(jnp.float32)        # (N_p, 3)
    leaf2 = leaf_of_row.astype(jnp.int32)[:, None]       # (N_p, 1)

    grid = (f_p // fc, n_p // c)
    out = pl.pallas_call(
        functools.partial(_hist_kernel, num_leaves=num_leaves,
                          num_bins=num_bins),
        grid=grid,
        in_specs=[
            pl.BlockSpec((fc, c), lambda fi, ri: (fi, ri)),
            pl.BlockSpec((c, 3), lambda fi, ri: (ri, 0)),
            pl.BlockSpec((c, 1), lambda fi, ri: (ri, 0)),
        ],
        out_specs=pl.BlockSpec((fc * num_bins, 3 * num_leaves),
                               lambda fi, ri: (fi, 0)),
        out_shape=jax.ShapeDtypeStruct(
            (f_p * num_bins, 3 * num_leaves), jnp.float32),
        interpret=interpret,
    )(bins_t, stats, leaf2)

    # (F_p*B, 3L) -> (3, L, F, B)
    hist = out.reshape(f_p, num_bins, num_leaves, 3).transpose(3, 2, 0, 1)
    if pad_feats:
        hist = hist[:, :, :f, :]
    return hist
