"""Mergeable quantile sketch — the out-of-core / distributed analog of
``BinMapper.fit``'s sort-based quantile pass.

The construction is the deterministic mergeable summary of Greenwald &
Khanna (SIGMOD'01) in the form XGBoost's weighted quantile sketch uses
(Chen & Guestrin, KDD'16 §3.3 + appendix): a summary is a sorted list of
values, each carrying RIGOROUS lower/upper bounds on its rank in the
data seen so far. Three operations:

- ``update(values)``  — absorb a block of raw values (one chunk's
  column). Non-finite values are DROPPED exactly like
  ``BinMapper.fit``'s ``col[np.isfinite(col)]`` (NaN and ±inf never
  influence cut placement; at transform time NaN still routes to bin 0
  and ±inf to the edge bins — that path is untouched).
- ``merge(other)``    — combine two sketches built over disjoint data
  (other chunks, other hosts). Rank bounds ADD, so correctness is by
  construction and merge order only moves results within the bound.
- ``cuts(max_bin)``   — equal-frequency cut values mirroring
  ``binning._bounds_from_counts``'s walk; bit-identical to it while the
  sketch is still exact (no compaction happened).

Error accounting is a measured CERTIFICATE, not a trusted constant:
every entry's rank interval ``[rmin, rmax]`` is maintained rigorously
through exact summarization (width 0), merging (widths add), and
pruning (surviving entries keep their intervals), so ``eps()`` — the
worst-case normalized rank error of answering any quantile query from
the current summary — is computed from the intervals actually present.
With prune width ``b`` the certificate lands near the textbook
``(1 + merge_depth) / (2b)``; tests and ``BinMapper.fit_streaming``
assert against the certificate itself.

Memory: one sketch holds O(b · log(n/b)) entries (a logarithmic
compactor cascade, KLL-style scheduling of GK-style summaries), a few
hundred KB per feature at 100M rows with the default ``b=512``.
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Tuple

import numpy as np


class _Summary:
    """Sorted values + rigorous rank-interval bounds.

    ``lmin/lmax`` bound L(v) = #elements strictly below v;
    ``rmin/rmax`` bound R(v) = #elements ≤ v; ``w`` is the total count
    the summary covers. An exact summary has lmin==lmax, rmin==rmax.
    """

    __slots__ = ("v", "lmin", "lmax", "rmin", "rmax", "w")

    def __init__(self, v, lmin, lmax, rmin, rmax, w):
        self.v = v
        self.lmin = lmin
        self.lmax = lmax
        self.rmin = rmin
        self.rmax = rmax
        self.w = float(w)

    def __len__(self) -> int:
        return len(self.v)


def _exact_summary(values: np.ndarray) -> _Summary:
    """Width-0 summary of a raw finite-value block (np.unique pass)."""
    distinct, counts = np.unique(values, return_counts=True)
    cum = np.cumsum(counts, dtype=np.float64)
    below = cum - counts
    return _Summary(distinct.astype(np.float64), below, below.copy(),
                    cum, cum.copy(), cum[-1] if len(cum) else 0.0)


def _bounds_at(s: _Summary, vm: np.ndarray
               ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Evaluate s's rank bounds at every value of ``vm``.

    Members keep their stored intervals. For a non-member v with
    predecessor p and successor q in s: every element ≤ p is < v and
    every element ≤ v is < q, so
    ``L(v), R(v) ∈ [rmin(p), lmax(q)]`` (0 / w at the ends). Merging
    two EXACT summaries therefore stays exact: with no elements strictly
    between p and q, rmin(p) == lmax(q).
    """
    n = len(s.v)
    if n == 0:
        z = np.zeros(len(vm))
        return z, z.copy(), z.copy(), z.copy()
    idx = np.searchsorted(s.v, vm, side="left")
    member = (idx < n) & (s.v[np.minimum(idx, n - 1)] == vm)
    pred = np.clip(idx - 1, 0, n - 1)
    succ = np.minimum(idx, n - 1)
    lo = np.where(idx > 0, s.rmin[pred], 0.0)
    hi = np.where(idx < n, s.lmax[succ], s.w)
    i = np.minimum(idx, n - 1)
    l_lo = np.where(member, s.lmin[i], lo)
    l_hi = np.where(member, s.lmax[i], hi)
    r_lo = np.where(member, s.rmin[i], lo)
    r_hi = np.where(member, s.rmax[i], hi)
    return l_lo, l_hi, r_lo, r_hi


def _merge(a: _Summary, b: _Summary) -> _Summary:
    """Summary of the union of the two underlying datasets: evaluate
    both summaries' bounds at the merged value set and ADD them."""
    if len(a) == 0:
        return b
    if len(b) == 0:
        return a
    vm = np.union1d(a.v, b.v)
    al_lo, al_hi, ar_lo, ar_hi = _bounds_at(a, vm)
    bl_lo, bl_hi, br_lo, br_hi = _bounds_at(b, vm)
    return _Summary(vm, al_lo + bl_lo, al_hi + bl_hi,
                    ar_lo + br_lo, ar_hi + br_hi, a.w + b.w)


def _prune(s: _Summary, b: int) -> _Summary:
    """Keep ~b+1 entries covering evenly spaced target ranks (plus both
    extremes — cut placement needs the true min/max neighborhoods).
    Survivors keep their ORIGINAL intervals, so bounds stay rigorous;
    the certificate absorbs the coarser coverage."""
    n = len(s.v)
    if n <= b + 1:
        return s
    mid = (s.rmin + s.rmax) * 0.5
    targets = s.w * np.arange(1, b) / b
    idx = np.searchsorted(mid, targets, side="left")
    idx = np.clip(idx, 1, n - 1)
    # the entry just below may sit closer to the target rank
    closer = (np.abs(mid[idx - 1] - targets)
              <= np.abs(mid[np.minimum(idx, n - 1)] - targets))
    idx = np.where(closer, idx - 1, idx)
    keep = np.unique(np.concatenate([[0], idx, [n - 1]]))
    return _Summary(s.v[keep], s.lmin[keep], s.lmax[keep],
                    s.rmin[keep], s.rmax[keep], s.w)


def _certificate(s: _Summary) -> float:
    """Worst-case normalized rank error of answering ANY rank query
    with the best entry of ``s``: returning entry i for target r costs
    at most max(rmax_i - r, r - rmin_i); maximizing the best choice
    over r lands either between two entries (half the uncovered span)
    or at the extremes."""
    n = len(s.v)
    if n == 0 or s.w <= 0:
        return 0.0
    worst = max(float(s.rmax[0]), float(s.w - s.rmin[-1]))
    if n > 1:
        worst = max(worst, float(np.max(s.rmax[1:] - s.rmin[:-1])) / 2.0)
    return worst / s.w


class QuantileSketch:
    """One feature's mergeable quantile summary (module docstring).

    ``b`` is the compaction width (error ~ merge_depth / 2b);
    ``buffer_rows`` is how many raw values buffer before a compaction
    pass — both bound host memory, neither changes correctness (the
    certificate reflects whatever happened).
    """

    def __init__(self, b: int = 512, buffer_rows: int = 131072):
        if b < 8:
            raise ValueError(f"sketch width b={b} is too small (>=8)")
        self.b = int(b)
        self.buffer_rows = int(buffer_rows)
        self._pending: List[np.ndarray] = []
        self._pending_n = 0
        self._levels: List[Optional[_Summary]] = []
        self._final: Optional[_Summary] = None
        self.count = 0        # finite values absorbed
        self.dropped = 0      # NaN/±inf dropped (BinMapper.fit parity)
        self.exact = True     # False after the first compaction

    # -- building ----------------------------------------------------------

    def update(self, values) -> "QuantileSketch":
        """Absorb a block of raw values (any shape; flattened).
        Non-finite values are dropped, exactly like ``BinMapper.fit``."""
        v = np.asarray(values, dtype=np.float64).ravel()
        finite = v[np.isfinite(v)]
        self.dropped += int(v.size - finite.size)
        if finite.size == 0:
            return self
        self.count += int(finite.size)
        # boolean indexing copied: no reference into the caller's chunk
        self._pending.append(finite)
        self._pending_n += int(finite.size)
        self._final = None
        if self._pending_n >= self.buffer_rows:
            self._flush()
        return self

    def _flush(self) -> None:
        if self._pending_n == 0:
            return
        vals = (self._pending[0] if len(self._pending) == 1
                else np.concatenate(self._pending))  # ooc:materialize-ok (bounded pending buffer)
        self._pending, self._pending_n = [], 0
        self._carry(_exact_summary(vals), 0)

    def _carry(self, s: _Summary, level: int) -> None:
        if len(s) > self.b + 1:
            s = _prune(s, self.b)
            self.exact = False
        while len(self._levels) <= level:
            self._levels.append(None)
        while self._levels[level] is not None:
            s = _merge(self._levels[level], s)
            self._levels[level] = None
            if len(s) > self.b + 1:
                s = _prune(s, self.b)
                self.exact = False
            level += 1
            if len(self._levels) <= level:
                self._levels.append(None)
        self._levels[level] = s
        self._final = None

    def merge(self, other: "QuantileSketch") -> "QuantileSketch":
        """Fold ``other`` (built over DIFFERENT data) into self.
        Deterministic; results depend on merge order only within the
        certificate bound."""
        other._flush()
        self._flush()
        for level, s in enumerate(other._levels):
            if s is not None:
                self._carry(s, level)
        self.count += other.count
        self.dropped += other.dropped
        self.exact = self.exact and other.exact
        self._final = None
        return self

    # -- reading -----------------------------------------------------------

    def summary(self) -> _Summary:
        """All levels + pending merged WITHOUT pruning (size is
        O(b·levels) — the read-side summary quantile queries run on)."""
        if self._final is None:
            acc: Optional[_Summary] = None
            if self._pending_n:
                vals = (self._pending[0] if len(self._pending) == 1
                        else np.concatenate(self._pending))  # ooc:materialize-ok (bounded pending buffer)
                acc = _exact_summary(vals)
            for s in self._levels:
                if s is not None:
                    acc = s if acc is None else _merge(acc, s)
            self._final = acc if acc is not None else _Summary(
                np.empty(0), np.empty(0), np.empty(0),
                np.empty(0), np.empty(0), 0.0)
        return self._final

    def eps(self) -> float:
        """Normalized rank-error CERTIFICATE of this sketch (0.0 while
        exact — no compaction has happened). Any quantile answered from
        the summary is within ``eps() * count`` ranks of the truth; the
        certificate is measured from the maintained intervals, so it
        already covers every merge/prune that actually occurred."""
        if self.exact:
            return 0.0
        return _certificate(self.summary())

    def query(self, q: float) -> float:
        """Value whose rank is within ``eps()*count`` of quantile ``q``
        (the entry whose rank-interval midpoint lands closest)."""
        s = self.summary()
        if len(s) == 0:
            return float("nan")
        r = float(np.clip(q, 0.0, 1.0)) * s.w
        mid = (s.rmin + s.rmax) * 0.5
        return float(s.v[int(np.argmin(np.abs(mid - r)))])

    @property
    def min(self) -> float:
        s = self.summary()
        return float(s.v[0]) if len(s) else float("nan")

    @property
    def max(self) -> float:
        s = self.summary()
        return float(s.v[-1]) if len(s) else float("nan")

    def cuts(self, max_bin: int) -> np.ndarray:
        """Equal-frequency cut values, mirroring
        ``binning._bounds_from_counts``: while the sketch is EXACT this
        routes through that very function (bit-identical to a one-shot
        ``BinMapper.fit`` over the same rows, f32 snapping aside);
        otherwise the same quota walk runs on estimated cumulative
        counts, placing each cut at the midpoint of the neighboring
        summary values. A cut spans the GAP containing its target, so
        its true rank sits within ``2·eps()·count`` of the target
        (rank interval of the gap's two endpoints) — the bound
        ``BinMapper.fit_streaming`` documents and the tests pin."""
        s = self.summary()
        if len(s) <= 1:
            return np.empty(0)
        if self.exact:
            from mmlspark_tpu.gbdt.binning import _bounds_from_counts
            counts = np.diff(np.concatenate([[0.0], s.rmin]))
            b, _ = _bounds_from_counts(s.v, counts, max_bin)
            return np.asarray(b)
        # approximate summary: one INDEPENDENT target rank per cut
        # (k·W/max_bin), each cut at the midpoint of the summary gap
        # containing its target — every cut's rank error is bounded by
        # the certificate alone (an accumulating walk would compound
        # per-entry overshoot across a pruned summary's coarse spacing)
        mid = (s.rmin + s.rmax) * 0.5
        targets = s.w * np.arange(1, max_bin) / max_bin
        idx = np.clip(np.searchsorted(mid, targets, side="left"),
                      1, len(s) - 1)
        cuts = (s.v[idx - 1] + s.v[idx]) / 2.0
        # heavy duplicates map several targets into one gap; keep cuts
        # strictly increasing like the exact walk (fewer bins, same
        # assignment semantics)
        keep = np.concatenate([[True], cuts[1:] > cuts[:-1]])
        return cuts[keep]

    # -- serialization (multi-host wire + persistence) ---------------------

    def to_state(self) -> dict:
        """Collapsed JSON-able state (one summary level)."""
        s = self.summary()
        return {"b": self.b, "count": self.count,
                "dropped": self.dropped, "exact": bool(self.exact),
                "v": s.v.tolist(), "lmin": s.lmin.tolist(),
                "lmax": s.lmax.tolist(), "rmin": s.rmin.tolist(),
                "rmax": s.rmax.tolist(), "w": s.w}

    @staticmethod
    def from_state(d: dict) -> "QuantileSketch":
        sk = QuantileSketch(b=int(d["b"]))
        s = _Summary(np.asarray(d["v"], np.float64),
                     np.asarray(d["lmin"], np.float64),
                     np.asarray(d["lmax"], np.float64),
                     np.asarray(d["rmin"], np.float64),
                     np.asarray(d["rmax"], np.float64), float(d["w"]))
        if len(s):
            sk._levels = [s]
        sk.count = int(d["count"])
        sk.dropped = int(d["dropped"])
        sk.exact = bool(d["exact"])
        return sk

    def to_wire(self, width: int) -> np.ndarray:
        """Fixed-shape float64 vector for collective transports
        (multi-host sketch agreement): the summary PRUNED to ``width``
        entries, packed as [m, count, dropped, exact, v…, lmin…, lmax…,
        rmin…, rmax…, w] with NaN padding. f64 end to end — rank bounds
        and cut values must not round on the wire."""
        s = _prune(self.summary(), max(8, int(width) - 1))
        if len(s) > width:
            raise AssertionError("prune exceeded wire width")
        m = len(s)
        out = np.full(4 + 5 * width + 1, np.nan)
        out[0] = m
        out[1] = self.count
        out[2] = self.dropped
        out[3] = float(self.exact and m == len(self.summary()))
        for k, arr in enumerate((s.v, s.lmin, s.lmax, s.rmin, s.rmax)):
            out[4 + k * width:4 + k * width + m] = arr
        out[-1] = s.w
        return out

    @staticmethod
    def from_wire(vec: np.ndarray, b: int = 512) -> "QuantileSketch":
        vec = np.asarray(vec, np.float64).ravel()
        width = (len(vec) - 5) // 5
        m = int(vec[0])
        sk = QuantileSketch(b=b)
        if m > 0:
            cols = [vec[4 + k * width:4 + k * width + m]
                    for k in range(5)]
            sk._levels = [_Summary(*cols, float(vec[-1]))]
        sk.count = int(vec[1])
        sk.dropped = int(vec[2])
        sk.exact = bool(vec[3])
        return sk


def sketch_block(X: np.ndarray, sketches: List[QuantileSketch]) -> None:
    """Update one per-feature sketch per column of a raw (N, F) block —
    the inner loop of ``BinMapper.fit_streaming``."""
    for j, sk in enumerate(sketches):
        sk.update(X[:, j])


def merge_sketch_lists(per_host: Iterable[List[QuantileSketch]]
                       ) -> List[QuantileSketch]:
    """Fold per-host per-feature sketch lists feature-wise (the
    distributed fit: hosts exchange SKETCHES, never rows). Every host
    folding the same inputs in the same order gets identical cuts."""
    acc: Optional[List[QuantileSketch]] = None
    for sketches in per_host:
        if acc is None:
            acc = list(sketches)
        else:
            if len(acc) != len(sketches):
                raise ValueError(
                    f"feature-count mismatch across hosts: "
                    f"{len(acc)} vs {len(sketches)}")
            for mine, theirs in zip(acc, sketches):
                mine.merge(theirs)
    if acc is None:
        raise ValueError("no sketches to merge")
    return acc
