"""TPUBoostClassifier / TPUBoostRegressor pipeline stages.

Stage-level parity with the reference's LightGBM estimators
(ref: src/lightgbm/src/main/scala/LightGBMClassifier.scala:36-68,
LightGBMRegressor.scala, TrainParams.scala:9-61): same param surface
(numIterations, learningRate, numLeaves, ... objective incl. quantile and
tweedie), fit() -> Model holding a string-serializable booster, and model
transform() producing rawPrediction / probability / prediction columns.
The model re-hydrates its booster lazily from the model string, like
LightGBMBooster.score (ref: LightGBMBooster.scala:20-33).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from mmlspark_tpu.core.params import (
    BoolParam, ColParam, EnumParam, FloatParam, HasFeaturesCol, HasLabelCol,
    HasPredictionCol, IntParam, StringParam, TableParam, range_domain,
)
from mmlspark_tpu.core.schema import Field, Schema, VECTOR, F64, I64
from mmlspark_tpu.core.stage import Estimator, Model
from mmlspark_tpu.core.table import DataTable
from mmlspark_tpu.gbdt.booster import Booster, train


class _BoostParams(HasFeaturesCol, HasLabelCol, HasPredictionCol):
    """Shared boosting params (ref: TrainParams.scala:9-47)."""

    numIterations = IntParam("number of boosting iterations", default=100,
                             domain=range_domain(lo=1))
    learningRate = FloatParam("shrinkage rate", default=0.1,
                              domain=range_domain(lo=0.0, lo_inc=False))
    numLeaves = IntParam("max leaves per tree", default=31,
                         domain=range_domain(lo=2))
    maxBin = IntParam("max feature bins", default=255,
                      domain=range_domain(lo=2))
    maxDepth = IntParam("max tree depth (<=0 unlimited)", default=0)
    minDataInLeaf = IntParam("min rows per leaf", default=20)
    minSumHessianInLeaf = FloatParam("min hessian sum per leaf", default=1e-3)
    lambdaL1 = FloatParam("L1 regularization", default=0.0)
    lambdaL2 = FloatParam("L2 regularization", default=0.0)
    minGainToSplit = FloatParam("min gain to split", default=0.0)
    featureFraction = FloatParam("feature subsample per tree", default=1.0,
                                 domain=range_domain(lo=0.0, hi=1.0,
                                                     lo_inc=False))
    baggingFraction = FloatParam("row subsample fraction", default=1.0,
                                 domain=range_domain(lo=0.0, hi=1.0,
                                                     lo_inc=False))
    baggingFreq = IntParam("bagging frequency (0 off)", default=0)
    earlyStoppingRound = IntParam("early stopping rounds (0 off)", default=0)
    boostFromAverage = BoolParam("start from average score", default=True)
    seed = IntParam("random seed", default=0)
    weightCol = ColParam("optional row-weight column", default=None)
    histMethod = EnumParam(
        ["auto", "scatter", "onehot", "pallas"],
        "device histogram strategy ('auto' = pallas MXU kernel on TPU, "
        "scatter elsewhere)", default="auto")
    histBits = IntParam(
        "histogram precision: 32 = classic f32 (bit-identical to the "
        "unquantized engine); 16/8 = per-round gradients stochastically "
        "rounded to narrow ints, exact integer histogram accumulation, "
        "int16 collective wire (2x fewer distributed bytes), one "
        "dequantize at split-gain time (Shi et al., NeurIPS'22)",
        default=32)
    histComm = EnumParam(
        ["auto", "psum", "reduce_scatter"],
        "data-parallel histogram collective: 'psum' allreduces the full "
        "(3, F, B) tensor; 'reduce_scatter' partitions features across "
        "devices (O(F*B/D) wire) and exchanges only (D, 4) split "
        "candidates; 'auto' = reduce_scatter for quantized data-"
        "parallel runs, psum otherwise", default="auto")
    parallelism = EnumParam(
        ["serial", "data", "feature", "voting"],
        "tree learner parallelism: 'data' shards rows, 'feature' shards "
        "the feature axis (the wide-data mode), 'voting' shards rows "
        "but allreduces only voted candidate histograms (PV-tree) "
        "(ref: TrainParams.scala:26 tree_learner=data/feature/voting)",
        default="serial")
    topK = IntParam("voting-parallel candidates per worker", default=20)
    boostChunk = IntParam(
        "boosting iterations fused per device dispatch (lax.scan "
        "chunk); 0 = auto (8 for long runs, per-iteration otherwise); "
        "capped at the early-stopping sync interval when validation is "
        "active", default=0, domain=range_domain(lo=0))
    deviceBinning = EnumParam(
        ["auto", "on", "off"],
        "bin raw features on device ('auto' = when the mapper's cuts "
        "are f32-exact, i.e. float32 input, and the input is dense "
        "single-host; host binning is the fallback)", default="auto")
    binFit = EnumParam(
        ["sample", "sketch"],
        "streaming/multi-host bin-boundary fit: 'sample' = reservoir-"
        "sample then exact fit (<=200k rows decide boundaries); "
        "'sketch' = mergeable quantile sketch over EVERY row in one "
        "bounded-memory pass (gbdt/sketch.py; multi-host fits merge "
        "per-host sketches instead of gathering rows). In-memory dense "
        "fits ignore this", default="sample")
    validationData = TableParam("held-out table for early stopping",
                                default=None)
    initModelString = StringParam(
        "serialized booster to warm-start from "
        "(ref: TrainParams modelString, TrainUtils.scala:74-77)",
        default="")
    keepTrainingData = BoolParam(
        "retain the device-resident training state on the fitted "
        "booster so Booster.boost_more(data=None) continues boosting "
        "exactly where fit() stopped (bit-identical to one longer run; "
        "costs the binned matrix's device memory for the model's "
        "lifetime; single-host, no warm start, no early stopping)",
        default=False)

    def _train_params(self) -> Dict[str, Any]:
        return {
            "keep_training_data": self.get("keepTrainingData"),
            "num_iterations": self.get("numIterations"),
            "learning_rate": self.get("learningRate"),
            "num_leaves": self.get("numLeaves"),
            "max_bin": self.get("maxBin"),
            "max_depth": self.get("maxDepth"),
            "min_data_in_leaf": self.get("minDataInLeaf"),
            "min_sum_hessian_in_leaf": self.get("minSumHessianInLeaf"),
            "lambda_l1": self.get("lambdaL1"),
            "lambda_l2": self.get("lambdaL2"),
            "min_gain_to_split": self.get("minGainToSplit"),
            "feature_fraction": self.get("featureFraction"),
            "bagging_fraction": self.get("baggingFraction"),
            "bagging_freq": self.get("baggingFreq"),
            "early_stopping_round": self.get("earlyStoppingRound"),
            "boost_from_average": self.get("boostFromAverage"),
            "seed": self.get("seed"),
            "hist_method": self.get("histMethod"),
            "hist_bits": self.get("histBits"),
            "hist_comm": self.get("histComm"),
            "parallelism": self.get("parallelism"),
            "top_k": self.get("topK"),
            "boost_chunk": self.get("boostChunk"),
            "device_binning": self.get("deviceBinning"),
            "bin_fit": self.get("binFit"),
        }

    def _features_matrix(self, table: DataTable) -> np.ndarray:
        from mmlspark_tpu.core.sparse import CSRMatrix
        from mmlspark_tpu.core.table import features_matrix
        col = table.column(self.get_features_col())
        if isinstance(col, CSRMatrix):
            return col    # booster.train bins CSR directly, no densify
        if isinstance(col, np.ndarray) and col.ndim == 2 \
                and col.dtype == np.float32:
            # keep float32 instead of the shared f64 coercion: binning
            # widens per-compare (exact), the 2x-size f64 copy never
            # materializes, and the f32-exact cut snapping keeps the
            # on-device binning ingest path eligible
            return col
        return features_matrix(table, self.get_features_col())

    def _fit_arrays(self, table: DataTable):
        X = self._features_matrix(table)
        y = np.asarray(table.column(self.get_label_col()), dtype=np.float64)
        wcol = self.get_or_none("weightCol")
        w = (np.asarray(table.column(wcol), dtype=np.float64)
             if wcol else None)
        vt = self.get_or_none("validationData")
        valid = None
        if vt is not None:
            valid = (self._features_matrix(vt),
                     np.asarray(vt.column(self.get_label_col()),
                                dtype=np.float64))
        return X, y, w, valid


class TPUBoostClassifier(Estimator, _BoostParams):
    """GBDT classifier (ref: LightGBMClassifier.scala:36)."""

    objective = EnumParam(["binary", "multiclass"],
                          "classification objective", default="binary")
    probabilityCol = ColParam("probability output column",
                              default="probability")
    rawPredictionCol = ColParam("raw score output column",
                                default="rawPrediction")

    def fit(self, table: DataTable) -> "TPUBoostClassificationModel":
        if not isinstance(table, DataTable):
            from mmlspark_tpu.io.ooc import ChunkedTable
            if isinstance(table, ChunkedTable):
                return self._fit_chunked(table)
        X, y, w, valid = self._fit_arrays(table)
        classes = np.unique(y)
        num_class = len(classes)
        if not np.array_equal(classes, np.arange(num_class)):
            raise ValueError(
                f"labels must be 0..K-1 integers, got {classes[:10]}; "
                f"use ValueIndexer / TrainClassifier for raw labels")
        params = self._train_params()
        if num_class > 2:
            params["objective"] = "multiclass"
            params["num_class"] = num_class
        else:
            params["objective"] = "binary"
        booster = train(params, X, y, sample_weight=w, valid=valid,
                        init_model=self.get("initModelString") or None)
        model = TPUBoostClassificationModel(
            modelString=booster.model_to_string(),
            numClasses=num_class)
        # seed the cache with the LIVE booster: the frozen BinMapper and
        # (with keepTrainingData) the retained device state ride along
        # for boost_more; a reloaded model parses the string instead
        model._booster = booster
        for name in ("featuresCol", "predictionCol", "probabilityCol",
                     "rawPredictionCol"):
            model.set(name, self.get(name))
        return model

    def _fit_chunked(self, chunked) -> "TPUBoostClassificationModel":
        """Out-of-core fit: chunks stream through ``train()``'s shard
        ingest (the raw float matrix never materializes; with
        binFit='sketch' the bin boundaries come from a one-pass
        mergeable sketch over every row). One extra label-scan pass
        determines the class count."""
        classes: np.ndarray = np.empty(0)
        for chunk in chunked.chunks():
            y = np.asarray(chunk[self.get_label_col()], np.float64)
            classes = np.union1d(classes, np.unique(y))
        num_class = len(classes)
        if not np.array_equal(classes, np.arange(num_class)):
            raise ValueError(
                f"labels must be 0..K-1 integers, got {classes[:10]}; "
                f"use ValueIndexer / TrainClassifier for raw labels")
        params = self._train_params()
        if num_class > 2:
            params["objective"] = "multiclass"
            params["num_class"] = num_class
        else:
            params["objective"] = "binary"
        if self.get("initModelString"):
            raise ValueError(
                "init-model warm start requires an in-memory table "
                "(streaming ingest cannot warm-start)")
        vt = self.get_or_none("validationData")
        valid = None
        if vt is not None:
            valid = (self._features_matrix(vt),
                     np.asarray(vt.column(self.get_label_col()),
                                dtype=np.float64))
        fac = chunked.as_xy(self.get_features_col(),
                            self.get_label_col(),
                            self.get_or_none("weightCol"))
        booster = train(params, fac, y=None, valid=valid)
        model = TPUBoostClassificationModel(
            modelString=booster.model_to_string(),
            numClasses=num_class)
        model._booster = booster
        for name in ("featuresCol", "predictionCol", "probabilityCol",
                     "rawPredictionCol"):
            model.set(name, self.get(name))
        return model

    def transform_schema(self, schema: Schema) -> Schema:
        schema.require(self.get_features_col())
        schema.require(self.get_label_col())
        return (schema
                .add_or_replace(Field(self.get("rawPredictionCol"), VECTOR))
                .add_or_replace(Field(self.get("probabilityCol"), VECTOR))
                .add_or_replace(Field(self.get_prediction_col(), F64)))


class TPUBoostClassificationModel(Model, HasFeaturesCol, HasPredictionCol):
    """Fitted GBDT classifier (ref: LightGBMClassificationModel)."""

    modelString = StringParam("serialized booster", default="")
    numClasses = IntParam("number of classes", default=2)
    probabilityCol = ColParam("probability output column",
                              default="probability")
    rawPredictionCol = ColParam("raw score output column",
                                default="rawPrediction")

    def _post_init(self):
        self._booster: Optional[Booster] = None

    def _on_param_change(self, name):
        if name == "modelString":
            self._booster = None

    def get_booster(self) -> Booster:
        if self._booster is None:
            self._booster = Booster.from_string(self.get("modelString"))
        return self._booster

    def reads_columns(self, schema):
        return [self.get_features_col()]

    def writes_columns(self, schema):
        return [self.get("rawPredictionCol"), self.get("probabilityCol"),
                self.get_prediction_col()]

    def device_op(self, schema):
        """Fusion hook (core/fusion.py): binned features -> forest
        traversal as one device op — the jitted fixed-depth pointer walk
        (``tree.predict_trees``) plus the objective transform, with the
        stacked forest arrays as device-resident consts. Forests whose
        thresholds need f64 routing score on host (the
        ``_needs_f64_inference`` discipline) and CSR features fall back
        to the host path."""
        from mmlspark_tpu.core import fusion as FZ
        from mmlspark_tpu.gbdt.tree import predict_trees
        import jax.numpy as jnp
        try:
            booster = self.get_booster()
        except Exception:  # noqa: BLE001 — unparseable model: host path
            return None
        if booster.num_trees == 0 or booster._needs_f64_inference():
            return None
        feat = self.get_features_col()
        K = booster.num_class
        it = booster._resolve_iterations(None)
        t_limit = it * K
        if t_limit <= 0:
            return None
        max_depth = booster._max_depth(t_limit)
        obj = booster.objective
        raw_col = self.get("rawPredictionCol")
        prob_col = self.get("probabilityCol")
        pred_col = self.get_prediction_col()

        def make_consts():
            b = self.get_booster()
            return {
                "trees": {k: np.asarray(b.trees[k][:t_limit])
                          for k in ("feature", "threshold", "left",
                                    "right", "value")},
                "init": np.asarray(b.init_score, np.float32)}

        def fn(consts, env, _f=feat, _it=it, _K=K, _depth=max_depth):
            X = env[_f]
            tr = consts["trees"]
            out = predict_trees(X, tr["feature"], tr["threshold"],
                                tr["left"], tr["right"], tr["value"],
                                max_depth=_depth)
            raw = out.reshape(_it, _K, X.shape[0]).sum(axis=0) \
                + consts["init"][:, None]
            prob = obj.transform(raw)
            if _K == 1:
                raw2 = jnp.stack([-raw[0], raw[0]], axis=1)
                prob2 = jnp.stack([1.0 - prob[0], prob[0]], axis=1)
            else:
                raw2 = raw.T
                prob2 = prob.T
            pred = jnp.argmax(prob2, axis=1).astype(jnp.float32)
            return {raw_col: raw2, prob_col: prob2, pred_col: pred}

        # raw/probability stay float32 like the host path's readback;
        # only the prediction column widens to f64 (legacy dtype)
        return FZ.DeviceOp(
            self, reads=[feat], writes=[raw_col, prob_col, pred_col],
            fn=fn, make_consts=make_consts,
            out_fields={raw_col: Field(raw_col, VECTOR),
                        prob_col: Field(prob_col, VECTOR),
                        pred_col: Field(pred_col, F64)},
            out_dtypes={pred_col: np.float64})

    def transform(self, table: DataTable) -> DataTable:
        import jax.numpy as jnp
        X = self._features_matrix(table)
        booster = self.get_booster()
        raw = booster.raw_score(X)   # single forest walk; reuse for both
        prob = np.asarray(booster.objective.transform(jnp.asarray(raw)))
        if booster.num_class == 1:          # binary
            raw2 = np.stack([-raw, raw], axis=1)
            prob2 = np.stack([1 - prob, prob], axis=1)
        else:
            raw2 = np.asarray(raw).T
            prob2 = prob.T
        pred = np.argmax(prob2, axis=1).astype(np.float64)
        return (table
                .with_column(self.get("rawPredictionCol"), raw2)
                .with_column(self.get("probabilityCol"), prob2)
                .with_column(self.get_prediction_col(), pred))

    _features_matrix = _BoostParams._features_matrix

    def transform_schema(self, schema: Schema) -> Schema:
        schema.require(self.get_features_col())
        return (schema
                .add_or_replace(Field(self.get("rawPredictionCol"), VECTOR))
                .add_or_replace(Field(self.get("probabilityCol"), VECTOR))
                .add_or_replace(Field(self.get_prediction_col(), F64)))

    def save_native_model(self, path: str) -> None:
        self.get_booster().save_native_model(path)

    def get_feature_importances(self, kind: str = "split") -> np.ndarray:
        return self.get_booster().feature_importance(kind)


class TPUBoostRegressor(Estimator, _BoostParams):
    """GBDT regressor with quantile/tweedie/poisson/huber objectives
    (ref: LightGBMRegressor.scala, TrainParams.scala:48-61)."""

    objective = EnumParam(
        ["regression", "regression_l1", "huber", "quantile", "poisson",
         "tweedie", "gamma", "l2", "l1", "mae", "mse"],
        "regression objective", default="regression")
    alpha = FloatParam("quantile level / huber delta", default=0.9)
    tweedieVariancePower = FloatParam("tweedie variance power in (1,2)",
                                      default=1.5)

    def fit(self, table: DataTable) -> "TPUBoostRegressionModel":
        params = self._train_params()
        params["objective"] = self.get("objective")
        params["alpha"] = self.get("alpha")
        params["tweedie_variance_power"] = self.get("tweedieVariancePower")
        if not isinstance(table, DataTable):
            from mmlspark_tpu.io.ooc import ChunkedTable
            if isinstance(table, ChunkedTable):
                # out-of-core fit through train()'s streaming ingest
                if self.get("initModelString"):
                    raise ValueError(
                        "init-model warm start requires an in-memory "
                        "table (streaming ingest cannot warm-start)")
                vt = self.get_or_none("validationData")
                valid = None
                if vt is not None:
                    valid = (self._features_matrix(vt),
                             np.asarray(vt.column(self.get_label_col()),
                                        dtype=np.float64))
                fac = table.as_xy(self.get_features_col(),
                                  self.get_label_col(),
                                  self.get_or_none("weightCol"))
                booster = train(params, fac, y=None, valid=valid)
                model = TPUBoostRegressionModel(
                    modelString=booster.model_to_string())
                model._booster = booster
                for name in ("featuresCol", "predictionCol"):
                    model.set(name, self.get(name))
                return model
        X, y, w, valid = self._fit_arrays(table)
        booster = train(params, X, y, sample_weight=w, valid=valid,
                        init_model=self.get("initModelString") or None)
        model = TPUBoostRegressionModel(modelString=booster.model_to_string())
        model._booster = booster   # live booster: bin_mapper + retained
        #                            state available for boost_more
        for name in ("featuresCol", "predictionCol"):
            model.set(name, self.get(name))
        return model

    def transform_schema(self, schema: Schema) -> Schema:
        schema.require(self.get_features_col())
        schema.require(self.get_label_col())
        return schema.add_or_replace(Field(self.get_prediction_col(), F64))


class TPUBoostRegressionModel(Model, HasFeaturesCol, HasPredictionCol):
    modelString = StringParam("serialized booster", default="")

    def _post_init(self):
        self._booster: Optional[Booster] = None

    def _on_param_change(self, name):
        if name == "modelString":
            self._booster = None

    def get_booster(self) -> Booster:
        if self._booster is None:
            self._booster = Booster.from_string(self.get("modelString"))
        return self._booster

    _features_matrix = _BoostParams._features_matrix

    def reads_columns(self, schema):
        return [self.get_features_col()]

    def writes_columns(self, schema):
        return [self.get_prediction_col()]

    def device_op(self, schema):
        """Fusion hook: forest walk + objective transform on device
        (see TPUBoostClassificationModel.device_op)."""
        from mmlspark_tpu.core import fusion as FZ
        from mmlspark_tpu.gbdt.tree import predict_trees
        try:
            booster = self.get_booster()
        except Exception:  # noqa: BLE001
            return None
        if booster.num_trees == 0 or booster._needs_f64_inference():
            return None
        feat = self.get_features_col()
        it = booster._resolve_iterations(None)
        if it <= 0:
            return None
        max_depth = booster._max_depth(it)
        obj = booster.objective
        pred_col = self.get_prediction_col()

        def make_consts():
            b = self.get_booster()
            return {
                "trees": {k: np.asarray(b.trees[k][:it])
                          for k in ("feature", "threshold", "left",
                                    "right", "value")},
                "init": np.asarray(b.init_score, np.float32)}

        def fn(consts, env, _f=feat, _it=it, _depth=max_depth):
            X = env[_f]
            tr = consts["trees"]
            out = predict_trees(X, tr["feature"], tr["threshold"],
                                tr["left"], tr["right"], tr["value"],
                                max_depth=_depth)
            raw = out.reshape(_it, 1, X.shape[0]).sum(axis=0)[0] \
                + consts["init"][0]
            return {pred_col: obj.transform(raw)}

        return FZ.DeviceOp(
            self, reads=[feat], writes=[pred_col], fn=fn,
            make_consts=make_consts,
            out_fields={pred_col: Field(pred_col, F64)},
            out_dtypes={pred_col: np.float64})

    def transform(self, table: DataTable) -> DataTable:
        X = self._features_matrix(table)
        pred = np.asarray(self.get_booster().predict(X), dtype=np.float64)
        return table.with_column(self.get_prediction_col(), pred)

    def transform_schema(self, schema: Schema) -> Schema:
        schema.require(self.get_features_col())
        return schema.add_or_replace(Field(self.get_prediction_col(), F64))

    def save_native_model(self, path: str) -> None:
        self.get_booster().save_native_model(path)

    def get_feature_importances(self, kind: str = "split") -> np.ndarray:
        return self.get_booster().feature_importance(kind)
