"""Boosting objectives: gradients/hessians, init scores, output transforms.

Parity targets: the reference exposes binary/multiclass classification and
regression objectives incl. quantile and tweedie
(ref: src/lightgbm/src/main/scala/TrainParams.scala:48-61,
LightGBMRegressor.scala objective param). Each objective supplies
first/second-order gradients of the loss w.r.t. the raw score — everything
is elementwise jnp, so XLA fuses it into the surrounding update.
"""

from __future__ import annotations

from typing import Callable, Dict, Tuple

import jax.numpy as jnp
import numpy as np


class Objective:
    """Base objective. ``score`` arrays are raw (margin) predictions."""

    name = "base"
    num_class = 1
    is_classification = False

    def init_score(self, y: np.ndarray, w: np.ndarray) -> np.ndarray:
        """boost_from_average starting score(s), shape (num_class,)."""
        return np.zeros(self.num_class)

    def grad_hess(self, score: jnp.ndarray, y: jnp.ndarray
                  ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        raise NotImplementedError

    def transform(self, score: jnp.ndarray) -> jnp.ndarray:
        """Raw score -> user-facing prediction (probability / mean)."""
        return score

    def loss(self, score: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
        """Mean eval loss (early-stopping metric; the reference's default
        per-objective metric, e.g. binary_logloss / l2)."""
        raise NotImplementedError


class RegressionL2(Objective):
    name = "regression"

    def init_score(self, y, w):
        return np.asarray([np.average(y, weights=w)])

    def grad_hess(self, score, y):
        return score - y, jnp.ones_like(score)

    def loss(self, score, y):
        return jnp.mean((score - y) ** 2)


class RegressionL1(Objective):
    name = "regression_l1"

    def init_score(self, y, w):
        return np.asarray([_weighted_quantile(y, w, 0.5)])

    def grad_hess(self, score, y):
        return jnp.sign(score - y), jnp.ones_like(score)

    def loss(self, score, y):
        return jnp.mean(jnp.abs(score - y))


class Huber(Objective):
    name = "huber"

    def __init__(self, alpha: float = 0.9):
        self.alpha = float(alpha)

    def init_score(self, y, w):
        return np.asarray([np.average(y, weights=w)])

    def grad_hess(self, score, y):
        d = score - y
        g = jnp.clip(d, -self.alpha, self.alpha)
        return g, jnp.ones_like(score)

    def loss(self, score, y):
        d = jnp.abs(score - y)
        return jnp.mean(jnp.where(d <= self.alpha, 0.5 * d * d,
                                  self.alpha * (d - 0.5 * self.alpha)))


class Quantile(Objective):
    name = "quantile"

    def __init__(self, alpha: float = 0.5):
        self.alpha = float(alpha)

    def init_score(self, y, w):
        return np.asarray([_weighted_quantile(y, w, self.alpha)])

    def grad_hess(self, score, y):
        # d/ds pinball loss: alpha-1 below the target, alpha above
        g = jnp.where(score >= y, 1.0 - self.alpha, -self.alpha)
        return g, jnp.ones_like(score)

    def loss(self, score, y):
        d = y - score
        return jnp.mean(jnp.maximum(self.alpha * d, (self.alpha - 1) * d))


class Poisson(Objective):
    name = "poisson"

    def init_score(self, y, w):
        mean = max(np.average(y, weights=w), 1e-9)
        return np.asarray([np.log(mean)])

    def grad_hess(self, score, y):
        e = jnp.exp(score)
        return e - y, e

    def transform(self, score):
        return jnp.exp(score)

    def loss(self, score, y):
        return jnp.mean(jnp.exp(score) - y * score)


class Tweedie(Objective):
    name = "tweedie"

    def __init__(self, rho: float = 1.5):
        self.rho = float(rho)  # variance power in (1, 2)

    def init_score(self, y, w):
        mean = max(np.average(y, weights=w), 1e-9)
        return np.asarray([np.log(mean)])

    def grad_hess(self, score, y):
        p = self.rho
        g = -y * jnp.exp((1.0 - p) * score) + jnp.exp((2.0 - p) * score)
        h = -y * (1.0 - p) * jnp.exp((1.0 - p) * score) \
            + (2.0 - p) * jnp.exp((2.0 - p) * score)
        return g, h

    def transform(self, score):
        return jnp.exp(score)

    def loss(self, score, y):
        p = self.rho
        return jnp.mean(jnp.exp((2 - p) * score) / (2 - p)
                        - y * jnp.exp((1 - p) * score) / (1 - p))


class Gamma(Tweedie):
    name = "gamma"

    def __init__(self):
        super().__init__(rho=2.0)

    def grad_hess(self, score, y):
        # rho=2 limit: grad = 1 - y*exp(-s), hess = y*exp(-s)
        e = y * jnp.exp(-score)
        return 1.0 - e, e

    def loss(self, score, y):
        return jnp.mean(score + y * jnp.exp(-score))


class Binary(Objective):
    name = "binary"
    is_classification = True
    num_class = 1

    def init_score(self, y, w):
        p = np.clip(np.average(y, weights=w), 1e-12, 1 - 1e-12)
        return np.asarray([np.log(p / (1 - p))])

    def grad_hess(self, score, y):
        p = jnp.clip(1.0 / (1.0 + jnp.exp(-score)), 1e-15, 1 - 1e-15)
        return p - y, p * (1.0 - p)

    def transform(self, score):
        return 1.0 / (1.0 + jnp.exp(-score))

    def loss(self, score, y):
        p = jnp.clip(1.0 / (1.0 + jnp.exp(-score)), 1e-15, 1 - 1e-15)
        return -jnp.mean(y * jnp.log(p) + (1 - y) * jnp.log(1 - p))


class Multiclass(Objective):
    """Softmax cross-entropy; score shape (K, N), y integer labels (N,)."""

    name = "multiclass"
    is_classification = True

    def __init__(self, num_class: int):
        self.num_class = int(num_class)

    def init_score(self, y, w):
        counts = np.asarray([np.sum(w * (y == k))
                             for k in range(self.num_class)])
        p = np.clip(counts / counts.sum(), 1e-12, 1.0)
        return np.log(p)

    def grad_hess(self, score, y):
        # score: (K, N); softmax over K
        m = score - jnp.max(score, axis=0, keepdims=True)
        e = jnp.exp(m)
        p = e / jnp.sum(e, axis=0, keepdims=True)
        onehot = (jnp.arange(self.num_class)[:, None] == y[None, :]
                  ).astype(p.dtype)
        g = p - onehot
        h = 2.0 * p * (1.0 - p)  # LightGBM's factor-2 multiclass hessian
        return g, h

    def transform(self, score):
        m = score - jnp.max(score, axis=0, keepdims=True)
        e = jnp.exp(m)
        return e / jnp.sum(e, axis=0, keepdims=True)

    def loss(self, score, y):
        m = score - jnp.max(score, axis=0, keepdims=True)
        logp = m - jnp.log(jnp.sum(jnp.exp(m), axis=0, keepdims=True))
        picked = jnp.take_along_axis(logp, y[None, :].astype(int), axis=0)
        return -jnp.mean(picked)


def _weighted_quantile(y, w, q):
    order = np.argsort(y)
    cw = np.cumsum(w[order])
    cut = q * cw[-1]
    i = int(np.searchsorted(cw, cut))
    return float(y[order[min(i, len(y) - 1)]])


_FACTORIES: Dict[str, Callable[..., Objective]] = {
    "regression": RegressionL2, "l2": RegressionL2, "mse": RegressionL2,
    "regression_l1": RegressionL1, "l1": RegressionL1, "mae": RegressionL1,
    "huber": Huber,
    "quantile": Quantile,
    "poisson": Poisson,
    "tweedie": Tweedie,
    "gamma": Gamma,
    "binary": Binary,
    "multiclass": Multiclass, "softmax": Multiclass,
}


def get_objective(name: str, num_class: int = 1, alpha: float = 0.9,
                  tweedie_variance_power: float = 1.5) -> Objective:
    key = name.lower()
    if key not in _FACTORIES:
        raise ValueError(f"unknown objective {name!r}; "
                         f"have {sorted(_FACTORIES)}")
    cls = _FACTORIES[key]
    if cls is Multiclass:
        return Multiclass(num_class)
    if cls is Quantile:
        return Quantile(alpha)
    if cls is Huber:
        return Huber(alpha)
    if cls is Tweedie:
        return Tweedie(tweedie_variance_power)
    return cls()
