"""TPU-native gradient-boosted decision trees.

Re-creation of the capabilities of the reference's distributed LightGBM
wrapper (ref: src/lightgbm/src/main/scala/*) as a TPU-first engine:
quantile binning fitted on host and applied on DEVICE when f32-safe
(raw feature blocks + jitted searchsorted; host kernels otherwise),
histogram building and leaf-wise tree growth as jitted XLA programs
(one-hot/matmul histograms on the MXU) batched ``boost_chunk``
iterations per dispatch via lax.scan, and data-parallel training via
shard_map + psum of histograms over the mesh — the ICI-collective
analog of LightGBM's socket allreduce ring
(ref: TrainUtils.scala:207 LGBM_NetworkInit).
"""

from mmlspark_tpu.gbdt.binning import BinMapper, bucketize_fm_device
from mmlspark_tpu.gbdt.booster import Booster, train
from mmlspark_tpu.gbdt.estimators import (
    TPUBoostClassificationModel,
    TPUBoostClassifier,
    TPUBoostRegressionModel,
    TPUBoostRegressor,
)

__all__ = [
    "BinMapper", "Booster", "bucketize_fm_device", "train",
    "TPUBoostClassifier", "TPUBoostClassificationModel",
    "TPUBoostRegressor", "TPUBoostRegressionModel",
]
