"""TPU-native gradient-boosted decision trees.

Re-creation of the capabilities of the reference's distributed LightGBM
wrapper (ref: src/lightgbm/src/main/scala/*) as a TPU-first engine:
quantile binning on host, histogram building and leaf-wise tree growth as
jitted XLA programs (one-hot/matmul histograms on the MXU), and
data-parallel training via shard_map + psum of histograms over the mesh —
the ICI-collective analog of LightGBM's socket allreduce ring
(ref: TrainUtils.scala:207 LGBM_NetworkInit).
"""

from mmlspark_tpu.gbdt.binning import BinMapper
from mmlspark_tpu.gbdt.booster import Booster, train
from mmlspark_tpu.gbdt.estimators import (
    TPUBoostClassificationModel,
    TPUBoostClassifier,
    TPUBoostRegressionModel,
    TPUBoostRegressor,
)

__all__ = [
    "BinMapper", "Booster", "train",
    "TPUBoostClassifier", "TPUBoostClassificationModel",
    "TPUBoostRegressor", "TPUBoostRegressionModel",
]
