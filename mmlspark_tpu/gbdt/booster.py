"""Booster: GBDT training driver + serialized model.

Capability parity with the reference's `LightGBMBooster`
(ref: src/lightgbm/src/main/scala/LightGBMBooster.scala:14-60 — model
string serialization, lazy scoring, saveNativeModel, feature importances)
and its train loop (ref: TrainUtils.scala:71-107 — booster create, iterate
``LGBM_BoosterUpdateOneIter``, early stopping via modelString warm start).

TPU design: the dataset is binned once on host, shipped to HBM once, and
every boosting iteration is a jitted program (gradients → tree growth →
score update). Data-parallel mode wraps the iteration in ``shard_map``
over the mesh's data axis with psum'd histograms — the ICI equivalent of
``LGBM_NetworkInit``'s socket allreduce ring (ref: TrainUtils.scala:207).
"""

from __future__ import annotations

import collections
import functools
import json
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from mmlspark_tpu.utils.jax_compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from mmlspark_tpu.gbdt import binning as binning_lib
from mmlspark_tpu.gbdt.binning import BinMapper
from mmlspark_tpu.gbdt.objectives import Objective, get_objective
from mmlspark_tpu.gbdt.tree import (
    GrowParams, Tree, grow_tree, predict_trees, sample_iteration_masks,
)
from mmlspark_tpu.parallel import mesh as mesh_lib

# trace-time counters: each entry increments when XLA (re)traces the
# named program, so `trace_counts()` deltas across repeated train()
# calls at the same shapes are the chunk-fn-cache regression guard
# (tests/test_perf_floors.py) — steady state must add ZERO traces.
TRACE_COUNTS: collections.Counter = collections.Counter()


def trace_counts() -> Dict[str, int]:
    """Snapshot of boosting-program trace counters (recompile guard)."""
    return dict(TRACE_COUNTS)

DEFAULTS: Dict[str, Any] = {
    # names mirror the reference's TrainParams (TrainParams.scala:9-61)
    "objective": "regression",
    "num_iterations": 100,
    "learning_rate": 0.1,
    "num_leaves": 31,
    "max_bin": 255,
    "max_depth": 0,
    "min_data_in_leaf": 20,
    "min_sum_hessian_in_leaf": 1e-3,
    "lambda_l1": 0.0,
    "lambda_l2": 0.0,
    "min_gain_to_split": 0.0,
    "feature_fraction": 1.0,
    "bagging_fraction": 1.0,
    "bagging_freq": 0,
    "num_class": 1,
    "boost_from_average": True,
    "early_stopping_round": 0,
    "seed": 0,
    "alpha": 0.9,                      # quantile / huber
    "tweedie_variance_power": 1.5,
    "hist_method": "auto",  # 'auto' | 'scatter' | 'onehot' | 'pallas'
    # histogram precision (Shi et al., NeurIPS'22 quantized GBDT):
    # 32 = classic f32 (bit-identical to the pre-quantization engine);
    # 16/8 = per-round gradients stochastically rounded to narrow ints,
    # exact int32 histogram accumulation, int16 collective wire (2x
    # fewer bytes than f32), one dequantize at split-gain time
    "hist_bits": 32,
    # data-parallel histogram collective: 'psum' allreduces the full
    # (3, F, B) tensor to every device; 'reduce_scatter' partitions
    # features across devices (O(F*B/D) wire; LightGBM's distributed
    # recipe) and exchanges only (D, 4) split candidates. 'auto' keeps
    # psum for f32 (bit-compat) and picks reduce_scatter for quantized
    # data-parallel runs, where the wire saving is the point.
    "hist_comm": "auto",
    "parallelism": "serial",  # 'serial' | 'data' | 'feature' | 'voting'
    "top_k": 20,               # voting-parallel candidates per worker
    # iterations fused per host dispatch (lax.scan chunk); 0 = auto
    # (8 for runs long enough to amortize the chunk compile, else 1);
    # with early stopping every chunk is capped at esr_sync so the
    # async loss-read contract holds
    "boost_chunk": 0,
    # 'auto' bins on device when the mapper's cuts are f32-exact
    # (float32 input) and the input is dense single-host; 'off' forces
    # host binning; 'on' asks for device binning and warns (falling
    # back) when ineligible
    "device_binning": "auto",
    # how streaming/multi-host ingest fits bin boundaries: 'sample' =
    # the reservoir-sample-then-fit discipline (LightGBM
    # bin_construct_sample_cnt analog; boundaries from <=200k rows);
    # 'sketch' = BinMapper.fit_streaming — a mergeable quantile sketch
    # sees EVERY row in one bounded-memory pass (Chen & Guestrin §3.3 /
    # GK), and multi-host fits agree by exchanging per-host sketches
    # instead of gathering sample rows. Dense in-memory input ignores
    # this (one-shot fit sees everything already).
    "bin_fit": "sample",
    # keep the device-resident training state (binned matrix, running
    # scores, forest buffer) on the returned Booster so
    # boost_more(data=None) continues boosting EXACTLY where train()
    # stopped — bit-identical to having trained longer in one call.
    # Costs the binned matrix's HBM for the Booster's lifetime;
    # single-host, early-stopping-off runs only.
    "keep_training_data": False,
}


class Booster:
    """A trained forest, serializable to a model string."""

    def __init__(self, objective: Objective, trees: Dict[str, np.ndarray],
                 init_score: np.ndarray, num_class: int,
                 feature_names: List[str], params: Dict[str, Any],
                 best_iteration: int = -1, tree_depths: Optional[List[int]] = None):
        self.objective = objective
        self.trees = trees  # stacked arrays (T, M): feature/threshold/left/right/value/is_leaf/gain/count
        self.init_score = np.asarray(init_score, dtype=np.float64)
        self.num_class = int(num_class)
        self.feature_names = list(feature_names)
        self.params = dict(params)
        self.best_iteration = int(best_iteration)
        self.tree_depths = list(tree_depths or [])
        self._f64_flag: Optional[bool] = None   # _needs_f64_inference cache
        # device-resident tree arrays, keyed by the t_limit they were
        # built for (raw_score used to re-upload the whole forest on
        # every call); invalidated whenever t_limit changes
        self._dev_forest: Optional[Tuple[int, Dict[str, Any]]] = None
        # per-phase fit wall seconds (set by train(); empty for loaded
        # models): {bin, ship[, bin_device], first_iter, boost, fetch}
        self.train_timing: Dict[str, float] = {}
        # non-numeric fit facts (set by train()): bin_path
        # ('device'|'host'), boost_chunk (fused iterations per
        # dispatch), boost_chunks (dispatch count)
        self.train_info: Dict[str, Any] = {}
        # incremental-refresh state (set by train(); both in-memory
        # only — a Booster rebuilt from a model string has neither):
        # the frozen BinMapper for boost_more on fresh data, and the
        # retained device training state for exact continuation
        self.bin_mapper = None
        self._resume: Optional[Dict[str, Any]] = None

    # -- inference ----------------------------------------------------------

    @property
    def num_trees(self) -> int:
        return 0 if not self.trees else int(self.trees["feature"].shape[0])

    def _max_depth(self, t_limit: int) -> int:
        depths = self.tree_depths[:t_limit] or [
            self.params.get("num_leaves", 31) - 1]
        return max(1, max(depths))

    def _needs_f64_inference(self) -> bool:
        """True when the jitted f32 walk could misroute rows. Primary
        signal: the fit-time flag recorded from the BinMapper's true
        data gaps ('f32_unsafe' in params). Fallback for models saved
        without the flag: thresholds beyond f32's 24-bit integer range
        (timestamps/IDs), or PER-FEATURE threshold spacing below the
        f32 rounding band. Such forests score on host in float64.
        Cached — trees are immutable after construction."""
        if self._f64_flag is None:
            self._f64_flag = self._compute_f64_flag()
        return self._f64_flag

    def _compute_f64_flag(self) -> bool:
        if "f32_unsafe" in self.params:
            return bool(self.params["f32_unsafe"])
        if not self.trees:
            return False
        internal = ~self.trees["is_leaf"].astype(bool)
        thr = self.trees["threshold"][internal]
        feats = self.trees["feature"][internal]
        keep = np.isfinite(thr)
        thr, feats = thr[keep], feats[keep]
        if not len(thr):
            return False
        if np.abs(thr).max() >= 2.0 ** 24:
            return True
        eps32 = float(np.finfo(np.float32).eps)
        for fid in np.unique(feats):
            t = np.unique(thr[feats == fid])
            if len(t) < 2:
                continue
            gaps = np.diff(t)
            band = 8.0 * eps32 * np.maximum(np.abs(t[:-1]), np.abs(t[1:]))
            if (gaps <= band).any():
                return True
        return False

    def raw_score(self, X: np.ndarray,
                  num_iteration: Optional[int] = None) -> np.ndarray:
        """Raw margin scores, shape (N,) or (K, N) for multiclass.
        CSRMatrix inputs score through chunked densification (8192 rows
        at a time) — bounded memory at any feature width."""
        from mmlspark_tpu.core.sparse import CSRMatrix
        if isinstance(X, CSRMatrix):
            if X.shape[0] == 0:
                return self.raw_score(
                    np.zeros((0, len(self.feature_names))), num_iteration)
            # rows per chunk from a ~256 MB dense budget, so memory stays
            # bounded at ANY feature width
            step = max(1, min(8192, (256 << 20) // (4 * X.shape[1])))
            outs = [self.raw_score(X[lo:min(lo + step, X.shape[0])]
                                   .toarray(), num_iteration)
                    for lo in range(0, X.shape[0], step)]
            return np.concatenate(outs, axis=-1)
        n = np.asarray(X).shape[0]
        K = self.num_class
        it = self._resolve_iterations(num_iteration)
        t_limit = it * K
        scores = np.broadcast_to(
            self.init_score[:, None].astype(np.float32), (K, n)).copy()
        if t_limit > 0 and self.num_trees > 0:
            if self._needs_f64_inference():
                out = _host_predict_trees(
                    np.asarray(X, dtype=np.float64),
                    {k: v[:t_limit] for k, v in self.trees.items()},
                    self._max_depth(t_limit))
            else:
                dev = self._device_trees(t_limit)
                out = np.asarray(predict_trees(
                    jnp.asarray(np.asarray(X, dtype=np.float32)),
                    dev["feature"], dev["threshold"], dev["left"],
                    dev["right"], dev["value"],
                    max_depth=self._max_depth(t_limit)))   # (T, N)
            out = out.reshape(it, K, n).sum(axis=0)
            scores += out
        return scores[0] if K == 1 else scores

    def _device_trees(self, t_limit: int) -> Dict[str, Any]:
        """Device-resident stacked tree arrays for the jitted f32 walk.
        Cached on the Booster (building five jnp arrays per predict()
        call re-shipped the whole forest every time — it dominated
        small-batch scoring); invalidated when ``t_limit`` changes
        (num_iteration / best_iteration truncation picks new rows)."""
        cached = self._dev_forest
        if cached is None or cached[0] != t_limit:
            arrs = {k: jnp.asarray(self.trees[k][:t_limit])
                    for k in ("feature", "threshold", "left", "right",
                              "value")}
            cached = (int(t_limit), arrs)
            self._dev_forest = cached
        # return the LOCAL tuple, not a re-read of the attribute: a
        # concurrent predict() with a different t_limit may swap the
        # cache between the check above and this return
        return cached[1]

    def predict(self, X: np.ndarray,
                num_iteration: Optional[int] = None) -> np.ndarray:
        """Transformed prediction (probability / mean). Multiclass returns
        (N, K) probabilities."""
        raw = self.raw_score(X, num_iteration)
        out = np.asarray(self.objective.transform(jnp.asarray(raw)))
        return out.T if self.num_class > 1 else out

    def _resolve_iterations(self, num_iteration: Optional[int]) -> int:
        total = self.num_trees // max(self.num_class, 1)
        if num_iteration is not None and num_iteration > 0:
            return min(num_iteration, total)
        if self.best_iteration > 0:
            return min(self.best_iteration, total)
        return total

    # -- introspection ------------------------------------------------------

    def feature_importance(self, importance_type: str = "split") -> np.ndarray:
        """Per-feature split counts or total gain
        (ref: LightGBMBooster.getFeatureImportances)."""
        f = len(self.feature_names)
        out = np.zeros(f)
        if self.num_trees == 0:
            return out
        internal = ~self.trees["is_leaf"].astype(bool)
        feats = self.trees["feature"][internal]
        if importance_type == "split":
            np.add.at(out, feats, 1.0)
        elif importance_type == "gain":
            np.add.at(out, feats, self.trees["gain"][internal])
        else:
            raise ValueError(f"importance_type {importance_type!r}")
        return out

    # -- incremental refresh (continued boosting) ---------------------------

    def boost_more(self, num_iterations: int, X=None,
                   y: Optional[np.ndarray] = None,
                   sample_weight: Optional[np.ndarray] = None,
                   valid: Optional[Tuple[np.ndarray, np.ndarray]] = None,
                   mesh: Optional[Mesh] = None) -> "Booster":
        """Append ``num_iterations`` boosting rounds and return the
        grown forest as a NEW Booster (this one is untouched apart from
        its retained device state being consumed — see below). The
        online-refresh path of the model-lifecycle story: keep serving
        the old forest while the new one trains, then hot-swap.

        Two modes:

        - ``X is None`` — EXACT continuation on the retained training
          state (requires ``train(..., {'keep_training_data': True})``).
          The device-resident binned matrix, running scores, and forest
          buffer pick up exactly where train() stopped, so the result
          is bit-identical to having trained ``it + num_iterations``
          rounds in one call (chunk-length invariance is pinned by the
          PR 3 parity suite; continuation just adds chunks). The
          retained state is single-use: the jitted chunk donates its
          score/forest buffers, so after this call the state moves to
          the RETURNED booster and this one's is marked consumed.

        - ``X, y`` given — continued boosting on FRESH data against the
          FROZEN ``bin_mapper``: new data bins with the original cuts
          (identical split semantics to the base forest; drifted values
          clamp into the original bin range), the base forest scores
          the new rows once, and new trees append. Deterministic for
          fixed inputs; per-iteration sampling masks continue at the
          base forest's iteration index, so a bagged continuation
          doesn't replay the base run's bags."""
        if num_iterations <= 0:
            raise ValueError(
                f"num_iterations must be positive: {num_iterations}")
        if X is None:
            if y is not None or sample_weight is not None \
                    or valid is not None:
                raise ValueError(
                    "boost_more(data=None) continues on the retained "
                    "training state; y/sample_weight/valid only apply "
                    "with fresh X")
            return self._boost_more_retained(int(num_iterations))
        if self.bin_mapper is None:
            raise ValueError(
                "this Booster carries no BinMapper (rebuilt from a "
                "model string?); boost_more on fresh data needs the "
                "frozen fit-time binning — keep the trained Booster "
                "object, or refit")
        params = {k: v for k, v in self.params.items() if k in DEFAULTS}
        params["num_iterations"] = int(num_iterations)
        # the fresh-data path rides the init_model warm start, which
        # cannot retain continuation state by design — carrying the
        # flag through would only trigger train()'s ineligibility
        # warning on every refresh cycle
        params.pop("keep_training_data", None)
        if valid is None:
            params["early_stopping_round"] = 0
        return train(params, X, y, sample_weight=sample_weight,
                     valid=valid, feature_names=self.feature_names,
                     mesh=mesh, init_model=self,
                     bin_mapper=self.bin_mapper)

    def _boost_more_retained(self, extra: int) -> "Booster":
        st = self._resume
        if st is None:
            raise ValueError(
                "no retained training state: pass "
                "{'keep_training_data': True} to train() (single-host, "
                "no init_model, no early stopping) to enable "
                "boost_more(data=None)")
        if st["consumed"]:
            raise ValueError(
                "retained training state already consumed: the jitted "
                "chunk donates its buffers, so continuation chains "
                "through the NEWEST booster returned by boost_more")
        import time as _time
        t_start = _time.perf_counter()
        K, it0 = st["K"], st["it_done"]
        total = it0 + extra
        forest, t_cap = st["forest"], st["t_cap"]
        need = total * K
        new_cap = t_cap
        while new_cap < need:
            new_cap *= 2    # keep the pow-2 capacity-bucket discipline
        if new_cap != t_cap:
            grow = new_cap - t_cap
            # grown rows are written before they are ever read, so the
            # pad values are inert (left/right 0 self-reference included)
            forest = Tree(*[jnp.pad(getattr(forest, fld),
                                    ((0, grow), (0, 0)))
                            for fld in Tree._fields])
        scores = st["scores"]
        S_cfg = int(self.params.get("boost_chunk", 0) or 0)
        if S_cfg <= 0:
            S_cfg = 8 if extra >= 16 else 1
        S_cfg = max(1, min(S_cfg, extra))
        # consumed BEFORE the first dispatch: the chunk donates the
        # score/forest buffers, so a mid-loop failure (compile error,
        # OOM on a grown buffer, interrupt) must not leave a state that
        # passes the guard while pointing at deleted device arrays
        st["consumed"] = True
        it = it0
        n_chunks = 0
        while it < total:
            S = min(S_cfg, total - it)
            chunk_fn = _make_chunk_step(
                st["obj_key"], st["gp"], st["lr"], K, st["axis_name"],
                st["mesh"], st["parallel_mode"], S, st["bag_cfg"],
                st["ff_cfg"], st["f"], st["f_eff"])
            scores, forest = chunk_fn(
                st["bins_d"], scores, st["y_d"], st["w_d"],
                st["fmask_base"], forest, np.int32(it), st["mask_key"])
            n_chunks += 1
            it += S
        jax.block_until_ready(scores)
        trees_done = total * K
        host = jax.device_get(forest._asdict())
        stacked = {name: arr[:trees_done] for name, arr in host.items()}
        mapper = st["mapper"]
        thr_lut = mapper.threshold_matrix(st["num_bins"])
        thr = thr_lut[stacked["feature"], stacked["bin_threshold"]]
        stacked["threshold"] = np.where(stacked["is_leaf"], 0.0, thr)
        stacked["value"] = stacked["value"] * st["lr"]
        tree_depths = [
            _tree_depth({k: v[t] for k, v in stacked.items()})
            for t in range(trees_done)]
        p2 = dict(self.params)
        p2["num_iterations"] = total
        booster = Booster(self.objective, stacked, st["init_score"], K,
                          st["feature_names"], p2, best_iteration=-1,
                          tree_depths=tree_depths)
        booster.bin_mapper = mapper
        booster._resume = {**st, "scores": scores, "forest": forest,
                           "it_done": total, "t_cap": new_cap,
                           "consumed": False}
        booster.train_timing = {
            "boost": round(_time.perf_counter() - t_start, 3)}
        booster.train_info = {"bin_path": "retained",
                              "boost_chunk": S_cfg,
                              "boost_chunks": n_chunks}
        return booster

    # -- serialization ------------------------------------------------------

    def model_to_string(self) -> str:
        d = {
            "format": "mmlspark_tpu.booster.v1",
            "objective": self.objective.name,
            "objective_config": {
                "num_class": self.num_class,
                "alpha": getattr(self.objective, "alpha", None),
                "rho": getattr(self.objective, "rho", None),
            },
            "num_class": self.num_class,
            "init_score": self.init_score.tolist(),
            "feature_names": self.feature_names,
            "best_iteration": self.best_iteration,
            "tree_depths": self.tree_depths,
            "params": {k: v for k, v in self.params.items()
                       if isinstance(v, (int, float, str, bool))},
            "trees": {k: v.tolist() for k, v in self.trees.items()},
        }
        return json.dumps(d)

    @staticmethod
    def from_string(s: str) -> "Booster":
        d = json.loads(s)
        cfg = d.get("objective_config", {})
        alpha = cfg.get("alpha")
        rho = cfg.get("rho")
        obj = get_objective(
            d["objective"], num_class=d["num_class"],
            alpha=0.9 if alpha is None else alpha,
            tweedie_variance_power=1.5 if rho is None else rho)
        tree_dtypes = {"feature": np.int32, "threshold": np.float64,
                       "left": np.int32, "right": np.int32,
                       "value": np.float32, "is_leaf": bool,
                       "gain": np.float32, "count": np.float32,
                       "bin_threshold": np.int32}
        trees = {k: np.asarray(v, dtype=tree_dtypes.get(k, np.float32))
                 for k, v in d["trees"].items()}
        return Booster(obj, trees, np.asarray(d["init_score"]),
                       d["num_class"], d["feature_names"], d["params"],
                       d.get("best_iteration", -1), d.get("tree_depths"))

    def save_native_model(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.model_to_string())

    @staticmethod
    def load_native_model(path: str) -> "Booster":
        with open(path) as f:
            return Booster.from_string(f.read())


# ---------------------------------------------------------------------------
# training
# ---------------------------------------------------------------------------


_RESERVOIR_CAP = 200_000


def _reservoir_rows(shard_iter, cap: int, seed: int) -> np.ndarray:
    """Uniform row sample across an entire shard stream (bounded memory,
    one pass) — vectorized Algorithm R over row blocks. This is the
    LightGBM BinMapper discipline: sample the WHOLE dataset, not the
    head (ref: LGBM bin_construct_sample_cnt over the full data)."""
    rng = np.random.default_rng(seed ^ 0x5EED)
    buf: Optional[np.ndarray] = None
    seen = 0
    for shard in shard_iter:
        Xs = np.asarray(shard[0], dtype=np.float64)
        i = 0
        if buf is None:
            take = min(cap, len(Xs))
            buf = Xs[:take].copy()
            seen = take
            i = take
        elif len(buf) < cap:
            take = min(cap - len(buf), len(Xs))
            buf = np.concatenate([buf, Xs[:take]])
            seen += take
            i = take
        rest = Xs[i:]
        if len(rest):
            t = seen + np.arange(1, len(rest) + 1)
            accept = rng.random(len(rest)) < (cap / t)
            n_acc = int(accept.sum())
            if n_acc:
                buf[rng.integers(0, cap, size=n_acc)] = rest[accept]
            seen += len(rest)
    if buf is None:
        raise ValueError("empty shard stream")
    return buf


def _multihost_sketch_mapper(X, streaming: bool, max_bin: int,
                             nproc: int) -> BinMapper:
    """Distributed bin-boundary agreement WITHOUT gathering rows: each
    host folds its LOCAL data into per-feature mergeable quantile
    sketches (gbdt/sketch.py), the fixed-shape sketch summaries are
    allgathered bit-exactly (f64 as uint32 pairs, like the row wire
    below), and every host merges the SAME per-host summaries in
    process order — so all hosts derive identical cuts from statistics
    of EVERY row, at O(F · width) wire bytes instead of O(sample · F)
    rows (the Chen & Guestrin §3.3 distributed-sketch recipe)."""
    from jax.experimental import multihost_utils
    from mmlspark_tpu.gbdt.sketch import QuantileSketch
    from mmlspark_tpu.core.sparse import CSRMatrix
    wire_width = 512
    sketches: List[QuantileSketch] = []

    def absorb(block: np.ndarray) -> None:
        block = np.asarray(block)
        if not sketches:
            sketches.extend(QuantileSketch()
                            for _ in range(block.shape[1]))
        for j, sk in enumerate(sketches):
            sk.update(block[:, j])

    if streaming:
        if not (isinstance(X, (list, tuple)) or callable(X)):
            raise ValueError(
                "multi-host streaming GBDT needs a replayable shard "
                "sequence (list or zero-arg factory), not a one-shot "
                "generator: bin boundaries must be agreed across hosts "
                "before any shard is binned")
        fac = X if callable(X) else (lambda: iter(X))
        for shard in fac():
            absorb(shard[0])
    elif isinstance(X, CSRMatrix):
        # bounded densification (the CSR fit path keeps no dense copy)
        step = max(1, (64 << 20) // max(1, X.shape[1] * 8))
        for i in range(0, X.shape[0], step):
            absorb(X.take(np.arange(i, min(i + step, X.shape[0])))
                   .toarray())
        if not sketches:
            absorb(np.empty((0, X.shape[1])))
    else:
        absorb(np.asarray(X))
    wire = np.stack([sk.to_wire(wire_width) for sk in sketches])
    as_u32 = np.ascontiguousarray(wire, dtype=np.float64).view(np.uint32)
    gathered = np.ascontiguousarray(np.asarray(
        multihost_utils.process_allgather(as_u32)))
    gathered = gathered.reshape(nproc, *as_u32.shape).view(np.float64)
    merged = [QuantileSketch.from_wire(gathered[0, j])
              for j in range(len(sketches))]
    for h in range(1, nproc):
        for j, sk in enumerate(merged):
            sk.merge(QuantileSketch.from_wire(gathered[h, j]))
    return BinMapper.fit_streaming([], max_bin=max_bin, sketches=merged)


def _multihost_mapper(X, streaming: bool, max_bin: int, seed: int,
                      nproc: int, bin_fit: str = "sample") -> BinMapper:
    """Identical bin boundaries on every host: each host reservoir- or
    choice-samples its LOCAL shard, the samples are allgathered, and
    every host fits the SAME mapper on the gathered rows — the
    distributed BinMapper agreement LightGBM reaches inside its native
    allreduce ring (ref: TrainUtils.scala:207 LGBM_NetworkInit +
    LGBM_DatasetCreateFromMat). With ``bin_fit='sketch'`` hosts instead
    exchange mergeable quantile-sketch summaries built over ALL their
    rows (``_multihost_sketch_mapper``) — no row ever crosses hosts."""
    from jax.experimental import multihost_utils
    from mmlspark_tpu.core.sparse import CSRMatrix
    if bin_fit == "sketch":
        return _multihost_sketch_mapper(X, streaming, max_bin, nproc)
    cap = max(1000, _RESERVOIR_CAP // nproc)
    rng = np.random.default_rng(seed)
    if streaming:
        if not (isinstance(X, (list, tuple)) or callable(X)):
            raise ValueError(
                "multi-host streaming GBDT needs a replayable shard "
                "sequence (list or zero-arg factory), not a one-shot "
                "generator: bin boundaries must be agreed across hosts "
                "before any shard is binned")
        fac = X if callable(X) else (lambda: iter(X))
        sample = _reservoir_rows(
            ((np.asarray(s[0], np.float64),) for s in fac()), cap, seed)
    elif isinstance(X, CSRMatrix):
        # the gathered sample is dense — budget rows by bytes so wide
        # hashed features can't OOM before the binned-matrix guard runs
        cap = min(cap, max(100, (256 << 20) // (X.shape[1] * 8)))
        idx = rng.choice(X.shape[0], size=min(X.shape[0], cap),
                         replace=False)
        sample = X.take(idx).toarray().astype(np.float64)
    else:
        n_loc = len(X)
        idx = rng.choice(n_loc, size=min(n_loc, cap), replace=False)
        sample = np.asarray(X[idx] if isinstance(X, np.ndarray)
                            else np.asarray(X)[idx], dtype=np.float64)
    s_len = int(np.min(np.asarray(multihost_utils.process_allgather(
        np.asarray([len(sample)]))).ravel()))
    # f64 BIT-EXACT on the wire: the collective layer would silently
    # downcast float64 to f32 (jax x64 is off), so ship the raw bits as
    # uint32 pairs and reinterpret after the gather. An f32 wire would
    # let an f32-unsafe feature (timestamps, 2^24-scale IDs) bin
    # differently multi-host vs single-host — the exact failure class
    # the f64 host-binning work eliminated elsewhere.
    wire = np.ascontiguousarray(
        sample[:s_len], dtype=np.float64).view(np.uint32)
    gathered = np.ascontiguousarray(np.asarray(
        multihost_utils.process_allgather(wire)))
    gathered = gathered.reshape(-1, wire.shape[1]).view(np.float64)
    return BinMapper.fit(gathered, max_bin=max_bin,
                         sample_cnt=len(gathered), seed=seed)


def _bin_stream(shards, max_bin: int, seed: int,
                mapper: Optional[BinMapper] = None,
                bin_fit: str = "sample"):
    """Streaming ingestion: ``shards`` yields (X, y[, w]) tuples; only
    the int32 binned matrix is retained on host, so the raw floats never
    need to fit in RAM at once.

    Bin-boundary fidelity (LightGBM samples across the WHOLE dataset):
    replayable inputs (list/tuple or zero-arg factory) get a two-pass
    treatment — with ``bin_fit='sample'`` reservoir-sample all shards
    then fit; with ``bin_fit='sketch'`` run ``BinMapper.fit_streaming``
    so the mergeable quantile sketch sees EVERY row (boundaries within
    the sketch's measured rank-error certificate of an all-rows exact
    fit, instead of exact-on-a-200k-sample) — then bin. One-shot
    generators can only be binned with boundaries from the first shard;
    a reservoir accumulated alongside then MEASURES the drift a skewed
    shard order introduced and warns loudly when the first-shard
    boundaries disagree with full-stream boundaries."""
    replayable = isinstance(shards, (list, tuple)) or callable(shards)
    factory = (shards if callable(shards)
               else (lambda: iter(shards)) if replayable else None)

    forced = mapper is not None
    if forced:
        stream = factory() if replayable else shards
    elif replayable and bin_fit == "sketch":
        mapper = BinMapper.fit_streaming(
            (s[0] for s in factory()), max_bin=max_bin)
        stream = factory()
    elif replayable:
        sample = _reservoir_rows(factory(), _RESERVOIR_CAP, seed)
        mapper = BinMapper.fit(sample, max_bin=max_bin, seed=seed)
        stream = factory()
    else:
        stream = shards

    rng = np.random.default_rng(seed ^ 0x5EED)
    res_buf: Optional[np.ndarray] = None
    res_seen = 0
    first_shard_rows = 0
    bins_parts, y_parts, w_parts = [], [], []
    for shard in stream:
        Xs = np.asarray(shard[0], dtype=np.float64)
        ys = np.asarray(shard[1], dtype=np.float64)
        ws = (np.asarray(shard[2], dtype=np.float64) if len(shard) > 2
              else np.ones(len(ys)))
        if mapper is None:
            mapper = BinMapper.fit(Xs, max_bin=max_bin, seed=seed)
            first_shard_rows = len(Xs)
        if not replayable and not forced:
            # accumulate the full-stream reservoir for the drift check
            # (same fill/top-up/replace discipline as _reservoir_rows —
            # without the top-up the buffer would stay first-shard-sized
            # and the "full-stream" sample would bias to the tail)
            i = 0
            if res_buf is None:
                take = min(_RESERVOIR_CAP, len(Xs))
                res_buf, res_seen, i = Xs[:take].copy(), take, take
            elif len(res_buf) < _RESERVOIR_CAP:
                take = min(_RESERVOIR_CAP - len(res_buf), len(Xs))
                res_buf = np.concatenate([res_buf, Xs[:take]])
                res_seen += take
                i = take
            rest = Xs[i:]
            if len(rest):
                t = res_seen + np.arange(1, len(rest) + 1)
                accept = rng.random(len(rest)) < (_RESERVOIR_CAP / t)
                n_acc = int(accept.sum())
                if n_acc and len(res_buf) >= 1:
                    res_buf[rng.integers(0, len(res_buf), size=n_acc)] \
                        = rest[accept]
                res_seen += len(rest)
        bins_parts.append(mapper.transform(Xs))
        y_parts.append(ys)
        w_parts.append(ws)
    if mapper is None:
        raise ValueError("empty shard stream")
    if (not replayable and not forced and res_buf is not None
            and res_seen > first_shard_rows):
        # did the one-shot stream's first shard misrepresent the data?
        full_mapper = BinMapper.fit(res_buf, max_bin=max_bin, seed=seed)
        drift = float(np.mean(mapper.transform(res_buf)
                              != full_mapper.transform(res_buf)))
        if drift > 0.01:
            import logging
            logging.getLogger("mmlspark_tpu.gbdt").warning(
                "streaming binning drift: %.1f%% of sampled cells bin "
                "differently under first-shard vs full-stream "
                "boundaries — the shard order looks skewed/sorted. "
                "Pass a list or zero-arg factory of shards for exact "
                "two-pass quantiles.", 100 * drift)
    return (mapper, np.concatenate(bins_parts), np.concatenate(y_parts),
            np.concatenate(w_parts))


def comm_payload_model(parallel_mode: str, hist_comm: str,
                       hist_bits: int, num_trees: int, num_leaves: int,
                       num_features: int, num_bins: int, n_shards: int,
                       voting_k: int, num_rows: int) -> Dict[str, float]:
    """Per-device collective payload bytes for one training run,
    keyed by collective type ('psum' | 'psum_scatter' | 'all_gather').

    The collectives run inside the jitted boosting program, so bytes
    cannot be counted on the wire; this models the schedule exactly
    (the grow_tree collective sequence is static — the fori_loop always
    runs num_leaves-1 split steps) under the standard ring costs per
    device: allreduce 2*S*(D-1)/D, reduce-scatter S*(D-1)/D, all-gather
    S*(D-1)/D for an S-byte payload over D devices. Quantized runs
    (hist_bits < 32) ship int16 histogram wire (2 bytes/cell vs 4) plus
    one (3,) f32 scale psum per tree.
    """
    D = max(int(n_shards), 1)
    if D < 2 or num_trees <= 0:
        return {"psum": 0.0, "psum_scatter": 0.0, "all_gather": 0.0}
    ring = (D - 1) / D
    L, F, B = int(num_leaves), int(num_features), int(num_bins)
    item = 2 if hist_bits < 32 else 4        # histogram wire itemsize
    psum = scatter = gather = 0.0
    if parallel_mode == "data" and hist_comm == "reduce_scatter":
        fp = -(-F // D) * D                  # feature dim padded to D
        # per tree: L leaf histograms, each one reduce-scatter of the
        # (3, Fp, B) wire + one psum of the (3, B) feature-0 slice;
        # 2L-1 best_split calls each all_gather a (4,) f32 candidate
        scatter += L * (3 * fp * B * item) * ring
        psum += L * 2 * (3 * B * item) * ring
        gather += (2 * L - 1) * 16 * ring
    elif parallel_mode == "data":
        # per tree: L full-histogram allreduces (root + L-1 children)
        psum += L * 2 * (3 * F * B * item) * ring
    elif parallel_mode == "voting":
        k = min(max(int(voting_k), 1), F)
        c = D * k + 1                        # vote union + feature-0
        # per tree: 2L-1 top-k vote all_gathers; L single-slice psums
        # (root + right children) + L-1 UNSUBTRACTED pair psums (2x);
        # two (L,) f32 leaf-total psums
        gather += (2 * L - 1) * 4 * k * ring
        psum += (L + (L - 1) * 2) * 2 * (3 * c * B * item) * ring
        psum += 2 * 2 * (4 * L) * ring
    elif parallel_mode == "feature":
        # per tree: 2L-1 candidate all_gathers + L-1 row-indicator
        # broadcasts ((N,) f32 psum)
        gather += (2 * L - 1) * 16 * ring
        psum += (L - 1) * 2 * (4 * int(num_rows)) * ring
    if hist_bits < 32 and parallel_mode in ("data", "voting"):
        psum += 2 * 12 * ring                # (3,) f32 scales, per tree
    t = int(num_trees)
    return {"psum": psum * t, "psum_scatter": scatter * t,
            "all_gather": gather * t}


def resolve_hist_method(hist_method: str, backend: str,
                        max_bin: int) -> str:
    """Resolve the ``hist_method`` knob against the backend.

    'auto' picks the Pallas MXU kernel ONLY on TPU-class backends (the
    analog of the reference's native histogram loop,
    TrainUtils.scala:82-89); everywhere else it would run in slow
    interpret mode, so CPU/GPU fall back to the scatter (segment_sum)
    path. An explicit 'pallas' request beyond the kernel's VMEM tiling
    range (max_bin + 1 > 2048: the minimum block can't fit the one-hot
    budget) degrades to 'onehot' with a warning instead of failing
    Mosaic allocation."""
    if hist_method == "auto":
        hist_method = ("pallas" if backend in ("tpu", "axon")
                       else "scatter")
    if hist_method == "pallas" and max_bin + 1 > 2048:
        import logging
        logging.getLogger("mmlspark_tpu.gbdt").warning(
            f"max_bin={max_bin} exceeds the Pallas kernel's VMEM "
            f"tiling range; using the onehot path")
        hist_method = "onehot"
    return hist_method


def _validate_hist_params(p: Dict[str, Any]) -> None:
    """Fail fast — an unsupported hist_bits/hist_comm combination must
    raise an actionable error, never silently run f32."""
    hist_bits = int(p["hist_bits"])
    if hist_bits not in (32, 16, 8):
        raise ValueError(
            f"hist_bits={p['hist_bits']} is not supported: use 32 "
            "(f32), 16 or 8 (quantized histograms)")
    if hist_bits < 32 and p["hist_method"] == "onehot":
        raise ValueError(
            f"hist_bits={hist_bits} is not supported by "
            "hist_method='onehot' (its einsum accumulates f32, so the "
            "run would silently lose the integer-exactness contract); "
            "use hist_method='scatter' (any backend) or 'pallas' "
            "(TPU), or hist_bits=32")
    if hist_bits < 32 and p["parallelism"] == "feature":
        raise ValueError(
            "hist_bits < 32 with parallelism='feature' is not "
            "supported: feature-parallel histograms never cross the "
            "wire, so quantization only adds rounding noise; use "
            "parallelism='data' or 'voting', or hist_bits=32")
    if p["hist_comm"] == "auto":
        # quantized data-parallel gets the reduce-scatter partition
        # (the wire saving is the point); f32 keeps psum so the
        # default path stays bit-identical to the pre-reduce-scatter
        # engine on any device count
        p["hist_comm"] = ("reduce_scatter"
                          if hist_bits < 32
                          and p["parallelism"] == "data"
                          else "psum")
    elif p["hist_comm"] == "reduce_scatter":
        if p["parallelism"] != "data":
            raise ValueError(
                "hist_comm='reduce_scatter' requires "
                "parallelism='data' (feature/voting modes already "
                f"keep histograms local); got {p['parallelism']!r}")
    elif p["hist_comm"] != "psum":
        raise ValueError(
            f"unknown hist_comm={p['hist_comm']!r}; expected 'auto', "
            "'psum' or 'reduce_scatter'")


def train(params: Dict[str, Any], X, y: Optional[np.ndarray] = None,
          sample_weight: Optional[np.ndarray] = None,
          valid: Optional[Tuple[np.ndarray, np.ndarray]] = None,
          feature_names: Optional[List[str]] = None,
          mesh: Optional[Mesh] = None,
          init_model: Optional["Booster | str"] = None,
          bin_mapper: Optional[BinMapper] = None) -> Booster:
    """Train a Booster. ``parallelism='data'`` shards rows over ``mesh``'s
    data axis and psums histograms (LightGBM data-parallel tree learner
    analog, ref: TrainParams.scala:26).

    ``X`` is either a dense (N, F) matrix with ``y`` labels, or — for
    datasets that should not be materialized as floats at once — an
    iterable of ``(X_shard, y_shard[, w_shard])`` tuples with ``y=None``
    (only the int32 binned matrix is kept per shard).

    ``init_model`` (Booster or model string) warm-starts boosting: the
    run continues from the given forest's scores and the returned
    Booster carries old + new trees (ref: TrainUtils.scala:74-77
    modelString warm start). Requires dense ``X`` (the base forest is
    scored on the raw features).

    ``bin_mapper`` overrides the bin-boundary fit with a FROZEN mapper
    (single-host only): the incremental-refresh path —
    ``Booster.boost_more(fresh_data)`` — bins new data against the
    original training distribution's cuts, so appended trees split in
    the same bin space as the base forest.

    The returned Booster carries ``train_timing``: per-phase wall
    seconds {bin, ship[, bin_device], first_iter (compile+first chunk),
    boost, fetch} so bench drift is attributable to a phase (host
    binning contention vs link bandwidth vs recompile vs device loop),
    and ``train_info``: {bin_path: 'device'|'host', boost_chunk,
    boost_chunks}."""
    import time as _time
    from mmlspark_tpu.core.trace import get_tracer
    _tracer = get_tracer()
    # one trace per train(): the phase marks below double as spans, so
    # the same bin/ship/boost intervals that feed the histograms are
    # readable per-run in /debug/traces and perfetto
    _trace = _tracer.new_trace("gbdt.train") if _tracer.enabled else None
    _phases: Dict[str, float] = {}
    _t_phase = _time.perf_counter()

    def _mark(name: str) -> None:
        nonlocal _t_phase
        now = _time.perf_counter()
        _phases[name] = _phases.get(name, 0.0) + (now - _t_phase)
        if _trace is not None:
            _tracer.emit(name, _t_phase, now, trace=_trace)
        _t_phase = now

    p = dict(DEFAULTS)
    p.update(params or {})
    p["hist_method"] = resolve_hist_method(
        p["hist_method"], jax.default_backend(), int(p["max_bin"]))
    _validate_hist_params(p)

    objective = get_objective(
        p["objective"], num_class=p["num_class"], alpha=p["alpha"],
        tweedie_variance_power=p["tweedie_variance_power"])
    K = objective.num_class

    # 1) bin on host, once (dense or streaming-shard input).
    # Streaming = an iterable of shards passed WITHOUT y; disambiguate
    # carefully so dense list-of-lists and mislabeled generators get a
    # clear error instead of a confusing unpack/object-cast failure.
    from mmlspark_tpu.core.sparse import CSRMatrix as _CSRMatrix
    from mmlspark_tpu.io.ooc import ChunkedTable as _ChunkedTable
    if isinstance(X, _ChunkedTable):
        # out-of-core ingest (io/ooc.py): chunks carry features+label
        # columns; adapt to the replayable (X, y) shard-factory shape.
        # Chunk decode runs on the source's prefetch worker.
        if y is not None:
            raise ValueError(
                "pass labels inside the ChunkedTable (label column), "
                "not as a separate y")
        X = X.as_xy()
    streaming = y is None and not isinstance(X, (np.ndarray, _CSRMatrix))
    if streaming and isinstance(X, (list, tuple)):
        try:
            X = np.asarray(X, dtype=np.float64)   # dense rows as lists
            streaming = False
        except (TypeError, ValueError):
            pass   # a genuine list of shard tuples / DataTables
    if not streaming and y is None:
        raise ValueError("y is required when X is a dense matrix")
    if y is not None and not isinstance(X, np.ndarray) \
            and hasattr(X, "__next__"):
        raise ValueError(
            "iterator X with a separate y is ambiguous: streaming mode "
            "passes y=None and the iterator yields "
            "(X_shard, y_shard[, w_shard]) tuples")
    # multi-host data-parallel: every process calls train() with its OWN
    # row shard; bin boundaries are agreed from allgathered samples and
    # the global binned matrix is assembled from per-process shards (the
    # LightGBM worker-partition flow, ref: TrainUtils.scala:188-214)
    from mmlspark_tpu.parallel import distributed as dist
    proc_info = dist.host_info()
    multi_host = (p["parallelism"] in ("data", "voting")
                  and proc_info.process_count > 1)
    # multi-host feature-parallel follows LightGBM's feature-parallel
    # data layout: EVERY worker holds the full dataset (rows replicated)
    # and owns a feature shard — LightGBM deliberately avoids the
    # split-partition broadcast this way (ref: TrainParams.scala:26
    # tree_learner=feature; docs "feature parallel ... every worker
    # holds the full data"). Each process therefore passes the same
    # full X; bin-boundary agreement is verified below.
    multi_host_fp = (p["parallelism"] == "feature"
                     and proc_info.process_count > 1)
    if multi_host_fp and streaming:
        raise ValueError(
            "multi-host tree_learner='feature' requires the full dense "
            "dataset on every process (LightGBM's feature-parallel "
            "layout); stream ingestion only supports "
            "parallelism='data'/'voting' across hosts")
    if p["parallelism"] == "serial" and proc_info.process_count > 1:
        import logging
        logging.getLogger("mmlspark_tpu.gbdt").warning(
            "train() called under %d jax processes with "
            "parallelism='serial': each host will fit an INDEPENDENT "
            "model on its local data. Use parallelism='data' for one "
            "globally-trained forest.", proc_info.process_count)
    forced_mapper = (_multihost_mapper(
        X, streaming, p["max_bin"], p["seed"], proc_info.process_count,
        bin_fit=p["bin_fit"])
        if multi_host else None)
    if bin_mapper is not None:
        if multi_host or multi_host_fp:
            raise ValueError(
                "bin_mapper override is single-host only (multi-host "
                "ingest agrees boundaries across processes itself)")
        forced_mapper = bin_mapper

    if streaming:
        if sample_weight is not None:
            raise ValueError(
                "pass per-shard weights inside the shard tuples in "
                "streaming mode")
        if init_model is not None:
            # fail fast — before consuming the (possibly huge) stream
            raise ValueError("init_model warm start requires dense X")
        mapper, bins_np, y, w_base = _bin_stream(
            X, p["max_bin"], p["seed"], mapper=forced_mapper,
            bin_fit=p["bin_fit"])
        n, f = bins_np.shape
    else:
        from mmlspark_tpu.core.sparse import CSRMatrix
        y = np.asarray(y, dtype=np.float64)
        if isinstance(X, CSRMatrix):
            # CSR ingestion: bin straight from the sparse structure —
            # the dense FLOAT matrix never exists (the
            # LGBM_DatasetCreateFromCSR analog, ref:
            # LightGBMUtils.scala:283-351). The engine's HBM layout is
            # still a dense (F, N) int bin matrix; guard its footprint.
            n, f = X.shape
            if f * n * 4 > 8 << 30:
                raise ValueError(
                    f"binned matrix for CSR input would need "
                    f"{f * n * 4 / 2**30:.1f} GB ({f} features x {n} "
                    f"rows); reduce the feature width (hashing) first")
            w_base = (np.ones(n) if sample_weight is None
                      else np.asarray(sample_weight, dtype=np.float64))
            mapper = forced_mapper or BinMapper.fit_sparse(
                X, max_bin=p["max_bin"], seed=p["seed"])
            # (F, N) natively; the .T view re-transposes to the row-major
            # shape the shared code expects and is undone at zero cost by
            # the ascontiguousarray(bins_np.T) below
            bins_np = mapper.transform_sparse(X).T
        else:
            # f32 input stays f32: the binning fast path widens values
            # per-compare (exact), so the 2x-size f64 matrix copy never
            # materializes for the common float32 dataset
            X = np.asarray(X)
            if X.dtype not in (np.float32, np.float64):
                X = X.astype(np.float64)
            n, f = X.shape
            w_base = (np.ones(n) if sample_weight is None
                      else np.asarray(sample_weight, dtype=np.float64))
            mapper = (forced_mapper or
                      BinMapper.fit(X, max_bin=p["max_bin"],
                                    seed=p["seed"]))
            bins_np = None   # dense path bins on device (below)
    if feature_names is None:
        feature_names = [f"Column_{i}" for i in range(f)]
    if bin_mapper is not None and len(mapper.num_bins) != f:
        raise ValueError(
            f"frozen bin_mapper covers {len(mapper.num_bins)} features, "
            f"X has {f}")
    num_bins = int(mapper.num_bins.max())
    if multi_host_fp:
        # every host fit its mapper on its own copy of the (supposedly
        # identical) full dataset — verify instead of trusting. The
        # digest covers shape, boundaries, labels/weights AND a strided
        # row sample of X itself: boundaries alone are row-ORDER
        # invariant, so a permuted copy would pass a boundary-only check
        # and then silently corrupt every split (the psum-broadcast row
        # bitmap is computed in the owner's row order)
        import hashlib
        from jax.experimental import multihost_utils
        h = hashlib.sha256()
        h.update(np.asarray([n, f], np.int64).tobytes())
        for u in mapper.upper_bounds:
            h.update(u.tobytes())
        h.update(np.ascontiguousarray(y).tobytes())
        h.update(np.ascontiguousarray(w_base).tobytes())
        from mmlspark_tpu.core.sparse import CSRMatrix as _CSRd
        if isinstance(X, _CSRd):
            # hash the CSR buffers — np.asarray(X) would densify the
            # whole matrix, the exact thing the sparse path forbids
            h.update(np.ascontiguousarray(X.indptr).tobytes())
            h.update(np.ascontiguousarray(X.indices).tobytes())
            h.update(np.ascontiguousarray(X.data).tobytes())
        else:
            stride = max(1, n // 1024)
            h.update(np.ascontiguousarray(
                np.asarray(X)[::stride]).tobytes())
        mine = np.frombuffer(h.digest(), np.uint8)
        alld = np.asarray(multihost_utils.process_allgather(mine))
        alld = alld.reshape(proc_info.process_count, -1)
        if not (alld == alld[0]).all():
            raise ValueError(
                "hosts disagree on the dataset (shape, bin boundaries, "
                "labels, or row content/order): multi-host "
                "tree_learner='feature' requires every process to pass "
                "the IDENTICAL full dataset (LightGBM feature-parallel "
                "layout)")

    # 2) parallel layout (tree_learner modes, ref: TrainParams.scala:26)
    # voting shards rows exactly like data-parallel; only the per-split
    # collective differs (tree.grow_tree best_split_voting)
    data_parallel = p["parallelism"] in ("data", "voting")
    feature_parallel = p["parallelism"] == "feature"
    axis_name = None
    n_shards = 1
    if data_parallel or feature_parallel:
        if mesh is None:
            mesh = mesh_lib.make_mesh()
        axis_name = mesh_lib.DATA_AXIS
        n_shards = mesh.shape[axis_name]

    if multi_host:
        # hosts truncate to the global-min LOCAL row count so every
        # process contributes an identically-shaped shard to the global
        # arrays (ragged shards would break make_array_from_process_
        # local_data and desynchronize the training loop)
        from jax.experimental import multihost_utils
        n_all = np.asarray(multihost_utils.process_allgather(
            np.asarray([n]))).ravel()
        n_min = int(n_all.min())
        if n_min != n:
            import logging
            logging.getLogger("mmlspark_tpu.gbdt").warning(
                "host shards are unequal (%s); truncating to %d rows "
                "per host", n_all.tolist(), n_min)
            y, w_base = y[:n_min], w_base[:n_min]
            if bins_np is not None:
                bins_np = bins_np[:n_min]
            if isinstance(X, np.ndarray):
                X = X[:n_min]
            else:
                from mmlspark_tpu.core.sparse import CSRMatrix as _C
                if isinstance(X, _C):
                    X = X[:n_min]   # warm-start scoring needs same rows
            n = n_min
        # pad LOCAL rows to this process's device count; the global
        # row count is then divisible by the full data axis
        pad = (-n) % max(len(jax.local_devices()), 1)
    else:
        # rows pad to the shard count only when rows are sharded
        pad = (-n) % max(n_shards if data_parallel else 1, 1)
    if pad:
        y_pad = np.pad(y, (0, pad))
        w_pad = np.pad(w_base, (0, pad))  # zero weight → padding inert
    else:
        y_pad, w_pad = y, w_base
    n_padded = n + pad
    # features-major (F, N) layout: per-split column reads become
    # contiguous rows and the Pallas kernel consumes it directly (see
    # tree.grow_tree docstring). Binning runs ON DEVICE when the mapper
    # is f32-safe (raw f32 blocks ship async, one jitted searchsorted
    # assigns bins — the host binning pass disappears entirely);
    # otherwise it happens on HOST (native OpenMP kernel or the
    # threaded numpy path; f64-exact for every feature scale) and the
    # NARROW bin matrix ships — at max_bin<=255 that is uint8, 4x fewer
    # bytes than the f32 feature matrix.
    # record f32 safety on the model so inference picks the right walk
    # (warm start below ORs in the base model's flag)
    p["f32_unsafe"] = not mapper.f32_safe()
    # feature-parallel shards the (F, N) feature dim: pad F to the shard
    # count with always-masked dummy features (fmask 0 keeps them out of
    # every split search)
    f_pad = (-f) % n_shards if feature_parallel else 0
    f_eff = f + f_pad
    # pipelined bin+ship (single-host): produce one feature CHUNK of the
    # (F, N) ship layout on the host while the previous chunk's
    # host->device DMA is in flight (device_put dispatch is async; only
    # the final concatenate waits). The two phases previously serialized
    # — HIGGS-1M paid bin 1.7s + ship 2.0s back to back; overlapped they
    # cost ~max of the two (ref: the reference's native path overlaps
    # per-partition dataset construction, TrainUtils.scala:19-64).
    # Dense input bins each chunk via transform_fm_range (native range
    # kernel when available, numpy fallback otherwise); pre-binned input
    # (streaming/CSR) transposes + narrows each column block while the
    # previous block flies. Multi-host keeps the one-shot numpy path —
    # its global array is assembled from per-process shards below.
    narrow = (np.uint8 if num_bins <= 256
              else np.int16 if num_bins <= 32767 else np.int32)
    # ~8 MB of rows per chunk amortizes per-transfer dispatch;
    # pipelining needs >= 2 chunks to overlap anything
    # (ship_chunk_bytes is a tuning/test knob, not a public param)
    chunk_bytes = int(p.get("ship_chunk_bytes", 8 << 20))
    chunk_f = max(1, chunk_bytes // max(n_padded, 1))
    # ON-DEVICE BINNING: when float32 compares provably reproduce the
    # f64 bin assignment (mapper.f32_safe — f32-snapped cuts for f32
    # input, gap+holdout certification otherwise), ship the RAW float32
    # feature blocks (overlapped async device_put per block, same shape
    # as the binned pipeline below) and bucketize on device with one
    # jitted vectorized searchsorted against the (F, B) bounds matrix.
    # Host binning — previously 43% of the HIGGS wall together with the
    # binned-matrix ship — collapses to a slice/cast staging pass plus
    # a ~100 ms device kernel. Host binning stays the fallback for
    # f32-unsafe mappers, CSR, streaming shards, and multi-host ingest.
    device_binning = str(p.get("device_binning", "auto"))
    # gate on f32_cuts_exact, NOT f32_safe: only f32-snapped cuts (f32
    # input) make the device f32 compare equal the host f64 compare for
    # EVERY row by construction. A margin+holdout-certified f64 mapper
    # is good enough for the f32 INFERENCE walk (residual risk on
    # unsampled rows is accepted there) but would let training bins
    # silently differ between device_binning='auto' and 'off'.
    use_device_bin = (device_binning != "off"
                      and bins_np is None
                      and not isinstance(X, _CSRMatrix)
                      and not (multi_host or multi_host_fp)
                      and mapper.f32_cuts_exact)
    if device_binning == "on" and not use_device_bin:
        import logging
        if multi_host or multi_host_fp:
            _reason = "multi-host ingest assembles per-process shards"
        elif bins_np is not None or isinstance(X, _CSRMatrix):
            _reason = "input is pre-binned/CSR/streaming"
        else:
            _reason = ("cuts are not f32-exact (pass float32 features "
                       "to enable on-device binning)")
        logging.getLogger("mmlspark_tpu.gbdt").warning(
            "device_binning='on' requested but ineligible (%s); binning "
            "on host", _reason)
    bin_path = "host"
    pipelined = False
    if use_device_bin:
        bin_path = "device"
        bounds_np = mapper.bounds_matrix(np.float32)
        # raw f32 rows are 4 bytes/cell (vs 1 for uint8 bins) — budget
        # the block width by bytes so each DMA stays ~chunk-bytes-sized
        chunk_f_raw = max(1, chunk_bytes // max(4 * n_padded, 1))
        parts = []
        for j0 in range(0, f, chunk_f_raw):
            j1 = min(f, j0 + chunk_f_raw)
            blk = np.ascontiguousarray(X[:, j0:j1], dtype=np.float32)
            # bucketize EACH block as its DMA lands (async dispatch) and
            # narrow to the bin dtype immediately: only bins stay
            # resident — device peak is one raw block + the bin matrix,
            # same footprint as the host-binning path (concatenating
            # the raw blocks first would hold 2x the raw matrix in HBM)
            parts.append(binning_lib.bucketize_fm_device(
                jnp.asarray(blk),
                jnp.asarray(bounds_np[j0:j1])).astype(narrow))
        _mark("bin")    # host staging: column slice + f32 cast only
        bins_dev = (parts[0] if len(parts) == 1
                    else jnp.concatenate(parts, axis=0))
        del parts
        if pad or f_pad:
            bins_dev = jnp.pad(bins_dev, ((0, f_pad), (0, pad)))
        bins_dev = bins_dev.astype(jnp.int32)
        jax.block_until_ready(bins_dev)
        _mark("bin_device")   # raw DMA + searchsorted kernel, overlapped
        pipelined = True      # skip the host bin+ship paths below
    if not pipelined and not (multi_host or multi_host_fp) \
            and f > chunk_f:
        parts = []
        if bins_np is None and not isinstance(X, _CSRMatrix):
            # normalize ONCE: the native kernel needs contiguous input,
            # and a per-chunk ascontiguousarray of a non-contiguous X
            # would copy the full matrix K times
            X = np.ascontiguousarray(X)
        for j0 in range(0, f, chunk_f):
            j1 = min(f, j0 + chunk_f)
            if bins_np is None:
                part = mapper.transform_fm_range(X, j0, j1)
            else:
                part = np.ascontiguousarray(bins_np[:, j0:j1].T)
            part = part.astype(narrow, copy=False)
            if pad:
                part = np.pad(part, ((0, 0), (0, pad)))
            parts.append(jnp.asarray(part))    # async H2D per block
        if f_pad:
            parts.append(jnp.zeros((f_pad, n_padded), narrow))
        _mark("bin")   # host binning/layout (block DMAs still in flight)
        bins_dev = jnp.concatenate(parts, axis=0).astype(jnp.int32)
        pipelined = True
    if not pipelined:
        if bins_np is None:
            # dense path: fused native bin+transpose+narrow straight
            # into the (F, N) ship layout (uint8 when bins fit)
            bins_t = mapper.transform_fm(X)
            if pad or f_pad:
                bins_t = np.pad(bins_t, ((0, f_pad), (0, pad)))
        else:
            if pad:
                bins_np = np.pad(bins_np, ((0, pad), (0, 0)))
            bins_t = np.ascontiguousarray(bins_np.T)
            if f_pad:
                bins_t = np.pad(bins_t, ((0, f_pad), (0, 0)))
        _mark("bin")   # mapper fit + host binning + (F, N) layout
        if multi_host or multi_host_fp:
            # multi-host keeps numpy — the global array is assembled
            # from per-process shards (or served via callback) below
            bins_dev = bins_t.astype(np.int32)
        else:
            # narrow dtype crosses the host->device link; the widen
            # runs on device (eager asarray+astype — no per-call
            # retrace). copy=False: the fused native path already
            # produced uint8
            bins_dev = jnp.asarray(
                bins_t.astype(narrow, copy=False)).astype(jnp.int32)

    # 3) init scores — fresh start or warm start from a base forest
    base_model: Optional[Booster] = None
    if init_model is not None:
        base_model = (Booster.from_string(init_model)
                      if isinstance(init_model, str) else init_model)
        if base_model.num_class != K:
            raise ValueError(
                f"init_model has {base_model.num_class} classes, "
                f"objective expects {K}")
        if base_model.objective.name != objective.name:
            raise ValueError(
                f"init_model was trained with objective "
                f"{base_model.objective.name!r}; resuming as "
                f"{objective.name!r} would mix link spaces")
        if len(base_model.feature_names) != f:
            raise ValueError(
                f"init_model was trained on "
                f"{len(base_model.feature_names)} features, X has {f} "
                f"(out-of-range gathers would clamp silently)")
        init_score = base_model.init_score
        p["f32_unsafe"] = bool(p["f32_unsafe"]) or bool(
            base_model.params.get("f32_unsafe", False))
        # score + merge against the base model's EFFECTIVE forest: an
        # early-stopped base contributes only its best_iteration trees
        # (raw_score truncates the same way)
        base_eff_trees = base_model._resolve_iterations(None) * K
        base_scores = np.pad(_base_raw_kn(base_model, X, K),
                             ((0, 0), (0, pad)))
    elif p["boost_from_average"]:
        if multi_host:
            # the init score must agree across hosts (quantile/average
            # objectives need the GLOBAL label distribution)
            from jax.experimental import multihost_utils
            y_g = np.asarray(multihost_utils.process_allgather(
                np.ascontiguousarray(y, dtype=np.float32))).reshape(-1)
            w_g = np.asarray(multihost_utils.process_allgather(
                np.ascontiguousarray(w_base, dtype=np.float32))
            ).reshape(-1)
            init_score = objective.init_score(
                y_g.astype(np.float64), w_g.astype(np.float64))
        else:
            init_score = objective.init_score(y, w_base)
    else:
        init_score = np.zeros(K)

    gp = GrowParams(
        num_leaves=int(p["num_leaves"]), num_bins=num_bins,
        min_data_in_leaf=int(p["min_data_in_leaf"]),
        min_sum_hessian_in_leaf=float(p["min_sum_hessian_in_leaf"]),
        max_depth=int(p["max_depth"]),
        lambda_l1=float(p["lambda_l1"]), lambda_l2=float(p["lambda_l2"]),
        min_gain_to_split=float(p["min_gain_to_split"]),
        hist_method=p["hist_method"],
        voting_k=int(p["top_k"]),
        hist_bits=int(p["hist_bits"]),
        hist_comm=p["hist_comm"],
        # n_shards is only consulted by the reduce-scatter partition;
        # pinning it to 1 otherwise keeps every other config's jit key
        # (and compiled-executable cache) identical across mesh sizes
        n_shards=(n_shards if p["hist_comm"] == "reduce_scatter"
                  else 1))
    lr = float(p["learning_rate"])

    scores_np = (base_scores if base_model is not None
                 else np.broadcast_to(
                     np.asarray(init_score, np.float32)[:, None],
                     (K, n_padded)))
    if multi_host:
        # assemble GLOBAL arrays from each process's local shard — the
        # collective-mesh replacement for the reference's per-worker
        # native Dataset + socket ring (ref: TrainUtils.scala:188-214)
        col_sh = jax.sharding.NamedSharding(
            mesh, P(None, mesh_lib.DATA_AXIS))
        row_sh = jax.sharding.NamedSharding(mesh, P(mesh_lib.DATA_AXIS))
        bins_d = jax.make_array_from_process_local_data(col_sh, bins_dev)
        y_d = jax.make_array_from_process_local_data(
            row_sh, np.asarray(y_pad, np.float32))
        scores = jax.make_array_from_process_local_data(
            col_sh, np.asarray(scores_np, np.float32))
    elif data_parallel:
        shard = mesh_lib.data_sharding(mesh)
        bins_d = jax.device_put(
            bins_dev,
            jax.sharding.NamedSharding(
                mesh, P(None, mesh_lib.DATA_AXIS)))   # rows on data axis
        y_d = jax.device_put(jnp.asarray(y_pad, jnp.float32), shard)
        scores = jax.device_put(
            jnp.asarray(scores_np, jnp.float32),
            jax.sharding.NamedSharding(mesh, P(None, mesh_lib.DATA_AXIS)))
    elif feature_parallel:
        col_sh = jax.sharding.NamedSharding(
            mesh, P(mesh_lib.DATA_AXIS, None))   # FEATURES on axis
        repl = jax.sharding.NamedSharding(mesh, P())
        if multi_host_fp:
            # every host holds the full (F, N) matrix; serve each device
            # its feature-shard via callback (process-order assumptions
            # of make_array_from_process_local_data don't apply — the
            # callback answers whatever index a local device owns)
            bins_host = bins_dev
            y_host = np.asarray(y_pad, np.float32)
            sc_host = np.ascontiguousarray(scores_np, np.float32)
            bins_d = jax.make_array_from_callback(
                bins_host.shape, col_sh, lambda idx: bins_host[idx])
            y_d = jax.make_array_from_callback(
                y_host.shape, repl, lambda idx: y_host[idx])
            scores = jax.make_array_from_callback(
                sc_host.shape, repl, lambda idx: sc_host[idx])
        else:
            bins_d = jax.device_put(bins_dev, col_sh)
            y_d = jax.device_put(jnp.asarray(y_pad, jnp.float32), repl)
            scores = jax.device_put(
                jnp.asarray(scores_np, jnp.float32), repl)
    else:
        bins_d = bins_dev
        y_d = jnp.asarray(y_pad, jnp.float32)
        scores = jnp.asarray(scores_np, jnp.float32)
    jax.block_until_ready((bins_d, y_d, scores))
    _mark("ship")   # narrow host->device transfer + placement

    # validation state — device-resident; the held-out set is scored
    # through the *binned* feature view (same comparisons training uses)
    # so the loop never converts a tree to host. The only per-iteration
    # device sync is the scalar early-stopping loss read.
    esr = int(p["early_stopping_round"])
    use_valid = valid is not None and esr > 0
    if use_valid:
        from mmlspark_tpu.core.sparse import CSRMatrix as _CSR
        if isinstance(valid[0], _CSR):
            bins_v_np = mapper.transform_sparse(valid[0]).T \
                .astype(np.float32)
        else:
            bins_v_np = mapper.transform(
                np.asarray(valid[0], dtype=np.float64)).astype(np.float32)
        yv_np = np.asarray(valid[1], dtype=np.float32)
        if multi_host or multi_host_fp:
            # every host must pass IDENTICAL valid data; lift it (and
            # the running scores below) to replicated global arrays so
            # the per-iteration scoring ops run on the global mesh
            _repl = jax.sharding.NamedSharding(mesh, P())
            bins_v = jax.make_array_from_process_local_data(
                _repl, np.ascontiguousarray(bins_v_np))
            yv = jax.make_array_from_process_local_data(_repl, yv_np)
        else:
            bins_v = jnp.asarray(bins_v_np)
            yv = jnp.asarray(yv_np)
        if base_model is not None:
            v_scores_np = _base_raw_kn(
                base_model, np.asarray(valid[0], dtype=np.float64), K)
        else:
            v_scores_np = np.broadcast_to(
                np.asarray(init_score, np.float32)[:, None],
                (K, bins_v.shape[0]))
        if multi_host or multi_host_fp:
            v_scores = jax.make_array_from_process_local_data(
                _repl, np.ascontiguousarray(v_scores_np, np.float32))
        else:
            v_scores = jnp.asarray(v_scores_np, jnp.float32)
    best_loss = np.inf
    best_iter = -1
    esr_sync = max(1, min(esr, 8)) if esr > 0 else 1
    # one fixed walk length -> one predict_trees compile for the whole
    # run (leaves self-loop, extra steps are no-ops)
    valid_depth = int(p["max_depth"]) if int(p["max_depth"]) > 0 \
        else int(p["num_leaves"]) - 1

    n_iter = int(p["num_iterations"])
    # iteration-batching: fuse boost_chunk iterations into one jitted
    # lax.scan dispatch (the models/learner.py run_chunk shape). Auto
    # mode only engages for runs long enough that the extra
    # remainder-length compile amortizes. An explicit boost_chunk is
    # honored EXCEPT under early stopping, where every chunk is capped
    # at esr_sync so the async loss-read cadence (and best_iteration)
    # keeps its contract — train_info reports the effective length.
    S_cfg = int(p.get("boost_chunk", 0) or 0)
    if S_cfg <= 0:
        S_cfg = 8 if n_iter >= 16 else 1
    if use_valid:
        S_cfg = min(S_cfg, esr_sync)
    S_cfg = max(1, min(S_cfg, n_iter))
    M = 2 * int(p["num_leaves"]) - 1
    # power-of-two capacity bucket: the forest buffer shape feeds the
    # jitted step, so tying it exactly to num_iterations would recompile
    # for every distinct iteration count (buffers here are tiny)
    t_cap = max(64, 1 << (n_iter * K - 1).bit_length())
    # the whole forest lives on device: K trees are written per step at
    # a traced row offset, one device_get fetches everything at the end
    _f_dtypes = {"feature": jnp.int32, "bin_threshold": jnp.int32,
                 "threshold": jnp.float32, "left": jnp.int32,
                 "right": jnp.int32, "value": jnp.float32,
                 "is_leaf": jnp.bool_, "gain": jnp.float32,
                 "count": jnp.float32}
    # numpy buffers in multi-host mode: jit treats them as replicated
    # inputs on the global mesh (a committed local jnp array would not
    # be addressable across processes)
    _zeros = np.zeros if (multi_host or multi_host_fp) else jnp.zeros
    forest = Tree(**{fld: _zeros((t_cap, M), dt)
                     for fld, dt in _f_dtypes.items()})

    bag_active = p["bagging_fraction"] < 1.0 and p["bagging_freq"] > 0
    ff_active = p["feature_fraction"] < 1.0
    # bagging/feature-fraction masks are derived ON DEVICE inside the
    # chunk program (tree.sample_iteration_masks: fold_in(key, it) +
    # threshold-compare — deterministic, resume-safe, chunking-
    # invariant), so the host RNG + per-iteration mask upload that used
    # to force one dispatch per iteration is gone.
    bag_cfg = ((float(p["bagging_fraction"]), int(p["bagging_freq"]))
               if bag_active else None)
    ff_cfg = float(p["feature_fraction"]) if ff_active else None
    # the mask key is a RUNTIME input to the chunk program (raw uint32
    # PRNGKey data), so a seed sweep with bagging active reuses one
    # compiled executable instead of recompiling the heaviest program
    # in the engine per seed; pinned to 0 when no mask is active
    # (is-None checks, not truthiness: ff_cfg == 0.0 is falsy but DOES
    # sample masks, and must honor the user's seed); quantized training
    # derives its per-round stochastic-rounding keys from the same
    # runtime key, so it must honor the seed too
    mask_key = jax.random.PRNGKey(
        int(p["seed"])
        if (bag_cfg is not None or ff_cfg is not None
            or int(p["hist_bits"]) < 32) else 0)
    def _rows_global(w_np):
        if multi_host:
            return jax.make_array_from_process_local_data(
                jax.sharding.NamedSharding(mesh, P(mesh_lib.DATA_AXIS)),
                np.asarray(w_np, np.float32))
        if multi_host_fp:   # rows replicated on the global mesh
            w_host = np.asarray(w_np, np.float32)
            return jax.make_array_from_callback(
                w_host.shape, jax.sharding.NamedSharding(mesh, P()),
                lambda idx: w_host[idx])
        return _maybe_shard(jnp.asarray(w_np, jnp.float32), mesh,
                            data_parallel)

    w_d = _rows_global(w_pad)
    fmask_base = np.zeros(f_eff, np.float32)
    fmask_base[:f] = 1.0          # padded dummy features stay masked

    from mmlspark_tpu.core.metrics import (gbdt_comm_add,
                                           gbdt_train_histograms)
    boost_chunk_hist = gbdt_train_histograms().get("boost_chunk")
    obj_key = (p["objective"], K, float(p["alpha"]),
               float(p["tweedie_variance_power"]))
    parallel_mode = (p["parallelism"]
                     if p["parallelism"] in ("feature", "voting")
                     else "data")
    trees_done = 0
    n_chunks = 0
    it0 = 0
    stop = False
    # pending per-chunk device loss vectors, flushed at esr_sync
    # iteration boundaries. The point is cadence, not pure asynchrony:
    # the stop decision consumes losses at the SAME boundaries for
    # every chunk length, which is what makes best_iteration/num_trees
    # chunk-length-invariant (the parity suite asserts it). Chunks
    # shorter than esr_sync stay fully async until the boundary; when
    # S == esr_sync (the capped default) each flush blocks on the
    # chunk dispatched just above — the cadence the per-iteration loop
    # already paid. Worst case trains up to esr_sync-1 extra
    # iterations past the stop point; best_iteration stays exact
    # (extra trees are truncated at scoring time).
    pending_val: List[Tuple[int, int, Any]] = []
    pending_iters = 0
    while it0 < n_iter and not stop:
        S = min(S_cfg, n_iter - it0)
        chunk_fn = _make_chunk_step(
            obj_key, gp, lr, K, axis_name, mesh, parallel_mode, S,
            bag_cfg, ff_cfg, f, f_eff)
        t_chunk = _time.perf_counter()
        scores, forest = chunk_fn(bins_d, scores, y_d, w_d, fmask_base,
                                  forest, np.int32(it0), mask_key)
        n_chunks += 1
        trees_done = (it0 + S) * K
        if it0 == 0:
            jax.block_until_ready(scores)
            _mark("first_iter")   # compile (unless cached) + first chunk
        elif boost_chunk_hist is not None:
            # host dispatch wall per chunk AFTER the first: enqueue time
            # plus any back-pressure once the dispatch queue fills — NOT
            # device execution (blocking here would serialize the async
            # pipeline). The compile-bearing first chunk lands under
            # first_iter, not in this series.
            _t_chunk_end = _time.perf_counter()
            boost_chunk_hist.observe((_t_chunk_end - t_chunk) * 1e3)
            if _trace is not None:
                _tracer.emit("boost_chunk", t_chunk, _t_chunk_end,
                             trace=_trace,
                             attrs={"it0": int(it0), "length": int(S)})

        if use_valid:
            eval_fn = _make_valid_eval(obj_key, K, lr, S, valid_depth)
            v_scores, losses = eval_fn(forest, bins_v, yv, v_scores,
                                       np.int32(it0 * K))
            pending_val.append((it0, S, losses))
            pending_iters += S
            if pending_iters >= esr_sync or it0 + S >= n_iter:
                for c_it0, c_len, c_losses in pending_val:
                    arr = np.asarray(c_losses)
                    for j in range(c_len):
                        cur = float(arr[j])
                        if cur < best_loss - 1e-12:
                            best_loss, best_iter = cur, c_it0 + j + 1
                        elif c_it0 + j + 1 - best_iter >= esr:
                            stop = True
                            break
                    if stop:
                        break
                pending_val.clear()
                pending_iters = 0
        it0 += S

    jax.block_until_ready(scores)
    _mark("boost")   # chunks 2..n of the jitted loop
    if trees_done:
        # one device->host transfer for the whole forest
        host = jax.device_get(forest._asdict())
        stacked = {name: arr[:trees_done] for name, arr in host.items()}
        # bin threshold -> raw value threshold, one vectorized gather.
        # Stored in float64: f32 storage would quantize away split
        # resolution for large-magnitude features (the jitted predict
        # path casts down itself when that is safe)
        thr_lut = mapper.threshold_matrix(num_bins)          # (F, B)
        thr = thr_lut[stacked["feature"], stacked["bin_threshold"]]
        stacked["threshold"] = np.where(stacked["is_leaf"], 0.0, thr)
        stacked["value"] = stacked["value"] * lr  # bake shrinkage
        tree_depths = [
            _tree_depth({k: v[t] for k, v in stacked.items()})
            for t in range(stacked["feature"].shape[0])]
    else:
        stacked = {}
        tree_depths = []

    if base_model is not None and base_eff_trees > 0:
        base_trees = {key: v[:base_eff_trees]
                      for key, v in base_model.trees.items()}
        stacked = _concat_forests(base_trees, stacked)
        tree_depths = (list(base_model.tree_depths[:base_eff_trees])
                       + tree_depths)
        if best_iter > 0:
            best_iter += base_eff_trees // K
    booster = Booster(objective, stacked, init_score, K, feature_names, p,
                      best_iteration=best_iter if esr > 0 else -1,
                      tree_depths=tree_depths)
    _mark("fetch")   # forest D2H + threshold conversion
    booster.train_timing = {k: round(v, 3) for k, v in _phases.items()}
    booster.train_info = {"bin_path": bin_path, "boost_chunk": S_cfg,
                          "boost_chunks": n_chunks}
    if axis_name is not None:
        comm = comm_payload_model(
            parallel_mode=parallel_mode, hist_comm=p["hist_comm"],
            hist_bits=int(p["hist_bits"]), num_trees=trees_done,
            num_leaves=int(p["num_leaves"]), num_features=f_eff,
            num_bins=num_bins, n_shards=n_shards,
            voting_k=int(p["top_k"]), num_rows=n_padded)
        for _coll, _nb in comm.items():
            if _nb:
                gbdt_comm_add(_coll, _nb)
        booster.train_info["comm_bytes"] = {
            k: round(v) for k, v in comm.items()}
    # the frozen mapper rides on the booster (in-memory only): the
    # continued-boosting path bins FRESH data against the original cuts
    booster.bin_mapper = mapper
    if (p.get("keep_training_data")
            and not (multi_host or multi_host_fp)
            and base_model is None and not use_valid):
        # exact-continuation state: everything the chunk loop consumes,
        # still device-resident. Restricted to the cases where
        # continuation is provably bit-identical to one longer run —
        # no warm-start base (its forest lives outside this buffer)
        # and no early stopping (a stopped run's scores include the
        # overshoot chunks).
        booster._resume = {
            "bins_d": bins_d, "y_d": y_d, "w_d": w_d,
            "scores": scores, "forest": forest,
            "fmask_base": fmask_base, "mask_key": mask_key,
            "it_done": it0, "t_cap": t_cap, "gp": gp, "lr": lr,
            "obj_key": obj_key, "parallel_mode": parallel_mode,
            "axis_name": axis_name, "mesh": mesh, "K": K,
            "f": f, "f_eff": f_eff, "num_bins": num_bins,
            "bag_cfg": bag_cfg, "ff_cfg": ff_cfg,
            "mapper": mapper, "init_score": init_score,
            "feature_names": feature_names, "consumed": False,
        }
    elif p.get("keep_training_data"):
        import logging
        logging.getLogger("mmlspark_tpu.gbdt").warning(
            "keep_training_data requested but continuation state is "
            "only retained for single-host runs without init_model or "
            "early stopping; boost_more(data=None) will be unavailable")
    hists = gbdt_train_histograms()
    for phase_name, secs in _phases.items():
        h = hists.get(phase_name)
        if h is not None:
            h.observe(secs * 1e3)
    if _trace is not None:
        _trace.root.set("bin_path", bin_path)
        _trace.root.set("boost_chunks", n_chunks)
        _trace.root.set("trees", trees_done)
        _tracer.finish(_trace)
    return booster


def _host_predict_trees(X: np.ndarray, trees: Dict[str, np.ndarray],
                        max_depth: int) -> np.ndarray:
    """float64 numpy tree walk — same semantics as predict_trees (leaves
    self-loop, NaN goes left) without the f32 cast. (T, N)."""
    t_count, n = trees["feature"].shape[0], X.shape[0]
    out = np.empty((t_count, n), np.float32)
    rows = np.arange(n)
    for t in range(t_count):
        feat, thr = trees["feature"][t], trees["threshold"][t]
        left, right = trees["left"][t], trees["right"][t]
        node = np.zeros(n, np.int64)
        for _ in range(max_depth):
            fv = X[rows, feat[node]]
            go_left = ~(fv > thr[node])        # NaN -> left, like binning
            node = np.where(go_left, left[node], right[node])
        out[t] = trees["value"][t][node]
    return out


def _base_raw_kn(base_model: Booster, X: np.ndarray, K: int) -> np.ndarray:
    """Base-forest raw margins as (K, N) float32 (warm-start init)."""
    raw = base_model.raw_score(X)
    if K == 1:
        raw = raw[None, :]
    return np.asarray(raw, dtype=np.float32)


def _pad_nodes(v: np.ndarray, m: int, key: str) -> np.ndarray:
    """Grow a (T, M) tree-array's node dim with inert self-loop leaves."""
    t, cur = v.shape
    if cur == m:
        return v
    pad = m - cur
    if key in ("left", "right"):
        idx = np.broadcast_to(np.arange(cur, m), (t, pad))
        return np.concatenate([v, idx.astype(v.dtype)], axis=1)
    if key == "is_leaf":
        return np.concatenate([v, np.ones((t, pad), v.dtype)], axis=1)
    return np.concatenate([v, np.zeros((t, pad), v.dtype)], axis=1)


def _concat_forests(a: Dict[str, np.ndarray],
                    b: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    """Stack two stacked-tree dicts along T, padding node dims to match
    (warm start may use a different num_leaves than the base model)."""
    if not a:
        return b
    if not b:
        return a
    m = max(a["feature"].shape[1], b["feature"].shape[1])
    return {key: np.concatenate(
        [_pad_nodes(a[key], m, key), _pad_nodes(b[key], m, key)], axis=0)
        for key in b}


def _maybe_shard(arr, mesh, data_parallel):
    if not data_parallel:
        return arr
    return jax.device_put(arr, mesh_lib.data_sharding(mesh, arr.ndim))


def _tree_depth(tree_host: Dict[str, np.ndarray]) -> int:
    """Max root→leaf depth (host-side BFS over the flat arrays)."""
    left, right = tree_host["left"], tree_host["right"]
    is_leaf = tree_host["is_leaf"].astype(bool)
    depth = 0
    frontier = [(0, 0)]
    while frontier:
        node, d = frontier.pop()
        if is_leaf[node] or left[node] == node:
            depth = max(depth, d)
            continue
        frontier.append((int(left[node]), d + 1))
        frontier.append((int(right[node]), d + 1))
    return max(depth, 1)


@functools.lru_cache(maxsize=128)
def _make_chunk_step(obj_key: Tuple[str, int, float, float],
                     gp: GrowParams, lr: float, K: int,
                     axis_name: Optional[str], mesh: Optional[Mesh],
                     parallel_mode: str, chunk_len: int,
                     bag_cfg: Optional[Tuple[float, int]],
                     ff_cfg: Optional[float],
                     f_valid: int, f_total: int):
    """Build the iteration-batched jitted boosting chunk:
    ``chunk_len`` iterations of gradients → K trees → score update
    fused into one ``lax.scan`` device program (the same shape as
    run_chunk in models/learner.py) — ONE host dispatch per chunk
    instead of per iteration, with bagging / feature-fraction masks
    derived on device per iteration (tree.sample_iteration_masks).
    lru_cached by (config, chunk length) so repeated train() calls at
    the same shapes reuse the compiled executable — including the
    remainder-length chunk.

    ``parallel_mode`` picks the tree_learner sharding (ref:
    TrainParams.scala:26): 'data' shards rows over the mesh axis,
    'feature' shards the (F, N) binned matrix's FEATURE dim and
    replicates rows (see tree.grow_tree)."""
    name, num_class, alpha, rho = obj_key
    objective = get_objective(name, num_class=num_class, alpha=alpha,
                              tweedie_variance_power=rho)

    def chunk(bins, scores, y, w_base, fmask_base, forest, it0, key):
        """forest: Tree of (T_cap, M) buffers; iteration it's K trees
        are written at rows it*K..it*K+K-1 ON DEVICE — no per-iteration
        host transfer or stacking (one device_get fetches the whole
        forest after the loop). ``key`` is the raw uint32 PRNGKey for
        the sampling masks — a runtime input, so the executable is
        seed-independent."""
        TRACE_COUNTS["boost_chunk"] += 1   # trace-time side effect

        def one_iter(carry, s):
            scores, forest = carry
            it = it0 + s
            w, fmask = sample_iteration_masks(
                key, it, w_base, fmask_base, bag_cfg, ff_cfg,
                f_valid, f_total, axis_name, parallel_mode)
            score_in = scores[0] if K == 1 else scores
            grad, hess = objective.grad_hess(score_in, y)
            if K == 1:
                grad, hess = grad[None, :], hess[None, :]
            # per-round stochastic-rounding key: fold 3 (disjoint from
            # bagging=1 / feature-fraction=2), then the iteration and
            # the class — every (round, class) rounds independently and
            # reproducibly across topologies
            kq = (jax.random.fold_in(jax.random.fold_in(key, it), 3)
                  if gp.hist_bits < 32 else None)
            for k in range(K):
                tree, leaf_of_row, leaf_vals, _ = grow_tree(
                    bins, grad[k], hess[k], w, fmask, gp, axis_name,
                    parallel_mode,
                    None if kq is None else jax.random.fold_in(kq, k))
                scores = scores.at[k].add(lr * leaf_vals[leaf_of_row])
                forest = Tree(*[
                    getattr(forest, fld).at[it * K + k].set(
                        getattr(tree, fld))
                    for fld in Tree._fields])
            return (scores, forest), None

        (scores, forest), _ = lax.scan(
            one_iter, (scores, forest),
            jnp.arange(chunk_len, dtype=jnp.int32))
        return scores, forest

    if axis_name is None:
        return jax.jit(chunk, donate_argnums=(1, 5))

    d = mesh_lib.DATA_AXIS
    tree_spec = Tree(*([P()] * len(Tree._fields)))
    if parallel_mode == "feature":
        # features sharded, rows replicated; tree/scores replicated
        in_specs = (P(d, None), P(), P(), P(), P(d), tree_spec, P(),
                    P())
        out_specs = (P(), tree_spec)
    else:
        in_specs = (P(None, d), P(None, d), P(d), P(d), P(None),
                    tree_spec, P(), P())
        out_specs = (P(None, d), tree_spec)
    mapped = shard_map(
        chunk, mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        check_vma=False)
    return jax.jit(mapped, donate_argnums=(1, 5))


@functools.lru_cache(maxsize=128)
def _make_valid_eval(obj_key: Tuple[str, int, float, float], K: int,
                     lr: float, chunk_len: int, valid_depth: int):
    """One jitted dispatch scoring a whole chunk's trees on the
    validation set: slice the chunk's S*K forest rows, walk them once
    (predict_trees), then sequentially accumulate per-iteration scores
    and losses with a lax.scan whose f32 add order matches the
    per-iteration loop exactly — the (S,) loss vector stays on device
    for the async early-stopping read."""
    name, num_class, alpha, rho = obj_key
    objective = get_objective(name, num_class=num_class, alpha=alpha,
                              tweedie_variance_power=rho)

    def eval_chunk(forest, bins_v, yv, v_scores, row0):
        TRACE_COUNTS["valid_eval"] += 1   # trace-time side effect

        def sl(a):
            return lax.dynamic_slice_in_dim(a, row0, chunk_len * K,
                                            axis=0)
        tv = predict_trees(
            bins_v, sl(forest.feature),
            sl(forest.bin_threshold).astype(jnp.float32),
            sl(forest.left), sl(forest.right), sl(forest.value),
            max_depth=valid_depth)                  # (S*K, Nv)
        tv = tv.reshape(chunk_len, K, -1)

        def body(vs, s):
            vs = vs + lr * tv[s]
            return vs, objective.loss(vs[0] if K == 1 else vs, yv)

        v_scores, losses = lax.scan(
            body, v_scores, jnp.arange(chunk_len, dtype=jnp.int32))
        return v_scores, losses

    return jax.jit(eval_chunk, donate_argnums=(3,))
