"""Leaf-wise tree growth as one jitted XLA program.

Where LightGBM grows trees in native C++ with pointer-chasing node
structures (driven from ref: src/lightgbm/src/main/scala/TrainUtils.scala
:82-89 ``LGBM_BoosterUpdateOneIter``), the TPU design makes the whole
tree a fixed-shape tensor program: a ``lax.fori_loop`` over ``num_leaves-1``
split steps, each step = histogram pass (MXU/scatter) → vectorized best-gain
scan over (leaf, feature, bin) → masked leaf reassignment. All shapes are
static (L leaf slots, 2L-1 node slots), so XLA compiles it once per
dataset shape and every iteration reuses the executable.

Distributed: when ``axis_name`` is set the histogram is psum'd across the
mesh data axis, so all devices see identical split decisions and grow
identical trees on disjoint row shards — the collective-based equivalent
of LightGBM's data-parallel tree learner (ref: TrainParams.scala:26
``tree_learner=data``).
"""

from __future__ import annotations

import math
from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from mmlspark_tpu.gbdt.histogram import build_histogram

NEG_INF = -1e30


class GrowParams(NamedTuple):
    """Static growth hyperparams (hashable → part of the jit key)."""
    num_leaves: int = 31
    num_bins: int = 64
    min_data_in_leaf: int = 20
    min_sum_hessian_in_leaf: float = 1e-3
    max_depth: int = 0  # <=0 means unlimited
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    min_gain_to_split: float = 0.0
    hist_method: str = "scatter"
    voting_k: int = 20   # tree_learner='voting' candidates per worker
    # quantized-histogram training (Shi et al., NeurIPS'22): 32 = f32
    # (bit-identical to the classic path), 16/8 = stochastic-rounded
    # integer gradients, exact int32 histogram accumulation, one
    # dequantize at split-gain time, int16 collective wire
    hist_bits: int = 32
    # data-parallel histogram collective: 'psum' allreduces the full
    # (3, F, B) tensor; 'reduce_scatter' gives each device ownership of
    # F/n_shards features' slices (LightGBM's reduce-scatter recipe,
    # Ke et al. NeurIPS'17) — O(F*B/D) wire per split instead of O(F*B)
    hist_comm: str = "psum"
    n_shards: int = 1    # mesh axis size (static: jax has no axis_size)


class Tree(NamedTuple):
    """Flat tree arrays; node 0 is the root, max 2L-1 nodes.

    Leaves have left == right == own index (self-loop), which makes batch
    inference a fixed-depth pointer-walk with gathers (see predict_trees).
    """
    feature: jnp.ndarray      # (M,) int32 split feature (internal nodes)
    bin_threshold: jnp.ndarray  # (M,) int32 'go left if bin <= t'
    threshold: jnp.ndarray    # (M,) f32 raw-value threshold (filled on host)
    left: jnp.ndarray         # (M,) int32
    right: jnp.ndarray        # (M,) int32
    value: jnp.ndarray        # (M,) f32 leaf output
    is_leaf: jnp.ndarray      # (M,) bool
    gain: jnp.ndarray         # (M,) f32 split gain at internal nodes
    count: jnp.ndarray        # (M,) f32 row count at node


def _index_uniforms(key, ids):
    """Counter-based uniforms: u[j] depends only on (key, ids[j]) —
    fold_in per index, vmapped (batched threefry). Unlike
    ``uniform(key, (n,))``, whose whole stream changes with n, these
    values are invariant to padding length and shard layout, so the
    same row/feature draws the same uniform in serial, data-parallel,
    and feature-parallel runs."""
    return jax.vmap(
        lambda i: jax.random.uniform(jax.random.fold_in(key, i)))(ids)


def sample_iteration_masks(key, it, w_base, fmask_base, bag_cfg, ff_cfg,
                           f_valid: int, f_total: int,
                           axis_name: Optional[str] = None,
                           parallel_mode: str = "data"):
    """Device-derived bagging / feature-fraction masks for boosting
    iteration ``it`` (a traced int32). Pure function of ``(key, it)``
    and the GLOBAL row/feature index — deterministic, resume-safe, and
    invariant to scan chunking, padding, and shard layout, so serial /
    data-parallel / feature-parallel runs draw identical masks and no
    host RNG or mask upload sits on the boosting hot path.

    - Bagging (``bag_cfg = (fraction, freq)``): per-row counter-based
      uniforms from the key folded with the last resample iteration
      (LightGBM reuses the bag between ``freq`` resamples), threshold-
      compared; row-sharded modes (data/voting) index by their global
      row offset, so every device agrees with the serial bag
      bit-for-bit — including when row padding differs between modes.
    - Feature fraction (``ff_cfg = fraction``): per-tree EXACT-k subset
      (k = ceil(fraction * f_valid), the LightGBM featureFraction
      semantics the host RNG used): the k features with the smallest
      per-tree uniforms are kept — an order-statistic threshold, so the
      count never varies tree to tree. Padded dummies stay masked;
      feature-parallel shards slice their local window of the global
      mask.
    """
    w = w_base
    if bag_cfg is not None:
        frac, freq = bag_cfg
        bag_it = (it // freq) * freq
        kb = jax.random.fold_in(jax.random.fold_in(key, bag_it), 1)
        n_loc = w_base.shape[0]
        row0 = (lax.axis_index(axis_name) * n_loc
                if axis_name is not None and parallel_mode != "feature"
                else 0)
        u = _index_uniforms(kb, row0 + jnp.arange(n_loc))
        w = w_base * (u < frac)
    fmask = fmask_base
    if ff_cfg is not None:
        kf = jax.random.fold_in(jax.random.fold_in(key, it), 2)
        # every device evaluates the full (tiny) global feature vector
        # so the exact-k threshold is a global decision
        uf = _index_uniforms(kf, jnp.arange(f_total))
        valid = jnp.arange(f_total) < f_valid
        uf = jnp.where(valid, uf, jnp.inf)
        # exact-k: keep the k smallest uniforms (k static — ff_cfg and
        # f_valid are trace constants). Padded slots hold +inf and
        # k <= f_valid, so they can never cross the k-th threshold.
        k = max(1, math.ceil(ff_cfg * f_valid))
        kth = -lax.top_k(-uf, k)[0][k - 1]
        m = ((uf <= kth) & valid).astype(fmask_base.dtype)
        f_loc = fmask_base.shape[0]
        if axis_name is not None and parallel_mode == "feature" \
                and f_total != f_loc:
            m = lax.dynamic_slice_in_dim(
                m, lax.axis_index(axis_name) * f_loc, f_loc)
        fmask = fmask_base * m
    return w, fmask


def _leaf_output(g, h, l1, l2):
    """Optimal leaf value with L1 soft-thresholding (LightGBM's
    ThresholdL1): -sgn(g)·max(|g|-l1, 0) / (h + l2)."""
    num = jnp.sign(g) * jnp.maximum(jnp.abs(g) - l1, 0.0)
    return -num / (h + l2)


def _split_gain(g, h, l1, l2):
    num = jnp.maximum(jnp.abs(g) - l1, 0.0)
    return num * num / (h + l2)


@partial(jax.jit, static_argnames=("p", "axis_name", "parallel_mode"))
def grow_tree(bins: jnp.ndarray, grad: jnp.ndarray, hess: jnp.ndarray,
              weight: jnp.ndarray, feature_mask: jnp.ndarray,
              p: GrowParams, axis_name: Optional[str] = None,
              parallel_mode: str = "data",
              quant_key: Optional[jnp.ndarray] = None):
    """Grow one tree; returns (Tree, leaf_of_row, leaf_values_per_slot).

    bins is FEATURES-MAJOR (F, N) int32 — row-major (N, F) would make
    the per-split split-column read a strided gather (one useful lane
    per 512-byte read on TPU); features-major makes it one contiguous
    row and is the layout the Pallas kernel consumes directly.
    grad/hess/weight (N,) f32; feature_mask (F,) f32
    (0 disables a feature this tree — featureFraction sampling).

    Histogram-cache + subtraction growth (LightGBM's strategy, ref:
    TrainUtils.scala:82-89 drives the native leaf-wise learner that does
    exactly this): per split, build ONE single-leaf (3, F, B) histogram
    for the new right child over masked rows, get the left sibling by
    subtracting from the cached parent histogram, and keep every leaf's
    best candidate split cached. Each split step therefore costs
    O(N·F[·B]) instead of O(L·N·F[·B]) — the difference between
    feasible and infeasible at HIGGS scale (255 leaves) for the
    MXU matmul formulations.

    Distribution (``axis_name`` set, inside shard_map) follows the
    reference's ``tree_learner`` modes (ref: TrainParams.scala:26):

    - ``parallel_mode='data'``: rows sharded; histograms psum over the
      axis; every device sees identical totals and grows the same tree.
    - ``parallel_mode='feature'``: FEATURES sharded, rows replicated —
      the wide-data mode. Histograms stay local (disjoint features);
      each device proposes its best local split, candidates are
      all_gather'd and argmax'd (LightGBM's split-communication step),
      and the winning feature's OWNER broadcasts the row partition via
      psum (LightGBM feature-parallel broadcasts exactly this bitmap).
      Feature ids in the returned tree are GLOBAL
      (device_index * local_F + local id).
    - ``parallel_mode='voting'``: rows sharded like 'data', but instead
      of psum'ing the FULL (3, F, B) histogram, each device votes its
      top ``p.voting_k`` features by local gain; the union of votes is
      all_gather'd and only those candidates' histograms allreduce —
      LightGBM's parallel-voting tree (PV-tree) scheme, cutting the
      per-split collective from O(F·B) to O(devices·k·B) on wide data.
      Exact split SEARCH when voting_k >= F — every worker votes every
      feature, so the candidate union is all of them and the search
      equals data-parallel's (root splits bitwise; deeper nodes up to
      f32 reassociation of the sibling-subtraction cache, which can
      flip near-ties whose gains differ by ~1e-6 relative).
      (devices·k >= F with k < F is NOT sufficient: workers' top-k
      votes can overlap, shrinking the union below F and possibly
      missing the true best split.)

    Quantized training (``p.hist_bits`` in {16, 8}; Shi et al.,
    *Quantized Training of GBDT*, NeurIPS'22): per-round gradients /
    hessians / weights are discretized ONCE per tree to narrow ints by
    DETERMINISTIC stochastic rounding — counter-based uniforms keyed by
    ``quant_key`` and the GLOBAL row index, so serial and sharded runs
    round identically — under a global-L1 scale
    ``delta = sum(|stat|) / Q`` (``Q = 2^(bits-2)``, psum'd when rows
    are sharded). The global-L1 scale is what makes the narrow wire
    safe: EVERY subset sum of quantized values is bounded by
    Q + O(sqrt(N)) rounding noise, so int16 holds any histogram bin /
    partial reduction at both bit widths. Histograms accumulate as
    exact int32 (i8->i32 MXU lowering in the Pallas path), sibling
    subtraction and bin cumsums stay in exact integer arithmetic —
    collective association CANNOT flip near-ties — and the single
    dequantize (* delta) happens at split-gain time.

    ``p.hist_comm='reduce_scatter'`` (data-parallel only): instead of
    psum'ing the full (3, F, B) histogram everywhere, each of the
    ``p.n_shards`` devices reduce-scatters into ownership of a
    contiguous F/D feature slice (plus one psum'd feature-0 slice that
    carries the leaf totals in data-parallel's exact association
    order), computes best splits for owned features locally, and only
    the (D, 4) candidate table all_gathers — O(F·B/D) wire per split.
    Winner selection reproduces psum's argmax tie-break exactly: the
    feature partition is contiguous in device order, so local-argmax +
    lowest-winning-device picks the globally lowest (feature, bin).
    """
    f, n = bins.shape
    L = p.num_leaves
    M = 2 * L - 1
    B = p.num_bins
    feat_par = parallel_mode == "feature" and axis_name is not None
    voting = parallel_mode == "voting" and axis_name is not None
    quantized = p.hist_bits < 32
    rs = (p.hist_comm == "reduce_scatter" and axis_name is not None
          and parallel_mode == "data")
    if p.hist_comm not in ("psum", "reduce_scatter"):
        raise ValueError(f"unknown hist_comm={p.hist_comm!r}; "
                         "expected 'psum' or 'reduce_scatter'")
    if p.hist_comm == "reduce_scatter" and (feat_par or voting):
        raise ValueError(
            "hist_comm='reduce_scatter' is a data-parallel recipe; "
            f"parallel_mode={parallel_mode!r} already keeps histograms "
            "local (feature/voting) — use hist_comm='psum'")
    if quantized:
        if p.hist_bits not in (16, 8):
            raise ValueError(
                f"hist_bits={p.hist_bits} is not supported: use 32 "
                "(f32), 16 or 8 (quantized stochastic rounding)")
        if feat_par:
            raise ValueError(
                "hist_bits < 32 with parallel_mode='feature' is not "
                "supported: feature-parallel histograms never cross "
                "the wire, so quantization only adds rounding noise")
        if quant_key is None:
            raise ValueError(
                "hist_bits < 32 requires quant_key (per-round PRNG key "
                "for deterministic stochastic rounding)")
    # voting keeps histograms LOCAL too — only candidate slices psum;
    # reduce_scatter runs its own collective inside leaf_hist
    hist_axis = None if (feat_par or voting or rs) else axis_name

    min_hess = p.min_sum_hessian_in_leaf
    min_data = float(p.min_data_in_leaf)
    zero_leaf = jnp.zeros(n, dtype=jnp.int32)

    # ---- quantization: discretize ONCE per tree (per boosting round) -
    if quantized:
        Q = 1 << (p.hist_bits - 2)
        sdt = jnp.int8 if p.hist_bits == 8 else jnp.int16
        gw = grad * weight
        hw = hess * weight
        # global-L1 scales: one stacked 3-scalar psum when rows sharded
        scales = jnp.stack([jnp.sum(jnp.abs(gw)), jnp.sum(jnp.abs(hw)),
                            jnp.sum(jnp.abs(weight))])
        if axis_name is not None:
            scales = lax.psum(scales, axis_name)
        tiny = jnp.float32(1e-30)
        dg = jnp.maximum(scales[0], tiny) / Q
        dh = jnp.maximum(scales[1], tiny) / Q
        dc = jnp.maximum(scales[2], tiny) / Q
        row0 = (lax.axis_index(axis_name) * n
                if axis_name is not None else 0)
        row_ids = row0 + jnp.arange(n)

        def _sround(vals, delta, chan):
            """floor + Bernoulli(frac) with counter-based uniforms —
            each (row, channel) draws the same uniform regardless of
            shard layout or padding, so every topology rounds every row
            identically (the bit-reproducibility contract)."""
            x = vals / delta
            fl = jnp.floor(x)
            u = _index_uniforms(jax.random.fold_in(quant_key, chan),
                                row_ids)
            return (fl + (u < (x - fl))).astype(sdt)

        qg = _sround(gw, dg, 0)
        qh = _sround(hw, dh, 1)
        qc = _sround(weight, dc, 2)   # 0-weight rows quantize to 0

    # ---- reduce-scatter feature partition geometry ------------------
    if rs:
        D = p.n_shards
        Fp = -(-f // D) * D           # F padded to a multiple of D
        fs = Fp // D                  # owned features per device

    # the split loop builds one histogram per split on the SAME bins:
    # pre-pad once to the Pallas kernel's block multiples so the
    # per-call full-matrix pad is a no-op (profiled at 17% of the
    # boost loop — 62 pads of the (F, N) matrix per tree otherwise;
    # the padded copy lives only inside this tree's program)
    if p.hist_method == "pallas":
        from mmlspark_tpu.gbdt.pallas_hist import padded_bins_shape
        f_tgt, n_tgt = padded_bins_shape(f, n, B, 1)
        bins_hist = (jnp.pad(bins, ((0, f_tgt - f), (0, n_tgt - n)))
                     if (f_tgt, n_tgt) != (f, n) else bins)
        hist_true_shape = (f, n)
    else:
        bins_hist = bins
        hist_true_shape = None

    def leaf_hist(mask_weight):
        """Histogram of the rows selected by mask_weight: (3, F, B) f32
        (classic), int32 (quantized — mask_weight is then the 0/1 row
        indicator; the weight lives inside qg/qh/qc), or (3, fs+1, B)
        under reduce_scatter (owned feature slices + the psum'd
        feature-0 slice whose bin sums are the leaf totals in the psum
        oracle's exact association order)."""
        if quantized:
            h = build_histogram(bins_hist, qg, qh, mask_weight,
                                zero_leaf, 1, B, method=p.hist_method,
                                axis_name=hist_axis,
                                true_shape=hist_true_shape,
                                count_values=qc,
                                wire_dtype=jnp.int16)[:, 0]
        else:
            h = build_histogram(bins_hist, grad, hess, mask_weight,
                                zero_leaf, 1, B, method=p.hist_method,
                                axis_name=hist_axis,
                                true_shape=hist_true_shape)[:, 0]
        if rs:
            wire = h.astype(jnp.int16) if quantized else h
            tot0 = lax.psum(wire[:, 0, :], axis_name)       # (3, B)
            wire_p = jnp.pad(wire, ((0, 0), (0, Fp - f), (0, 0)))
            owned = lax.psum_scatter(wire_p, axis_name,
                                     scatter_dimension=1,
                                     tiled=True)            # (3, fs, B)
            h = jnp.concatenate([owned, tot0[:, None, :]], axis=1)
            if quantized:
                h = h.astype(jnp.int32)
        return h

    def best_split_voting(hist, depth_ok, hist_sub=None):
        """PV-tree split search: rank features by LOCAL gain, vote the
        union of every worker's top-k, allreduce only the candidates'
        histogram slices, then pick the global best among them.

        ``hist_sub`` carries the sibling-subtraction pair (parent cache,
        right child) UNSUBTRACTED: the f32 subtraction must happen AFTER
        the psum — the association order the data-parallel learner uses
        (psum'd parent minus psum'd child) — or near-tie splits flip and
        voting_k >= F would not reproduce data-parallel trees bitwise.
        """
        local = hist if hist_sub is None else hist - hist_sub
        Gh, Hh = local[0], local[1]                      # (F, B) LOCAL
        if quantized:
            # exact int cumsums, one dequantize at gain time
            Gt, Ht = Gh[0].sum() * dg, Hh[0].sum() * dh
            GLl = jnp.cumsum(Gh, axis=-1) * dg
            HLl = jnp.cumsum(Hh, axis=-1) * dh
        else:
            Gt, Ht = Gh[0].sum(), Hh[0].sum()
            GLl = jnp.cumsum(Gh, axis=-1)
            HLl = jnp.cumsum(Hh, axis=-1)
        parent_l = _split_gain(Gt, Ht, p.lambda_l1, p.lambda_l2)
        gain_l = (_split_gain(GLl, HLl, p.lambda_l1, p.lambda_l2)
                  + _split_gain(Gt - GLl, Ht - HLl,
                                p.lambda_l1, p.lambda_l2) - parent_l)
        gain_f = jnp.max(
            jnp.where(feature_mask[:, None] > 0, gain_l, NEG_INF),
            axis=-1)                                      # (F,) local rank
        k = min(max(p.voting_k, 1), f)
        _, topk = lax.top_k(gain_f, k)
        cand = lax.all_gather(topk, axis_name).reshape(-1)  # (n_dev*k,)

        # one candidate-sized collective: the voted slices plus the
        # FEATURE-0 slice (any feature's bins partition all rows), whose
        # Σ_bin-of-Σ_dev totals match data-parallel's association order
        # exactly (psum'ing local Σ_bin totals would reassociate)
        sel = jnp.concatenate([cand, jnp.zeros(1, cand.dtype)])
        # quantized candidates ride the NARROW int16 wire (the global-L1
        # scale bounds every partial sum) and widen back to exact int32
        if hist_sub is None:
            sl = hist[:, sel, :]
            if quantized:
                ps = lax.psum(sl.astype(jnp.int16), axis_name) \
                    .astype(jnp.int32)
            else:
                ps = lax.psum(sl, axis_name)              # (3, C+1, B)
        else:
            pair = jnp.stack([hist[:, sel, :], hist_sub[:, sel, :]])
            if quantized:
                pair = lax.psum(pair.astype(jnp.int16), axis_name) \
                    .astype(jnp.int32)
            else:
                pair = lax.psum(pair, axis_name)
            ps = pair[0] - pair[1]
        ch, tot = ps[:, :-1, :], ps[:, -1, :]             # global
        if quantized:
            G = tot[0].sum() * dg
            H = tot[1].sum() * dh
            C = tot[2].sum() * dc
            GL = jnp.cumsum(ch[0], axis=-1) * dg
            HL = jnp.cumsum(ch[1], axis=-1) * dh
            CL = jnp.cumsum(ch[2], axis=-1) * dc
        else:
            G, H, C = tot[0].sum(), tot[1].sum(), tot[2].sum()
            GL = jnp.cumsum(ch[0], axis=-1)
            HL = jnp.cumsum(ch[1], axis=-1)
            CL = jnp.cumsum(ch[2], axis=-1)
        GR, HR, CR = G - GL, H - HL, C - CL
        parent_score = _split_gain(G, H, p.lambda_l1, p.lambda_l2)
        gain = (_split_gain(GL, HL, p.lambda_l1, p.lambda_l2)
                + _split_gain(GR, HR, p.lambda_l1, p.lambda_l2)
                - parent_score)
        ok = ((CL >= min_data) & (CR >= min_data)
              & (HL >= min_hess) & (HR >= min_hess)
              & (feature_mask[cand][:, None] > 0) & depth_ok)
        gain = jnp.where(ok, gain, NEG_INF)
        # tie-break by GLOBAL (feature, bin) — not candidate-vote order,
        # which differs per worker's local ranking: serial's argmax picks
        # the lowest (f, b) flat index among equal gains, and matching it
        # exactly is what makes voting_k >= F bitwise-identical to serial
        best = jnp.max(gain)
        B_ = gain.shape[-1]
        fb_key = cand[:, None].astype(jnp.int32) * B_ + jnp.arange(
            B_, dtype=jnp.int32)    # fits int32 up to F*B < 2^31
        keyed = jnp.where(gain >= best, fb_key,
                          jnp.iinfo(jnp.int32).max)
        flat = jnp.argmin(keyed)
        ci, bb = jnp.unravel_index(flat, gain.shape)
        return (gain.reshape(-1)[flat], cand[ci].astype(jnp.int32),
                bb.astype(jnp.int32), CL[ci, bb], C)

    def best_split(hist, depth_ok, hist_sub=None):
        """Best candidate split of one leaf from its (3, F, B) histogram.
        Returns (gain, feature, bin, left_count, total_count).
        ``hist_sub`` (voting only): see best_split_voting."""
        if voting:
            return best_split_voting(hist, depth_ok, hist_sub)
        if rs:
            # owned feature slices; the appended [-1] slice is the
            # psum'd global feature-0 histogram → exact leaf totals
            Gh, Hh, Ch = hist[0, :-1], hist[1, :-1], hist[2, :-1]
            tot = hist[:, -1, :]                         # (3, B) global
            t_g, t_h, t_c = tot[0].sum(), tot[1].sum(), tot[2].sum()
        else:
            Gh, Hh, Ch = hist[0], hist[1], hist[2]       # (F, B)
            # any feature's bins partition all rows; feature 0's
            # sums = totals
            t_g, t_h, t_c = Gh[0].sum(), Hh[0].sum(), Ch[0].sum()
        if quantized:
            # exact int cumsums; ONE dequantize at split-gain time
            G, H, C = t_g * dg, t_h * dh, t_c * dc
            GL = jnp.cumsum(Gh, axis=-1) * dg            # (F, B)
            HL = jnp.cumsum(Hh, axis=-1) * dh
            CL = jnp.cumsum(Ch, axis=-1) * dc
        else:
            G, H, C = t_g, t_h, t_c
            GL = jnp.cumsum(Gh, axis=-1)                 # (F, B)
            HL = jnp.cumsum(Hh, axis=-1)
            CL = jnp.cumsum(Ch, axis=-1)
        GR, HR, CR = G - GL, H - HL, C - CL
        parent_score = _split_gain(G, H, p.lambda_l1, p.lambda_l2)
        gain = (_split_gain(GL, HL, p.lambda_l1, p.lambda_l2)
                + _split_gain(GR, HR, p.lambda_l1, p.lambda_l2)
                - parent_score)
        if rs:
            # every device masks with ITS owned window of the global
            # feature mask (padded slots → phantom features blocked)
            fm = lax.dynamic_slice_in_dim(
                jnp.pad(feature_mask, (0, Fp - f)),
                lax.axis_index(axis_name) * fs, fs)
        else:
            fm = feature_mask
        ok = ((CL >= min_data) & (CR >= min_data)
              & (HL >= min_hess) & (HR >= min_hess)
              & (fm[:, None] > 0) & depth_ok)
        gain = jnp.where(ok, gain, NEG_INF)
        flat = jnp.argmax(gain)
        bf, bb = jnp.unravel_index(flat, gain.shape)
        gain_v, cl_v = gain.reshape(-1)[flat], CL[bf, bb]
        bf, bb = bf.astype(jnp.int32), bb.astype(jnp.int32)
        if rs:
            # LightGBM's split-communication step: each device proposes
            # its owned-slice winner, the tiny (D, 4) table all_gathers,
            # every device argmaxes the same table. The partition is
            # contiguous in device order and argmax takes the FIRST
            # max, so ties resolve to the globally lowest (feature,
            # bin) — exactly the psum oracle's flat-argmax tie-break.
            bf_g = lax.axis_index(axis_name) * fs + bf
            cand = jnp.stack([gain_v, bf_g.astype(jnp.float32),
                              bb.astype(jnp.float32), cl_v])
            allc = lax.all_gather(cand, axis_name)       # (D, 4)
            win = jnp.argmax(allc[:, 0])
            return (allc[win, 0], allc[win, 1].astype(jnp.int32),
                    allc[win, 2].astype(jnp.int32), allc[win, 3], C)
        if feat_par:
            # exchange candidates; every device argmaxes the same table
            # so split decisions stay identical (tie → lowest device id)
            bf_g = lax.axis_index(axis_name) * f + bf
            cand = jnp.stack([gain_v, bf_g.astype(jnp.float32),
                              bb.astype(jnp.float32), cl_v])
            allc = lax.all_gather(cand, axis_name)       # (n_dev, 4)
            win = jnp.argmax(allc[:, 0])
            return (allc[win, 0], allc[win, 1].astype(jnp.int32),
                    allc[win, 2].astype(jnp.int32), allc[win, 3], C)
        return gain_v, bf, bb, cl_v, C

    def split_indicator(leaf_of_row, bl, bf, bb):
        """rows of leaf ``bl`` that go RIGHT under split (bf, bb); in
        feature-parallel mode only the owner holds column bf, so it
        computes the bitmap and psum broadcasts it to the other shards."""
        if feat_par:
            owner = bf // f
            ind = (bins[bf % f] > bb) & (leaf_of_row == bl)
            ind = jnp.where(lax.axis_index(axis_name) == owner, ind, False)
            return lax.psum(ind.astype(jnp.float32), axis_name) > 0
        return (leaf_of_row == bl) & (bins[bf] > bb)

    # root: slot 0 holds all rows (its children sit at depth 1, legal for
    # any max_depth >= 1, so the root's candidate is never depth-blocked).
    # Quantized mode selects with a 0/1 int mask — the row weight is
    # already inside qg/qh/qc (0-weight rows quantized to exactly 0).
    root_hist = leaf_hist(jnp.ones(n, sdt) if quantized else weight)
    g0, f0, b0, cl0, c0 = best_split(root_hist, jnp.bool_(True))
    state = dict(
        leaf_of_row=zero_leaf,
        n_leaves=jnp.int32(1),
        next_node=jnp.int32(1),
        done=jnp.bool_(False),
        feature=jnp.zeros(M, jnp.int32),
        bin_threshold=jnp.zeros(M, jnp.int32),
        left=jnp.arange(M, dtype=jnp.int32),   # self-loops by default
        right=jnp.arange(M, dtype=jnp.int32),
        is_leaf=jnp.ones(M, dtype=bool),
        gain_arr=jnp.zeros(M, jnp.float32),
        count_arr=jnp.zeros(M, jnp.float32),
        # leaf slot -> node id; slot 0 starts at root
        leaf_to_node=jnp.zeros(L, dtype=jnp.int32),
        leaf_depth=jnp.zeros(L, dtype=jnp.int32),
        # per-leaf histogram cache + cached best candidate split
        # (shape/dtype follow the histogram contract: int32 quantized,
        # (3, fs+1, B) owned-slices+totals under reduce_scatter)
        hist_cache=jnp.zeros((L,) + root_hist.shape,
                             root_hist.dtype).at[0].set(root_hist),
        best_gain=jnp.full(L, NEG_INF, jnp.float32).at[0].set(g0),
        best_feat=jnp.zeros(L, jnp.int32).at[0].set(f0),
        best_bin=jnp.zeros(L, jnp.int32).at[0].set(b0),
        best_cl=jnp.zeros(L, jnp.float32).at[0].set(cl0),
        leaf_count=jnp.zeros(L, jnp.float32).at[0].set(c0),
    )

    def body(_, st):
        bl = jnp.argmax(st["best_gain"]).astype(jnp.int32)
        best_gain = st["best_gain"][bl]
        bf = st["best_feat"][bl]
        bb = st["best_bin"][bl]

        do = (~st["done"]) & (best_gain > p.min_gain_to_split) \
            & (best_gain > NEG_INF / 2)

        new_leaf = st["n_leaves"]
        goes_right = split_indicator(st["leaf_of_row"], bl, bf, bb)
        leaf_of_row2 = jnp.where(goes_right & do, new_leaf,
                                 st["leaf_of_row"])

        # one masked single-leaf histogram for the right child; the left
        # sibling is parent - right (the LightGBM subtraction trick —
        # exact in int32 when quantized, so association cannot flip ties)
        if quantized:
            mask_w = ((leaf_of_row2 == new_leaf) & do).astype(sdt)
        else:
            mask_w = weight * (leaf_of_row2 == new_leaf) * do
        hist_r = leaf_hist(mask_w)
        hist_l = st["hist_cache"][bl] - hist_r

        child_depth = st["leaf_depth"][bl] + 1
        depth_ok = jnp.bool_(True) if p.max_depth <= 0 \
            else child_depth < p.max_depth
        if voting:
            # ship the (parent, right) pair unsubtracted — the psum-then-
            # subtract order must match data-parallel (see best_split_voting)
            gl_, fl_, bl_bin, cll, cl_tot = best_split(
                st["hist_cache"][bl], depth_ok, hist_sub=hist_r)
        else:
            gl_, fl_, bl_bin, cll, cl_tot = best_split(hist_l, depth_ok)
        gr_, fr_, br_bin, clr, cr_tot = best_split(hist_r, depth_ok)

        parent = st["leaf_to_node"][bl]
        lid = st["next_node"]
        rid = st["next_node"] + 1

        def upd(arr, idx, val):
            return arr.at[idx].set(jnp.where(do, val, arr[idx]))

        st2 = dict(st)
        st2["leaf_of_row"] = leaf_of_row2
        st2["feature"] = upd(st["feature"], parent, bf)
        st2["bin_threshold"] = upd(st["bin_threshold"], parent, bb)
        st2["left"] = upd(st["left"], parent, lid)
        st2["right"] = upd(st["right"], parent, rid)
        st2["is_leaf"] = st["is_leaf"].at[parent].set(
            jnp.where(do, False, st["is_leaf"][parent]))
        st2["gain_arr"] = upd(st["gain_arr"], parent, best_gain)
        cl_best = st["best_cl"][bl]
        st2["count_arr"] = upd(
            upd(st["count_arr"], lid, cl_best),
            rid, st["leaf_count"][bl] - cl_best)
        st2["leaf_to_node"] = upd(
            upd(st["leaf_to_node"], bl, lid), new_leaf, rid)
        st2["leaf_depth"] = upd(
            upd(st["leaf_depth"], bl, child_depth), new_leaf, child_depth)
        st2["hist_cache"] = upd(
            upd(st["hist_cache"], bl, hist_l), new_leaf, hist_r)
        st2["best_gain"] = upd(
            upd(st["best_gain"], bl, gl_), new_leaf, gr_)
        st2["best_feat"] = upd(
            upd(st["best_feat"], bl, fl_), new_leaf, fr_)
        st2["best_bin"] = upd(
            upd(st["best_bin"], bl, bl_bin), new_leaf, br_bin)
        st2["best_cl"] = upd(
            upd(st["best_cl"], bl, cll), new_leaf, clr)
        st2["leaf_count"] = upd(
            upd(st["leaf_count"], bl, cl_tot), new_leaf, cr_tot)
        st2["n_leaves"] = st["n_leaves"] + jnp.where(do, 1, 0)
        st2["next_node"] = st["next_node"] + jnp.where(do, 2, 0)
        st2["done"] = st["done"] | (~do)
        return st2

    st = lax.fori_loop(0, L - 1, body, state)

    # final per-leaf grad/hess sums straight from the cached histograms:
    # any feature's bins partition a leaf's rows, so feature 0's bin
    # sums ARE the leaf totals (LightGBM derives leaf outputs from
    # histogram sums the same way). The previous 1M-row segment_sum
    # pair was scatter-lowered, ~9 ms each on TPU — 15% of the boost
    # loop — for a number the engine already had.
    #
    # Feature-parallel is the exception: each device's "feature 0" is a
    # DIFFERENT global feature, so the bin-sum order (and hence the f32
    # rounding) varies per device — leaf values claimed replicated
    # would silently diverge across devices/hosts. Rows are replicated
    # there, so the direct row reduction stays (identical order
    # everywhere).
    if feat_par:
        seg = st["leaf_of_row"]
        g_leaf = jax.ops.segment_sum(grad * weight, seg, num_segments=L)
        h_leaf = jax.ops.segment_sum(hess * weight, seg, num_segments=L)
    else:
        # reduce_scatter caches carry the psum'd global feature-0
        # histogram in the appended [-1] slice; psum-mode caches hold
        # it at feature index 0
        fslot = -1 if rs else 0
        g_leaf = st["hist_cache"][:, 0, fslot, :].sum(-1)
        h_leaf = st["hist_cache"][:, 1, fslot, :].sum(-1)
        if voting:
            # voting keeps cached histograms LOCAL (only candidate
            # slices psum during splits); leaf totals must allreduce.
            # Data-parallel caches are already global (build_histogram
            # psums) — summing again would double-count.
            g_leaf = lax.psum(g_leaf, axis_name)
            h_leaf = lax.psum(h_leaf, axis_name)
        if quantized:
            g_leaf = g_leaf * dg
            h_leaf = h_leaf * dh
    leaf_values = _leaf_output(g_leaf, h_leaf, p.lambda_l1, p.lambda_l2)
    active = jnp.arange(L) < st["n_leaves"]
    leaf_values = jnp.where(active, leaf_values, 0.0)

    # inactive slots all hold leaf_to_node=0; route them to a dummy slot M
    # so the scatter can't zero the root's value (node 0)
    scatter_idx = jnp.where(active, st["leaf_to_node"], M)
    value = jnp.zeros(M + 1, jnp.float32).at[scatter_idx].set(
        jnp.where(active, leaf_values, 0.0))[:M]

    tree = Tree(feature=st["feature"],
                bin_threshold=st["bin_threshold"],
                threshold=jnp.zeros(M, jnp.float32),
                left=st["left"], right=st["right"],
                value=value, is_leaf=st["is_leaf"],
                gain=st["gain_arr"], count=st["count_arr"])
    return tree, st["leaf_of_row"], leaf_values, st["n_leaves"]


@partial(jax.jit, static_argnames=("max_depth",))
def predict_trees(features: jnp.ndarray, feature_arr: jnp.ndarray,
                  threshold_arr: jnp.ndarray, left_arr: jnp.ndarray,
                  right_arr: jnp.ndarray, value_arr: jnp.ndarray,
                  max_depth: int) -> jnp.ndarray:
    """Batch inference over stacked trees.

    features (N, F) f32; tree arrays (T, M). Returns (T, N) leaf outputs.
    Fixed-depth pointer walk: leaves self-loop, so walking max_depth steps
    from the root always lands on the reached leaf — no data-dependent
    control flow, pure gathers that XLA vectorizes.
    """
    def one_tree(feat, thr, lft, rgt, val):
        def step(node, _):
            f = feat[node]                       # (N,)
            fv = features[jnp.arange(features.shape[0]), f]
            # NaN must go LEFT to match training, where binning maps NaN
            # to bin 0 (binning.py); `~(fv > thr)` is True for NaN
            go_left = ~(fv > thr[node])
            return jnp.where(go_left, lft[node], rgt[node]), None
        node0 = jnp.zeros(features.shape[0], dtype=jnp.int32)
        node, _ = lax.scan(step, node0, None, length=max_depth)
        return val[node]

    return jax.vmap(one_tree)(feature_arr, threshold_arr, left_arr,
                              right_arr, value_arr)
