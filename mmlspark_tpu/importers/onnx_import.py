"""Dependency-free ONNX ingestion: wire-format reader + jax executor.

The reference's model zoo serves published CNN checkpoints in a
framework-neutral way (ref: src/downloader/src/main/scala/
ModelDownloader.scala:209, Schema.scala:54 — CNTK model files behind
URI+sha256 schemas). ONNX is today's dominant neutral interchange
format, so "load a real published checkpoint" must hold for it, not
just the torch ecosystem (importers/torch_import.py).

No ``onnx`` package exists in the image, so this module parses the
protobuf WIRE FORMAT directly (varint / length-delimited walking over
the public onnx.proto field numbers — ModelProto.graph=7,
GraphProto.{node=1, initializer=5, input=11, output=12},
NodeProto.{input=1, output=2, name=3, op_type=4, attribute=5},
AttributeProto.{name=1, f=2, i=3, s=4, t=5, ints=8},
TensorProto.{dims=1, data_type=2, float_data=4, int64_data=7, name=8,
raw_data=9}). The supported operator subset covers the published CNN
families (torchvision resnet18/34 exports): Conv, BatchNormalization,
Relu, MaxPool, AveragePool, GlobalAveragePool, Add, Gemm, MatMul,
Flatten, Reshape, Identity, Constant, Clip.

Execution is a small jax interpreter over the graph in ONNX's native
NCHW layout (lax.conv_general_dilated carries the layout directly, so
imported numerics match the exporter bit-comparably in f32). The
executor object is picklable and plugs into TPUModel as ``modelFn`` —
the same serving contract every zoo model uses.
"""

from __future__ import annotations

import struct
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

# ---------------------------------------------------------------------------
# protobuf wire-format primitives
# ---------------------------------------------------------------------------


def _read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 70:
            raise ValueError("varint overflow (corrupt ONNX file?)")


def _fields(buf: bytes):
    """Yield (field_number, wire_type, value) over a message's bytes.
    Values: varint -> int, 64-bit -> 8 bytes, length-delimited -> bytes,
    32-bit -> 4 bytes. Truncated payloads raise (a short slice would
    otherwise parse into a wrong-sized tensor and fail far away, or not
    at all); groups (deprecated) are rejected."""
    pos = 0
    n = len(buf)
    while pos < n:
        key, pos = _read_varint(buf, pos)
        field, wt = key >> 3, key & 7
        if wt == 0:
            val, pos = _read_varint(buf, pos)
        elif wt == 1:
            end = pos + 8
        elif wt == 2:
            ln, pos = _read_varint(buf, pos)
            end = pos + ln
        elif wt == 5:
            end = pos + 4
        else:
            raise ValueError(f"unsupported protobuf wire type {wt}")
        if wt != 0:
            if end > n:
                raise ValueError(
                    f"truncated protobuf: field {field} needs bytes "
                    f"[{pos}, {end}) of {n}")
            val, pos = buf[pos:end], end
        yield field, wt, val


# ---------------------------------------------------------------------------
# onnx message readers (subset)
# ---------------------------------------------------------------------------

# TensorProto.DataType (public enum values)
_DT_FLOAT, _DT_UINT8, _DT_INT8, _DT_INT32, _DT_INT64 = 1, 2, 3, 6, 7
_DT_DOUBLE, _DT_FLOAT16 = 11, 10

_TENSOR_DTYPES = {
    _DT_FLOAT: np.float32,
    _DT_DOUBLE: np.float64,
    _DT_INT32: np.int32,
    _DT_INT64: np.int64,
    _DT_UINT8: np.uint8,
    _DT_INT8: np.int8,
    _DT_FLOAT16: np.float16,
}


def _parse_tensor(buf: bytes) -> Tuple[str, np.ndarray]:
    dims: List[int] = []
    data_type = _DT_FLOAT
    raw = b""
    float_data: List[float] = []
    double_data: List[float] = []
    int64_data: List[int] = []
    int32_data: List[int] = []
    name = ""
    for field, wt, val in _fields(buf):
        if field == 1:                      # dims (repeated int64)
            if wt == 0:
                dims.append(val)
            else:                           # packed
                pos = 0
                while pos < len(val):
                    d, pos = _read_varint(val, pos)
                    dims.append(d)
        elif field == 2:
            data_type = val
        elif field == 4:                    # float_data
            if wt == 5:
                float_data.append(struct.unpack("<f", val)[0])
            else:                           # packed
                float_data.extend(
                    struct.unpack(f"<{len(val) // 4}f", val))
        elif field == 5:                    # int32_data
            if wt == 0:
                int32_data.append(val)
            else:
                pos = 0
                while pos < len(val):
                    d, pos = _read_varint(val, pos)
                    int32_data.append(d)
        elif field == 7:                    # int64_data
            if wt == 0:
                int64_data.append(val)
            else:
                pos = 0
                while pos < len(val):
                    d, pos = _read_varint(val, pos)
                    int64_data.append(d)
        elif field == 8:
            name = val.decode("utf-8")
        elif field == 9:
            raw = val
        elif field == 10:                   # double_data
            if wt == 1:
                double_data.append(struct.unpack("<d", val)[0])
            else:                           # packed
                double_data.extend(
                    struct.unpack(f"<{len(val) // 8}d", val))
    if data_type not in _TENSOR_DTYPES:
        raise ValueError(
            f"tensor {name!r}: unsupported ONNX data_type {data_type}")
    dtype = _TENSOR_DTYPES[data_type]
    if raw:
        arr = np.frombuffer(raw, dtype=dtype).copy()
    elif float_data:
        arr = np.asarray(float_data, dtype=dtype)
    elif double_data:
        arr = np.asarray(double_data, dtype=dtype)
    elif int64_data:
        arr = np.asarray(int64_data, dtype=dtype)
    elif int32_data:
        if data_type == _DT_FLOAT16:
            # spec: FLOAT16 payloads in int32_data are uint16 BIT
            # patterns, not values — reinterpret, never cast
            arr = np.asarray(int32_data, dtype=np.uint16).view(np.float16)
        else:
            arr = np.asarray(int32_data, dtype=dtype)
    else:
        arr = np.zeros(0, dtype=dtype)
    if dims and arr.size != int(np.prod(dims)):
        raise ValueError(
            f"tensor {name!r}: payload has {arr.size} elements but dims "
            f"{dims} need {int(np.prod(dims))} (unsupported storage "
            f"field or corrupt file)")
    return name, arr.reshape(dims) if dims else arr


def _parse_attribute(buf: bytes) -> Tuple[str, Any]:
    name = ""
    out: Any = None
    ints: List[int] = []
    for field, wt, val in _fields(buf):
        if field == 1:
            name = val.decode("utf-8")
        elif field == 2:                    # f (float, fixed32)
            out = struct.unpack("<f", val)[0]
        elif field == 3:                    # i (int)
            out = _signed(val)
        elif field == 4:                    # s (bytes)
            out = val.decode("utf-8", "replace")
        elif field == 5:                    # t (tensor)
            out = _parse_tensor(val)[1]
        elif field == 8:                    # ints (repeated)
            if wt == 0:
                ints.append(_signed(val))
            else:
                pos = 0
                while pos < len(val):
                    d, pos = _read_varint(val, pos)
                    ints.append(_signed(d))
    return name, (ints if ints else out)


def _signed(v: int) -> int:
    """proto int64 varints are two's-complement in 64 bits."""
    return v - (1 << 64) if v >= (1 << 63) else v


class OnnxNode:
    def __init__(self, op_type: str, inputs: List[str], outputs: List[str],
                 attrs: Dict[str, Any], name: str = ""):
        self.op_type = op_type
        self.inputs = inputs
        self.outputs = outputs
        self.attrs = attrs
        self.name = name

    def __repr__(self):
        return f"OnnxNode({self.op_type}, {self.inputs} -> {self.outputs})"


def _parse_node(buf: bytes) -> OnnxNode:
    inputs: List[str] = []
    outputs: List[str] = []
    attrs: Dict[str, Any] = {}
    op_type = ""
    name = ""
    for field, _wt, val in _fields(buf):
        if field == 1:
            inputs.append(val.decode("utf-8"))
        elif field == 2:
            outputs.append(val.decode("utf-8"))
        elif field == 3:
            name = val.decode("utf-8")
        elif field == 4:
            op_type = val.decode("utf-8")
        elif field == 5:
            k, v = _parse_attribute(val)
            attrs[k] = v
    return OnnxNode(op_type, inputs, outputs, attrs, name)


def _value_info_name(buf: bytes) -> str:
    for field, _wt, val in _fields(buf):
        if field == 1:
            return val.decode("utf-8")
    return ""


class OnnxGraph:
    """Parsed ONNX graph: topologically-ordered nodes, initializers,
    graph input/output names (initializer-backed inputs excluded)."""

    def __init__(self, nodes: List[OnnxNode],
                 initializers: Dict[str, np.ndarray],
                 inputs: List[str], outputs: List[str]):
        self.nodes = nodes
        self.initializers = initializers
        self.inputs = [i for i in inputs if i not in initializers]
        self.outputs = outputs


SUPPORTED_OPS = {
    "Conv", "BatchNormalization", "Relu", "MaxPool", "AveragePool",
    "GlobalAveragePool", "Add", "Gemm", "MatMul", "Flatten", "Reshape",
    "Identity", "Constant", "Clip",
}


def load_onnx(path: str) -> OnnxGraph:
    """Parse an .onnx file into an OnnxGraph; raises with the offending
    op list when the graph uses operators outside the supported subset
    (fail at load, not mid-inference)."""
    with open(path, "rb") as f:
        buf = f.read()
    graph_buf: Optional[bytes] = None
    try:
        for field, _wt, val in _fields(buf):
            if field == 7:                  # ModelProto.graph
                graph_buf = val
    except (IndexError, ValueError, struct.error) as e:
        raise ValueError(
            f"{path!r} is not a parseable ONNX protobuf: {e}") from e
    if graph_buf is None:
        raise ValueError(f"{path!r} has no graph — not an ONNX model file")
    nodes: List[OnnxNode] = []
    inits: Dict[str, np.ndarray] = {}
    inputs: List[str] = []
    outputs: List[str] = []
    try:
        for field, _wt, val in _fields(graph_buf):
            if field == 1:
                nodes.append(_parse_node(val))
            elif field == 5:
                name, arr = _parse_tensor(val)
                inits[name] = arr
            elif field == 11:
                inputs.append(_value_info_name(val))
            elif field == 12:
                outputs.append(_value_info_name(val))
    except (IndexError, struct.error) as e:
        raise ValueError(
            f"{path!r}: corrupt/truncated ONNX graph: {e}") from e
    unsupported = sorted({n.op_type for n in nodes} - SUPPORTED_OPS)
    if unsupported:
        raise ValueError(
            f"ONNX graph uses unsupported operators {unsupported}; "
            f"supported subset: {sorted(SUPPORTED_OPS)}")
    return OnnxGraph(nodes, inits, inputs, outputs)


# ---------------------------------------------------------------------------
# jax executor
# ---------------------------------------------------------------------------


def _pairs(pads: List[int]) -> List[Tuple[int, int]]:
    """ONNX pads [b0, b1, ..., e0, e1, ...] -> [(b0, e0), (b1, e1), ...]."""
    k = len(pads) // 2
    return [(pads[i], pads[k + i]) for i in range(k)]


class OnnxApply:
    """Picklable jax executor for a supported-subset ONNX graph —
    TPUModel's ``modelFn`` contract: ``(weights, inputs_dict) -> out``.
    Inputs/outputs are NCHW (ONNX's native layout; the convs carry it
    through lax dimension_numbers, no transposes)."""

    def __init__(self, graph: OnnxGraph, input_shape=None):
        self.nodes = graph.nodes
        self.input_names = graph.inputs
        self.output_names = graph.outputs
        # per-row shape (e.g. (3, 224, 224)) to unflatten table rows to
        self.input_shape = tuple(input_shape) if input_shape else None
        # Reshape targets are initializer int64 vectors in exported
        # graphs; resolve them STATICALLY here — under jit (TPUModel
        # compiles this apply) the weights pytree arrives as tracers and
        # a traced shape could not concretize
        self._static_shapes: Dict[str, List[int]] = {}
        for node in graph.nodes:
            if node.op_type == "Reshape" and len(node.inputs) > 1:
                t = graph.initializers.get(node.inputs[1])
                if t is not None:
                    self._static_shapes[node.inputs[1]] = [
                        int(v) for v in np.asarray(t).ravel()]

    def __call__(self, weights: Dict[str, Any], inputs: Dict[str, Any]):
        import jax.numpy as jnp
        from jax import lax

        env: Dict[str, Any] = dict(weights)
        vals = list(inputs.values())
        for name, v in zip(self.input_names, vals):
            if self.input_shape:
                v = v.reshape((v.shape[0],) + self.input_shape)
            env[name] = v
        for node in self.nodes:
            a = node.attrs
            x = [env[i] if i else None for i in node.inputs]
            op = node.op_type
            if op == "Conv":
                strides = a.get("strides", [1, 1])
                pads = a.get("pads", [0] * 4)
                dil = a.get("dilations", [1, 1])
                groups = int(a.get("group", 1))
                out = lax.conv_general_dilated(
                    x[0], jnp.asarray(x[1]), strides, _pairs(pads),
                    rhs_dilation=dil, feature_group_count=groups,
                    dimension_numbers=("NCHW", "OIHW", "NCHW"))
                if len(x) > 2 and x[2] is not None:
                    out = out + jnp.asarray(x[2])[None, :, None, None]
            elif op == "BatchNormalization":
                eps = a.get("epsilon", 1e-5)
                scale, b, mean, var = (jnp.asarray(t) for t in x[1:5])
                inv = scale / jnp.sqrt(var + eps)
                out = (x[0] - mean[None, :, None, None]) \
                    * inv[None, :, None, None] + b[None, :, None, None]
            elif op == "Relu":
                out = jnp.maximum(x[0], 0)
            elif op in ("MaxPool", "AveragePool"):
                ks = a["kernel_shape"]
                strides = a.get("strides", [1] * len(ks))
                pads = _pairs(a.get("pads", [0] * (2 * len(ks))))
                if op == "MaxPool":
                    init, fn = -jnp.inf, lax.max
                    out = lax.reduce_window(
                        x[0], init, fn, (1, 1) + tuple(ks),
                        (1, 1) + tuple(strides),
                        [(0, 0), (0, 0)] + pads)
                else:
                    s = lax.reduce_window(
                        x[0], 0.0, lax.add, (1, 1) + tuple(ks),
                        (1, 1) + tuple(strides),
                        [(0, 0), (0, 0)] + pads)
                    if a.get("count_include_pad", 0):
                        out = s / float(np.prod(ks))
                    else:
                        ones = jnp.ones_like(x[0])
                        cnt = lax.reduce_window(
                            ones, 0.0, lax.add, (1, 1) + tuple(ks),
                            (1, 1) + tuple(strides),
                            [(0, 0), (0, 0)] + pads)
                        out = s / cnt
            elif op == "GlobalAveragePool":
                out = jnp.mean(x[0], axis=(2, 3), keepdims=True)
            elif op == "Add":
                out = x[0] + x[1]
            elif op == "Gemm":
                alpha = a.get("alpha", 1.0)
                beta = a.get("beta", 1.0)
                A = x[0].T if a.get("transA", 0) else x[0]
                B = jnp.asarray(x[1])
                if a.get("transB", 0):
                    B = B.T
                out = alpha * (A @ B)
                if len(x) > 2 and x[2] is not None:
                    out = out + beta * jnp.asarray(x[2])
            elif op == "MatMul":
                out = x[0] @ jnp.asarray(x[1])
            elif op == "Flatten":
                ax = int(a.get("axis", 1))
                shape = x[0].shape
                out = x[0].reshape(
                    (int(np.prod(shape[:ax])) if ax else 1, -1))
            elif op == "Reshape":
                target = self._static_shapes.get(node.inputs[1])
                if target is None:
                    # non-initializer shape: must be concrete (eager
                    # path only — a traced shape cannot concretize)
                    target = np.asarray(x[1]).astype(np.int64).tolist()
                shape = list(x[0].shape)
                target = [shape[i] if t == 0 else int(t)
                          for i, t in enumerate(target)]
                out = x[0].reshape(target)
            elif op == "Identity":
                out = x[0]
            elif op == "Constant":
                out = jnp.asarray(a["value"])
            elif op == "Clip":
                lo = x[1] if len(x) > 1 and x[1] is not None \
                    else a.get("min", -np.inf)
                hi = x[2] if len(x) > 2 and x[2] is not None \
                    else a.get("max", np.inf)
                out = jnp.clip(x[0], lo, hi)
            else:  # pragma: no cover — load_onnx validated the op set
                raise ValueError(f"unsupported op {op}")
            env[node.outputs[0]] = out
        outs = [env[o] for o in self.output_names]
        return outs[0] if len(outs) == 1 else tuple(outs)


def import_onnx_model(path: str, batch_size: int = 64,
                      input_shape=None):
    """ONNX file -> ready-to-serve TPUModel (the ModelDownloader /
    ImageFeaturizer contract). Weights are the graph initializers; the
    modelFn is the jax graph executor. Inputs are NCHW float32;
    ``input_shape`` (e.g. [3, 224, 224]) unflattens table rows."""
    from mmlspark_tpu.models.tpu_model import TPUModel

    graph = load_onnx(path)
    if len(graph.inputs) != 1:
        raise ValueError(
            f"expected a single graph input, got {graph.inputs}")
    model = TPUModel(
        modelFn=OnnxApply(graph, input_shape=input_shape),
        weights={k: np.asarray(v) for k, v in graph.initializers.items()},
        inputCol="images", outputCol="scores", batchSize=batch_size,
        computeDtype="float32")
    return model


def onnx_summary(path: str) -> Dict[str, Any]:
    """Structural manifest of an ONNX file (op histogram, initializer
    count/bytes, inputs/outputs) — the validation hook ModelDownloader
    schemas record, mirroring the torchvision manifest discipline."""
    graph = load_onnx(path)
    ops: Dict[str, int] = {}
    for node in graph.nodes:
        ops[node.op_type] = ops.get(node.op_type, 0) + 1
    return {
        "ops": dict(sorted(ops.items())),
        "num_initializers": len(graph.initializers),
        "initializer_bytes": int(sum(
            v.nbytes for v in graph.initializers.values())),
        "inputs": graph.inputs,
        "outputs": graph.outputs,
    }
