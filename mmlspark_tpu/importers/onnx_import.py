"""Dependency-free ONNX ingestion: wire-format reader + jax executor.

The reference's model zoo serves published checkpoints in a
framework-neutral way (ref: src/downloader/src/main/scala/
ModelDownloader.scala:209, Schema.scala:54 — CNTK model files behind
URI+sha256 schemas), and its workhorse model stage ingests arbitrary
serialized graphs, not just CNNs (ref: src/cntk-model/src/main/scala/
CNTKModel.scala:147, SerializableFunction.scala:85-140). ONNX is
today's dominant neutral interchange format, so "load a real published
checkpoint" must hold for it across model families — CNNs, MLPs, and
recurrent taggers (the notebook-304 BiLSTM flagship).

No ``onnx`` package exists in the image, so this module parses the
protobuf WIRE FORMAT directly (varint / length-delimited walking over
the public onnx.proto field numbers — ModelProto.{graph=7,
opset_import=8}, GraphProto.{node=1, initializer=5, input=11,
output=12}, NodeProto.{input=1, output=2, name=3, op_type=4,
attribute=5}, AttributeProto.{name=1, f=2, i=3, s=4, t=5, floats=7,
ints=8, strings=9}, TensorProto.{dims=1, data_type=2, float_data=4,
int32_data=5, int64_data=7, name=8, raw_data=9},
ValueInfoProto.{name=1, type=2} with nested tensor_type/shape dims).

Supported operators (validated at load — unknown ops AND
semantics-changing attributes outside the supported envelope are
rejected with actionable errors, so a graph that loads executes
faithfully):

  CNN family  : Conv (1-D and 2-D), BatchNormalization, Relu,
                MaxPool, AveragePool, GlobalAveragePool, Flatten
  linear      : Gemm, MatMul
  recurrent   : LSTM, GRU (each forward / reverse / bidirectional)
  activations : Sigmoid, Tanh, Softmax, LogSoftmax, LeakyRelu, Clip,
                Erf (the BERT-GELU building block)
  elementwise : Add, Sub, Mul, Div, Neg, Exp, Sqrt, Pow, Where,
                Min, Max (variadic)
  structure   : Concat, Split, Transpose, Reshape, Squeeze, Unsqueeze,
                Slice, Shape, Gather, Cast, Expand, Identity, Constant,
                ReduceMean, ReduceSum, ReduceMax, ReduceMin,
                ArgMax, ArgMin

Opset-version semantics are honored where they differ: Squeeze /
Unsqueeze axes move from attribute (opset <= 12) to input (>= 13),
Slice moves from attributes (<= 9) to inputs (>= 10), and Softmax's
default axis flips from 1 (flatten-to-2D semantics, <= 12) to -1
(per-axis, >= 13). The model's declared default-domain opset drives
the choice; out-of-range opsets are rejected at load.

Execution is a small jax interpreter over the graph in ONNX's native
NCHW layout (lax.conv_general_dilated carries the layout directly, so
imported numerics match the exporter bit-comparably in f32). The LSTM
is TPU-first: the input projection for the whole sequence is hoisted
out of the recurrence into ONE large (T*B, I)x(I, 4H) MXU matmul;
only the (B, 4H) recurrent matmul rides lax.scan. The executor object
is picklable and plugs into TPUModel as ``modelFn`` — the same serving
contract every zoo model uses. Graph inputs declared with integer
element types mark the executor ``int_input`` so TPUModel feeds token
ids as int32 instead of round-tripping them through float compute
dtypes; a symbolic (dim_param) batch dimension is the dynamic-batch
contract — the executor is shape-polymorphic over it.
"""

from __future__ import annotations

import struct
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

# ---------------------------------------------------------------------------
# protobuf wire-format primitives
# ---------------------------------------------------------------------------


def _read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 70:
            raise ValueError("varint overflow (corrupt ONNX file?)")


def _fields(buf: bytes):
    """Yield (field_number, wire_type, value) over a message's bytes.
    Values: varint -> int, 64-bit -> 8 bytes, length-delimited -> bytes,
    32-bit -> 4 bytes. Truncated payloads raise (a short slice would
    otherwise parse into a wrong-sized tensor and fail far away, or not
    at all); groups (deprecated) are rejected."""
    pos = 0
    n = len(buf)
    while pos < n:
        key, pos = _read_varint(buf, pos)
        field, wt = key >> 3, key & 7
        if wt == 0:
            val, pos = _read_varint(buf, pos)
        elif wt == 1:
            end = pos + 8
        elif wt == 2:
            ln, pos = _read_varint(buf, pos)
            end = pos + ln
        elif wt == 5:
            end = pos + 4
        else:
            raise ValueError(f"unsupported protobuf wire type {wt}")
        if wt != 0:
            if end > n:
                raise ValueError(
                    f"truncated protobuf: field {field} needs bytes "
                    f"[{pos}, {end}) of {n}")
            val, pos = buf[pos:end], end
        yield field, wt, val


def _signed(v: int) -> int:
    """proto int64 varints are two's-complement in 64 bits."""
    return v - (1 << 64) if v >= (1 << 63) else v


# ---------------------------------------------------------------------------
# onnx message readers (subset)
# ---------------------------------------------------------------------------

# TensorProto.DataType (public enum values)
_DT_FLOAT, _DT_UINT8, _DT_INT8, _DT_INT32, _DT_INT64 = 1, 2, 3, 6, 7
_DT_DOUBLE, _DT_FLOAT16, _DT_BOOL = 11, 10, 9

_TENSOR_DTYPES = {
    _DT_FLOAT: np.float32,
    _DT_DOUBLE: np.float64,
    _DT_INT32: np.int32,
    _DT_INT64: np.int64,
    _DT_UINT8: np.uint8,
    _DT_INT8: np.int8,
    _DT_FLOAT16: np.float16,
    _DT_BOOL: np.bool_,
}

_INT_ELEM_TYPES = (_DT_INT32, _DT_INT64, _DT_UINT8, _DT_INT8)


def _parse_tensor(buf: bytes) -> Tuple[str, np.ndarray]:
    dims: List[int] = []
    data_type = _DT_FLOAT
    raw = b""
    float_data: List[float] = []
    double_data: List[float] = []
    int64_data: List[int] = []
    int32_data: List[int] = []
    name = ""
    for field, wt, val in _fields(buf):
        if field == 1:                      # dims (repeated int64)
            if wt == 0:
                dims.append(val)
            else:                           # packed
                pos = 0
                while pos < len(val):
                    d, pos = _read_varint(val, pos)
                    dims.append(d)
        elif field == 2:
            data_type = val
        elif field == 4:                    # float_data
            if wt == 5:
                float_data.append(struct.unpack("<f", val)[0])
            else:                           # packed
                float_data.extend(
                    struct.unpack(f"<{len(val) // 4}f", val))
        elif field == 5:                    # int32_data
            # int32 varints are sign-extended to 64 bits on the wire —
            # without _signed a negative decodes as ~2^64 and the
            # np.asarray below overflows (FLOAT16 bit patterns are
            # 0..65535, where _signed is a no-op)
            if wt == 0:
                int32_data.append(_signed(val))
            else:
                pos = 0
                while pos < len(val):
                    d, pos = _read_varint(val, pos)
                    int32_data.append(_signed(d))
        elif field == 7:                    # int64_data
            # same two's-complement rule: a Reshape shape [-1, C] or a
            # negative axis stored here (not raw_data) must decode signed
            if wt == 0:
                int64_data.append(_signed(val))
            else:
                pos = 0
                while pos < len(val):
                    d, pos = _read_varint(val, pos)
                    int64_data.append(_signed(d))
        elif field == 8:
            name = val.decode("utf-8")
        elif field == 9:
            raw = val
        elif field == 10:                   # double_data
            if wt == 1:
                double_data.append(struct.unpack("<d", val)[0])
            else:                           # packed
                double_data.extend(
                    struct.unpack(f"<{len(val) // 8}d", val))
    if data_type not in _TENSOR_DTYPES:
        raise ValueError(
            f"tensor {name!r}: unsupported ONNX data_type {data_type}")
    dtype = _TENSOR_DTYPES[data_type]
    if raw:
        arr = np.frombuffer(raw, dtype=dtype).copy()
    elif float_data:
        arr = np.asarray(float_data, dtype=dtype)
    elif double_data:
        arr = np.asarray(double_data, dtype=dtype)
    elif int64_data:
        arr = np.asarray(int64_data, dtype=dtype)
    elif int32_data:
        if data_type == _DT_FLOAT16:
            # spec: FLOAT16 payloads in int32_data are uint16 BIT
            # patterns, not values — reinterpret, never cast
            arr = np.asarray(int32_data, dtype=np.uint16).view(np.float16)
        else:
            arr = np.asarray(int32_data, dtype=dtype)
    else:
        arr = np.zeros(0, dtype=dtype)
    if dims and arr.size != int(np.prod(dims)):
        raise ValueError(
            f"tensor {name!r}: payload has {arr.size} elements but dims "
            f"{dims} need {int(np.prod(dims))} (unsupported storage "
            f"field or corrupt file)")
    if dims:
        return name, arr.reshape(dims)
    # spec: absent dims means a 0-d scalar (dims=[] and "not written"
    # are indistinguishable on the wire)
    return name, arr.reshape(()) if arr.size == 1 else arr


def _parse_attribute(buf: bytes) -> Tuple[str, Any]:
    name = ""
    out: Any = None
    ints: List[int] = []
    floats: List[float] = []
    strings: List[str] = []
    for field, wt, val in _fields(buf):
        if field == 1:
            name = val.decode("utf-8")
        elif field == 2:                    # f (float, fixed32)
            out = struct.unpack("<f", val)[0]
        elif field == 3:                    # i (int)
            out = _signed(val)
        elif field == 4:                    # s (bytes)
            out = val.decode("utf-8", "replace")
        elif field == 5:                    # t (tensor)
            out = _parse_tensor(val)[1]
        elif field == 6:                    # g (GraphProto — subgraph)
            # subgraph-carrying ops (If/Loop/Scan) are outside the
            # supported set; the op check rejects them, so the bytes
            # are skipped here rather than mis-parsed
            pass
        elif field == 7:                    # floats (repeated fixed32)
            if wt == 5:
                floats.append(struct.unpack("<f", val)[0])
            else:                           # packed
                floats.extend(
                    struct.unpack(f"<{len(val) // 4}f", val))
        elif field == 9:                    # strings (repeated bytes)
            strings.append(val.decode("utf-8", "replace"))
        elif field == 8:                    # ints (repeated)
            if wt == 0:
                ints.append(_signed(val))
            else:
                pos = 0
                while pos < len(val):
                    d, pos = _read_varint(val, pos)
                    ints.append(_signed(d))
    if ints:
        return name, ints
    if floats:
        return name, floats
    if strings:
        return name, strings
    return name, out


class OnnxNode:
    def __init__(self, op_type: str, inputs: List[str], outputs: List[str],
                 attrs: Dict[str, Any], name: str = ""):
        self.op_type = op_type
        self.inputs = inputs
        self.outputs = outputs
        self.attrs = attrs
        self.name = name

    def __repr__(self):
        return f"OnnxNode({self.op_type}, {self.inputs} -> {self.outputs})"


def _parse_node(buf: bytes) -> OnnxNode:
    inputs: List[str] = []
    outputs: List[str] = []
    attrs: Dict[str, Any] = {}
    op_type = ""
    name = ""
    for field, _wt, val in _fields(buf):
        if field == 1:
            inputs.append(val.decode("utf-8"))
        elif field == 2:
            outputs.append(val.decode("utf-8"))
        elif field == 3:
            name = val.decode("utf-8")
        elif field == 4:
            op_type = val.decode("utf-8")
        elif field == 5:
            k, v = _parse_attribute(val)
            attrs[k] = v
    return OnnxNode(op_type, inputs, outputs, attrs, name)


def _parse_value_info(buf: bytes) -> Tuple[str, Optional[int],
                                           Optional[List[Optional[int]]]]:
    """ValueInfoProto -> (name, elem_type, dims) where a symbolic
    dim_param (the dynamic-batch convention) or absent dim parses as
    None. TypeProto.tensor_type=1 {elem_type=1, shape=2};
    TensorShapeProto.dim=1 {dim_value=1, dim_param=2}."""
    name = ""
    elem_type: Optional[int] = None
    dims: Optional[List[Optional[int]]] = None
    for field, _wt, val in _fields(buf):
        if field == 1:
            name = val.decode("utf-8")
        elif field == 2:                    # TypeProto
            for f2, _w2, v2 in _fields(val):
                if f2 != 1:                 # tensor_type only
                    continue
                for f3, _w3, v3 in _fields(v2):
                    if f3 == 1:
                        elem_type = v3
                    elif f3 == 2:           # TensorShapeProto
                        dims = []
                        for f4, _w4, v4 in _fields(v3):
                            if f4 != 1:
                                continue
                            d: Optional[int] = None
                            for f5, _w5, v5 in _fields(v4):
                                if f5 == 1:
                                    d = _signed(v5) if isinstance(
                                        v5, int) else None
                            dims.append(d)
    return name, elem_type, dims


class OnnxGraph:
    """Parsed ONNX graph: topologically-ordered nodes, initializers,
    graph input/output names (initializer-backed inputs excluded),
    per-input (elem_type, dims) info, and the default-domain opset."""

    def __init__(self, nodes: List[OnnxNode],
                 initializers: Dict[str, np.ndarray],
                 inputs: List[str], outputs: List[str],
                 input_infos: Optional[Dict[str, Tuple[
                     Optional[int], Optional[List[Optional[int]]]]]] = None,
                 opset: int = 13):
        self.nodes = nodes
        self.initializers = initializers
        self.inputs = [i for i in inputs if i not in initializers]
        self.outputs = outputs
        self.input_infos = input_infos or {}
        self.opset = opset


SUPPORTED_OPS = {
    "Conv", "BatchNormalization", "Relu", "MaxPool", "AveragePool",
    "GlobalAveragePool", "Add", "Gemm", "MatMul", "Flatten", "Reshape",
    "Identity", "Constant", "Clip",
    "Sigmoid", "Tanh", "Softmax", "LogSoftmax", "LeakyRelu",
    "Sub", "Mul", "Div", "Neg", "Exp", "Sqrt", "Pow",
    "Concat", "Transpose", "Squeeze", "Unsqueeze", "Slice", "Shape",
    "Gather", "Cast", "ReduceMean", "LSTM", "GRU",
    "Erf", "Where", "Split", "Expand",
    "Min", "Max", "ReduceSum", "ReduceMax", "ReduceMin",
    "ArgMax", "ArgMin",
}

# inclusive default-domain opset envelope this importer implements
_OPSET_MIN, _OPSET_MAX = 7, 22

_LSTM_DEFAULT_ACTS = {
    1: ["Sigmoid", "Tanh", "Tanh"],
    2: ["Sigmoid", "Tanh", "Tanh", "Sigmoid", "Tanh", "Tanh"],
}


def _node_label(node: OnnxNode) -> str:
    return f"{node.op_type} node {node.name or node.outputs[:1]}"


_CONSTANT_SPELLINGS = ("value", "value_float", "value_int",
                       "value_floats", "value_ints")


def _constant_value(attrs: Dict[str, Any]) -> Optional[np.ndarray]:
    """The numpy value of a Constant node under any of the value_*
    attribute spellings (opset 12+); None when only unsupported forms
    (sparse/string) are present. numpy (not jnp) so shape-computing
    chains that consume constants stay concrete under jit."""
    if "value" in attrs:
        return np.asarray(attrs["value"])
    if "value_float" in attrs:
        return np.asarray(attrs["value_float"], np.float32)
    if "value_int" in attrs:
        return np.asarray(attrs["value_int"], np.int64)
    if "value_floats" in attrs:
        return np.asarray(attrs["value_floats"], np.float32)
    if "value_ints" in attrs:
        return np.asarray(attrs["value_ints"], np.int64)
    return None


def _validate_recurrent_envelope(node: OnnxNode, lbl: str) -> None:
    """Checks common to every recurrent op (LSTM/GRU): cell clipping,
    batch-major layout, direction values, per-row sequence lengths."""
    a = node.attrs
    if a.get("clip") is not None:
        raise ValueError(f"{lbl}: cell clipping is not supported")
    if a.get("layout", 0):
        raise ValueError(
            f"{lbl}: layout=1 (batch-major) is not supported — "
            f"re-export with the default layout=0")
    if a.get("direction", "forward") not in (
            "forward", "reverse", "bidirectional"):
        raise ValueError(
            f"{lbl}: direction={a.get('direction')!r} invalid")
    if len(node.inputs) > 4 and node.inputs[4]:
        raise ValueError(
            f"{lbl}: per-row sequence_lens is not supported — pad "
            f"to fixed length (TPU graphs are static-shape)")


def _validate_node(node: OnnxNode, opset: int,
                   inits: Optional[Dict[str, np.ndarray]] = None) -> None:
    """Reject semantics-changing attributes outside the implemented
    envelope — the 'fail at load, not mid-inference' contract. Without
    this, e.g. auto_pad=SAME_UPPER or ceil_mode=1 would pass the op-set
    check and execute with silently wrong padding/window math."""
    a = node.attrs
    op = node.op_type
    lbl = _node_label(node)
    if op in ("Conv", "MaxPool", "AveragePool"):
        ap = a.get("auto_pad", "NOTSET")
        if ap not in ("NOTSET", ""):
            raise ValueError(
                f"{lbl}: auto_pad={ap!r} is not supported — re-export "
                f"with explicit 'pads' (auto_pad is deprecated in ONNX)")
        # 1-D (NCW) and 2-D (NCHW) convs/pools are implemented; a 3-D
        # export would otherwise die mid-inference in lax with an
        # unrelated-looking dimension_numbers error
        ks = a.get("kernel_shape")
        if ks is not None and len(ks) not in (1, 2):
            raise ValueError(
                f"{lbl}: only 1-D/2-D spatial kernels are supported, "
                f"got kernel_shape={ks}")
        if op == "Conv" and inits is not None and len(node.inputs) > 1:
            w = inits.get(node.inputs[1])
            if w is not None and w.ndim not in (3, 4):
                raise ValueError(
                    f"{lbl}: only 1-D (OIW) / 2-D (OIHW) convolution "
                    f"weights are supported, got rank {w.ndim}")
    if op in ("MaxPool", "AveragePool"):
        if a.get("ceil_mode", 0):
            raise ValueError(
                f"{lbl}: ceil_mode=1 is not supported — re-export with "
                f"ceil_mode=0 (floor) or pad explicitly")
    if op == "MaxPool":
        if any(d != 1 for d in a.get("dilations", [1])):
            raise ValueError(
                f"{lbl}: dilated max-pooling is not supported")
        if a.get("storage_order", 0):
            raise ValueError(f"{lbl}: storage_order=1 is not supported")
        if len(node.outputs) > 1 and node.outputs[1]:
            raise ValueError(
                f"{lbl}: the Indices output is not supported")
    if op == "Constant" and not any(
            k in a for k in _CONSTANT_SPELLINGS):
        raise ValueError(
            f"{lbl}: only tensor/float/int (scalar or list) constant "
            f"values are supported, got attributes {sorted(a)}")
    if op == "Split" and opset >= 13 and "split" in a:
        raise ValueError(
            f"{lbl}: attribute-form split sizes inside an "
            f"opset-{opset} graph (moved to an input at opset 13) — "
            f"file is inconsistent")
    if op == "Concat" and "axis" not in a:
        raise ValueError(f"{lbl}: required attribute 'axis' missing")
    if op == "Cast":
        to = a.get("to")
        if to not in _TENSOR_DTYPES:
            raise ValueError(
                f"{lbl}: cast target data_type {to} is not supported "
                f"(supported: {sorted(_TENSOR_DTYPES)})")
    if op == "LSTM":
        ndir = 2 if a.get("direction", "forward") == "bidirectional" else 1
        acts = a.get("activations")
        if acts is not None and list(acts) != _LSTM_DEFAULT_ACTS[ndir]:
            raise ValueError(
                f"{lbl}: non-default activations {acts} are not "
                f"supported (only {_LSTM_DEFAULT_ACTS[ndir]})")
        if a.get("input_forget", 0):
            raise ValueError(f"{lbl}: input_forget=1 is not supported")
        _validate_recurrent_envelope(node, lbl)
    if op == "LSTM" and len(node.inputs) > 7 and node.inputs[7]:
        raise ValueError(
            f"{lbl}: peephole weights (input P) are not supported — "
            f"the gates would compute without the P*c terms")
    if op == "GRU":
        ndir = 2 if a.get("direction", "forward") == "bidirectional" else 1
        acts = a.get("activations")
        if acts is not None and list(acts) != \
                ["Sigmoid", "Tanh"] * ndir:
            raise ValueError(
                f"{lbl}: non-default activations {acts} are not "
                f"supported (only Sigmoid/Tanh)")
        _validate_recurrent_envelope(node, lbl)
    if op in ("Squeeze", "Unsqueeze") and opset >= 13 and "axes" in a:
        raise ValueError(
            f"{lbl}: attribute-form axes inside an opset-{opset} graph "
            f"(axes moved to an input at opset 13) — file is "
            f"inconsistent")
    if op == "Unsqueeze" and opset >= 13 and (
            len(node.inputs) < 2 or not node.inputs[1]):
        raise ValueError(
            f"{lbl}: required 'axes' input missing (opset >= 13)")
    axes_input_opset = {"ReduceSum": 13, "ReduceMean": 18,
                        "ReduceMax": 18, "ReduceMin": 18}
    if op in axes_input_opset and opset >= axes_input_opset[op] \
            and "axes" in a:
        raise ValueError(
            f"{lbl}: attribute-form axes inside an opset-{opset} graph "
            f"(axes moved to an input at opset "
            f"{axes_input_opset[op]}) — file is inconsistent")
    if op in ("ArgMax", "ArgMin") and a.get("select_last_index", 0):
        raise ValueError(
            f"{lbl}: select_last_index=1 is not supported")
    if op == "Reshape" and a.get("allowzero", 0):
        raise ValueError(
            f"{lbl}: allowzero=1 is not supported (0 always means "
            f"'copy input dim' here)")
    if op == "Slice" and opset >= 10 and "starts" in a:
        raise ValueError(
            f"{lbl}: attribute-form Slice inside an opset-{opset} "
            f"graph — file is inconsistent")


def load_onnx(path: str) -> OnnxGraph:
    """Parse an .onnx file into an OnnxGraph; raises with the offending
    op list when the graph uses operators outside the supported subset,
    with the offending attribute when a supported op carries
    unsupported semantics, and with the declared opset when it falls
    outside [_OPSET_MIN, _OPSET_MAX] (fail at load, not
    mid-inference)."""
    with open(path, "rb") as f:
        buf = f.read()
    graph_buf: Optional[bytes] = None
    opset: Optional[int] = None
    try:
        for field, _wt, val in _fields(buf):
            if field == 7:                  # ModelProto.graph
                graph_buf = val
            elif field == 8:                # ModelProto.opset_import
                domain, version = "", None
                for f2, _w2, v2 in _fields(val):
                    if f2 == 1:
                        domain = v2.decode("utf-8")
                    elif f2 == 2:
                        version = v2
                if domain in ("", "ai.onnx") and version is not None:
                    opset = version
    except (IndexError, ValueError, struct.error) as e:
        raise ValueError(
            f"{path!r} is not a parseable ONNX protobuf: {e}") from e
    if graph_buf is None:
        raise ValueError(f"{path!r} has no graph — not an ONNX model file")
    if opset is None:
        opset = 13                          # spec default when absent
    if not _OPSET_MIN <= opset <= _OPSET_MAX:
        raise ValueError(
            f"{path!r} declares default-domain opset {opset}; this "
            f"importer implements opsets {_OPSET_MIN}..{_OPSET_MAX} — "
            f"re-export the model targeting a supported opset")
    nodes: List[OnnxNode] = []
    inits: Dict[str, np.ndarray] = {}
    inputs: List[str] = []
    outputs: List[str] = []
    input_infos: Dict[str, Tuple[Optional[int],
                                 Optional[List[Optional[int]]]]] = {}
    try:
        for field, _wt, val in _fields(graph_buf):
            if field == 1:
                nodes.append(_parse_node(val))
            elif field == 5:
                name, arr = _parse_tensor(val)
                inits[name] = arr
            elif field == 11:
                name, elem, dims = _parse_value_info(val)
                inputs.append(name)
                input_infos[name] = (elem, dims)
            elif field == 12:
                outputs.append(_parse_value_info(val)[0])
    except (IndexError, struct.error) as e:
        raise ValueError(
            f"{path!r}: corrupt/truncated ONNX graph: {e}") from e
    unsupported = sorted({n.op_type for n in nodes} - SUPPORTED_OPS)
    if unsupported:
        raise ValueError(
            f"ONNX graph uses unsupported operators {unsupported}; "
            f"supported subset: {sorted(SUPPORTED_OPS)}")
    for node in nodes:
        _validate_node(node, opset, inits)
    return OnnxGraph(nodes, inits, inputs, outputs, input_infos, opset)


# ---------------------------------------------------------------------------
# jax executor
# ---------------------------------------------------------------------------


def _pairs(pads: List[int]) -> List[Tuple[int, int]]:
    """ONNX pads [b0, b1, ..., e0, e1, ...] -> [(b0, e0), (b1, e1), ...]."""
    k = len(pads) // 2
    return [(pads[i], pads[k + i]) for i in range(k)]


# node input slots that carry SHAPE-LIKE values (reshape targets, axes,
# slice bounds): these must resolve to static python ints at
# construction time, because under jit the weights pytree arrives as
# tracers and a traced value cannot drive an output shape
_SHAPE_SLOTS = {
    "Reshape": (1,),
    "Squeeze": (1,),
    "Unsqueeze": (1,),
    "Slice": (1, 2, 3, 4),
    "ReduceMean": (1,),
    "ReduceSum": (1,),
    "ReduceMax": (1,),
    "ReduceMin": (1,),
    "Split": (1,),
    "Expand": (1,),
}

_INT64_MAX = (1 << 63) - 1
_INT32_MAX = (1 << 31) - 1


def _concrete_np(v: Any) -> bool:
    """True for values that are plain host numbers/arrays (numpy keeps
    shape-computing chains concrete under jit — np.take on a 0-d index
    returns an np.generic SCALAR, so np.ndarray alone is not enough)."""
    return isinstance(v, (np.ndarray, np.generic, int, float))


def _lib_for(*vals):
    """numpy when every operand is a plain host value, else jax.numpy.
    The single dispatch point for the shape-chain-stays-concrete rule:
    jnp ops stage even concrete operands under jit, so structural ops
    (Transpose/Concat/Squeeze/Unsqueeze/Gather/Cast) must run in numpy
    whenever their operands are host values, or a downstream Reshape
    target becomes a tracer."""
    import jax.numpy as jnp
    return np if all(_concrete_np(v) for v in vals) else jnp


class OnnxApply:
    """Picklable jax executor for a supported-subset ONNX graph —
    TPUModel's ``modelFn`` contract: ``(weights, inputs_dict) -> out``.
    Inputs/outputs follow the graph's native layout (NCHW for CNNs, the
    exporter's layout otherwise — the convs carry NCHW through lax
    dimension_numbers, no transposes)."""

    def __init__(self, graph: OnnxGraph, input_shape=None):
        """``input_shape``: per-row shape to unflatten table rows to —
        a tuple for single-input graphs, or a dict {input_name: shape}
        for multi-input ones (None entries leave rows as-is)."""
        self.nodes = graph.nodes
        self.input_names = graph.inputs
        self.output_names = graph.outputs
        self.opset = graph.opset
        # per-row shape (e.g. (3, 224, 224)) to unflatten table rows to
        if isinstance(input_shape, dict):
            self.input_shape = {k: (tuple(v) if v else None)
                                for k, v in input_shape.items()}
        else:
            self.input_shape = tuple(input_shape) if input_shape else None
        # int-element graph inputs (token ids) — TPUModel reads this to
        # feed int32 instead of the float compute dtype
        infos = [graph.input_infos.get(n, (None, None))
                 for n in graph.inputs]
        self.int_input = bool(infos) and all(
            e in _INT_ELEM_TYPES for e, _ in infos if e is not None
        ) and any(e is not None for e, _ in infos)
        # shape-like inputs (reshape targets, axes, slice bounds) come
        # from initializers or Constant nodes in exported graphs;
        # resolve them STATICALLY here (see _SHAPE_SLOTS)
        consts: Dict[str, np.ndarray] = {}
        for node in graph.nodes:
            if node.op_type == "Constant" and node.outputs:
                v_c = _constant_value(node.attrs)
                if v_c is not None:
                    consts[node.outputs[0]] = v_c
        needed = set()
        for node in graph.nodes:
            for slot in _SHAPE_SLOTS.get(node.op_type, ()):
                if slot < len(node.inputs) and node.inputs[slot]:
                    needed.add(node.inputs[slot])
        self._static: Dict[str, np.ndarray] = {}
        for name in needed:
            if name in graph.initializers:
                self._static[name] = np.asarray(graph.initializers[name])
            elif name in consts:
                self._static[name] = consts[name]
        # also capture every SMALL integer initializer/constant: under
        # jit the weights pytree is traced, but shape-computing chains
        # (Shape->Gather->Concat->Reshape) must stay concrete, so their
        # integer scalars/axes are overlaid into the env statically
        for src in (graph.initializers, consts):
            for name, arr in src.items():
                arr = np.asarray(arr)
                if arr.size <= 64 and np.issubdtype(arr.dtype, np.integer):
                    self._static.setdefault(name, arr)

    # -- static helpers -----------------------------------------------------

    def _static_ints(self, node: OnnxNode, slot: int,
                     x: List[Any]) -> Optional[List[int]]:
        """Resolve a shape-like input to a list of python ints: from the
        pre-resolved static table, else from a concrete (non-tracer)
        runtime value (Shape-op chains stay concrete under jit because
        array shapes are static at trace time)."""
        if slot >= len(node.inputs) or not node.inputs[slot]:
            return None
        name = node.inputs[slot]
        if name in self._static:
            return [int(v) for v in self._static[name].ravel()]
        v = x[slot]
        if v is None:
            return None
        import jax.core
        if isinstance(v, jax.core.Tracer):
            raise ValueError(
                f"{_node_label(node)}: input {slot} ({name!r}) is "
                f"data-dependent — shape-like inputs must be constants "
                f"(initializer / Constant / Shape-derived)")
        return [int(q) for q in np.asarray(v).ravel()]

    def __call__(self, weights: Dict[str, Any], inputs: Dict[str, Any]):
        import jax
        import jax.numpy as jnp
        from jax import lax

        env: Dict[str, Any] = dict(weights)
        # static overlay: small integer constants stay concrete numpy
        # even when the weights pytree arrives traced (see __init__)
        env.update(self._static)
        # bind by NAME when the feed keys are the graph input names
        # (multi-input models — dict param storage may reorder);
        # positional zip only for the single-input case (whose feed key
        # is "input") — a positional fallback for several inputs could
        # silently cross-bind same-shaped columns
        if set(self.input_names) <= set(inputs.keys()):
            bound = [(n, inputs[n]) for n in self.input_names]
        elif len(self.input_names) == 1:
            bound = list(zip(self.input_names, inputs.values()))
        else:
            raise KeyError(
                f"multi-input graph needs feeds keyed by its input "
                f"names {self.input_names}, got {sorted(inputs)}")
        for name, v in bound:
            shp = (self.input_shape.get(name)
                   if isinstance(self.input_shape, dict)
                   else self.input_shape)
            if shp:
                v = v.reshape((v.shape[0],) + tuple(shp))
            env[name] = v
        for node in self.nodes:
            a = node.attrs
            x = [env[i] if i else None for i in node.inputs]
            op = node.op_type
            if op == "Conv":
                w_c = jnp.asarray(x[1])
                sp = w_c.ndim - 2          # spatial rank: 1-D or 2-D
                strides = a.get("strides", [1] * sp)
                pads = a.get("pads", [0] * (2 * sp))
                dil = a.get("dilations", [1] * sp)
                groups = int(a.get("group", 1))
                dn = (("NCW", "OIW", "NCW") if sp == 1
                      else ("NCHW", "OIHW", "NCHW"))
                out = lax.conv_general_dilated(
                    x[0], w_c, strides, _pairs(pads),
                    rhs_dilation=dil, feature_group_count=groups,
                    dimension_numbers=dn)
                if len(x) > 2 and x[2] is not None:
                    bias_shape = (1, -1) + (1,) * sp
                    out = out + jnp.asarray(x[2]).reshape(bias_shape)
            elif op == "BatchNormalization":
                eps = a.get("epsilon", 1e-5)
                scale, b, mean, var = (jnp.asarray(t) for t in x[1:5])
                inv = scale / jnp.sqrt(var + eps)
                out = (x[0] - mean[None, :, None, None]) \
                    * inv[None, :, None, None] + b[None, :, None, None]
            elif op == "Relu":
                out = jnp.maximum(x[0], 0)
            elif op in ("MaxPool", "AveragePool"):
                ks = a["kernel_shape"]
                strides = a.get("strides", [1] * len(ks))
                pads = _pairs(a.get("pads", [0] * (2 * len(ks))))
                if op == "MaxPool":
                    init, fn = -jnp.inf, lax.max
                    out = lax.reduce_window(
                        x[0], init, fn, (1, 1) + tuple(ks),
                        (1, 1) + tuple(strides),
                        [(0, 0), (0, 0)] + pads)
                else:
                    s = lax.reduce_window(
                        x[0], 0.0, lax.add, (1, 1) + tuple(ks),
                        (1, 1) + tuple(strides),
                        [(0, 0), (0, 0)] + pads)
                    if a.get("count_include_pad", 0):
                        out = s / float(np.prod(ks))
                    else:
                        ones = jnp.ones_like(x[0])
                        cnt = lax.reduce_window(
                            ones, 0.0, lax.add, (1, 1) + tuple(ks),
                            (1, 1) + tuple(strides),
                            [(0, 0), (0, 0)] + pads)
                        out = s / cnt
            elif op == "GlobalAveragePool":
                out = jnp.mean(x[0], axis=tuple(range(2, x[0].ndim)),
                               keepdims=True)
            elif op == "Add":
                out = x[0] + x[1]
            elif op == "Sub":
                out = x[0] - x[1]
            elif op == "Mul":
                out = x[0] * x[1]
            elif op == "Div":
                out = x[0] / x[1]
            elif op == "Pow":
                out = x[0] ** x[1]
            elif op == "Neg":
                out = -x[0]
            elif op == "Exp":
                out = jnp.exp(x[0])
            elif op == "Erf":
                out = lax.erf(x[0])
            elif op == "Where":
                out = jnp.where(x[0], x[1], x[2])
            elif op == "Sqrt":
                out = jnp.sqrt(x[0])
            elif op == "Sigmoid":
                out = jax.nn.sigmoid(x[0])
            elif op == "Tanh":
                out = jnp.tanh(x[0])
            elif op == "LeakyRelu":
                alpha = a.get("alpha", 0.01)
                out = jnp.where(x[0] >= 0, x[0], alpha * x[0])
            elif op in ("Softmax", "LogSoftmax"):
                fn = jax.nn.softmax if op == "Softmax" \
                    else jax.nn.log_softmax
                if self.opset >= 13:
                    out = fn(x[0], axis=int(a.get("axis", -1)))
                else:
                    # legacy semantics: flatten to 2D at axis, softmax
                    # over the trailing block, restore shape
                    ax = int(a.get("axis", 1)) % x[0].ndim
                    shape = x[0].shape
                    flat = x[0].reshape(
                        (int(np.prod(shape[:ax])) if ax else 1, -1))
                    out = fn(flat, axis=-1).reshape(shape)
            elif op == "Gemm":
                alpha = a.get("alpha", 1.0)
                beta = a.get("beta", 1.0)
                A = x[0].T if a.get("transA", 0) else x[0]
                B = jnp.asarray(x[1])
                if a.get("transB", 0):
                    B = B.T
                out = alpha * (A @ B)
                if len(x) > 2 and x[2] is not None:
                    out = out + beta * jnp.asarray(x[2])
            elif op == "MatMul":
                out = x[0] @ jnp.asarray(x[1])
            elif op == "Flatten":
                ax = int(a.get("axis", 1))
                shape = x[0].shape
                out = x[0].reshape(
                    (int(np.prod(shape[:ax])) if ax else 1, -1))
            elif op == "Reshape":
                target = self._static_ints(node, 1, x)
                shape = list(x[0].shape)
                target = [shape[i] if t == 0 else int(t)
                          for i, t in enumerate(target)]
                out = x[0].reshape(target)
            elif op == "Transpose":
                perm = a.get("perm")
                out = _lib_for(x[0]).transpose(
                    x[0], tuple(perm) if perm else None)
            elif op == "Concat":
                # shape-computing chains stay concrete: jnp ops stage
                # even concrete operands under jit, so pure-numpy
                # inputs must concat in numpy
                parts = [t for t in x if t is not None]
                lib = _lib_for(*parts)
                out = lib.concatenate(
                    [np.atleast_1d(t) if _concrete_np(t) else t
                     for t in parts], axis=int(a["axis"]))
            elif op == "Squeeze":
                axes = (a.get("axes") if self.opset < 13
                        else self._static_ints(node, 1, x))
                lib = _lib_for(x[0])
                if axes:
                    out = lib.squeeze(
                        x[0], axis=tuple(ax % x[0].ndim for ax in axes))
                else:
                    out = lib.squeeze(x[0])
            elif op == "Unsqueeze":
                axes = (a.get("axes") if self.opset < 13
                        else self._static_ints(node, 1, x))
                ndim = x[0].ndim + len(axes)
                lib = _lib_for(x[0])
                out = lib.expand_dims(
                    x[0], axis=tuple(ax % ndim for ax in axes))
            elif op == "Slice":
                if self.opset < 10:
                    starts = list(a["starts"])
                    ends = list(a["ends"])
                    axes = list(a.get("axes", range(len(starts))))
                    steps = [1] * len(starts)
                else:
                    starts = self._static_ints(node, 1, x)
                    ends = self._static_ints(node, 2, x)
                    axes = self._static_ints(node, 3, x) \
                        or list(range(len(starts)))
                    steps = self._static_ints(node, 4, x) \
                        or [1] * len(starts)
                idx: List[Any] = [slice(None)] * x[0].ndim
                for st, en, ax, sp in zip(starts, ends, axes, steps):
                    # spec: huge sentinels mean "to the end"
                    en_s = None if en >= _INT32_MAX else en
                    st_s = None if (sp < 0 and st >= _INT32_MAX) else st
                    if sp < 0 and en <= -_INT32_MAX:
                        en_s = None
                    idx[ax % x[0].ndim] = slice(st_s, en_s, sp)
                out = x[0][tuple(idx)]
            elif op == "Split":
                ax = int(a.get("axis", 0)) % x[0].ndim
                sizes = (list(a["split"]) if "split" in a
                         else self._static_ints(node, 1, x))
                n_out = len([o for o in node.outputs if o])
                if sizes is None:
                    # even split; ONNX lets the LAST chunk be smaller
                    # when the axis is not divisible (ceil-sized rest)
                    n_out = int(a.get("num_outputs", n_out))
                    dim = x[0].shape[ax]
                    chunk = -(-dim // n_out)
                    sizes = [chunk] * (dim // chunk)
                    if dim % chunk:
                        sizes.append(dim % chunk)
                    if len(sizes) != n_out:
                        raise ValueError(
                            f"{_node_label(node)}: cannot split axis "
                            f"of size {dim} into {n_out} outputs")
                bounds = np.cumsum(sizes)[:-1].tolist()
                out = tuple(jnp.split(x[0], bounds, axis=ax))
            elif op == "Expand":
                target = self._static_ints(node, 1, x)
                # ONNX Expand: bidirectional broadcast; a target dim of
                # 1 keeps the input's dim
                shape = list(x[0].shape)
                nd = max(len(target), len(shape))
                shape = [1] * (nd - len(shape)) + shape
                target = [1] * (nd - len(target)) + list(target)
                final = [max(s_, int(t)) for s_, t in zip(shape, target)]
                out = jnp.broadcast_to(
                    x[0].reshape(shape) if len(shape) != x[0].ndim
                    else x[0], final)
            elif op == "Shape":
                # array shapes are static under jit — returning numpy
                # keeps Shape->Gather->Concat->Reshape chains concrete.
                # start/end slicing attrs (opset 15+) honored; defaults
                # cover the whole rank
                r = x[0].ndim
                st = int(a.get("start", 0))
                en = a.get("end")
                en = r if en is None else int(en)
                st = max(st + r, 0) if st < 0 else min(st, r)
                en = max(en + r, 0) if en < 0 else min(en, r)
                out = np.asarray(x[0].shape[st:en], dtype=np.int64)
            elif op == "Gather":
                ax = int(a.get("axis", 0))
                if _lib_for(x[0], x[1]) is np:
                    # keep Shape-derived chains concrete numpy so a
                    # downstream Reshape can use them as a static target
                    out = np.take(np.asarray(x[0]), np.asarray(x[1]),
                                  axis=ax)
                else:
                    out = jnp.take(jnp.asarray(x[0]), x[1], axis=ax)
            elif op == "Cast":
                out = _lib_for(x[0]).asarray(x[0]).astype(
                    _TENSOR_DTYPES[a["to"]])
            elif op in ("ReduceMean", "ReduceSum", "ReduceMax",
                        "ReduceMin"):
                # axes: attribute in old opsets, input once moved
                # (ReduceSum at 13, the others at 18)
                moved = 13 if op == "ReduceSum" else 18
                axes = (a.get("axes") if self.opset < moved
                        else self._static_ints(node, 1, x))
                keep = bool(a.get("keepdims", 1))
                if not axes and self.opset >= moved and \
                        a.get("noop_with_empty_axes", 0):
                    out = x[0]
                else:
                    fn = {"ReduceMean": jnp.mean, "ReduceSum": jnp.sum,
                          "ReduceMax": jnp.max,
                          "ReduceMin": jnp.min}[op]
                    out = fn(x[0], axis=tuple(axes) if axes else None,
                             keepdims=keep)
            elif op in ("Min", "Max"):
                fn = jnp.minimum if op == "Min" else jnp.maximum
                out = x[0]
                for t in x[1:]:
                    out = fn(out, t)
            elif op in ("ArgMax", "ArgMin"):
                fn = jnp.argmax if op == "ArgMax" else jnp.argmin
                ax = int(a.get("axis", 0))
                out = fn(x[0], axis=ax)
                if int(a.get("keepdims", 1)):
                    out = jnp.expand_dims(out, ax)
                out = out.astype(jnp.int32)
            elif op == "Identity":
                out = x[0]
            elif op == "Constant":
                out = _constant_value(a)
                if out is None:  # pragma: no cover — load validated
                    raise ValueError(
                        f"{_node_label(node)}: no supported value "
                        f"attribute (have {sorted(a)})")
            elif op == "Clip":
                lo = x[1] if len(x) > 1 and x[1] is not None \
                    else a.get("min", -np.inf)
                hi = x[2] if len(x) > 2 and x[2] is not None \
                    else a.get("max", np.inf)
                out = jnp.clip(x[0], lo, hi)
            elif op == "LSTM":
                out = self._lstm(node, x, a)
            elif op == "GRU":
                out = self._gru(node, x, a)
            else:  # pragma: no cover — load_onnx validated the op set
                raise ValueError(f"unsupported op {op}")
            outs_t = out if isinstance(out, tuple) else (out,)
            for oname, oval in zip(node.outputs, outs_t):
                if oname:
                    env[oname] = oval
        outs = [env[o] for o in self.output_names]
        return outs[0] if len(outs) == 1 else tuple(outs)

    @staticmethod
    def _lstm(node: OnnxNode, x: List[Any], a: Dict[str, Any]):
        """ONNX LSTM (gate order i,o,f,c; activations sigmoid/tanh/tanh
        — load_onnx rejected anything else). TPU-first: the input
        projection X@W^T for the WHOLE sequence is hoisted out of the
        recurrence into one (T*B, I)x(I, 4H) MXU matmul; lax.scan only
        carries the (B, 4H) recurrent matmul. Returns the full ONNX
        output triple (Y [T, dirs, B, H], Y_h, Y_c)."""
        import jax
        import jax.numpy as jnp
        from jax import lax

        X = x[0]                                   # (T, B, I)
        W = jnp.asarray(x[1])                      # (D, 4H, I)
        R = jnp.asarray(x[2])                      # (D, 4H, H)
        hid = R.shape[-1]
        bsz = X.shape[1]
        bias = jnp.asarray(x[3]) if len(x) > 3 and x[3] is not None \
            else None                              # (D, 8H)
        h0 = x[5] if len(x) > 5 and x[5] is not None else None
        c0 = x[6] if len(x) > 6 and x[6] is not None else None

        def run_dir(d: int, reverse: bool):
            Wd, Rd = W[d], R[d]
            if bias is not None:
                bsum = bias[d, :4 * hid] + bias[d, 4 * hid:]
            else:
                bsum = jnp.zeros((4 * hid,), X.dtype)
            h = h0[d] if h0 is not None \
                else jnp.zeros((bsz, hid), X.dtype)
            c = c0[d] if c0 is not None \
                else jnp.zeros((bsz, hid), X.dtype)
            xs = jnp.flip(X, 0) if reverse else X
            xw = xs @ Wd.T + bsum                  # (T, B, 4H) on MXU

            def step(carry, xt):
                h, c = carry
                g = xt + h @ Rd.T
                i, o, f, cc = jnp.split(g, 4, axis=-1)
                i = jax.nn.sigmoid(i)
                o = jax.nn.sigmoid(o)
                f = jax.nn.sigmoid(f)
                cc = jnp.tanh(cc)
                c = f * c + i * cc
                h = o * jnp.tanh(c)
                return (h, c), h

            (hT, cT), ys = lax.scan(step, (h, c), xw)
            if reverse:
                ys = jnp.flip(ys, 0)
            return ys, hT, cT

        direction = a.get("direction", "forward")
        revs = {"forward": [False], "reverse": [True],
                "bidirectional": [False, True]}[direction]
        ys_l, h_l, c_l = [], [], []
        for d, rev in enumerate(revs):
            ys, hT, cT = run_dir(d, rev)
            ys_l.append(ys)
            h_l.append(hT)
            c_l.append(cT)
        Y = jnp.stack(ys_l, axis=1)                # (T, D, B, H)
        return Y, jnp.stack(h_l, 0), jnp.stack(c_l, 0)


    @staticmethod
    def _gru(node: OnnxNode, x: List[Any], a: Dict[str, Any]):
        """ONNX GRU (gate order z,r,h; activations sigmoid/tanh —
        load_onnx rejected anything else). Same TPU-first hoist as the
        LSTM: the whole-sequence input projection is one MXU matmul;
        lax.scan carries only the recurrent part. Honors
        ``linear_before_reset`` both ways (=1 is what torch exports).
        Returns (Y [T, dirs, B, H], Y_h [dirs, B, H])."""
        import jax
        import jax.numpy as jnp
        from jax import lax

        X = x[0]                                   # (T, B, I)
        W = jnp.asarray(x[1])                      # (D, 3H, I)
        R = jnp.asarray(x[2])                      # (D, 3H, H)
        hid = R.shape[-1]
        bsz = X.shape[1]
        bias = jnp.asarray(x[3]) if len(x) > 3 and x[3] is not None \
            else None                              # (D, 6H)
        h0 = x[5] if len(x) > 5 and x[5] is not None else None
        lbr = int(a.get("linear_before_reset", 0))

        def run_dir(d: int, reverse: bool):
            Wd, Rd = W[d], R[d]
            if bias is not None:
                wb = bias[d, :3 * hid]             # (3H,)
                rb = bias[d, 3 * hid:]             # (3H,)
            else:
                wb = rb = jnp.zeros((3 * hid,), X.dtype)
            h = h0[d] if h0 is not None \
                else jnp.zeros((bsz, hid), X.dtype)
            xs = jnp.flip(X, 0) if reverse else X
            xw = xs @ Wd.T + wb                    # (T, B, 3H) on MXU
            Rz, Rr, Rh = jnp.split(Rd, 3, axis=0)
            rbz, rbr, rbh = jnp.split(rb, 3)

            def step(h, xt):
                xz, xr, xh = jnp.split(xt, 3, axis=-1)
                z = jax.nn.sigmoid(xz + h @ Rz.T + rbz)
                r = jax.nn.sigmoid(xr + h @ Rr.T + rbr)
                if lbr:
                    hh = jnp.tanh(xh + r * (h @ Rh.T + rbh))
                else:
                    hh = jnp.tanh(xh + (r * h) @ Rh.T + rbh)
                h = (1 - z) * hh + z * h
                return h, h

            hT, ys = lax.scan(step, h, xw)
            if reverse:
                ys = jnp.flip(ys, 0)
            return ys, hT

        direction = a.get("direction", "forward")
        revs = {"forward": [False], "reverse": [True],
                "bidirectional": [False, True]}[direction]
        ys_l, h_l = [], []
        for d, rev in enumerate(revs):
            ys, hT = run_dir(d, rev)
            ys_l.append(ys)
            h_l.append(hT)
        return jnp.stack(ys_l, axis=1), jnp.stack(h_l, 0)


def import_onnx_model(path: str, batch_size: int = 64,
                      input_shape=None, feed_cols=None):
    """ONNX file -> ready-to-serve TPUModel (the ModelDownloader /
    ImageFeaturizer contract). Weights are the graph initializers; the
    modelFn is the jax graph executor.

    Single-input graphs feed from the ``images`` column; ``input_shape``
    (e.g. [3, 224, 224]) unflattens table rows, inferred from the
    graph's declared input shape when omitted (trailing dims after the
    batch axis — a symbolic batch dim_param is the dynamic-batch
    convention). Integer-typed graph inputs (token ids) make the model
    feed int32 rows instead of floats.

    MULTI-input graphs (two-tower scorers, sequence+mask models) feed
    each graph input from the table column of the same name —
    ``feed_cols={input_name: column}`` overrides the mapping;
    ``input_shape`` may then be a {input_name: shape} dict. All inputs
    must share one element class (all integer or all float): TPUModel's
    feed casts per model, not per column."""
    from mmlspark_tpu.models.tpu_model import TPUModel

    graph = load_onnx(path)
    if not graph.inputs:
        raise ValueError("graph declares no runtime inputs")
    elems = [graph.input_infos.get(n, (None, None))[0]
             for n in graph.inputs]
    int_flags = {e in _INT_ELEM_TYPES for e in elems if e is not None}
    if len(int_flags) > 1:
        raise ValueError(
            f"graph mixes integer and float inputs "
            f"({dict(zip(graph.inputs, elems))}); TPUModel feeds one "
            f"element class per model — split the graph or cast inside "
            f"it")
    if feed_cols:
        unknown = sorted(set(feed_cols) - set(graph.inputs))
        if unknown:
            raise ValueError(
                f"feed_cols keys {unknown} are not graph inputs "
                f"{graph.inputs}")
    if isinstance(input_shape, dict):
        unknown = sorted(set(input_shape) - set(graph.inputs))
        if unknown:
            raise ValueError(
                f"input_shape keys {unknown} are not graph inputs "
                f"{graph.inputs}")
    apply_fn = OnnxApply(graph, input_shape=input_shape)

    def _declared(name):
        _e, dims = graph.input_infos.get(name, (None, None))
        if dims and len(dims) > 1 and all(
                d is not None for d in dims[1:]):
            return tuple(dims[1:])
        return None

    shared = dict(
        modelFn=apply_fn,
        weights={k: np.asarray(v)
                 for k, v in graph.initializers.items()},
        outputCol="scores", batchSize=batch_size,
        computeDtype="float32")
    if len(graph.inputs) == 1:
        if apply_fn.input_shape is None:
            apply_fn.input_shape = _declared(graph.inputs[0])
        return TPUModel(inputCol="images", **shared)
    if apply_fn.input_shape is not None and not isinstance(
            apply_fn.input_shape, dict):
        raise ValueError(
            "multi-input graphs need input_shape as a "
            "{input_name: shape} dict (or omitted)")
    # a PARTIAL dict still infers the unlisted inputs from the
    # declared value infos (an explicit None entry disables)
    given = dict(apply_fn.input_shape or {})
    apply_fn.input_shape = {
        n: given[n] if n in given else _declared(n)
        for n in graph.inputs}
    feed = {n: (feed_cols or {}).get(n, n) for n in graph.inputs}
    return TPUModel(feedDict=feed, **shared)


def onnx_summary(path: str) -> Dict[str, Any]:
    """Structural manifest of an ONNX file (op histogram, opset,
    initializer count/bytes, inputs/outputs) — the validation hook
    ModelDownloader schemas record, mirroring the torchvision manifest
    discipline."""
    graph = load_onnx(path)
    ops: Dict[str, int] = {}
    for node in graph.nodes:
        ops[node.op_type] = ops.get(node.op_type, 0) + 1
    return {
        "ops": dict(sorted(ops.items())),
        "opset": graph.opset,
        "num_initializers": len(graph.initializers),
        "initializer_bytes": int(sum(
            v.nbytes for v in graph.initializers.values())),
        "inputs": graph.inputs,
        "outputs": graph.outputs,
    }
