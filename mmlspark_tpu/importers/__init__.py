"""Pretrained-graph ingestion.

The reference's inference story rests on loading *externally trained*
graphs — CNTKModel deserializes a trained CNTK Function
(ref: src/cntk-model/src/main/scala/CNTKModel.scala:147,
SerializableFunction.scala:85) and ModelDownloader fetches CNN zoo models
(ref: src/downloader/src/main/scala/ModelDownloader.scala:209). The
TPU-native equivalent ingests torch checkpoints (state_dicts) into flax
variable pytrees for the zoo network specs, and ONNX graphs (the
framework-neutral interchange format) through a dependency-free reader
+ jax executor.
"""

from mmlspark_tpu.importers.onnx_import import (
    OnnxApply, import_onnx_model, load_onnx, onnx_summary,
)
from mmlspark_tpu.importers.torch_import import (
    TORCHVISION_RESNET18_SPEC, TORCHVISION_RESNET34_SPEC,
    import_torch_checkpoint, import_torchvision_resnet,
    load_checkpoint_file, load_safetensors_file, load_torch_file,
)

__all__ = [
    "TORCHVISION_RESNET18_SPEC", "TORCHVISION_RESNET34_SPEC",
    "OnnxApply", "import_onnx_model", "import_torch_checkpoint",
    "import_torchvision_resnet", "load_checkpoint_file", "load_onnx",
    "load_safetensors_file", "load_torch_file", "onnx_summary",
]
