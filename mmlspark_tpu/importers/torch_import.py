"""torch state_dict -> flax variables importer.

The TPU-native analog of the reference's pretrained-graph ingestion
(ref: src/cntk-model/.../CNTKModel.scala:147 deserializes a trained CNTK
Function; ModelDownloader.scala:209 fetches zoo CNNs): weights trained
*outside* this framework become flax variable pytrees for the zoo network
specs (models/networks.build_network), after which TPUModel /
ImageFeaturizer serve them like any native model.

Layout conversions (torch -> flax):
  - Conv2d weight  (O, I, kH, kW) -> kernel (kH, kW, I, O)
  - Linear weight  (O, I)         -> kernel (I, O)
  - BatchNorm weight/bias         -> scale/bias params;
    running_mean/running_var      -> batch_stats mean/var
  - Embedding weight              -> embedding (unchanged)

Name conventions accepted per family:
  - resnet: torchvision naming — ``conv1``/``bn1`` stem,
    ``layer{s+1}.{b}.conv1/bn1/conv2/bn2[/downsample.0/.1]``, ``fc``
    head. Covers BOTH CIFAR-style stage counts and the PUBLISHED
    ImageNet checkpoints (``import_torchvision_resnet`` validates a
    resnet18/34 file against the exact key/shape manifest; .pth and
    .safetensors both load — see load_checkpoint_file).
  - convnet: ``conv{i}``, ``dense{i}``, ``head``.
  - mlp: ``dense{i}``, ``head``.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np


def _to_numpy(t: Any) -> np.ndarray:
    if hasattr(t, "detach"):          # torch tensor
        t = t.detach().cpu().numpy()
    return np.asarray(t, dtype=np.float32)


def _conv_kernel(t: Any) -> np.ndarray:
    """torch OIHW -> flax HWIO."""
    return np.transpose(_to_numpy(t), (2, 3, 1, 0))


def _linear_kernel(t: Any) -> np.ndarray:
    """torch (out, in) -> flax (in, out)."""
    return np.transpose(_to_numpy(t))


def load_torch_file(path: str) -> Dict[str, Any]:
    """Load a .pt/.pth checkpoint to a flat state_dict (CPU tensors)."""
    import torch
    obj = torch.load(path, map_location="cpu", weights_only=True)
    if isinstance(obj, dict) and "state_dict" in obj:
        obj = obj["state_dict"]
    return obj


_SAFETENSORS_DTYPES = {
    "F64": np.float64, "F32": np.float32, "F16": np.float16,
    "I64": np.int64, "I32": np.int32, "I16": np.int16, "I8": np.int8,
    "U8": np.uint8, "BOOL": np.bool_,
}


def load_safetensors_file(path: str) -> Dict[str, np.ndarray]:
    """Dependency-free safetensors reader (the format hugging-face zoo
    checkpoints ship in): u64-LE header length, JSON header mapping
    tensor name -> {dtype, shape, data_offsets}, then raw little-endian
    tensor bytes. BF16 decodes via ml_dtypes (bundled with jax)."""
    import json
    import struct
    with open(path, "rb") as f:
        (hlen,) = struct.unpack("<Q", f.read(8))
        header = json.loads(f.read(hlen).decode("utf-8"))
        blob = f.read()
    out: Dict[str, np.ndarray] = {}
    for name, meta in header.items():
        if name == "__metadata__":
            continue
        lo, hi = meta["data_offsets"]
        dt = meta["dtype"]
        if dt == "BF16":
            import ml_dtypes
            arr = np.frombuffer(blob[lo:hi], dtype=ml_dtypes.bfloat16)
        elif dt in _SAFETENSORS_DTYPES:
            arr = np.frombuffer(blob[lo:hi], dtype=_SAFETENSORS_DTYPES[dt])
        else:
            raise ValueError(f"unsupported safetensors dtype {dt!r}")
        if dt in ("F16", "BF16"):
            out[name] = arr.reshape(meta["shape"]).astype(np.float32)
        else:
            # frombuffer views are read-only; downstream in-place edits
            # of imported tensors would raise — hand out owned arrays
            out[name] = arr.reshape(meta["shape"]).copy()
    return out


def load_checkpoint_file(path: str) -> Dict[str, Any]:
    """Extension-dispatched checkpoint reader: .safetensors or torch
    .pt/.pth/.bin."""
    if path.endswith(".safetensors"):
        return load_safetensors_file(path)
    return load_torch_file(path)


class _TreeBuilder:
    """Accumulates nested params/batch_stats trees and tracks which
    state_dict keys were consumed (unused keys are an import error —
    silent drops are how weight-porting bugs hide)."""

    def __init__(self, sd: Dict[str, Any]):
        self.sd = dict(sd)
        self.used: set = set()
        self.params: Dict[str, Any] = {}
        self.stats: Dict[str, Any] = {}

    def take(self, key: str) -> Any:
        if key not in self.sd:
            raise KeyError(
                f"torch checkpoint is missing {key!r}; available keys "
                f"include {sorted(self.sd)[:8]}...")
        self.used.add(key)
        return self.sd[key]

    def has(self, key: str) -> bool:
        return key in self.sd

    def _set(self, tree: Dict[str, Any], path: List[str], val: np.ndarray):
        node = tree
        for part in path[:-1]:
            node = node.setdefault(part, {})
        node[path[-1]] = val

    def conv(self, flax_path: List[str], torch_name: str,
             bias: bool = False):
        self._set(self.params, flax_path + ["kernel"],
                  _conv_kernel(self.take(f"{torch_name}.weight")))
        if bias:
            self._set(self.params, flax_path + ["bias"],
                      _to_numpy(self.take(f"{torch_name}.bias")))

    def linear(self, flax_path: List[str], torch_name: str):
        self._set(self.params, flax_path + ["kernel"],
                  _linear_kernel(self.take(f"{torch_name}.weight")))
        self._set(self.params, flax_path + ["bias"],
                  _to_numpy(self.take(f"{torch_name}.bias")))

    def layernorm(self, flax_path: List[str], torch_name: str):
        self._set(self.params, flax_path + ["scale"],
                  _to_numpy(self.take(f"{torch_name}.weight")))
        self._set(self.params, flax_path + ["bias"],
                  _to_numpy(self.take(f"{torch_name}.bias")))

    def batchnorm(self, flax_path: List[str], torch_name: str):
        self._set(self.params, flax_path + ["scale"],
                  _to_numpy(self.take(f"{torch_name}.weight")))
        self._set(self.params, flax_path + ["bias"],
                  _to_numpy(self.take(f"{torch_name}.bias")))
        self._set(self.stats, flax_path + ["mean"],
                  _to_numpy(self.take(f"{torch_name}.running_mean")))
        self._set(self.stats, flax_path + ["var"],
                  _to_numpy(self.take(f"{torch_name}.running_var")))

    def finish(self, strict: bool = True) -> Dict[str, Any]:
        if strict:
            unused = [k for k in self.sd
                      if k not in self.used
                      and not k.endswith("num_batches_tracked")]
            if unused:
                raise ValueError(
                    f"torch checkpoint keys not consumed by the import "
                    f"(shape/name mismatch?): {sorted(unused)}")
        out: Dict[str, Any] = {"params": self.params}
        if self.stats:
            out["batch_stats"] = self.stats
        return out


def _import_resnet(sd: Dict[str, Any], spec: Dict[str, Any],
                   strict: bool,
                   input_shape: Optional[List[int]]) -> Dict[str, Any]:
    b = _TreeBuilder(sd)
    b.conv(["stem"], "conv1")
    b.batchnorm(["BatchNorm_0"], "bn1")
    stage_sizes = list(spec.get("stage_sizes", (3, 3, 3)))
    for s, n_blocks in enumerate(stage_sizes):
        for blk in range(n_blocks):
            t = f"layer{s + 1}.{blk}"
            fx = f"stage{s}_block{blk}"
            b.conv([fx, "Conv_0"], f"{t}.conv1")
            b.batchnorm([fx, "BatchNorm_0"], f"{t}.bn1")
            b.conv([fx, "Conv_1"], f"{t}.conv2")
            b.batchnorm([fx, "BatchNorm_1"], f"{t}.bn2")
            if b.has(f"{t}.downsample.0.weight"):
                b.conv([fx, "proj"], f"{t}.downsample.0")
                b.batchnorm([fx, "BatchNorm_2"], f"{t}.downsample.1")
    b.linear(["head"], "fc")
    return b.finish(strict)


def _import_convnet(sd: Dict[str, Any], spec: Dict[str, Any],
                    strict: bool,
                    input_shape: Optional[List[int]]) -> Dict[str, Any]:
    b = _TreeBuilder(sd)
    conv_features = list(spec.get("conv_features", (64, 64, 64)))
    pool_every = int(spec.get("pool_every", 1))
    for i in range(len(conv_features)):
        b.conv([f"conv_{i}"], f"conv{i}", bias=True)
    for i in range(len(spec.get("dense_features", (256,)))):
        b.linear([f"dense_{i}"], f"dense{i}")
    b.linear(["head"], "head")
    out = b.finish(strict)

    # flatten-boundary fix: torch flattens NCHW (C,H,W order), flax
    # flattens NHWC (H,W,C order) — permute the input dim of the first
    # Dense after the flatten (dense_0, or the head when there are no
    # dense layers). Needs the conv-stack output spatial shape, so
    # input_shape is mandatory for convnet imports: skipping the
    # permutation would load cleanly and predict garbage.
    if input_shape is None:
        raise ValueError(
            "convnet imports require validate_input_shape (e.g. "
            "[32, 32, 3]): the flatten-boundary NCHW->NHWC kernel "
            "permutation needs the conv-stack output shape")
    h, w, _ = input_shape
    for i in range(len(conv_features)):
        if (i + 1) % pool_every == 0:
            h, w = h // 2, w // 2
    c = conv_features[-1]
    first_dense = "dense_0" if "dense_0" in out["params"] else "head"
    k = out["params"][first_dense]["kernel"]          # (C*H*W, O)
    if k.shape[0] != c * h * w:
        raise ValueError(
            f"{first_dense} kernel input dim {k.shape[0]} != "
            f"C*H*W={c * h * w} from input_shape {input_shape}")
    k = k.reshape(c, h, w, -1).transpose(1, 2, 0, 3).reshape(h * w * c, -1)
    out["params"][first_dense]["kernel"] = k
    return out


def _import_mlp(sd: Dict[str, Any], spec: Dict[str, Any],
                strict: bool,
                input_shape: Optional[List[int]]) -> Dict[str, Any]:
    b = _TreeBuilder(sd)
    for i in range(len(spec.get("features", (256, 128)))):
        b.linear([f"dense_{i}"], f"dense{i}")
    b.linear(["head"], "head")
    return b.finish(strict)


def _import_bilstm(sd: Dict[str, Any], spec: Dict[str, Any],
                   strict: bool,
                   input_shape: Optional[List[int]]) -> Dict[str, Any]:
    """torch bidirectional ``nn.LSTM`` -> BiLSTMTagger variables
    (the notebook-304 pretrained Bi-LSTM ingestion path).

    Expected torch names: ``embed`` (nn.Embedding), ``lstm``
    (nn.LSTM(bidirectional=True, batch_first=True)), ``head``
    (nn.Linear). torch packs gates (i, f, g, o) along dim 0 of
    ``weight_ih/hh``; flax's OptimizedLSTMCell keeps one Dense per gate
    with the bias only on the recurrent half, so torch's two biases are
    summed."""
    h = int(spec.get("hidden", 128))
    b = _TreeBuilder(sd)
    b._set(b.params, ["embed", "embedding"],
           _to_numpy(b.take("embed.weight")))
    # forward cell = OptimizedLSTMCell_0, reverse = _1 (creation order
    # in BiLSTMTagger.__call__)
    for suffix, cell in (("", "OptimizedLSTMCell_0"),
                         ("_reverse", "OptimizedLSTMCell_1")):
        wih = _to_numpy(b.take(f"lstm.weight_ih_l0{suffix}"))   # (4H, E)
        whh = _to_numpy(b.take(f"lstm.weight_hh_l0{suffix}"))   # (4H, H)
        bias = (_to_numpy(b.take(f"lstm.bias_ih_l0{suffix}"))
                + _to_numpy(b.take(f"lstm.bias_hh_l0{suffix}")))
        for gi, gate in enumerate("ifgo"):
            sl = slice(gi * h, (gi + 1) * h)
            b._set(b.params, [cell, f"i{gate}", "kernel"], wih[sl].T)
            b._set(b.params, [cell, f"h{gate}", "kernel"], whh[sl].T)
            b._set(b.params, [cell, f"h{gate}", "bias"], bias[sl])
    b.linear(["head"], "head")
    return b.finish(strict)


def _import_transformer(sd: Dict[str, Any], spec: Dict[str, Any],
                        strict: bool,
                        input_shape: Optional[List[int]]) -> Dict[str, Any]:
    """GPT-2-shaped torch decoder -> Transformer variables.

    Expected torch names (the GPT-2 block structure with fused qkv):
    ``embed`` (nn.Embedding), ``pos_embed`` (nn.Parameter (max_len, D)),
    ``block_{i}.ln1/qkv/proj/ln2/mlp_up/mlp_down``, ``ln_f``, and
    ``lm_head`` (or ``head`` when num_classes > 0). qkv packs q|k|v
    along the output dim, matching TransformerBlock's fused Dense."""
    b = _TreeBuilder(sd)
    b._set(b.params, ["embed", "embedding"],
           _to_numpy(b.take("embed.weight")))
    b._set(b.params, ["pos_embed"], _to_numpy(b.take("pos_embed")))
    for i in range(int(spec.get("depth", 4))):
        t = f"block_{i}"
        for ln in ("ln1", "ln2"):
            b.layernorm([t, ln], f"{t}.{ln}")
        for lin in ("qkv", "proj", "mlp_up", "mlp_down"):
            b.linear([t, lin], f"{t}.{lin}")
    b.layernorm(["ln_f"], "ln_f")
    head = "head" if int(spec.get("num_classes", 0)) > 0 else "lm_head"
    b.linear([head], head)
    return b.finish(strict)


_IMPORTERS = {
    "resnet": _import_resnet,
    "convnet": _import_convnet,
    "mlp": _import_mlp,
    "bilstm": _import_bilstm,
    "transformer": _import_transformer,
}


def import_torch_checkpoint(state_dict: Any, network_spec: Dict[str, Any],
                            strict: bool = True,
                            validate_input_shape: Optional[List[int]] = None
                            ) -> Dict[str, Any]:
    """Convert a torch ``state_dict`` (dict or .pt path) to flax variables
    for ``network_spec`` (a models/networks.build_network spec).

    strict: fail on unconsumed checkpoint keys.
    validate_input_shape: when given (e.g. [32, 32, 3]), init the flax
    module on a dummy input and verify every imported array matches the
    module's expected tree structure and shapes. Also required for
    convnet imports (the flatten-boundary NCHW->NHWC permutation of the
    first dense kernel needs the conv-stack output shape).
    """
    if isinstance(state_dict, str):
        state_dict = load_checkpoint_file(state_dict)
    kind = network_spec.get("type")
    if kind not in _IMPORTERS:
        raise NotImplementedError(
            f"no torch importer for network type {kind!r}; "
            f"have {sorted(_IMPORTERS)}")
    variables = _IMPORTERS[kind](state_dict, network_spec, strict,
                                 validate_input_shape)

    if validate_input_shape is not None:
        _validate(variables, network_spec, validate_input_shape)
    return variables


def _validate(variables: Dict[str, Any], network_spec: Dict[str, Any],
              input_shape: List[int]) -> None:
    import jax
    import jax.numpy as jnp
    from mmlspark_tpu.models.networks import build_network
    module = build_network(network_spec)
    dummy_dtype = (jnp.int32 if getattr(module, "int_input", False)
                   else jnp.float32)
    target = module.init(jax.random.PRNGKey(0),
                         jnp.zeros([1] + list(input_shape), dummy_dtype))
    t_paths = {tuple(str(p.key) for p in path): leaf.shape
               for path, leaf in jax.tree_util.tree_leaves_with_path(target)}
    v_paths = {tuple(str(p.key) for p in path): leaf.shape
               for path, leaf in
               jax.tree_util.tree_leaves_with_path(variables)}
    missing = sorted(set(t_paths) - set(v_paths))
    extra = sorted(set(v_paths) - set(t_paths))
    bad = [(p, v_paths[p], t_paths[p]) for p in v_paths
           if p in t_paths and tuple(v_paths[p]) != tuple(t_paths[p])]
    if missing or extra or bad:
        raise ValueError(
            f"imported variables do not match module structure:\n"
            f"  missing: {missing}\n  extra: {extra}\n"
            f"  shape mismatches (path, got, want): {bad}")


# ---------------------------------------------------------------------------
# published torchvision ImageNet ResNets (BasicBlock family)
# ---------------------------------------------------------------------------

# the exact spec whose flax twin (models/networks.ResNet stem='imagenet')
# reproduces torchvision.models.resnet18 numerics
TORCHVISION_RESNET18_SPEC: Dict[str, Any] = {
    "type": "resnet", "stem": "imagenet", "stage_sizes": [2, 2, 2, 2],
    "width": 64, "num_classes": 1000,
}
TORCHVISION_RESNET34_SPEC: Dict[str, Any] = {
    "type": "resnet", "stem": "imagenet", "stage_sizes": [3, 4, 6, 3],
    "width": 64, "num_classes": 1000,
}


def _torchvision_manifest(stage_sizes: List[int], num_classes: int
                          ) -> Dict[str, tuple]:
    """Key -> shape manifest of a torchvision BasicBlock ResNet
    state_dict (the published resnet18/34 layout: ``conv1``/``bn1``
    stem, ``layer{1-4}.{b}.conv1/bn1/conv2/bn2[.downsample.0/.1]``,
    ``fc``; ref: ModelDownloader.scala:209 — zoo ingestion is anchored
    on real published checkpoints)."""
    m: Dict[str, tuple] = {"conv1.weight": (64, 3, 7, 7)}
    for tag, c in (("bn1", 64),):
        m[f"{tag}.weight"] = (c,)
        m[f"{tag}.bias"] = (c,)
        m[f"{tag}.running_mean"] = (c,)
        m[f"{tag}.running_var"] = (c,)
    cin = 64
    for s, n_blocks in enumerate(stage_sizes):
        cout = 64 * (2 ** s)
        for blk in range(n_blocks):
            t = f"layer{s + 1}.{blk}"
            stride_block = blk == 0 and s > 0
            m[f"{t}.conv1.weight"] = (cout, cin if blk == 0 else cout,
                                      3, 3)
            m[f"{t}.conv2.weight"] = (cout, cout, 3, 3)
            for bn in ("bn1", "bn2"):
                for suffix in ("weight", "bias", "running_mean",
                               "running_var"):
                    m[f"{t}.{bn}.{suffix}"] = (cout,)
            if blk == 0 and (stride_block or cin != cout):
                m[f"{t}.downsample.0.weight"] = (cout, cin, 1, 1)
                for suffix in ("weight", "bias", "running_mean",
                               "running_var"):
                    m[f"{t}.downsample.1.{suffix}"] = (cout,)
        cin = cout
    m["fc.weight"] = (num_classes, cin)
    m["fc.bias"] = (num_classes,)
    return m


def import_torchvision_resnet(source: Any,
                              spec: Optional[Dict[str, Any]] = None
                              ) -> Dict[str, Any]:
    """Import a PUBLISHED torchvision BasicBlock-ResNet checkpoint
    (resnet18 by default; pass TORCHVISION_RESNET34_SPEC for resnet34).

    ``source`` is a state_dict, .pth, or .safetensors path. The
    checkpoint is validated against the torchvision key/shape manifest
    BEFORE conversion, so a wrong or truncated download fails with the
    offending keys rather than a cryptic import error. Returns flax
    variables for ``build_network(spec)`` — serve through TPUModel /
    ImageFeaturizer like any zoo model (examples/301, 305)."""
    spec = dict(spec or TORCHVISION_RESNET18_SPEC)
    if isinstance(source, str):
        source = load_checkpoint_file(source)
    manifest = _torchvision_manifest(list(spec["stage_sizes"]),
                                     int(spec["num_classes"]))
    got = {k: tuple(np.asarray(_to_numpy(v)).shape)
           for k, v in source.items()
           if not k.endswith("num_batches_tracked")}
    missing = sorted(set(manifest) - set(got))
    extra = sorted(set(got) - set(manifest))
    bad = [(k, got[k], manifest[k]) for k in got
           if k in manifest and got[k] != manifest[k]]
    if missing or extra or bad:
        raise ValueError(
            f"not a torchvision ResNet{'18' if spec['stage_sizes'] == [2, 2, 2, 2] else ''} "
            f"state_dict:\n  missing: {missing[:6]}\n"
            f"  unexpected: {extra[:6]}\n"
            f"  shape mismatches (key, got, want): {bad[:6]}")
    return import_torch_checkpoint(source, spec, strict=True,
                                   validate_input_shape=[224, 224, 3])
