"""AutoML convenience tier.

Parity with the reference's L5 layer (ref: SURVEY.md §2 "L5 AutoML"):
Featurize/AssembleFeatures, TrainClassifier/TrainRegressor,
ComputeModelStatistics/ComputePerInstanceStatistics,
TuneHyperparameters + param spaces, FindBestModel.
"""

from mmlspark_tpu.automl.featurize import AssembleFeatures, Featurize
from mmlspark_tpu.automl.train import (
    TrainClassifier, TrainRegressor,
    TrainedClassifierModel, TrainedRegressorModel,
)
from mmlspark_tpu.automl.statistics import (
    ComputeModelStatistics, ComputePerInstanceStatistics,
)
from mmlspark_tpu.automl.tuning import (
    DiscreteHyperParam, FindBestModel, GridSpace, HyperparamBuilder,
    RandomSpace, RangeHyperParam, TuneHyperparameters,
)

__all__ = [
    "AssembleFeatures", "Featurize",
    "TrainClassifier", "TrainRegressor",
    "TrainedClassifierModel", "TrainedRegressorModel",
    "ComputeModelStatistics", "ComputePerInstanceStatistics",
    "TuneHyperparameters", "FindBestModel",
    "GridSpace", "RandomSpace", "HyperparamBuilder",
    "DiscreteHyperParam", "RangeHyperParam",
]
