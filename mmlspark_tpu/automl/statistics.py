"""Model evaluation as table-producing stages.

Analog of compute-model-statistics / compute-per-instance-statistics
(ref: src/compute-model-statistics/.../ComputeModelStatistics.scala:57,
src/compute-per-instance-statistics/.../ComputePerInstanceStatistics.scala:42):
evaluation metrics are a *table* a pipeline produces, not a side-channel
service. Classification: confusion matrix, accuracy, per-class precision/
recall (macro + micro), AUC + binned ROC for binary. Regression:
mse/rmse/r2/mae.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from mmlspark_tpu.core import metrics as MC
from mmlspark_tpu.core.params import ColParam, EnumParam, IntParam
from mmlspark_tpu.core.schema import Field, Schema, F64, VECTOR
from mmlspark_tpu.core.stage import Transformer
from mmlspark_tpu.core.table import DataTable


def roc_curve(y: np.ndarray, score: np.ndarray
              ) -> Tuple[np.ndarray, np.ndarray, float]:
    """(fpr, tpr, auc) via rank statistics — vectorized numpy.

    Tied scores are collapsed into one ROC point (a tied group moves
    diagonally), so AUC is exact and row-order independent."""
    order = np.argsort(-score, kind="stable")
    y_sorted = y[order]
    s_sorted = score[order]
    tps = np.cumsum(y_sorted)
    fps = np.cumsum(1 - y_sorted)
    if len(s_sorted):
        # keep only the last index of each tied-score group
        keep = np.r_[np.nonzero(np.diff(s_sorted))[0], len(s_sorted) - 1]
        tps, fps = tps[keep], fps[keep]
    n_pos = max(tps[-1], 1e-12) if len(tps) else 1e-12
    n_neg = max(fps[-1], 1e-12) if len(fps) else 1e-12
    tpr = np.concatenate([[0.0], tps / n_pos])
    fpr = np.concatenate([[0.0], fps / n_neg])
    auc = float(np.trapezoid(tpr, fpr))
    return fpr, tpr, auc


class ComputeModelStatistics(Transformer):
    """Evaluate scored tables (ref: ComputeModelStatistics.scala:57).

    Column discovery follows the reference's metadata-driven approach:
    defaults match what TrainClassifier/TPUBoost models emit
    (label / prediction / probability), overridable via params.
    """

    evaluationMetric = EnumParam(
        ["classification", "regression", "auto", MC.ALL_METRICS],
        "metric family", default="auto")
    labelCol = ColParam("ground-truth column", default="label")
    scoresCol = ColParam("prediction column", default="prediction")
    scoredProbabilitiesCol = ColParam("probability vector column",
                                      default="probability")
    numBins = IntParam("ROC bins (parity: BinaryClassificationMetrics)",
                       default=100)

    def _mode(self, table: DataTable) -> str:
        mode = self.get("evaluationMetric")
        if mode not in ("auto", MC.ALL_METRICS):
            return mode
        y = np.asarray(table[self.get("labelCol")], dtype=np.float64)
        distinct = np.unique(y[np.isfinite(y)])
        if len(distinct) <= max(20, int(np.sqrt(len(y)))) and \
                np.allclose(distinct, np.round(distinct)):
            return "classification"
        return "regression"

    def transform(self, table: DataTable) -> DataTable:
        y = np.asarray(table[self.get("labelCol")], dtype=np.float64)
        pred = np.asarray(table[self.get("scoresCol")], dtype=np.float64)
        if self._mode(table) == "regression":
            err = pred - y
            mse = float(np.mean(err ** 2))
            row = {
                MC.MSE: mse,
                MC.RMSE: float(np.sqrt(mse)),
                MC.R2: float(1.0 - mse / max(np.var(y), 1e-300)),
                MC.MAE: float(np.mean(np.abs(err))),
            }
            return DataTable.from_rows([row])

        # classification
        classes = np.unique(np.concatenate([y, pred])).astype(int)
        if len(classes) and classes.min() < 0:
            raise ValueError(
                f"negative class labels {classes[classes < 0]} — index "
                f"labels to 0..K-1 first (ValueIndexer)")
        k = int(classes.max()) + 1 if len(classes) else 2
        cm = np.zeros((k, k))
        for t, p in zip(y.astype(int), pred.astype(int)):
            cm[t, p] += 1
        accuracy = float(np.trace(cm) / max(cm.sum(), 1e-12))
        # macro-average only over classes actually present, so gaps in
        # the label range don't drag the averages down
        present = np.zeros(k, dtype=bool)
        present[classes] = True
        with np.errstate(invalid="ignore", divide="ignore"):
            per_class_prec = np.nan_to_num(np.diag(cm) / cm.sum(axis=0))
            per_class_rec = np.nan_to_num(np.diag(cm) / cm.sum(axis=1))
        precision = float(per_class_prec[present].mean())
        recall = float(per_class_rec[present].mean())
        row: Dict[str, Any] = {
            MC.CONFUSION_MATRIX: cm,
            MC.ACCURACY: accuracy,
            MC.PRECISION: precision,
            MC.RECALL: recall,
        }
        # binary AUC from the probability column when present
        prob_col = self.get("scoredProbabilitiesCol")
        if k == 2 and prob_col in table:
            prob = table[prob_col]
            p1 = (np.asarray(prob)[:, 1]
                  if isinstance(prob, np.ndarray) and prob.ndim == 2
                  else np.asarray([np.asarray(v)[1] for v in prob]))
            _, _, auc = roc_curve(y, p1)
            row[MC.AUC] = auc
        return DataTable.from_rows([row])

    def roc_table(self, table: DataTable) -> DataTable:
        """Binned ROC curve table (the reference records it as a df)."""
        y = np.asarray(table[self.get("labelCol")], dtype=np.float64)
        prob = table[self.get("scoredProbabilitiesCol")]
        p1 = (np.asarray(prob)[:, 1]
              if isinstance(prob, np.ndarray) and prob.ndim == 2
              else np.asarray([np.asarray(v)[1] for v in prob]))
        fpr, tpr, _ = roc_curve(y, p1)
        nb = self.get("numBins")
        idx = np.linspace(0, len(fpr) - 1, min(nb, len(fpr))).astype(int)
        return DataTable({"false_positive_rate": fpr[idx],
                          "true_positive_rate": tpr[idx]})

    def transform_schema(self, schema: Schema) -> Schema:
        mode = self.get("evaluationMetric")
        if mode in ("auto", MC.ALL_METRICS):
            # data-dependent; promise only the universally-present rows
            return Schema([])
        if mode == "regression":
            return Schema([Field(m, F64) for m in
                           (MC.MSE, MC.RMSE, MC.R2, MC.MAE)])
        return Schema([Field(MC.ACCURACY, F64), Field(MC.PRECISION, F64),
                       Field(MC.RECALL, F64)])


class ComputePerInstanceStatistics(Transformer):
    """Per-row L1/L2 loss (regression) or log-loss (classification)
    (ref: ComputePerInstanceStatistics.scala:42)."""

    evaluationMetric = EnumParam(["classification", "regression", "auto"],
                                 "metric family", default="auto")
    labelCol = ColParam("ground-truth column", default="label")
    scoresCol = ColParam("prediction column", default="prediction")
    scoredProbabilitiesCol = ColParam("probability vector column",
                                      default="probability")

    def transform(self, table: DataTable) -> DataTable:
        y = np.asarray(table[self.get("labelCol")], dtype=np.float64)
        mode = self.get("evaluationMetric")
        if mode == "auto":
            prob_col = self.get("scoredProbabilitiesCol")
            mode = ("classification" if prob_col in table
                    else "regression")
        if mode == "regression":
            pred = np.asarray(table[self.get("scoresCol")],
                              dtype=np.float64)
            out = table.with_column(MC.L1_LOSS, np.abs(pred - y),
                                    Field(MC.L1_LOSS, F64))
            return out.with_column(MC.L2_LOSS, (pred - y) ** 2,
                                   Field(MC.L2_LOSS, F64))
        prob = table[self.get("scoredProbabilitiesCol")]
        mat = (np.asarray(prob) if isinstance(prob, np.ndarray)
               and prob.ndim == 2
               else np.stack([np.asarray(v) for v in prob]))
        picked = mat[np.arange(len(y)), y.astype(int)]
        log_loss = -np.log(np.clip(picked, 1e-15, 1.0))
        return table.with_column(MC.LOG_LOSS, log_loss,
                                 Field(MC.LOG_LOSS, F64))

    def transform_schema(self, schema: Schema) -> Schema:
        mode = self.get("evaluationMetric")
        if mode == "regression":
            return (schema.add_or_replace(Field(MC.L1_LOSS, F64))
                    .add_or_replace(Field(MC.L2_LOSS, F64)))
        if mode == "classification":
            return schema.add_or_replace(Field(MC.LOG_LOSS, F64))
        return schema  # auto: data-dependent
