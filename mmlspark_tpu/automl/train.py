"""TrainClassifier / TrainRegressor — one-call model training.

Analog of the reference's train-classifier / train-regressor components
(ref: src/train-classifier/.../TrainClassifier.scala:40-288,
src/train-regressor/.../TrainRegressor.scala:20-149): index the label if
non-numeric, auto-featurize the inputs, fit the underlying model, and
return a wrapper model that featurizes + scores + un-indexes labels.
"""

from __future__ import annotations

from typing import Any, List, Optional

import numpy as np

from mmlspark_tpu.automl.featurize import Featurize
from mmlspark_tpu.core.params import (
    BoolParam, HasLabelCol, IntParam, ListParam, StageParam,
)
from mmlspark_tpu.core.schema import Field, Schema, F64, STRING, VECTOR
from mmlspark_tpu.core.stage import Estimator, Model, Transformer
from mmlspark_tpu.core.table import DataTable
from mmlspark_tpu.stages.dataprep import ValueIndexer, ValueIndexerModel

_FEATURES_COL = "TrainClassifier_features"


class TrainClassifier(Estimator, HasLabelCol):
    """Auto-featurize + fit a classifier
    (ref: TrainClassifier.scala:102-260). ``model`` is any Estimator with
    featuresCol/labelCol params; default TPUBoostClassifier."""

    model = StageParam("underlying classifier estimator", default=None)
    featureColumns = ListParam("columns to featurize (None = all)",
                               default=None)
    numFeatures = IntParam("hash width for token columns",
                           default=1 << 12)  # see Featurize note on 2^18
    oneHotEncodeCategoricals = BoolParam("one-hot categoricals",
                                         default=False)
    reindexLabel = BoolParam("index the label column", default=True)

    def _get_model(self) -> Estimator:
        m = self.get_or_none("model")
        if m is None:
            from mmlspark_tpu.gbdt import TPUBoostClassifier
            m = TPUBoostClassifier()
        return m

    def fit(self, table: DataTable) -> "TrainedClassifierModel":
        label_col = self.get_label_col()
        levels: Optional[List[Any]] = None
        work = table
        if self.get("reindexLabel"):
            f = work.schema[label_col]
            needs_index = f.tag == STRING
            if not needs_index:
                y = np.asarray(work[label_col], dtype=np.float64)
                classes = np.unique(y)
                needs_index = not np.array_equal(
                    classes, np.arange(len(classes)))
            if needs_index:
                idx_model = ValueIndexer(
                    inputCol=label_col, outputCol=label_col).fit(work)
                levels = idx_model.get("levels")
                work = idx_model.transform(work)

        feat_cols = self.get_or_none("featureColumns")
        if feat_cols is None:
            feat_cols = [c for c in work.column_names if c != label_col]
        featurizer = Featurize(
            featureColumns=feat_cols, outputCol=_FEATURES_COL,
            oneHotEncodeCategoricals=self.get("oneHotEncodeCategoricals"),
            numberOfFeatures=self.get("numFeatures")).fit(work)
        feats = featurizer.transform(work)

        est = self._get_model().copy()
        est.set("featuresCol", _FEATURES_COL)
        est.set("labelCol", label_col)
        fitted = est.fit(feats)
        return TrainedClassifierModel(
            featurizer=featurizer, innerModel=fitted, levels=levels,
            labelCol=label_col)


class TrainedClassifierModel(Model):
    """ref: TrainClassifier.scala:288 TrainedClassifierModel — scores and
    un-indexes the predicted label back to original values."""

    featurizer = StageParam("fitted featurizer", default=None)
    innerModel = StageParam("fitted classifier model", default=None)
    levels = ListParam("original label levels (None = numeric)",
                       default=None)

    from mmlspark_tpu.core.params import ColParam as _CP
    labelCol = _CP("label column name", default="label")

    def transform(self, table: DataTable) -> DataTable:
        out = self.get("featurizer").transform(table)
        out = self.get("innerModel").transform(out)
        out = out.drop(_FEATURES_COL)
        levels = self.get_or_none("levels")
        if levels:
            from mmlspark_tpu.stages.dataprep import unindex_codes
            orig = unindex_codes(out["prediction"], levels)
            out = out.with_column("scored_labels", orig)
        else:
            out = out.with_column("scored_labels", out["prediction"])
        return out

    def transform_schema(self, schema: Schema) -> Schema:
        return schema.add_or_replace(Field("scored_labels", F64))


class TrainRegressor(Estimator, HasLabelCol):
    """ref: TrainRegressor.scala:20-149."""

    model = StageParam("underlying regressor estimator", default=None)
    featureColumns = ListParam("columns to featurize (None = all)",
                               default=None)
    numFeatures = IntParam("hash width for token columns",
                           default=1 << 12)  # see Featurize note on 2^18
    oneHotEncodeCategoricals = BoolParam("one-hot categoricals",
                                         default=False)

    def _get_model(self) -> Estimator:
        m = self.get_or_none("model")
        if m is None:
            from mmlspark_tpu.gbdt import TPUBoostRegressor
            m = TPUBoostRegressor()
        return m

    def fit(self, table: DataTable) -> "TrainedRegressorModel":
        label_col = self.get_label_col()
        feat_cols = self.get_or_none("featureColumns")
        if feat_cols is None:
            feat_cols = [c for c in table.column_names if c != label_col]
        featurizer = Featurize(
            featureColumns=feat_cols, outputCol=_FEATURES_COL,
            oneHotEncodeCategoricals=self.get("oneHotEncodeCategoricals"),
            numberOfFeatures=self.get("numFeatures")).fit(table)
        feats = featurizer.transform(table)
        est = self._get_model().copy()
        est.set("featuresCol", _FEATURES_COL)
        est.set("labelCol", label_col)
        fitted = est.fit(feats)
        return TrainedRegressorModel(featurizer=featurizer,
                                     innerModel=fitted,
                                     labelCol=label_col)


class TrainedRegressorModel(Model):
    featurizer = StageParam("fitted featurizer", default=None)
    innerModel = StageParam("fitted regressor model", default=None)

    from mmlspark_tpu.core.params import ColParam as _CP
    labelCol = _CP("label column name", default="label")

    def transform(self, table: DataTable) -> DataTable:
        out = self.get("featurizer").transform(table)
        out = self.get("innerModel").transform(out)
        return out.drop(_FEATURES_COL)

    def transform_schema(self, schema: Schema) -> Schema:
        return schema.add_or_replace(Field("prediction", F64))
