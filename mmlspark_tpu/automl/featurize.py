"""Auto-featurization: per-type column pipelines → one features vector.

Analog of the reference's featurize component
(ref: src/featurize/src/main/scala/Featurize.scala:24-96,
AssembleFeatures.scala:92-303): numeric columns are imputed and passed
through, string/categorical columns are indexed (one-hot optionally),
token-list columns are hash-vectorized, vector columns concatenate
as-is, and everything is assembled into a single dense ``features``
column (FastVectorAssembler analog — the assembled matrix is exactly the
(N, D) array device stages consume, so assembly is one np.concatenate,
no metadata walk; ref: src/core/spark/.../FastVectorAssembler.scala:23).

Every per-column kernel is COLUMNAR: token hashing runs through the
vectorized distinct-token kernels in ``stages/text`` (each distinct
token hashes once, counts scatter in one key sort), string
index/one-hot map through a unique-value LUT instead of a per-row dict
probe, and fit's level scan uses np.unique. The pre-vectorization
per-row loops survive as ``_build_parts_rowloop`` — the bit-parity
oracle the tests and ``bench.py``'s automl scenario measure against.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from mmlspark_tpu.core import metrics as MC
from mmlspark_tpu.core.params import (
    BoolParam, ColParam, IntParam, ListParam, DictParam, StageParam,
)
from mmlspark_tpu.core.schema import (
    Field, Schema, BOOL, F32, F64, I8, I16, I32, I64, LIST, STRING, VECTOR,
)
from mmlspark_tpu.core.stage import Estimator, Model
from mmlspark_tpu.core.table import DataTable
from mmlspark_tpu.stages.text import (
    HashingTF, _hash_counts, _stable_hash, hash_counts_csr,
    hash_counts_dense, string_codes as _string_codes,
)

_NUMERIC_TAGS = {F32, F64, I8, I16, I32, I64, BOOL}


def _column_spec(c: str, f: Field, *, one_hot: bool, hash_width: int,
                 sparse: bool, mean: float,
                 levels: Optional[List[Any]]) -> Optional[Dict[str, Any]]:
    """THE per-column spec switch, shared by the in-memory and
    streaming fits — only where ``mean``/``levels`` come from differs
    between them, so the two paths cannot drift. Returns None for
    unsupported tags (struct/bytes/object), which both fits skip like
    the reference drops unsupported columns."""
    if f.tag in _NUMERIC_TAGS:
        if f.meta.get("categorical") and one_hot:
            n = len(f.meta.get("levels") or [])
            return {"col": c, "kind": "onehot", "size": n}
        return {"col": c, "kind": "numeric", "fill": mean}
    if f.tag == STRING:
        if one_hot:
            return {"col": c, "kind": "string_onehot", "levels": levels}
        return {"col": c, "kind": "string_index", "levels": levels}
    if f.tag == LIST:
        return {"col": c, "kind": "hash", "size": hash_width,
                "sparse": sparse}
    if f.tag == VECTOR:
        return {"col": c, "kind": "vector"}
    return None


def _distinct_levels(col) -> List[Any]:
    """Non-None distinct values of a string column, sorted when
    comparable — the vectorized fit-side level scan. String columns with
    no Nones take the C-speed np.unique path; anything else falls back
    to the original first-seen dict + try-sorted discipline (identical
    output: sorted distinct when sortable, first-seen order when not)."""
    vals = col if isinstance(col, list) else list(col)
    try:
        arr = np.asarray(vals)
    except Exception:  # noqa: BLE001 — fall through to the dict scan
        arr = None
    if arr is not None and arr.dtype.kind in ("U", "S"):
        return list(np.unique(arr).tolist())
    seen: Dict[Any, None] = {}
    for v in vals:
        if v is not None:
            seen.setdefault(v, None)
    levels = list(seen.keys())
    try:
        levels = sorted(levels)
    except TypeError:
        pass
    return levels


class Featurize(Estimator):
    """Auto-featurize selected columns into a single vector column
    (ref: Featurize.scala:24; defaults :13-19 — oneHot off, 262144
    hashing features for text)."""

    featureColumns = ListParam("input columns (None = all but output)",
                               default=None)
    outputCol = ColParam("assembled features column", default="features")
    oneHotEncodeCategoricals = BoolParam("one-hot index columns",
                                         default=False)
    # The reference defaults to 262144 (Featurize.scala:13-19) and keeps
    # hashing-TF output *sparse*. Dense mode lowers the default to 2^12
    # (dense 2^18 is ~2 MB/row); sparse=True restores the reference
    # behavior: CSR assembly at the full 262144 width, never densified.
    numberOfFeatures = IntParam("hash width for token columns",
                                default=1 << 12)
    sparse = BoolParam(
        "assemble a CSR sparse features column (hash width defaults to "
        "the reference's 262144 when unset; ref: Featurize.scala:13-19)",
        default=False)
    allowImages = BoolParam("parity param (image passthrough)",
                            default=False)

    def _hash_width(self) -> int:
        if self.get("sparse") and "numberOfFeatures" not in self._paramMap:
            return 1 << 18    # the reference's sparse default
        return self.get("numberOfFeatures")

    def reads_columns(self, schema):
        cols = self.get_or_none("featureColumns")
        if cols is not None:
            return list(cols)
        if schema is None:
            return None
        return [c for c in schema.names if c != self.get("outputCol")]

    def writes_columns(self, schema):
        return [self.get("outputCol")]

    def fit(self, table: DataTable) -> "FeaturizeModel":
        if not isinstance(table, DataTable):
            from mmlspark_tpu.io.ooc import ChunkedTable
            if isinstance(table, ChunkedTable):
                return self._fit_streaming(table)
        t0 = time.perf_counter()
        cols = self.get_or_none("featureColumns")
        if cols is None:
            cols = [c for c in table.column_names
                    if c != self.get("outputCol")]
        specs: List[Dict[str, Any]] = []
        for c in cols:
            f = table.schema[c]
            mean = 0.0
            levels: Optional[List[Any]] = None
            if f.tag in _NUMERIC_TAGS:
                col = np.asarray(table[c], dtype=np.float64)
                finite = col[np.isfinite(col)]
                mean = float(finite.mean()) if finite.size else 0.0
            elif f.tag == STRING:
                levels = _distinct_levels(table[c])
            spec = _column_spec(
                c, f, one_hot=self.get("oneHotEncodeCategoricals"),
                hash_width=self._hash_width(),
                sparse=self.get("sparse"), mean=mean, levels=levels)
            if spec is not None:
                specs.append(spec)
        MC.automl_histograms()["featurize_fit"].observe(
            (time.perf_counter() - t0) * 1e3)
        from mmlspark_tpu.core.trace import get_tracer
        get_tracer().emit("automl.featurize_fit", t0,
                          attrs={"columns": len(cols),
                                 "specs": len(specs)})
        return FeaturizeModel(specs=specs,
                              outputCol=self.get("outputCol"))

    def _fit_streaming(self, chunked) -> "FeaturizeModel":
        """One bounded-memory pass over a ChunkedTable: every fit
        statistic is streaming/mergeable — numeric impute means from
        per-chunk finite sums (f64), string levels from per-chunk
        distinct-set unions (same sorted-when-comparable discipline as
        ``_distinct_levels``), everything else from the schema. The
        resulting specs match the in-memory ``fit`` on the same rows
        (means to f64 summation order)."""
        t0 = time.perf_counter()
        schema = chunked.schema
        out_col = self.get("outputCol")
        cols = self.get_or_none("featureColumns")
        if cols is None:
            cols = [c for c in schema.names if c != out_col]
        sums: Dict[str, float] = {}
        counts: Dict[str, int] = {}
        levels: Dict[str, Dict[Any, None]] = {}
        num_cols = [c for c in cols if schema[c].tag in _NUMERIC_TAGS]
        str_cols = [c for c in cols if schema[c].tag == STRING]
        n_chunks = 0
        for chunk in chunked.chunks():
            n_chunks += 1
            for c in num_cols:
                col = np.asarray(chunk[c], dtype=np.float64)
                finite = col[np.isfinite(col)]
                sums[c] = sums.get(c, 0.0) + float(finite.sum())
                counts[c] = counts.get(c, 0) + int(finite.size)
            for c in str_cols:
                seen = levels.setdefault(c, {})
                for v in _distinct_levels(chunk[c]):
                    seen.setdefault(v, None)
        specs: List[Dict[str, Any]] = []
        for c in cols:
            f = schema[c]
            mean = (sums.get(c, 0.0) / counts[c]
                    if counts.get(c) else 0.0)
            lv: Optional[List[Any]] = None
            if f.tag == STRING:
                lv = list(levels.get(c, {}).keys())
                try:
                    lv = sorted(lv)
                except TypeError:
                    pass
            spec = _column_spec(
                c, f, one_hot=self.get("oneHotEncodeCategoricals"),
                hash_width=self._hash_width(),
                sparse=self.get("sparse"), mean=mean, levels=lv)
            if spec is not None:
                specs.append(spec)
        MC.automl_histograms()["featurize_fit"].observe(
            (time.perf_counter() - t0) * 1e3)
        from mmlspark_tpu.core.trace import get_tracer
        get_tracer().emit("automl.featurize_fit", t0,
                          attrs={"columns": len(cols),
                                 "specs": len(specs),
                                 "chunks": n_chunks})
        return FeaturizeModel(specs=specs, outputCol=out_col)


def _spec_width(spec: Dict[str, Any], table: DataTable) -> int:
    """Output width of one spec's block in the assembled matrix."""
    kind = spec["kind"]
    if kind in ("numeric", "string_index"):
        return 1
    if kind in ("onehot", "hash"):
        return spec["size"]
    if kind == "string_onehot":
        return len(spec["levels"])
    if kind == "vector":
        col = table[spec["col"]]
        if isinstance(col, np.ndarray) and col.ndim == 2:
            return col.shape[1]
        return int(np.asarray(col[0], dtype=np.float32).shape[0]) \
            if len(col) else 0
    raise ValueError(f"unknown featurize spec kind {kind!r}")


def _fill_part(spec: Dict[str, Any], table: DataTable,
               view: np.ndarray) -> None:
    """One spec -> its (N, w) float32 slice of the assembled matrix,
    written IN PLACE (``view`` is a column slice of the final array, so
    dense assembly needs no per-part temporaries and no concat copy)."""
    c = spec["col"]
    kind = spec["kind"]
    n = len(table)
    if kind == "numeric":
        col = np.asarray(table[c], dtype=np.float32)
        view[:, 0] = np.where(np.isfinite(col), col,
                              np.float32(spec["fill"]))
    elif kind == "onehot":
        col = np.asarray(table[c], dtype=np.int64)
        size = spec["size"]
        view[:] = 0.0
        ok = (col >= 0) & (col < size)
        view[np.arange(n)[ok], col[ok]] = 1.0
    elif kind == "string_index":
        codes = _string_codes(table[c], spec["levels"])
        view[:, 0] = codes.astype(np.float32)
    elif kind == "string_onehot":
        codes = _string_codes(table[c], spec["levels"])
        view[:] = 0.0
        ok = codes >= 0
        view[np.nonzero(ok)[0], codes[ok]] = 1.0
    elif kind == "hash":
        # float32 counts: TF counts are small integers, exact in f32
        hash_counts_dense(table[c], spec["size"], binary=False, out=view)
    elif kind == "vector":
        col = table[c]
        if isinstance(col, np.ndarray) and col.ndim == 2:
            view[:] = col
        elif len(col):
            view[:] = np.stack(
                [np.asarray(v, dtype=np.float32) for v in col])
    else:
        raise ValueError(f"unknown featurize spec kind {kind!r}")


def _build_part(spec: Dict[str, Any], table: DataTable):
    """One spec -> one standalone columnar block (the mixed
    sparse/dense assembly path; dense-only assembly fills slices of
    the final matrix directly instead)."""
    if spec["kind"] == "hash" and spec.get("sparse"):
        # reference behavior: 262144-wide hashed text stays a
        # SparseVector end to end (Featurize.scala:13-19) — here a
        # CSR block that never densifies
        return hash_counts_csr(table[spec["col"]], spec["size"],
                               binary=False)
    out = np.empty((len(table), _spec_width(spec, table)), np.float32)
    _fill_part(spec, table, out)
    return out


def _build_parts_rowloop(specs, table: DataTable) -> List[Any]:
    """The pre-vectorization per-row loops, verbatim — the bit-parity
    ORACLE for the columnar kernels (pinned by tests) and the baseline
    ``bench.py``'s automl scenario measures the speedup against. Not on
    any hot path."""
    parts: List[Any] = []
    n = len(table)
    for spec in specs or []:
        c = spec["col"]
        kind = spec["kind"]
        if kind == "numeric":
            col = np.asarray(table[c], dtype=np.float32)
            col = np.where(np.isfinite(col), col, np.float32(spec["fill"]))
            parts.append(col[:, None])
        elif kind == "onehot":
            col = np.asarray(table[c], dtype=np.int64)
            size = spec["size"]
            oh = np.zeros((n, size), dtype=np.float32)
            ok = (col >= 0) & (col < size)
            oh[np.arange(n)[ok], col[ok]] = 1.0
            parts.append(oh)
        elif kind == "string_index":
            index = {v: i for i, v in enumerate(spec["levels"])}
            col = np.asarray([index.get(v, -1) for v in table[c]],
                             dtype=np.float32)
            parts.append(col[:, None])
        elif kind == "string_onehot":
            index = {v: i for i, v in enumerate(spec["levels"])}
            size = len(spec["levels"])
            oh = np.zeros((n, size), dtype=np.float32)
            for i, v in enumerate(table[c]):
                j = index.get(v)
                if j is not None:
                    oh[i, j] = 1.0
            parts.append(oh)
        elif kind == "hash":
            m = spec["size"]
            if spec.get("sparse"):
                from mmlspark_tpu.core.sparse import CSRMatrix
                parts.append(CSRMatrix.from_rows(
                    (_hash_counts(toks, m, False)
                     for toks in table[c]), num_cols=m))
                continue
            mat = np.zeros((n, m), dtype=np.float32)
            for i, toks in enumerate(table[c]):
                for t in toks or []:
                    mat[i, _stable_hash(str(t)) % m] += 1.0
            parts.append(mat)
        elif kind == "vector":
            col = table[c]
            if isinstance(col, np.ndarray) and col.ndim == 2:
                parts.append(np.asarray(col, dtype=np.float32))
            else:
                parts.append(np.stack(
                    [np.asarray(v, dtype=np.float32) for v in col]))
    return parts


def _assemble(parts: List[Any], output_col: str, table: DataTable
              ) -> DataTable:
    if not parts:
        raise ValueError("no featurizable columns found")
    from mmlspark_tpu.core.sparse import CSRMatrix as _CSR, hstack
    if any(isinstance(p, _CSR) for p in parts):
        feats: Any = hstack(parts)
        field = Field(output_col, VECTOR, {"sparse": True})
    else:
        feats = np.concatenate(parts, axis=1)
        field = Field(output_col, VECTOR)
    return table.with_column(output_col, feats, field)


class FeaturizeModel(Model):
    specs = ListParam("per-column featurization specs", default=None)
    outputCol = ColParam("assembled features column", default="features")

    def reads_columns(self, schema):
        return [s["col"] for s in (self.get("specs") or [])]

    def writes_columns(self, schema):
        return [self.get("outputCol")]

    def device_op(self, schema):
        """Fusion hook (core/fusion.py): the host-only kernels (arrow
        dictionary string codes, FNV token hashing — the PR 4 columnar
        paths) run as ``Feed`` loaders on the host/batcher thread; the
        impute / one-hot / assembly runs inside the fused program, so
        the assembled (N, D) matrix is an XLA intermediate flowing
        straight into the model forward, never a host column. All parts
        are exact in f32 (selects, compares, small-int counts), so the
        fused featurize is bit-identical to the host ``transform``."""
        from mmlspark_tpu.core import fusion as FZ
        import jax.numpy as jnp
        specs = self.get("specs") or []
        if not specs or any(s["kind"] == "hash" and s.get("sparse")
                            for s in specs):
            return None    # CSR assembly stays on host
        out_col = self.get("outputCol")
        reads: List[str] = []
        feeds: List[Any] = []
        metas: List[Dict[str, Any]] = []
        for i, spec in enumerate(specs):
            c, kind = spec["col"], spec["kind"]
            m: Dict[str, Any] = {"kind": kind}
            if kind in ("numeric", "vector"):
                if c not in reads:
                    reads.append(c)
                m["read"] = c
            elif kind == "onehot":
                name = f"{self.uid}:{i}:{c}:i32"
                feeds.append(FZ.Feed(
                    name, lambda t, _c=c: np.asarray(
                        t[_c], dtype=np.int64).astype(np.int32)))
                m["feed"] = name
                m["size"] = spec["size"]
            elif kind in ("string_index", "string_onehot"):
                name = f"{self.uid}:{i}:{c}:codes"
                levels = spec["levels"]
                feeds.append(FZ.Feed(
                    name, lambda t, _c=c, _lv=levels:
                    _string_codes(t[_c], _lv).astype(np.int32)))
                m["feed"] = name
                if kind == "string_onehot":
                    m["size"] = len(levels)
            elif kind == "hash":
                name = f"{self.uid}:{i}:{c}:hash"
                size = spec["size"]
                feeds.append(FZ.Feed(
                    name, lambda t, _c=c, _m=size:
                    hash_counts_dense(t[_c], _m, binary=False)))
                m["feed"] = name
            else:
                return None
            if kind == "numeric":
                m["ci"] = sum(1 for mm in metas if mm["kind"] == "numeric")
            metas.append(m)

        def make_consts():
            return {"fills": np.asarray(
                [s["fill"] for s in specs if s["kind"] == "numeric"],
                np.float32)}

        def fn(consts, env, _metas=tuple(metas), _o=out_col):
            parts = []
            for m in _metas:
                kind = m["kind"]
                if kind == "numeric":
                    x = env[m["read"]]
                    parts.append(jnp.where(
                        jnp.isfinite(x), x,
                        consts["fills"][m["ci"]])[:, None])
                elif kind == "vector":
                    parts.append(env[m["read"]].astype(jnp.float32))
                elif kind == "string_index":
                    parts.append(env[m["feed"]]
                                 .astype(jnp.float32)[:, None])
                elif kind in ("onehot", "string_onehot"):
                    codes = env[m["feed"]]
                    size = m["size"]
                    oh = (codes[:, None] == jnp.arange(size, dtype=codes.dtype)
                          ).astype(jnp.float32)
                    parts.append(oh)
                else:   # hash counts, already (N, m) f32
                    parts.append(env[m["feed"]])
            return {_o: jnp.concatenate(parts, axis=1)}

        return FZ.DeviceOp(
            self, reads=reads, writes=[out_col], fn=fn,
            make_consts=make_consts, feeds=feeds,
            out_fields={out_col: Field(out_col, VECTOR)})

    def transform(self, table: DataTable) -> DataTable:
        # all parts float32: device stages consume f32/bf16 anyway, and a
        # single float64 part would upcast the whole concatenate (doubling
        # the wide hashed block's footprint)
        if not isinstance(table, DataTable):
            from mmlspark_tpu.io.ooc import ChunkedTable
            if isinstance(table, ChunkedTable):
                # spill-aware transform: a lazy per-chunk map — the
                # (N, D) features matrix only ever exists chunk-sized
                return table.map(self.transform,
                                 label=f"{table.label}|featurize")
        t0 = time.perf_counter()
        specs = self.get("specs") or []
        if any(s["kind"] == "hash" and s.get("sparse") for s in specs):
            parts = [_build_part(spec, table) for spec in specs]
            out = _assemble(parts, self.get("outputCol"), table)
        else:
            # all-dense: preallocate the final (N, D) matrix once and
            # let every kernel write its column slice in place — no
            # per-part temporaries, no concatenate copy. WIDE blocks
            # fill first (their bulk writes absorb the first-touch page
            # faults at sequential speed); consecutive NARROW specs
            # batch through one compact temp so the matrix sees one
            # strided pass instead of a cache-hostile 4-bytes-per-row
            # pass per column.
            if not specs:
                raise ValueError("no featurizable columns found")
            widths = [_spec_width(s, table) for s in specs]
            offs = np.concatenate([[0], np.cumsum(widths)])
            feats = np.empty((len(table), int(offs[-1])), np.float32)
            narrow = 8
            for i, spec in enumerate(specs):
                if widths[i] > narrow:
                    _fill_part(spec, table,
                               feats[:, offs[i]:offs[i + 1]])
            i = 0
            while i < len(specs):
                if widths[i] > narrow:
                    i += 1
                    continue
                j = i
                while j < len(specs) and widths[j] <= narrow:
                    j += 1
                tmp = np.empty((len(table), int(offs[j] - offs[i])),
                               np.float32)
                for k in range(i, j):
                    a = int(offs[k] - offs[i])
                    _fill_part(specs[k], table,
                               tmp[:, a:a + widths[k]])
                feats[:, offs[i]:offs[j]] = tmp
                i = j
            out_col = self.get("outputCol")
            out = table.with_column(out_col, feats,
                                    Field(out_col, VECTOR))
        MC.automl_histograms()["featurize_transform"].observe(
            (time.perf_counter() - t0) * 1e3)
        from mmlspark_tpu.core.trace import get_tracer
        get_tracer().emit("automl.featurize_transform", t0,
                          attrs={"rows": len(table),
                                 "specs": len(specs)})
        return out

    def transform_rowloop(self, table: DataTable) -> DataTable:
        """Transform via the retained per-row reference loops — the
        parity/bench baseline; see ``_build_parts_rowloop``."""
        parts = _build_parts_rowloop(self.get("specs"), table)
        return _assemble(parts, self.get("outputCol"), table)

    def transform_schema(self, schema: Schema) -> Schema:
        sparse = any(s.get("sparse") and s.get("kind") == "hash"
                     for s in (self.get("specs") or []))
        meta = {"sparse": True} if sparse else {}
        return schema.add_or_replace(
            Field(self.get("outputCol"), VECTOR, meta))


class AssembleFeatures(Estimator):
    """Column assembler sharing FeaturizeModel's machinery
    (ref: AssembleFeatures.scala:92 — the lower-level stage Featurize
    drives; exposed for parity)."""

    columnsToFeaturize = ListParam("columns to assemble", default=None)
    featuresCol = ColParam("output features column", default="features")
    oneHotEncodeCategoricals = BoolParam("one-hot categoricals",
                                         default=False)
    numberOfFeatures = IntParam("hash width for token columns",
                                default=1 << 12)  # see Featurize note

    def fit(self, table: DataTable) -> FeaturizeModel:
        feat = Featurize(
            featureColumns=self.get_or_none("columnsToFeaturize"),
            outputCol=self.get("featuresCol"),
            oneHotEncodeCategoricals=self.get("oneHotEncodeCategoricals"),
            numberOfFeatures=self.get("numberOfFeatures"))
        return feat.fit(table)
