"""Auto-featurization: per-type column pipelines → one features vector.

Analog of the reference's featurize component
(ref: src/featurize/src/main/scala/Featurize.scala:24-96,
AssembleFeatures.scala:92-303): numeric columns are imputed and passed
through, string/categorical columns are indexed (one-hot optionally),
token-list columns are hash-vectorized, vector columns concatenate
as-is, and everything is assembled into a single dense ``features``
column (FastVectorAssembler analog — the assembled matrix is exactly the
(N, D) array device stages consume, so assembly is one np.concatenate,
no metadata walk; ref: src/core/spark/.../FastVectorAssembler.scala:23).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from mmlspark_tpu.core.params import (
    BoolParam, ColParam, IntParam, ListParam, DictParam, StageParam,
)
from mmlspark_tpu.core.schema import (
    Field, Schema, BOOL, F32, F64, I8, I16, I32, I64, LIST, STRING, VECTOR,
)
from mmlspark_tpu.core.stage import Estimator, Model
from mmlspark_tpu.core.table import DataTable
from mmlspark_tpu.stages.text import HashingTF, _stable_hash

_NUMERIC_TAGS = {F32, F64, I8, I16, I32, I64, BOOL}


class Featurize(Estimator):
    """Auto-featurize selected columns into a single vector column
    (ref: Featurize.scala:24; defaults :13-19 — oneHot off, 262144
    hashing features for text)."""

    featureColumns = ListParam("input columns (None = all but output)",
                               default=None)
    outputCol = ColParam("assembled features column", default="features")
    oneHotEncodeCategoricals = BoolParam("one-hot index columns",
                                         default=False)
    # The reference defaults to 262144 (Featurize.scala:13-19) and keeps
    # hashing-TF output *sparse*. Dense mode lowers the default to 2^12
    # (dense 2^18 is ~2 MB/row); sparse=True restores the reference
    # behavior: CSR assembly at the full 262144 width, never densified.
    numberOfFeatures = IntParam("hash width for token columns",
                                default=1 << 12)
    sparse = BoolParam(
        "assemble a CSR sparse features column (hash width defaults to "
        "the reference's 262144 when unset; ref: Featurize.scala:13-19)",
        default=False)
    allowImages = BoolParam("parity param (image passthrough)",
                            default=False)

    def _hash_width(self) -> int:
        if self.get("sparse") and "numberOfFeatures" not in self._paramMap:
            return 1 << 18    # the reference's sparse default
        return self.get("numberOfFeatures")

    def fit(self, table: DataTable) -> "FeaturizeModel":
        cols = self.get_or_none("featureColumns")
        if cols is None:
            cols = [c for c in table.column_names
                    if c != self.get("outputCol")]
        specs: List[Dict[str, Any]] = []
        for c in cols:
            f = table.schema[c]
            if f.tag in _NUMERIC_TAGS:
                col = np.asarray(table[c], dtype=np.float64)
                finite = col[np.isfinite(col)]
                mean = float(finite.mean()) if finite.size else 0.0
                if f.meta.get("categorical") and \
                        self.get("oneHotEncodeCategoricals"):
                    n = len(f.meta.get("levels") or [])
                    specs.append({"col": c, "kind": "onehot", "size": n})
                else:
                    specs.append({"col": c, "kind": "numeric",
                                  "fill": mean})
            elif f.tag == STRING:
                levels = [v for v in table.distinct_values(c)
                          if v is not None]
                try:
                    levels = sorted(levels)
                except TypeError:
                    pass
                if self.get("oneHotEncodeCategoricals"):
                    specs.append({"col": c, "kind": "string_onehot",
                                  "levels": levels})
                else:
                    specs.append({"col": c, "kind": "string_index",
                                  "levels": levels})
            elif f.tag == LIST:
                specs.append({"col": c, "kind": "hash",
                              "size": self._hash_width(),
                              "sparse": self.get("sparse")})
            elif f.tag == VECTOR:
                specs.append({"col": c, "kind": "vector"})
            # other tags (struct/bytes/object) are skipped, like the
            # reference drops unsupported columns
        return FeaturizeModel(specs=specs,
                              outputCol=self.get("outputCol"))


class FeaturizeModel(Model):
    specs = ListParam("per-column featurization specs", default=None)
    outputCol = ColParam("assembled features column", default="features")

    def transform(self, table: DataTable) -> DataTable:
        # all parts float32: device stages consume f32/bf16 anyway, and a
        # single float64 part would upcast the whole concatenate (doubling
        # the wide hashed block's footprint)
        parts: List[np.ndarray] = []
        n = len(table)
        for spec in self.get("specs") or []:
            c = spec["col"]
            kind = spec["kind"]
            if kind == "numeric":
                col = np.asarray(table[c], dtype=np.float32)
                col = np.where(np.isfinite(col), col, np.float32(spec["fill"]))
                parts.append(col[:, None])
            elif kind == "onehot":
                col = np.asarray(table[c], dtype=np.int64)
                size = spec["size"]
                oh = np.zeros((n, size), dtype=np.float32)
                ok = (col >= 0) & (col < size)
                oh[np.arange(n)[ok], col[ok]] = 1.0
                parts.append(oh)
            elif kind == "string_index":
                index = {v: i for i, v in enumerate(spec["levels"])}
                col = np.asarray([index.get(v, -1) for v in table[c]],
                                 dtype=np.float32)
                parts.append(col[:, None])
            elif kind == "string_onehot":
                index = {v: i for i, v in enumerate(spec["levels"])}
                size = len(spec["levels"])
                oh = np.zeros((n, size), dtype=np.float32)
                for i, v in enumerate(table[c]):
                    j = index.get(v)
                    if j is not None:
                        oh[i, j] = 1.0
                parts.append(oh)
            elif kind == "hash":
                m = spec["size"]
                if spec.get("sparse"):
                    # reference behavior: 262144-wide hashed text stays a
                    # SparseVector end to end (Featurize.scala:13-19) —
                    # here a CSR block that never densifies
                    from mmlspark_tpu.core.sparse import CSRMatrix
                    from mmlspark_tpu.stages.text import _hash_counts
                    parts.append(CSRMatrix.from_rows(
                        (_hash_counts(toks, m, False)
                         for toks in table[c]), num_cols=m))
                    continue
                # float32 halves the dense-materialization footprint; TF
                # counts are small integers so no precision is lost
                mat = np.zeros((n, m), dtype=np.float32)
                for i, toks in enumerate(table[c]):
                    for t in toks or []:
                        mat[i, _stable_hash(str(t)) % m] += 1.0
                parts.append(mat)
            elif kind == "vector":
                col = table[c]
                if isinstance(col, np.ndarray) and col.ndim == 2:
                    parts.append(np.asarray(col, dtype=np.float32))
                else:
                    parts.append(np.stack(
                        [np.asarray(v, dtype=np.float32) for v in col]))
        if not parts:
            raise ValueError("no featurizable columns found")
        from mmlspark_tpu.core.sparse import CSRMatrix as _CSR, hstack
        if any(isinstance(p, _CSR) for p in parts):
            feats: Any = hstack(parts)
            field = Field(self.get("outputCol"), VECTOR, {"sparse": True})
        else:
            feats = np.concatenate(parts, axis=1)
            field = Field(self.get("outputCol"), VECTOR)
        return table.with_column(self.get("outputCol"), feats, field)

    def transform_schema(self, schema: Schema) -> Schema:
        sparse = any(s.get("sparse") and s.get("kind") == "hash"
                     for s in (self.get("specs") or []))
        meta = {"sparse": True} if sparse else {}
        return schema.add_or_replace(
            Field(self.get("outputCol"), VECTOR, meta))


class AssembleFeatures(Estimator):
    """Column assembler sharing FeaturizeModel's machinery
    (ref: AssembleFeatures.scala:92 — the lower-level stage Featurize
    drives; exposed for parity)."""

    columnsToFeaturize = ListParam("columns to assemble", default=None)
    featuresCol = ColParam("output features column", default="features")
    oneHotEncodeCategoricals = BoolParam("one-hot categoricals",
                                         default=False)
    numberOfFeatures = IntParam("hash width for token columns",
                                default=1 << 12)  # see Featurize note

    def fit(self, table: DataTable) -> FeaturizeModel:
        feat = Featurize(
            featureColumns=self.get_or_none("columnsToFeaturize"),
            outputCol=self.get("featuresCol"),
            oneHotEncodeCategoricals=self.get("oneHotEncodeCategoricals"),
            numberOfFeatures=self.get("numberOfFeatures"))
        return feat.fit(table)
