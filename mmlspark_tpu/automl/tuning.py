"""Hyperparameter search + model selection.

Analog of tune-hyperparameters / find-best-model
(ref: src/tune-hyperparameters/.../TuneHyperparameters.scala:33-188,
ParamSpace.scala:11-40, HyperparamBuilder.scala:11-98,
src/find-best-model/.../FindBestModel.scala:50,
EvaluationUtils.scala:13): randomized/grid search over typed param
spaces with k-fold CV, candidates evaluated in parallel (thread pool —
the reference uses scala Futures; each fit releases the GIL into XLA),
and FindBestModel evaluating fitted models on a validation table.
"""

from __future__ import annotations

import itertools
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from mmlspark_tpu.automl.statistics import ComputeModelStatistics
from mmlspark_tpu.core import metrics as MC
from mmlspark_tpu.core.params import (
    BoolParam, EnumParam, IntParam, ListParam, StageParam, StringParam,
)
from mmlspark_tpu.core.stage import Estimator, Model, Transformer
from mmlspark_tpu.core.table import DataTable

# metric -> larger-is-better? (ref: EvaluationUtils.getMetricWithOperator)
_METRIC_ASCENDING = {
    MC.MSE: False, MC.RMSE: False, MC.MAE: False, MC.R2: True,
    MC.AUC: True, MC.ACCURACY: True, MC.PRECISION: True, MC.RECALL: True,
}


class Dist:
    """A sampling distribution for one hyperparameter
    (ref: ParamSpace.scala:34 Dist)."""

    def sample(self, rng: np.random.Generator) -> Any:
        raise NotImplementedError

    def grid(self) -> List[Any]:
        raise NotImplementedError


class DiscreteHyperParam(Dist):
    """ref: HyperparamBuilder.scala DiscreteHyperParam."""

    def __init__(self, values: Sequence[Any]):
        self.values = list(values)

    def sample(self, rng):
        return self.values[int(rng.integers(0, len(self.values)))]

    def grid(self):
        return list(self.values)


class RangeHyperParam(Dist):
    """Uniform numeric range; int if both ends are ints
    (ref: HyperparamBuilder.scala:40-98 typed RangeHyperParams)."""

    def __init__(self, low, high, n_grid: int = 5, log: bool = False):
        self.low, self.high = low, high
        self.is_int = isinstance(low, int) and isinstance(high, int)
        self.n_grid = n_grid
        self.log = log

    def sample(self, rng):
        if self.log:
            v = float(np.exp(rng.uniform(np.log(self.low),
                                         np.log(self.high))))
        else:
            v = float(rng.uniform(self.low, self.high))
        return int(round(v)) if self.is_int else v

    def grid(self):
        if self.log:
            vals = np.exp(np.linspace(np.log(self.low), np.log(self.high),
                                      self.n_grid))
        else:
            vals = np.linspace(self.low, self.high, self.n_grid)
        return [int(round(v)) if self.is_int else float(v) for v in vals]


class HyperparamBuilder:
    """Collects (param-name -> Dist) pairs (ref:
    HyperparamBuilder.scala:11)."""

    def __init__(self):
        self._space: Dict[str, Dist] = {}

    def add_hyperparam(self, name: str, dist: Dist) -> "HyperparamBuilder":
        self._space[name] = dist
        return self

    def build(self) -> Dict[str, Dist]:
        return dict(self._space)


class GridSpace:
    """Exhaustive cartesian grid (ref: ParamSpace.scala:11)."""

    def __init__(self, space: Dict[str, Dist]):
        self.space = space

    def param_maps(self) -> Iterator[Dict[str, Any]]:
        names = list(self.space)
        for combo in itertools.product(
                *(self.space[n].grid() for n in names)):
            yield dict(zip(names, combo))


class RandomSpace:
    """Random sampling (ref: ParamSpace.scala:25)."""

    def __init__(self, space: Dict[str, Dist], seed: int = 0):
        self.space = space
        self.seed = seed

    def param_maps(self) -> Iterator[Dict[str, Any]]:
        rng = np.random.default_rng(self.seed)
        while True:
            yield {n: d.sample(rng) for n, d in self.space.items()}


def _evaluate(model: Model, table: DataTable, metric: str) -> float:
    scored = model.transform(table)
    mode = ("regression" if metric in MC.REGRESSION_METRICS
            else "classification" if metric in MC.CLASSIFICATION_METRICS
            else "auto")
    stats = ComputeModelStatistics(evaluationMetric=mode).transform(scored)
    row = stats.row(0)
    if metric not in row:
        raise KeyError(f"metric {metric!r} not computed; have {list(row)}")
    return float(row[metric])


class TuneHyperparameters(Estimator):
    """Randomized/grid search with k-fold CV over one or more estimators
    (ref: TuneHyperparameters.scala:112-188)."""

    models = ListParam("candidate estimators", default=None)
    paramSpace = StageParam("GridSpace or RandomSpace (or list of spaces "
                            "aligned with models)", default=None)
    evaluationMetric = StringParam("metric to optimize", default=MC.ACCURACY)
    numFolds = IntParam("k-fold count", default=3)
    numRuns = IntParam("sampled configs per model (random spaces)",
                       default=10)
    parallelism = IntParam("concurrent evaluations", default=4)
    seed = IntParam("shuffle seed", default=0)

    def fit(self, table: DataTable) -> "TuneHyperparametersModel":
        models: List[Estimator] = self.get("models")
        space = self.get("paramSpace")
        metric = self.get("evaluationMetric")
        ascending = _METRIC_ASCENDING.get(metric, True)
        k = self.get("numFolds")
        shuffled = table.shuffle(self.get("seed"))
        folds = shuffled.shards(k)

        candidates: List[Tuple[Estimator, Dict[str, Any]]] = []
        for est in models:
            maps = space.param_maps()
            if isinstance(space, RandomSpace):
                maps = itertools.islice(maps, self.get("numRuns"))
            for pm in maps:
                usable = {n: v for n, v in pm.items()
                          if _has_param(est, n)}
                candidates.append((est, usable))

        def eval_candidate(args):
            est, pm = args
            scores = []
            for i in range(k):
                train_t = DataTable.concat(
                    [f for j, f in enumerate(folds) if j != i])
                val_t = folds[i]
                e = est.copy()
                for n, v in pm.items():
                    e.set(n, v)
                model = e.fit(train_t)
                scores.append(_evaluate(model, val_t, metric))
            return float(np.mean(scores))

        with ThreadPoolExecutor(self.get("parallelism")) as pool:
            results = list(pool.map(eval_candidate, candidates))

        best_i = int(np.argmax(results) if ascending
                     else np.argmin(results))
        best_est, best_pm = candidates[best_i]
        final = best_est.copy()
        for n, v in best_pm.items():
            final.set(n, v)
        best_model = final.fit(table)
        history = [{"model": type(e).__name__, "params": pm,
                    "metric": r}
                   for (e, pm), r in zip(candidates, results)]
        return TuneHyperparametersModel(
            bestModel=best_model, bestMetric=results[best_i],
            bestParams=best_pm, history=history)


def _has_param(stage, name: str) -> bool:
    try:
        stage.param(name)
        return True
    except KeyError:
        return False


class TuneHyperparametersModel(Model):
    bestModel = StageParam("the winning fitted model", default=None)
    from mmlspark_tpu.core.params import FloatParam as _FP, DictParam as _DP
    bestMetric = _FP("winning CV metric", default=0.0)
    bestParams = _DP("winning param map", default=None)
    history = ListParam("all (model, params, metric) records", default=None)

    def transform(self, table: DataTable) -> DataTable:
        return self.get("bestModel").transform(table)

    def get_best_model_info(self) -> str:
        return (f"{type(self.get('bestModel')).__name__} "
                f"params={self.get('bestParams')} "
                f"metric={self.get('bestMetric')}")


class FindBestModel(Estimator):
    """Evaluate fitted models on the given table, keep the best
    (ref: FindBestModel.scala:50)."""

    models = ListParam("candidate fitted models", default=None)
    evaluationMetric = StringParam("metric", default=MC.ACCURACY)

    def fit(self, table: DataTable) -> "BestModel":
        metric = self.get("evaluationMetric")
        ascending = _METRIC_ASCENDING.get(metric, True)
        models: List[Model] = self.get("models")
        scores = [_evaluate(m, table, metric) for m in models]
        best_i = int(np.argmax(scores) if ascending
                     else np.argmin(scores))
        rows = [{"model": type(m).__name__, metric: s}
                for m, s in zip(models, scores)]
        # record all-metrics evaluation of the winner (ref: FindBestModel
        # records ROC/metrics dfs)
        scored = models[best_i].transform(table)
        all_metrics = ComputeModelStatistics().transform(scored)
        return BestModel(bestModel=models[best_i],
                         bestModelMetrics=all_metrics,
                         allModelMetrics=DataTable.from_rows(rows))


class BestModel(Model):
    bestModel = StageParam("winning model", default=None)
    from mmlspark_tpu.core.params import TableParam as _TP
    bestModelMetrics = _TP("metrics table of the winner", default=None)
    allModelMetrics = _TP("metric per candidate", default=None)

    def transform(self, table: DataTable) -> DataTable:
        return self.get("bestModel").transform(table)

    def get_evaluation_results(self) -> DataTable:
        return self.get("allModelMetrics")
