"""Hyperparameter search + model selection.

Analog of tune-hyperparameters / find-best-model
(ref: src/tune-hyperparameters/.../TuneHyperparameters.scala:33-188,
ParamSpace.scala:11-40, HyperparamBuilder.scala:11-98,
src/find-best-model/.../FindBestModel.scala:50,
EvaluationUtils.scala:13): randomized/grid search over typed param
spaces with k-fold CV, candidates evaluated in parallel (thread pool —
the reference uses scala Futures; each fit releases the GIL into XLA),
and FindBestModel evaluating fitted models on a validation table.

The CV sweep is fold-cached and device-batched: the k (train, val)
fold pairs are assembled ONCE and shared by every candidate (the old
path rebuilt the train table with DataTable.concat inside every
candidate x fold evaluation — k x C full-dataset copies), each fold's
dense (N, D) feature matrix is extracted once, and when every candidate
is the same vmappable linear-model family with numeric-only
hyperparameter deltas the whole C x k sweep stacks into one jitted
vmap program per (fold, static-config group) — a handful of dispatches
instead of C x k serial fits. The serial thread-pool path stays as the
general fallback (any estimator, sparse features, structural params).
"""

from __future__ import annotations

import itertools
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from mmlspark_tpu.automl.statistics import ComputeModelStatistics
from mmlspark_tpu.core import metrics as MC
from mmlspark_tpu.core.logging_utils import get_logger
from mmlspark_tpu.core.params import (
    BoolParam, EnumParam, IntParam, ListParam, StageParam, StringParam,
)
from mmlspark_tpu.core.stage import Estimator, Model, Transformer
from mmlspark_tpu.core.table import DataTable

_LOG = get_logger("automl.tuning")

# metric -> larger-is-better? (ref: EvaluationUtils.getMetricWithOperator)
_METRIC_ASCENDING = {
    MC.MSE: False, MC.RMSE: False, MC.MAE: False, MC.R2: True,
    MC.AUC: True, MC.ACCURACY: True, MC.PRECISION: True, MC.RECALL: True,
}


class Dist:
    """A sampling distribution for one hyperparameter
    (ref: ParamSpace.scala:34 Dist)."""

    def sample(self, rng: np.random.Generator) -> Any:
        raise NotImplementedError

    def grid(self) -> List[Any]:
        raise NotImplementedError


class DiscreteHyperParam(Dist):
    """ref: HyperparamBuilder.scala DiscreteHyperParam."""

    def __init__(self, values: Sequence[Any]):
        self.values = list(values)

    def sample(self, rng):
        return self.values[int(rng.integers(0, len(self.values)))]

    def grid(self):
        return list(self.values)


class RangeHyperParam(Dist):
    """Uniform numeric range; int if both ends are ints
    (ref: HyperparamBuilder.scala:40-98 typed RangeHyperParams)."""

    def __init__(self, low, high, n_grid: int = 5, log: bool = False):
        self.low, self.high = low, high
        self.is_int = isinstance(low, int) and isinstance(high, int)
        self.n_grid = n_grid
        self.log = log

    def sample(self, rng):
        if self.log:
            v = float(np.exp(rng.uniform(np.log(self.low),
                                         np.log(self.high))))
        else:
            v = float(rng.uniform(self.low, self.high))
        return int(round(v)) if self.is_int else v

    def grid(self):
        if self.log:
            vals = np.exp(np.linspace(np.log(self.low), np.log(self.high),
                                      self.n_grid))
        else:
            vals = np.linspace(self.low, self.high, self.n_grid)
        return [int(round(v)) if self.is_int else float(v) for v in vals]


class HyperparamBuilder:
    """Collects (param-name -> Dist) pairs (ref:
    HyperparamBuilder.scala:11)."""

    def __init__(self):
        self._space: Dict[str, Dist] = {}

    def add_hyperparam(self, name: str, dist: Dist) -> "HyperparamBuilder":
        self._space[name] = dist
        return self

    def build(self) -> Dict[str, Dist]:
        return dict(self._space)


class GridSpace:
    """Exhaustive cartesian grid (ref: ParamSpace.scala:11)."""

    def __init__(self, space: Dict[str, Dist]):
        self.space = space

    def param_maps(self) -> Iterator[Dict[str, Any]]:
        names = list(self.space)
        for combo in itertools.product(
                *(self.space[n].grid() for n in names)):
            yield dict(zip(names, combo))


class RandomSpace:
    """Random sampling (ref: ParamSpace.scala:25)."""

    def __init__(self, space: Dict[str, Dist], seed: int = 0):
        self.space = space
        self.seed = seed

    def param_maps(self) -> Iterator[Dict[str, Any]]:
        rng = np.random.default_rng(self.seed)
        while True:
            yield {n: d.sample(rng) for n, d in self.space.items()}


def _evaluate_scored(scored: DataTable, metric: str) -> float:
    mode = ("regression" if metric in MC.REGRESSION_METRICS
            else "classification" if metric in MC.CLASSIFICATION_METRICS
            else "auto")
    stats = ComputeModelStatistics(evaluationMetric=mode).transform(scored)
    row = stats.row(0)
    if metric not in row:
        raise KeyError(f"metric {metric!r} not computed; have {list(row)}")
    return float(row[metric])


def _evaluate(model: Model, table: DataTable, metric: str) -> float:
    return _evaluate_scored(model.transform(table), metric)


# ---------------------------------------------------------------------------
# device-batched trials
# ---------------------------------------------------------------------------

# the only hyperparameters the vmap trial path may sweep: stepSize and
# regParam enter the jitted fit as traced scalars (vmappable), maxIter
# is a static loop bound (candidates group by it — one dispatch per
# distinct value per fold)
_SWEEPABLE = {"stepSize", "regParam", "maxIter"}


def _batched_trials(candidates: List[Tuple[Estimator, Dict[str, Any]]],
                    fold_pairs: List[Tuple[DataTable, DataTable]],
                    metric: str, info: Dict[str, Any]
                    ) -> Optional[List[float]]:
    """The device-batched CV sweep. Returns per-candidate mean scores
    ordered like ``candidates``, or None when the sweep is not
    vmappable (mixed estimator families, structural params, sparse
    features) — the caller then runs the serial thread-pool path.

    Per fold: ONE feature-matrix extraction + standardization shared by
    all C candidates, then one jitted vmap dispatch per distinct
    maxIter group fitting every candidate in that group at once.
    Candidate weights come back stacked; scoring reuses the fold's
    cached validation matrix (``transform_from_matrix``), and selection
    runs the exact serial-path code on the scores."""
    from mmlspark_tpu.core.sparse import CSRMatrix
    from mmlspark_tpu.models.linear import (
        TPULinearRegression, TPULogisticRegression,
        TPULinearRegressionModel, TPULogisticRegressionModel,
        _Standardizer, _fit_linear_batch, _fit_logistic_batch,
        _features_matrix,
    )
    import jax.numpy as jnp

    if not candidates:
        return None
    ests = [e for e, _ in candidates]
    cls = type(ests[0])
    if cls not in (TPULogisticRegression, TPULinearRegression):
        return None
    if any(type(e) is not cls for e in ests):
        return None
    if any(set(pm) - _SWEEPABLE for _, pm in candidates):
        return None
    fcol = ests[0].get_features_col()
    lcol = ests[0].get_label_col()
    pcol = ests[0].get_prediction_col()
    if any(e.get_features_col() != fcol or e.get_label_col() != lcol
           or e.get_prediction_col() != pcol for e in ests):
        return None
    try:
        if any(isinstance(t.column(fcol), CSRMatrix)
               or isinstance(v.column(fcol), CSRMatrix)
               for t, v in fold_pairs):
            return None   # the sparse gather fit has per-fold
            #               data-dependent shapes; serial path keeps it
    except KeyError:
        return None

    logistic = cls is TPULogisticRegression
    # effective (stepSize, regParam, maxIter) per candidate: estimator
    # value overridden by the swept param map — exactly what the serial
    # path's est.copy()+set() produces
    configs = []
    for est, pm in candidates:
        cfg = {n: est.get(n) for n in ("stepSize", "regParam", "maxIter")}
        cfg.update(pm)
        configs.append(cfg)
    groups: Dict[int, List[int]] = {}
    for i, cfg in enumerate(configs):
        groups.setdefault(int(cfg["maxIter"]), []).append(i)

    scores = np.empty((len(candidates), len(fold_pairs)), np.float64)
    dispatches = 0
    for fi, (train_t, val_t) in enumerate(fold_pairs):
        # fold-cached matrices: ONE extraction + standardization per
        # fold, shared by every candidate's fit AND scoring
        X = _features_matrix(train_t, fcol)
        y = np.asarray(train_t[lcol], dtype=np.float64)
        mu, sd = _Standardizer.compute(X)
        Xs = (X - mu) / sd
        Xval = _features_matrix(val_t, fcol)
        Xd = jnp.asarray(Xs, jnp.float32)
        yd = jnp.asarray(y, jnp.float32)
        if logistic:
            num_class = int(y.max()) + 1 if len(y) else 2
            num_class = max(num_class, 2)
        else:
            y_mu, y_sd = float(y.mean()), float(y.std() or 1.0)
            ysd = jnp.asarray((y - y_mu) / y_sd, jnp.float32)
        for n_steps, idxs in groups.items():
            lrs = jnp.asarray([configs[i]["stepSize"] for i in idxs],
                              jnp.float32)
            l2s = jnp.asarray([configs[i]["regParam"] for i in idxs],
                              jnp.float32)
            if logistic:
                params = _fit_logistic_batch(Xd, yd, lrs, l2s, n_steps,
                                             num_class)
            else:
                params = _fit_linear_batch(Xd, ysd, lrs, l2s, n_steps)
            dispatches += 1
            stacked = {k2: np.asarray(v) for k2, v in params.items()}
            for j, ci in enumerate(idxs):
                if logistic:
                    weights = {"W": stacked["W"][j], "b": stacked["b"][j],
                               "mu": mu, "sd": sd}
                    mdl: Model = TPULogisticRegressionModel(
                        weights=weights)
                else:
                    weights = {"w": stacked["w"][j], "b": stacked["b"][j],
                               "mu": mu, "sd": sd,
                               "y_mu": y_mu, "y_sd": y_sd}
                    mdl = TPULinearRegressionModel(weights=weights)
                mdl.set("featuresCol", fcol)
                mdl.set("predictionCol", pcol)
                scored = mdl.transform_from_matrix(val_t, Xval)
                scores[ci, fi] = _evaluate_scored(scored, metric)
    info.update(path="vmap", dispatches=dispatches,
                groups=len(groups))
    return [float(np.mean(scores[c])) for c in range(len(candidates))]


class TuneHyperparameters(Estimator):
    """Randomized/grid search with k-fold CV over one or more estimators
    (ref: TuneHyperparameters.scala:112-188). Fold pairs are assembled
    once and shared across candidates; homogeneous linear-model sweeps
    with numeric-only deltas run device-batched (see ``batchTrials``)."""

    models = ListParam("candidate estimators", default=None)
    paramSpace = StageParam("GridSpace or RandomSpace (or list of spaces "
                            "aligned with models)", default=None)
    evaluationMetric = StringParam("metric to optimize", default=MC.ACCURACY)
    numFolds = IntParam("k-fold count", default=3)
    numRuns = IntParam("sampled configs per model (random spaces)",
                       default=10)
    parallelism = IntParam("concurrent evaluations", default=4)
    seed = IntParam("shuffle seed", default=0)
    batchTrials = EnumParam(
        ["auto", "on", "off"],
        "device-batched CV trials: stack all candidates of a vmappable "
        "linear-model sweep into one jitted vmap program per fold "
        "('auto' = when eligible, 'on' = warn + serial fallback when "
        "not, 'off' = always the serial thread pool)", default="auto")

    def fit(self, table: DataTable) -> "TuneHyperparametersModel":
        from mmlspark_tpu.core.trace import get_tracer
        tracer = get_tracer()
        tune_trace = tracer.new_trace("automl.tune") \
            if tracer.enabled else None
        hists = MC.automl_histograms()
        models: List[Estimator] = self.get("models")
        space = self.get("paramSpace")
        metric = self.get("evaluationMetric")
        ascending = _METRIC_ASCENDING.get(metric, True)
        k = self.get("numFolds")

        # fold pairs built ONCE, outside the candidate loop: the old
        # path re-ran this concat inside every candidate evaluation —
        # k x C full-dataset copies before any model trained
        t0 = time.perf_counter()
        shuffled = table.shuffle(self.get("seed"))
        folds = shuffled.shards(k)
        fold_pairs: List[Tuple[DataTable, DataTable]] = [
            (DataTable.concat([f for j, f in enumerate(folds) if j != i]),
             folds[i])
            for i in range(k)]
        hists["tune_fold_build"].observe(
            (time.perf_counter() - t0) * 1e3)
        if tune_trace is not None:
            tracer.emit("tune_fold_build", t0, trace=tune_trace,
                        attrs={"folds": k})

        candidates: List[Tuple[Estimator, Dict[str, Any]]] = []
        for est in models:
            maps = space.param_maps()
            if isinstance(space, RandomSpace):
                maps = itertools.islice(maps, self.get("numRuns"))
            for pm in maps:
                usable = {n: v for n, v in pm.items()
                          if _has_param(est, n)}
                candidates.append((est, usable))

        info: Dict[str, Any] = {"path": "serial", "dispatches": 0,
                                "candidates": len(candidates),
                                "folds": k}
        t0 = time.perf_counter()
        results: Optional[List[float]] = None
        batch_mode = self.get("batchTrials")
        if batch_mode != "off":
            results = _batched_trials(candidates, fold_pairs, metric,
                                      info)
            if results is None and batch_mode == "on":
                _LOG.warning(
                    "batchTrials='on' but the sweep is not vmappable "
                    "(mixed estimator families, non-numeric params, or "
                    "sparse features); falling back to serial trials")

        if results is None:
            def eval_candidate(args):
                est, pm = args
                scores = []
                for train_t, val_t in fold_pairs:
                    e = est.copy()
                    for n, v in pm.items():
                        e.set(n, v)
                    model = e.fit(train_t)
                    scores.append(_evaluate(model, val_t, metric))
                return float(np.mean(scores))

            with ThreadPoolExecutor(self.get("parallelism")) as pool:
                results = list(pool.map(eval_candidate, candidates))
        hists["tune_trials"].observe((time.perf_counter() - t0) * 1e3)
        if tune_trace is not None:
            tracer.emit("tune_trials", t0, trace=tune_trace,
                        attrs={"path": info["path"],
                               "candidates": info["candidates"]})

        best_i = int(np.argmax(results) if ascending
                     else np.argmin(results))
        best_est, best_pm = candidates[best_i]
        final = best_est.copy()
        for n, v in best_pm.items():
            final.set(n, v)
        t0 = time.perf_counter()
        best_model = final.fit(table)
        hists["tune_refit"].observe((time.perf_counter() - t0) * 1e3)
        if tune_trace is not None:
            tracer.emit("tune_refit", t0, trace=tune_trace)
            tune_trace.root.set("path", info["path"])
            tracer.finish(tune_trace)
        history = [{"model": type(e).__name__, "params": pm,
                    "metric": r}
                   for (e, pm), r in zip(candidates, results)]
        tuned = TuneHyperparametersModel(
            bestModel=best_model, bestMetric=results[best_i],
            bestParams=best_pm, history=history)
        tuned.search_info = info
        return tuned


def _has_param(stage, name: str) -> bool:
    try:
        stage.param(name)
        return True
    except KeyError:
        return False


class TuneHyperparametersModel(Model):
    bestModel = StageParam("the winning fitted model", default=None)
    from mmlspark_tpu.core.params import FloatParam as _FP, DictParam as _DP
    bestMetric = _FP("winning CV metric", default=0.0)
    bestParams = _DP("winning param map", default=None)
    history = ListParam("all (model, params, metric) records", default=None)

    def _post_init(self):
        # how the sweep ran (path: 'vmap'|'serial', dispatches, groups)
        # — runtime diagnostics, not a persisted param
        self.search_info: Dict[str, Any] = {}

    def transform(self, table: DataTable) -> DataTable:
        return self.get("bestModel").transform(table)

    def get_best_model_info(self) -> str:
        return (f"{type(self.get('bestModel')).__name__} "
                f"params={self.get('bestParams')} "
                f"metric={self.get('bestMetric')}")


class FindBestModel(Estimator):
    """Evaluate fitted models on the given table, keep the best
    (ref: FindBestModel.scala:50)."""

    models = ListParam("candidate fitted models", default=None)
    evaluationMetric = StringParam("metric", default=MC.ACCURACY)

    def fit(self, table: DataTable) -> "BestModel":
        metric = self.get("evaluationMetric")
        ascending = _METRIC_ASCENDING.get(metric, True)
        models: List[Model] = self.get("models")
        scores = [_evaluate(m, table, metric) for m in models]
        best_i = int(np.argmax(scores) if ascending
                     else np.argmin(scores))
        rows = [{"model": type(m).__name__, metric: s}
                for m, s in zip(models, scores)]
        # record all-metrics evaluation of the winner (ref: FindBestModel
        # records ROC/metrics dfs)
        scored = models[best_i].transform(table)
        all_metrics = ComputeModelStatistics().transform(scored)
        return BestModel(bestModel=models[best_i],
                         bestModelMetrics=all_metrics,
                         allModelMetrics=DataTable.from_rows(rows))


class BestModel(Model):
    bestModel = StageParam("winning model", default=None)
    from mmlspark_tpu.core.params import TableParam as _TP
    bestModelMetrics = _TP("metrics table of the winner", default=None)
    allModelMetrics = _TP("metric per candidate", default=None)

    def transform(self, table: DataTable) -> DataTable:
        return self.get("bestModel").transform(table)

    def get_evaluation_results(self) -> DataTable:
        return self.get("allModelMetrics")
