"""Multi-model serving plane: a zoo of versioned models behind one fleet.

The reference framework existed to serve a *model zoo* (downloader +
Spark Serving, SURVEY L3), but every serving layer here so far bound
exactly ONE pipeline per engine. ``ModelZoo`` closes that gap in the
spirit of Clipper's model-abstraction layer (Crankshaw et al.,
NSDI'17) and INFaaS's automated model placement (Romero et al.,
ATC'21): many versioned models, one fleet, bounded tail latency.

- **Distribution format = the AOT artifact store** (serving/aot.py).
  ``register_artifact``/``scan`` point at ``<root>/<name>/<version>/``
  directories; activation is ``load_model`` + warmup — deserialize and
  go, hundreds of milliseconds, **no JIT trace** — so a cold model can
  activate while the fleet serves. Factories and eager pipelines are
  also accepted (tests, non-AOT models).
- **Device-memory-aware cache.** Models load lazily on FIRST request
  (a daemon loader thread, never the serving hot path) and evict LRU
  under pressure: a resident-count cap, an estimated-bytes cap, and —
  when the backend reports them — the PR 7 ``device_memory_stats``
  sampler as the live signal (``bytes_in_use`` over
  ``memory_headroom`` x ``bytes_limit``). Eviction NEVER touches a
  model with outstanding batches: the victim scan and the hot path's
  ``acquire`` run under one lock, so a batch routed to a handle pins
  it until the worker releases.
- **Model-routed hot path.** Requests carry ``model=name@version`` (an
  ``X-Model`` header, a ``/models/<name@version>`` URL path, or a
  ``?model=`` query — see ``model_key_of``); the engine's batcher keys
  micro-batches by (model, bucket) so a batch never mixes models, and
  every reply echoes ``X-Model`` so clients can audit the routing.
- **Audit + observability.** Every register/activate/evict/load-failure
  lands a ``ZooEvent`` in the registry event log (the ``SwapEvent``
  discipline); per-model metadata rides ``serving_model_info{model,
  version,precision,aot,state}`` and per-model latency histograms ride
  ``serving_model_latency_ms{model=...}`` under a hard
  label-cardinality cap (overflow models fold into ``model="_other"``
  — docs/model_zoo.md). A zoo-attached engine's SLO monitor
  (core/slo.py) also records its burn-rate ``AlertEvent``s into the
  SAME inherited event log, so swaps, evictions, and SLO breaches read
  as one interleaved timeline — and its per-model SLO streams follow
  this module's cardinality-cap discipline (overflow models share the
  ``"_other"`` stream).

``ModelZoo`` *is* a ``ModelRegistry``: the version-ordered bookkeeping,
``lookup``/``list`` consistent-snapshot reads, and the event log are
inherited, with keys of the form ``"name@version"``.
"""

from __future__ import annotations

import itertools
import os
import queue
import threading
import time
import urllib.parse
from typing import Any, Callable, Dict, List, Optional, Tuple

from mmlspark_tpu.core.logging_utils import get_logger
from mmlspark_tpu.core.metrics import LabelledHistograms
from mmlspark_tpu.serving.lifecycle import ModelRegistry
from mmlspark_tpu.serving.server import PipelineHandle

log = get_logger("serving.zoo")

# entry lifecycle states (reported by lookup()/list()/stats())
UNLOADED = "unloaded"     # registered, not resident (never loaded/evicted)
LOADING = "loading"       # a loader thread is activating it
RESIDENT = "resident"     # live handle, serving
FAILED = "failed"         # last activation raised (retried after cooldown)

# acquire() verdicts that are not entry states
UNKNOWN = "unknown"


def model_key_of(request: Optional[Dict[str, Any]]) -> Optional[str]:
    """The ``name@version`` (or bare ``name``) a request routes to, or
    None for the engine's default pipeline. Three carriers, checked in
    order: the ``X-Model`` header (case-insensitive), a
    ``/models/<spec>`` URL path, a ``?model=<spec>`` query param."""
    if not request:
        return None
    from mmlspark_tpu.serving.admission import header_get
    header = header_get(request, "x-model")
    if header is not None:
        spec = header.strip()
        return spec or None
    uri = (request.get("requestLine") or {}).get("uri", "") or ""
    parts = urllib.parse.urlsplit(uri)
    path = parts.path or ""
    if path.startswith("/models/"):
        spec = urllib.parse.unquote(path[len("/models/"):]).strip("/")
        if spec:
            return spec
    if parts.query:
        q = urllib.parse.parse_qs(parts.query)
        if q.get("model"):
            spec = q["model"][0].strip()
            return spec or None
    return None


class ZooEvent:
    """Typed audit record: one zoo lifecycle decision (the ``SwapEvent``
    discipline applied to the multi-model plane). Recorded into the
    inherited registry event log, so one audit trail tells the whole
    lifecycle story — swaps and zoo churn interleaved by time."""

    def __init__(self, kind: str, model: str, version: str,
                 reason: str = "",
                 stats: Optional[Dict[str, Any]] = None):
        self.kind = kind      # 'register'|'activate'|'evict'|'load_failed'
        self.model = model
        self.version = version
        self.reason = reason
        self.stats = dict(stats or {})
        self.at = time.time()

    def __repr__(self) -> str:
        extra = f", reason={self.reason!r}" if self.reason else ""
        if "ms" in self.stats:
            extra += f", {self.stats['ms']:.0f}ms"
        return (f"ZooEvent({self.kind}, {self.model!r}@"
                f"{self.version!r}{extra})")


class ZooEntry:
    """One registered (name, version): its source, lifecycle state, and
    (when resident) the live ``PipelineHandle``. All fields are guarded
    by the zoo's registry lock."""

    __slots__ = ("name", "version", "key", "kind", "source", "metadata",
                 "state", "handle", "cost_bytes", "last_used", "loads",
                 "evictions", "pinned", "failure", "failed_at",
                 "loading_since", "waiters")

    def __init__(self, name: str, version: str, kind: str, source: Any,
                 metadata: Optional[Dict[str, Any]] = None):
        self.name = str(name)
        self.version = str(version)
        self.key = f"{self.name}@{self.version}"
        self.kind = kind              # 'artifact' | 'factory' | 'pipeline'
        self.source = source
        self.metadata = dict(metadata or {})
        self.state = UNLOADED
        self.handle: Optional[PipelineHandle] = None
        self.cost_bytes = int(self.metadata.get("cost_bytes", 0))
        self.last_used = 0
        self.loads = 0
        self.evictions = 0
        self.pinned = False
        self.failure: Optional[str] = None
        self.failed_at = 0.0
        self.loading_since = 0.0
        # engines parked on this model (requests waiting for its
        # activation): eviction must not touch an awaited model, or
        # demand > capacity becomes a load/evict livelock — the model
        # would evict between its activation and the batcher's flush
        # poll, reload, and starve its requests forever
        self.waiters = 0


# default for ModelZoo(memory_probe=...): "use device_memory_stats".
# A sentinel, NOT None — explicit None must mean "live signal OFF"
# (tests/benches on CPU, hosts where JAX preallocation makes
# bytes_in_use meaningless), and a default of None could never be
# told apart from that.
_DEFAULT_PROBE = object()


class ModelZoo(ModelRegistry):
    """A ``ModelRegistry`` grown into a device-memory-aware lazy cache
    of serving-ready models (see module docstring).

    Budget knobs (any subset; unset = unbounded on that axis):

    - ``max_resident``: hard cap on resident model count (LRU beyond).
    - ``max_resident_bytes``: cap on the sum of per-model cost
      estimates (artifact weight/program file sizes; ``cost_bytes``
      metadata or a duck-typed ``resident_bytes`` hook override).
    - ``memory_probe`` + ``memory_headroom``: live signal — when the
      probe (default ``utils.profiling.device_memory_stats``, the PR 7
      sampler's source) reports ``bytes_in_use`` above ``headroom`` x
      ``bytes_limit``, LRU models evict down to (but never below) one
      resident — full eviction would just thrash reloads.

    ``label_cardinality_cap`` bounds the per-model metric label space:
    at most that many models get their own ``serving_model_info`` /
    ``serving_model_latency_ms`` series; latency overflow folds into
    ``model="_other"`` (``serving_zoo_models{state=...}`` always counts
    everything). Thread-safe throughout; loads run on a daemon loader
    thread so activation storms never block the serving hot path.
    """

    def __init__(self, artifact_root: Optional[str] = None,
                 max_resident: Optional[int] = None,
                 max_resident_bytes: Optional[int] = None,
                 memory_probe: Any = _DEFAULT_PROBE,
                 memory_headroom: float = 0.9,
                 label_cardinality_cap: int = 64,
                 failure_cooldown_s: float = 30.0,
                 loading_requeue_s: float = 10.0):
        super().__init__()
        self._entries: Dict[str, ZooEntry] = {}
        self._by_name: Dict[str, List[str]] = {}
        self.max_resident = max_resident
        self.max_resident_bytes = max_resident_bytes
        if memory_probe is _DEFAULT_PROBE:
            # MESH-wide stats: a sharded model spends memory on every
            # device, so the live pressure signal sums bytes_in_use /
            # bytes_limit across the mesh (utils/profiling)
            from mmlspark_tpu.utils.profiling import mesh_memory_stats
            memory_probe = mesh_memory_stats
        self.memory_probe = memory_probe   # None = live signal OFF
        self.memory_headroom = float(memory_headroom)
        self.failure_cooldown_s = float(failure_cooldown_s)
        self.loading_requeue_s = float(loading_requeue_s)
        self.label_cardinality_cap = int(label_cardinality_cap)
        self._model_hists = LabelledHistograms(cap=label_cardinality_cap)
        # monotone recency ticks (itertools.count: atomic under the GIL)
        self._tick = itertools.count(1)
        self._load_q: "queue.Queue[Optional[str]]" = queue.Queue()
        self._loader: Optional[threading.Thread] = None
        self._loader_lock = threading.Lock()
        self._last_enforce = 0.0
        self.activations = 0
        self.evictions = 0
        self.load_failures = 0
        # the chaos drill's invariant probe: bumped if an eviction ever
        # observes outstanding batches on its victim (must stay 0 — the
        # victim scan and acquire share the registry lock)
        self.evictions_with_outstanding = 0
        self.artifact_root = artifact_root
        if artifact_root:
            self.scan(artifact_root)

    # -- registration -------------------------------------------------------

    def _register_entry(self, entry: ZooEntry) -> None:
        with self._lock:
            if entry.key in self._entries:
                raise ValueError(f"model {entry.key!r} already registered")
            self._entries[entry.key] = entry
            self._by_name.setdefault(entry.name, []).append(entry.version)
            # keep the inherited registry bookkeeping coherent:
            # versions()/latest()/previous() see zoo keys; the pipeline
            # slot holds the RESIDENT object (None while unloaded)
            self._versions[entry.key] = None
            self._order.append(entry.key)
            self._meta[entry.key] = entry.metadata
        self.record_event(ZooEvent("register", entry.name, entry.version,
                                   stats={"kind": entry.kind}))

    def register_artifact(self, name: str, version: str, art_dir: str,
                          metadata: Optional[Dict[str, Any]] = None
                          ) -> None:
        """Register an AOT artifact directory (serving/aot.py
        ``export_model`` output) as a lazily-activated model. The
        manifest is read now (cheap) for precision/aot/bucket metadata;
        weights/programs load on first request."""
        from mmlspark_tpu.serving.aot import read_manifest
        manifest = read_manifest(art_dir)
        meta = dict(metadata or {})
        meta.setdefault("precision", manifest.get("precision", "f32"))
        meta.setdefault("aot", True)
        meta.setdefault("buckets", manifest.get("buckets"))
        meta.setdefault("artifact_kind", manifest.get("kind"))
        if manifest.get("sharded"):
            # sharded manifests (serving/aot.py): activation rebuilds
            # the mesh from these axes; surfaced in stats()/model_info
            meta.setdefault("sharded", True)
            meta.setdefault("mesh", manifest.get("mesh"))
        entry = ZooEntry(name, version, "artifact", art_dir, meta)
        if not entry.cost_bytes:
            entry.cost_bytes = _artifact_bytes(art_dir)
        self._register_entry(entry)

    def register_factory(self, name: str, version: str,
                         factory: Callable[[], Any],
                         metadata: Optional[Dict[str, Any]] = None
                         ) -> None:
        """Register a zero-arg factory returning a serving stage (the
        ``json_scoring_pipeline`` contract). ``metadata``'s optional
        ``warmup_example`` runs the stage's warmup hook at activation;
        ``cost_bytes`` feeds the bytes budget."""
        entry = ZooEntry(name, version, "factory", factory, metadata)
        self._register_entry(entry)

    def register_pipeline(self, name: str, version: str, pipeline: Any,
                          metadata: Optional[Dict[str, Any]] = None
                          ) -> None:
        """Register an already-built serving stage (loads instantly —
        the eager path for models that are already in memory)."""
        entry = ZooEntry(name, version, "pipeline", pipeline, metadata)
        self._register_entry(entry)

    def scan(self, artifact_root: Optional[str] = None) -> List[str]:
        """Discover ``<root>/<name>/<version>/manifest.json`` artifact
        directories and register every (name, version) not yet known.
        Returns the newly registered keys — the zoo's pull-based analog
        of the reference's model downloader."""
        root = artifact_root or self.artifact_root
        if not root or not os.path.isdir(root):
            return []
        added: List[str] = []
        for name in sorted(os.listdir(root)):
            name_dir = os.path.join(root, name)
            if not os.path.isdir(name_dir):
                continue
            # NATURAL version order, not lexicographic: plain sorted()
            # would register v9 after v12 and bare-name resolution
            # (latest = last registered) would serve the wrong model
            for version in sorted(os.listdir(name_dir),
                                  key=_natural_key):
                art_dir = os.path.join(name_dir, version)
                if not os.path.isfile(
                        os.path.join(art_dir, "manifest.json")):
                    continue
                key = f"{name}@{version}"
                with self._lock:
                    known = key in self._entries
                if known:
                    continue
                try:
                    self.register_artifact(name, version, art_dir)
                    added.append(key)
                except Exception as e:  # noqa: BLE001 — skip bad dirs
                    log.warning("zoo scan: skipping %s (%s)", art_dir, e)
        return added

    # -- resolution + the hot-path acquire ----------------------------------

    def _resolve_locked(self, spec: str) -> Optional[str]:
        spec = str(spec).strip()
        if spec in self._entries:
            return spec
        versions = self._by_name.get(spec)
        if versions:
            return f"{spec}@{versions[-1]}"    # bare name -> latest
        return None

    def resolve(self, spec: str) -> Optional[str]:
        """``name`` or ``name@version`` -> the full key (bare names
        resolve to the latest registered version), or None."""
        with self._lock:
            return self._resolve_locked(spec)

    def registered_names(self) -> List[str]:
        """Sorted model names (ops/introspection; error paths use the
        capped ``names_preview`` instead)."""
        with self._lock:
            names = list(self._by_name)
        return sorted(names)

    def _names_preview_locked(self, cap: int = 20) -> str:
        """Short registered-names string (registry lock held). Capped:
        a 404 body must not embed a 256-name list, and the batcher
        must not sort the whole registry per bad request."""
        n = len(self._by_name)
        names = sorted(itertools.islice(self._by_name, cap + 1))[:cap]
        if n > cap:
            names.append(f"... ({n} total)")
        return ", ".join(names) if names else "(none)"

    def names_preview(self, cap: int = 20) -> str:
        """``_names_preview_locked`` with the lock taken (the server's
        unknown-model 404 body)."""
        with self._lock:
            return self._names_preview_locked(cap)

    def add_waiter(self, spec: str) -> None:
        """An engine parked requests awaiting this model's activation:
        until ``remove_waiter``, eviction will not touch it (the
        outstanding-batches rule extended to queued demand — without
        it, demand > capacity livelocks: an awaited model evicts
        between activation and the batcher's flush poll, reloads, and
        its requests starve to the activation timeout)."""
        with self._lock:
            key = self._resolve_locked(spec)
            if key is not None:
                self._entries[key].waiters += 1

    def remove_waiter(self, spec: str) -> None:
        """Release one ``add_waiter`` hold (flush, timeout, or load
        failure — every parked key removes its waiter exactly once)."""
        with self._lock:
            key = self._resolve_locked(spec)
            if key is not None:
                e = self._entries[key]
                if e.waiters > 0:
                    e.waiters -= 1

    def acquire(self, spec: str
                ) -> Tuple[Optional[PipelineHandle], str, str]:
        """The batcher's non-blocking resolve: returns
        ``(handle, state, message)``.

        - ``resident``: the handle, ALREADY acquired (outstanding
          bumped under the registry lock — atomic with the eviction
          scan, so the victim can never be a model with batches in
          flight). The caller must eventually ``release()`` it (the
          engine's worker does, like any batch handle).
        - ``loading``: activation scheduled/running on the loader
          thread; park the requests and poll again.
        - ``failed``: the last activation raised (message carries the
          reason); retried automatically after ``failure_cooldown_s``.
        - ``unknown``: no such model.
        """
        schedule = False
        with self._lock:
            key = self._resolve_locked(spec)
            if key is None:
                return None, UNKNOWN, (
                    f"unknown model {spec!r}; registered: "
                    f"{self._names_preview_locked()}")
            e = self._entries[key]
            if e.state == RESIDENT:
                e.handle.acquire()
                e.last_used = next(self._tick)
                return e.handle, RESIDENT, ""
            if e.state == FAILED:
                if time.monotonic() < e.failed_at + self.failure_cooldown_s:
                    return None, FAILED, e.failure or "load failed"
                e.state = UNLOADED          # cooldown over: retry
            if e.state == UNLOADED:
                e.state = LOADING
                e.loading_since = time.monotonic()
                schedule = True
            elif e.state == LOADING and time.monotonic() \
                    > e.loading_since + self.loading_requeue_s:
                # lost-load watchdog: a queued load can vanish (loader
                # killed by a BaseException, close() racing a submit);
                # without this the entry is LOADING forever and every
                # request 503s with no recovery path. Requeueing is
                # idempotent — _load_one no-ops unless still LOADING.
                e.loading_since = time.monotonic()
                schedule = True
        if schedule:
            self._submit_load(key)
        return None, LOADING, ""

    def get(self, spec: str, timeout: float = 120.0):
        """Blocking fetch of a resident serving stage: triggers the
        lazy activation if needed and waits for it (embedders, tests,
        warm-ahead scripts — the hot path uses ``acquire``)."""
        deadline = time.monotonic() + timeout
        while True:
            handle, state, msg = self.acquire(spec)
            if state == RESIDENT:
                handle.release()      # get() hands out no outstanding
                return handle.pipeline
            if state == UNKNOWN:
                raise KeyError(msg)
            if state == FAILED:
                raise RuntimeError(
                    f"model {spec!r} failed to load: {msg}")
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"model {spec!r} still {state} after {timeout}s")
            time.sleep(0.005)

    def pin(self, spec: str, pinned: bool = True) -> None:
        """Exempt a model from eviction (un-pin with ``pinned=False``)."""
        with self._lock:
            key = self._resolve_locked(spec)
            if key is None:
                raise KeyError(f"unknown model {spec!r}")
            self._entries[key].pinned = bool(pinned)

    # -- the loader thread --------------------------------------------------

    def _submit_load(self, key: str) -> None:
        with self._loader_lock:
            if self._loader is None or not self._loader.is_alive():
                self._loader = threading.Thread(
                    target=self._loader_loop, daemon=True,
                    name="zoo-loader")
                self._loader.start()
            # put INSIDE the lock: close() holds it while enqueueing
            # the shutdown sentinel, so a racing submit can't land its
            # key behind the sentinel of an exiting loader (the lost
            # load would leave the entry LOADING until the watchdog)
            self._load_q.put(key)

    def _loader_loop(self) -> None:
        while True:
            key = self._load_q.get()
            if key is None:
                return
            try:
                self._load_one(key)
            except Exception as e:  # noqa: BLE001 — keep loading others
                log.error("zoo loader error on %s (continuing): %s",
                          key, e)

    def _load_one(self, key: str) -> None:
        with self._lock:
            e = self._entries.get(key)
            if e is None or e.state != LOADING:
                return
        t0 = time.perf_counter()
        try:
            stage, example, extra_meta, cost = self._build(e)
            warm = None
            hook = getattr(stage, "warmup", None)
            if callable(hook) and example is not None:
                warm = hook(example)
        except Exception as exc:  # noqa: BLE001 — FAILED, not crashed
            reason = f"{type(exc).__name__}: {exc}"
            with self._lock:
                e.state = FAILED
                e.failure = reason
                e.failed_at = time.monotonic()
                self.load_failures += 1
            self.record_event(ZooEvent("load_failed", e.name, e.version,
                                       reason=reason))
            log.warning("zoo: activation of %s FAILED: %s", key, reason)
            return
        ms = (time.perf_counter() - t0) * 1e3
        handle = PipelineHandle(stage, e.version)
        handle.model_name = e.name
        handle.model_key = e.key
        # MEASURED device residency (the stage's duck-typed
        # resident_bytes: per-device shard bytes summed across the
        # mesh — warmup just shipped the weights/tables, so the
        # reading is live). It replaces the static estimate (manifest
        # file bytes) unless the registrant pinned cost_bytes
        # explicitly — eviction pressure then reflects what a SHARDED
        # model actually holds per device, not its disk size.
        measured = _duck_bytes(stage)
        cost_source = "estimate"
        with self._lock:
            e.metadata.update(extra_meta)
            if warm is not None:
                e.metadata["warmup_compiles"] = int(warm)
            if e.metadata.get("cost_bytes"):
                cost_source = "metadata"
            elif measured:
                e.cost_bytes = int(measured)
                cost_source = "device"
            elif cost and not e.cost_bytes:
                e.cost_bytes = int(cost)
            e.metadata["cost_source"] = cost_source
            # cold-start cost: the variant plane prices activating a
            # non-resident variant against serving a warm one
            e.metadata["activation_ms"] = round(ms, 1)
            e.state = RESIDENT
            e.handle = handle
            e.failure = None
            e.loads += 1
            e.last_used = next(self._tick)
            self._versions[e.key] = stage
            self.activations += 1
        self.record_event(ZooEvent(
            "activate", e.name, e.version,
            stats={"ms": round(ms, 1), "kind": e.kind,
                   "aot": bool(extra_meta.get("aot")),
                   "cost_bytes": e.cost_bytes,
                   "cost_source": cost_source}))
        log.info("zoo: activated %s in %.0f ms (%s)", key, ms, e.kind)
        self.enforce()

    def _build(self, e: ZooEntry
               ) -> Tuple[Any, Any, Dict[str, Any], int]:
        """Materialize one entry's serving stage (NO lock held):
        returns (stage, warmup_example, metadata_updates, cost_bytes)."""
        from mmlspark_tpu.core.quantize import stage_precision
        if e.kind == "artifact":
            from mmlspark_tpu.serving import aot as AOT
            from mmlspark_tpu.serving.fleet import json_scoring_pipeline
            manifest = AOT.read_manifest(e.source)
            model = AOT.load_model(e.source)
            kwargs = {} if manifest["kind"] == "pipeline" \
                else {"field": manifest["serve"]["field"]}
            stage = json_scoring_pipeline(model, **kwargs)
            example = _artifact_example(e.source, manifest)
            extra = {"precision": manifest.get("precision", "f32"),
                     "aot": True, "buckets": manifest.get("buckets")}
            return stage, example, extra, _artifact_bytes(e.source)
        stage = e.source() if e.kind == "factory" else e.source
        example = e.metadata.get("warmup_example")
        extra = {"precision": stage_precision(stage),
                 "aot": bool(getattr(stage, "aot", False))}
        return stage, example, extra, _duck_bytes(stage)

    # -- eviction -----------------------------------------------------------

    def _pressure_reason(self) -> Optional[str]:
        """Why the cache must shrink right now, or None. The memory
        probe runs OUTSIDE the registry lock (it may touch the
        backend)."""
        with self._lock:
            resident = [e for e in self._entries.values()
                        if e.state == RESIDENT]
            n = len(resident)
            total = sum(e.cost_bytes for e in resident)
        if self.max_resident is not None and n > self.max_resident:
            return "count_cap"
        if self.max_resident_bytes is not None \
                and total > self.max_resident_bytes:
            return "bytes_cap"
        if self.memory_probe is not None and n > 1:
            # memory-pressure evictions stop at ONE resident model:
            # evicting the last one would reload it on the next request
            # — pure thrash, no relief the caps wouldn't give better
            try:
                stats = self.memory_probe()
            except Exception:  # noqa: BLE001 — a sick probe never
                stats = None   # takes the serving plane down
            if stats:
                in_use = stats.get("bytes_in_use")
                limit = stats.get("bytes_limit")
                if in_use is not None and limit:
                    if in_use > self.memory_headroom * limit:
                        return "memory_pressure"
        return None

    def enforce(self, min_interval_s: float = 0.0) -> int:
        """Evict LRU resident models while over budget. Cheap enough to
        call from the batcher loop (``min_interval_s`` rate-gates it);
        also runs after every activation. Returns the eviction count.

        The victim scan requires ``outstanding == 0`` and runs under
        the same lock ``acquire`` bumps outstanding under — an eviction
        can NEVER hit a model with batches in flight."""
        now = time.monotonic()
        if min_interval_s > 0.0 and now < self._last_enforce \
                + min_interval_s:
            return 0
        self._last_enforce = now
        evicted = 0
        while True:
            reason = self._pressure_reason()
            if reason is None:
                return evicted
            with self._lock:
                residents = [e for e in self._entries.values()
                             if e.state == RESIDENT]
                # the sole resident is never a victim: a single model
                # whose cost exceeds a cap would otherwise evict
                # itself right after every activation — a load/evict
                # livelock that never serves the request that
                # triggered the load. Brief overshoot beats thrash
                # (the memory-pressure signal already stops at one).
                if len(residents) <= 1:
                    return evicted
                # ... and the MRU resident is never a victim while
                # others exist, for the same reason: with a tight
                # budget the just-activated model would be its own
                # post-load eviction's only candidate.
                mru = max(residents, key=lambda e: e.last_used)
                victims = [e for e in residents
                           if not e.pinned and e is not mru
                           and e.waiters == 0
                           and e.handle is not None
                           and e.handle.outstanding == 0]
                if not victims:
                    return evicted     # nothing evictable right now
                victim = min(victims, key=lambda e: e.last_used)
                event, pipeline = self._evict_locked(
                    victim, f"lru:{reason}")
            self._unload(pipeline)
            self.record_event(event)
            log.info("zoo: evicted %s@%s (%s)", event.model,
                     event.version, event.reason)
            evicted += 1

    def _evict_locked(self, e: ZooEntry, reason: str
                      ) -> Tuple[ZooEvent, Any]:
        """Detach one RESIDENT entry (registry lock held). Returns the
        event AND the detached pipeline — the caller runs its
        ``unload`` hook AFTER releasing the lock (a slow backend
        release must not stall every ``acquire`` on the hot path)."""
        if e.handle is not None and e.handle.outstanding != 0:
            # unreachable by the lock discipline; counted so the chaos
            # drill can assert the invariant held
            self.evictions_with_outstanding += 1
        pipeline = e.handle.pipeline if e.handle is not None else None
        e.state = UNLOADED
        e.handle = None
        e.evictions += 1
        self.evictions += 1
        self._versions[e.key] = None
        event = ZooEvent("evict", e.name, e.version, reason=reason,
                         stats={"cost_bytes": e.cost_bytes,
                                "loads": e.loads})
        return event, pipeline

    @staticmethod
    def _unload(pipeline: Any) -> None:
        unload = getattr(pipeline, "unload", None)
        if callable(unload):
            try:
                unload()
            except Exception:  # noqa: BLE001 — best-effort release
                pass

    def evict(self, spec: str, reason: str = "manual") -> bool:
        """Explicit eviction (ops hook). Refuses — returns False — when
        the model has outstanding batches or is pinned."""
        with self._lock:
            key = self._resolve_locked(spec)
            if key is None:
                raise KeyError(f"unknown model {spec!r}")
            e = self._entries[key]
            if e.state != RESIDENT or e.pinned or e.waiters != 0 \
                    or e.handle is None or e.handle.outstanding != 0:
                return False
            event, pipeline = self._evict_locked(e, reason)
        self._unload(pipeline)
        self.record_event(event)
        return True

    # -- consistent reads (the ModelRegistry lookup/list contract) ----------

    def _entry_locked(self, key: str) -> Tuple[Any, str, Dict[str, Any]]:
        e = self._entries.get(key)
        if e is None:       # registered through the base API
            return super()._entry_locked(key)
        handle = e.handle if e.state == RESIDENT else None
        return handle, e.state, dict(e.metadata)

    # -- observability ------------------------------------------------------

    def entry_status(self, spec: str) -> Optional[Dict[str, Any]]:
        """One entry's advisory snapshot for the variant plane:
        state, residency cost + source, and the last measured
        activation (cold-start) ms. None for unknown specs."""
        with self._lock:
            key = self._resolve_locked(spec)
            e = self._entries.get(key) if key is not None else None
            if e is None:
                return None
            return {
                "key": e.key, "state": e.state,
                "cost_bytes": e.cost_bytes,
                "cost_source": str(
                    e.metadata.get("cost_source", "estimate")),
                "activation_ms": e.metadata.get("activation_ms"),
                "precision": str(e.metadata.get("precision", "f32")),
                "outstanding": (e.handle.outstanding
                                if e.handle is not None else 0),
                "waiters": e.waiters,
            }

    def observe_latency(self, model: str, ms: float) -> None:
        """Per-model batch latency (the engine observes after every
        scored batch); cardinality-capped — see LabelledHistograms."""
        self._model_hists.observe(model, ms)

    def model_histograms(self) -> Dict[str, Any]:
        """The per-model latency histogram family (label -> histogram;
        overflow models share ``_other``)."""
        return self._model_hists.snapshot()

    def stats(self) -> Dict[str, Any]:
        """ONE consistent snapshot: counts by state, budget usage, and
        per-model metadata rows (resident-first, most-recently-used
        first, capped at ``label_cardinality_cap``)."""
        with self._lock:
            entries = list(self._entries.values())
            by_state: Dict[str, int] = {}
            for e in entries:
                by_state[e.state] = by_state.get(e.state, 0) + 1
            resident = [e for e in entries if e.state == RESIDENT]
            resident.sort(key=lambda e: -e.last_used)
            rest = [e for e in entries if e.state != RESIDENT]
            rows = []
            for e in (resident + rest)[:self.label_cardinality_cap]:
                rows.append({
                    "model": e.name, "version": e.version,
                    "state": e.state,
                    "precision": str(e.metadata.get("precision", "f32")),
                    "aot": bool(e.metadata.get("aot", False)),
                    "pinned": e.pinned, "loads": e.loads,
                    "evictions": e.evictions,
                    "cost_bytes": e.cost_bytes,
                    "cost_source": str(
                        e.metadata.get("cost_source", "estimate")),
                    "outstanding": (e.handle.outstanding
                                    if e.handle is not None else 0),
                    "waiters": e.waiters,
                })
            return {
                "registered": len(entries),
                "by_state": by_state,
                "resident_bytes": sum(e.cost_bytes for e in resident),
                "activations": self.activations,
                "evictions": self.evictions,
                "load_failures": self.load_failures,
                "evictions_with_outstanding":
                    self.evictions_with_outstanding,
                "label_cardinality_cap": self.label_cardinality_cap,
                "models": rows,
            }

    def close(self) -> None:
        """Stop the loader thread (queued loads finish first)."""
        with self._loader_lock:
            if self._loader is not None and self._loader.is_alive():
                self._load_q.put(None)
                self._loader.join(timeout=5)
            self._loader = None


def _natural_key(s: str) -> Tuple:
    """Sort key treating digit runs as numbers: v2 < v10 (plain string
    sort would put v10 first)."""
    import re
    return tuple(int(part) if part.isdigit() else part
                 for part in re.split(r"(\d+)", s))


def _artifact_bytes(art_dir: str) -> int:
    """Cost estimate for an AOT artifact: weights + serialized
    programs on disk (the device-resident footprint's proxy)."""
    total = 0
    for fname in ("weights.pkl", "programs.pkl"):
        try:
            total += os.path.getsize(os.path.join(art_dir, fname))
        except OSError:
            pass
    return total


def _artifact_example(art_dir: str, manifest: Dict[str, Any]):
    """The artifact's warmup example (example.pkl), shaped for the
    stage's warmup hook."""
    import pickle
    path = os.path.join(art_dir, "example.pkl")
    if not os.path.exists(path):
        return None
    with open(path, "rb") as f:
        example = pickle.load(f)
    if manifest.get("kind") == "pipeline":
        from mmlspark_tpu.core.table import DataTable
        return DataTable(dict(example))
    return example


def _duck_bytes(stage: Any) -> int:
    """Duck-typed cost estimate: a ``resident_bytes`` attr/callable on
    the stage, else 0 (count-cap and the live memory probe still
    bound the cache)."""
    rb = getattr(stage, "resident_bytes", None)
    try:
        if callable(rb):
            return int(rb())
        if rb is not None:
            return int(rb)
    except Exception:  # noqa: BLE001 — estimate only
        pass
    return 0
