"""Serving engine: HTTP source/sink with reply-by-uuid routing.

TPU-native re-creation of Spark Serving
(ref: src/io/http/src/main/scala/HTTPSource.scala:48-178 single-node
source/sink; DistributedHTTPSource.scala:33-472 per-executor
JVMSharedServer with batch-indexed request routing and reply-by-uuid;
PartitionConsolidator.scala:17).

Design: each serving host runs one threaded HTTP server (the
JVMSharedServer analog). Accepted requests park their connection and
enqueue (uuid, request-struct); the serving engine drains the queue into
DataTable micro-batches, runs the user pipeline (whose heavy stages are
jitted/sharded on the TPU mesh), and the sink answers each row back
through the SAME host's held connection — the reply-routing invariant of
the reference (replies must flow through the host that accepted the
request, DistributedHTTPSource.scala:188-192). On a multi-host mesh, run
one ServingEngine per host behind any TCP load balancer; model state is
replicated by jax, no cross-host reply routing is ever needed.
"""

from __future__ import annotations

import contextlib
import itertools
import json
import math
import os
import queue
import secrets
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from mmlspark_tpu.core.logging_utils import get_logger
from mmlspark_tpu.core.stage import Transformer
from mmlspark_tpu.core.table import DataTable
from mmlspark_tpu.io.http import HTTPSchema, _jsonable as _to_jsonable

log = get_logger("serving")


def _request_id_factory():
    """Unique request ids without a per-request os.urandom syscall
    (uuid4 was ~2% of a loaded engine's wall): one random process
    prefix + an atomic counter. Uniqueness holds per process, which is
    the reply-routing scope; the prefix keeps ids unguessable and
    distinct across engine restarts."""
    prefix = secrets.token_hex(8)
    counter = itertools.count()
    return lambda: f"{prefix}-{next(counter)}"


class SharedVariable:
    """Process-wide lazily-initialized shared value
    (ref: io/http SharedVariable.scala double-checked lazy singleton)."""

    def __init__(self, factory: Callable[[], Any]):
        self._factory = factory
        self._value = None
        self._have = False
        self._lock = threading.Lock()

    def get(self) -> Any:
        if not self._have:
            with self._lock:
                if not self._have:
                    self._value = self._factory()
                    self._have = True
        return self._value


class SharedSingleton:
    """Keyed process-wide singletons (ref: SharedSingleton.scala)."""

    _instances: Dict[str, Any] = {}
    _lock = threading.Lock()

    @classmethod
    def get_or_create(cls, key: str, factory: Callable[[], Any]) -> Any:
        with cls._lock:
            if key not in cls._instances:
                cls._instances[key] = factory()
            return cls._instances[key]


class _ParkedRequest:
    """A request whose connection is held open until respond()."""

    def __init__(self, rid: str, request_struct: Dict[str, Any]):
        self.id = rid
        self.request = request_struct
        self._event = threading.Event()
        self.response: Optional[Dict[str, Any]] = None
        # stamped at enqueue / at leaving the queue; their difference
        # is the queue-wait histogram sample (dequeue stamps are set by
        # drain_parked/top_up, the two exits from the source queue)
        self.enqueued_at: float = 0.0
        self.dequeued_at: float = 0.0
        # the request's Trace (core.trace) when the engine traces; the
        # handler thread is the single finalization point (success,
        # shed, timeout, client-gone — every exit buffers the trace)
        self.trace = None

    def respond(self, response: Dict[str, Any]) -> None:
        self.response = response
        self._event.set()

    def wait(self, timeout: float) -> Optional[Dict[str, Any]]:
        if self._event.wait(timeout):
            return self.response
        return None


class HTTPSource:
    """One host's HTTP server + request queue
    (ref: HTTPSource.scala:48-138; JVMSharedServer
    DistributedHTTPSource.scala:96-246 incl. port scanning and
    requestsSeen/Accepted counters)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 8899,
                 api_path: str = "/", max_queue: int = 10_000,
                 reply_timeout: float = 60.0, port_scan: int = 20,
                 max_parked: Optional[int] = None,
                 retry_after_s: int = 1):
        self.api_path = api_path
        self.queue: "queue.Queue[_ParkedRequest]" = queue.Queue(max_queue)
        self.requests_seen = 0
        self.requests_accepted = 0
        self.requests_answered = 0
        self.requests_rejected = 0
        # the parked-request table is BOUNDED: a stalled engine must shed
        # load with 503 + Retry-After, not hold thousands of connections
        # hostage until reply_timeout (the load-shedding half of the
        # Tail-at-Scale story). Default bound = the queue bound.
        self.max_parked = max_parked if max_parked is not None else max_queue
        self.retry_after_s = max(1, int(retry_after_s))
        # closed sources must tell persistent (keep-alive) connections
        # to go away: without this, a handler thread that outlives
        # close() would keep parking requests into a dead engine until
        # every one of them burned the full reply timeout
        self._closed = False
        # set by ServingEngine.start(): () -> bool engine liveness; the
        # /healthz endpoint folds it into its verdict
        self.health_probe: Optional[Callable[[], bool]] = None
        # set by ServingEngine.start(): () -> dict of latency-histogram
        # summaries (queue-wait/pad/device/respond), exported on /healthz
        self.metrics_probe: Optional[Callable[[], Dict[str, Any]]] = None
        # set by ServingEngine.start(): the engine's Tracer (ingress
        # creates each request's trace, honoring X-Trace-Id), the
        # /debug/traces exporter, and the /metrics Prometheus renderer
        self.tracer = None
        self.trace_probe: Optional[Callable[..., Dict[str, Any]]] = None
        self.prom_probe: Optional[Callable[[], str]] = None
        # set by ServingEngine.start(): the windowed SLO monitor
        # (core/slo.py — one sample per answered request, burn-rate
        # status folded into /healthz) and the flight-recorder bundle
        # probe behind /debug/bundle
        self.slo = None
        self.bundle_probe: Optional[Callable[..., Dict[str, Any]]] = None
        # set by ContinuousTrainer.start(): () -> control-loop status
        # dict (serving/controlplane.py); a degraded loop (circuit open
        # or dead trainer thread) degrades /healthz but stays HTTP 200
        # — training death must never take serving down
        self.controlplane_probe: Optional[
            Callable[[], Dict[str, Any]]] = None
        self._pending: Dict[str, _ParkedRequest] = {}
        self._lock = threading.Lock()
        self._new_rid = _request_id_factory()
        source = self

        class Handler(BaseHTTPRequestHandler):
            # HTTP/1.1 keep-alive: a load balancer (or the fleet client)
            # reuses its connection across requests, so the per-request
            # TCP handshake + server thread spawn disappear from the hot
            # path — at high client counts that overhead rivaled the
            # model itself. Every reply path below sends Content-Length,
            # which 1.1 persistence requires.
            protocol_version = "HTTP/1.1"
            # idle persistent connections fold after this many seconds
            # (also bounds how long a dead client can pin a handler
            # thread in its blocking read)
            timeout = 20

            def _send_json(self, code: int, payload: Dict[str, Any],
                           headers: Optional[Dict[str, str]] = None):
                body = json.dumps(payload).encode("utf-8")
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def _shed(self, reason: str):
                with source._lock:
                    source.requests_rejected += 1
                self._send_json(
                    503, {"error": reason,
                          "retry_after": source.retry_after_s},
                    {"Retry-After": str(source.retry_after_s)})

            def _send_text(self, code: int, text: str,
                           content_type: str = "text/plain"):
                body = text.encode("utf-8")
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _query_limit(self):
                """``?limit=`` parsed strictly: (ok, value). A
                non-integer or negative limit is the CALLER's mistake
                and must 400 — the old silent-ignore turned typos into
                full-buffer dumps, and a crash here was a 500 stack
                trace on a debug endpoint."""
                query = urllib.parse.parse_qs(
                    urllib.parse.urlsplit(self.path).query,
                    keep_blank_values=True)
                vals = query.get("limit")
                if vals is None:
                    return True, None
                try:
                    limit = int(vals[0])
                except (TypeError, ValueError):
                    return False, None
                if limit < 0:
                    return False, None
                return True, limit

            def _query_flag(self, name: str) -> bool:
                query = urllib.parse.parse_qs(
                    urllib.parse.urlsplit(self.path).query)
                vals = query.get(name)
                return bool(vals) and vals[0] not in ("0", "false", "")

            def do_GET(self):  # noqa: N802 (http.server API)
                path_only = self.path.split("?", 1)[0].rstrip("/")
                if path_only == "/metrics":
                    # Prometheus text exposition of every counter,
                    # histogram, swap/drift state (see core.prometheus)
                    if source.prom_probe is None:
                        self.send_error(
                            404, "no engine attached (metrics)")
                        return
                    try:
                        text = source.prom_probe()
                    except Exception as e:  # noqa: BLE001
                        self.send_error(500, f"metrics render: {e}")
                        return
                    from mmlspark_tpu.core.prometheus import \
                        PROM_CONTENT_TYPE
                    self._send_text(200, text, PROM_CONTENT_TYPE)
                    return
                if path_only == "/debug/traces":
                    # tail-sampled completed traces as Chrome
                    # trace-event JSON (open directly in Perfetto)
                    if source.trace_probe is None:
                        self.send_error(
                            404, "no engine attached (traces)")
                        return
                    ok, limit = self._query_limit()
                    if not ok:
                        self._send_json(400, {
                            "error": "limit must be a non-negative "
                                     "integer"})
                        return
                    try:
                        payload = source.trace_probe(limit)
                    except Exception as e:  # noqa: BLE001
                        self.send_error(500, f"trace export: {e}")
                        return
                    self._send_json(200, payload)
                    return
                if path_only == "/debug/bundle":
                    # the flight recorder's self-contained post-mortem
                    # bundle (core/flightrecorder.py). Multi-MB on a
                    # busy engine, so a casual scrape must opt in with
                    # ?confirm=1 — crawlers and dashboard wildcards do
                    # not get to dump the black box by accident.
                    if source.bundle_probe is None:
                        self.send_error(
                            404, "no flight recorder attached (bundle)")
                        return
                    ok, limit = self._query_limit()
                    if not ok:
                        self._send_json(400, {
                            "error": "limit must be a non-negative "
                                     "integer"})
                        return
                    if not self._query_flag("confirm"):
                        self._send_json(400, {
                            "error": "bundle dumps are large; re-request"
                                     " with ?confirm=1"})
                        return
                    try:
                        payload = source.bundle_probe(limit)
                    except Exception as e:  # noqa: BLE001
                        self.send_error(500, f"bundle export: {e}")
                        return
                    self._send_json(200, payload)
                    return
                if path_only != "/healthz":
                    self.send_error(404, f"unknown path {path_only}")
                    return
                healthy = True
                if source.health_probe is not None:
                    try:
                        healthy = bool(source.health_probe())
                    except Exception:  # noqa: BLE001 — probe crash = sick
                        healthy = False
                metrics: Optional[Dict[str, Any]] = None
                if source.metrics_probe is not None:
                    try:  # outside source._lock — the probe takes its own
                        metrics = source.metrics_probe()
                    except Exception:  # noqa: BLE001 — stats stay partial
                        metrics = {"error": "metrics probe failed"}
                slo_status: Optional[Dict[str, Any]] = None
                if source.slo is not None:
                    try:
                        # a scrape-driven evaluation (tightly gated) so
                        # alert state is fresh even on an idle engine
                        source.slo.evaluate(min_interval_s=0.2)
                        slo_status = source.slo.status()
                    except Exception:  # noqa: BLE001 — stats stay partial
                        slo_status = {"error": "slo probe failed"}
                cp_status: Optional[Dict[str, Any]] = None
                if source.controlplane_probe is not None:
                    try:
                        cp_status = source.controlplane_probe()
                    except Exception:  # noqa: BLE001 — stats stay
                        cp_status = {"error": "controlplane probe "
                                              "failed",
                                     "degraded": True}
                # DEGRADED: alive and serving, but an SLO is burning or
                # the continuous-training loop is unhealthy (circuit
                # open / trainer thread dead — frozen-model serving) —
                # stays HTTP 200 (a degraded engine must keep taking
                # traffic; pulling it from the LB would turn a burn
                # into an outage) with the machine-readable verdict
                status = "ok" if healthy else "unhealthy"
                if healthy and slo_status is not None and \
                        slo_status.get("degraded"):
                    status = "degraded"
                if healthy and cp_status is not None and \
                        cp_status.get("degraded"):
                    status = "degraded"
                with source._lock:
                    stats = {
                        "status": status,
                        "seen": source.requests_seen,
                        "accepted": source.requests_accepted,
                        "answered": source.requests_answered,
                        "rejected": source.requests_rejected,
                        "parked": len(source._pending),
                        "queue_depth": source.queue.qsize(),
                    }
                if metrics is not None:
                    stats["metrics"] = metrics
                if slo_status is not None:
                    stats["slo"] = slo_status
                if cp_status is not None:
                    stats["controlplane"] = cp_status
                self._send_json(200 if healthy else 503, stats)

            def do_POST(self):  # noqa: N802 (http.server API)
                if source._closed:
                    # drain persistent connections of a closed source:
                    # shed with an EXPLICIT Connection: close (so the
                    # client's will_close fires and it reconnects —
                    # reaching whatever replaced us) instead of parking
                    # requests into a dead engine
                    with source._lock:
                        source.requests_rejected += 1
                    self._send_json(
                        503, {"error": "source closed", "retry_after": 1},
                        {"Retry-After": "1", "Connection": "close"})
                    return
                with source._lock:
                    source.requests_seen += 1
                t_req = time.perf_counter()
                path_only = self.path.split("?", 1)[0]
                if source.api_path not in ("/", "") and \
                        path_only.rstrip("/") != source.api_path.rstrip("/"):
                    self.send_error(404, f"unknown path {path_only}")
                    return
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length) if length else b""
                req = HTTPSchema.request(
                    self.path, "POST", body,
                    {k: v for k, v in self.headers.items()})
                parked = _ParkedRequest(source._new_rid(), req)
                tracer = source.tracer
                if tracer is not None and tracer.enabled:
                    # request-scoped trace: root span from ingress. A
                    # traceparent header (or the legacy X-Trace-Id
                    # alias) CONTINUES the caller's trace — the root
                    # becomes a child of the remote client span, so a
                    # fleet request spanning several engine processes
                    # reassembles into one trace. This handler is the
                    # single finalization point — every exit below
                    # buffers it.
                    ctx = tracer.extract(self.headers)
                    parked.trace = tracer.continue_trace("request", ctx)
                    parked.trace.root.set("path", self.path)
                    if ctx is not None and ctx.parent_id:
                        parked.trace.root.set("remote_parent", True)

                def _finalize(code: int) -> None:
                    # every exit records exactly one SLO sample: the
                    # caller-observed verdict (5xx = unavailability,
                    # shed 503s included) and wall latency
                    if source.slo is not None:
                        try:
                            source.slo.record(
                                code < 500,
                                (time.perf_counter() - t_req) * 1e3)
                        except Exception:  # noqa: BLE001 — best-effort
                            pass
                    tr = parked.trace
                    if tr is None:
                        return
                    from mmlspark_tpu.core.trace import SHED_STATUSES
                    tr.root.set("http_status", code)
                    if code in SHED_STATUSES:
                        # load shedding / admission rejections are
                        # EXPECTED back-pressure, not failures: marking
                        # them as errors would let an overload flood the
                        # protected tail ring and evict the genuine
                        # error traces it exists for
                        tr.root.set("shed", True)
                    elif code >= 500:
                        tr.root.error()
                    tracer.finish(tr)

                with source._lock:
                    if len(source._pending) >= source.max_parked:
                        shed = True
                    else:
                        source._pending[parked.id] = parked
                        shed = False
                if shed:
                    self._shed("parked-request table full")
                    _finalize(503)
                    return
                parked.enqueued_at = time.perf_counter()
                try:
                    source.queue.put_nowait(parked)
                    with source._lock:
                        source.requests_accepted += 1
                except queue.Full:
                    with source._lock:
                        source._pending.pop(parked.id, None)
                    self._shed("queue full")
                    _finalize(503)
                    return
                resp = parked.wait(reply_timeout)
                with source._lock:
                    source._pending.pop(parked.id, None)
                try:
                    if resp is None:
                        self.send_error(504, "serving timeout")
                        _finalize(504)
                        return
                    code = resp["statusLine"]["statusCode"]
                    entity = resp.get("entity") or b""
                    if isinstance(entity, str):
                        entity = entity.encode("utf-8")
                    self.send_response(code)
                    # framing/hop-by-hop headers are computed by this
                    # server; forwarding pipeline-supplied ones would
                    # duplicate/conflict
                    _framing = {"content-length", "transfer-encoding",
                                "connection"}
                    sent_trace_id = False
                    for k, v in (resp.get("headers") or {}).items():
                        if k.lower() not in _framing:
                            if k.lower() == "x-trace-id":
                                sent_trace_id = True
                            self.send_header(k, v)
                    if parked.trace is not None and not sent_trace_id:
                        self.send_header("X-Trace-Id",
                                         parked.trace.trace_id)
                    self.send_header("Content-Length", str(len(entity)))
                    self.end_headers()
                    self.wfile.write(entity)
                except OSError:
                    # client gave up (timeout/disconnect) before the
                    # reply flushed: fold the connection quietly instead
                    # of killing the handler thread with a stack trace
                    if parked.trace is not None:
                        parked.trace.root.set("client_disconnected", True)
                    _finalize(499)
                    self.close_connection = True
                    return
                with source._lock:
                    source.requests_answered += 1
                _finalize(code)

            def log_message(self, *a):  # silence default stderr logging
                pass

        class Server(ThreadingHTTPServer):
            request_queue_size = 128  # listen backlog for bursty clients
            daemon_threads = True

        last_err: Optional[Exception] = None
        for p in range(port, port + port_scan):
            try:
                self.server = Server((host, p), Handler)
                # read the BOUND port back from the socket: port=0 asks
                # the OS for an ephemeral port (the collision-proof
                # choice for tests/fleets on shared hosts), and the
                # scan's requested p is not the truth there
                self.port = self.server.server_address[1]
                break
            except OSError as e:  # port taken — scan upward (ref :234)
                last_err = e
        else:
            raise OSError(f"no free port in [{port}, {port+port_scan}): "
                          f"{last_err}")
        self.address = f"http://{host}:{self.port}"
        self._thread = threading.Thread(target=self.server.serve_forever,
                                        daemon=True)
        self._thread.start()
        log.info("serving source listening on %s", self.address)

    def get_batch(self, max_rows: int = 64,
                  wait_s: float = 0.05) -> Tuple[DataTable, List[str]]:
        """Drain up to max_rows parked requests into a table
        (ref: HTTPSource.getBatch). Fixed-window poll — kept for the
        synchronous ``process_one_batch`` API; the engine's hot path is
        ``get_batch_adaptive``."""
        parked: List[_ParkedRequest] = []
        deadline = time.time() + wait_s
        while len(parked) < max_rows:
            remaining = deadline - time.time()
            if remaining <= 0 and parked:
                break
            try:
                parked.append(self.queue.get(
                    timeout=max(remaining, 0.001)))
            except queue.Empty:
                break
        if not parked:
            return DataTable({"id": [], "request": []}), []
        return (DataTable({"id": [p.id for p in parked],
                           "request": [p.request for p in parked]}),
                [p.id for p in parked])

    def drain_parked(self, max_rows: int, max_wait_s: float,
                     poll_s: float = 0.05) -> List[_ParkedRequest]:
        """Adaptive micro-batch drain (Clipper-style bounded queueing
        delay): block until the FIRST request arrives (bounded by
        ``poll_s`` so a stopping engine stays responsive), then flush as
        soon as EITHER ``max_rows`` rows are collected OR ``max_wait_s``
        has elapsed since that first request was picked up. A backed-up
        queue therefore dispatches full batches with zero added wait,
        while a lone request waits at most ``max_wait_s`` — unlike the
        fixed-window ``get_batch``, which charged every cycle the full
        window."""
        try:
            first = self.queue.get(timeout=poll_s)
        except queue.Empty:
            return []
        first.dequeued_at = time.perf_counter()
        parked: List[_ParkedRequest] = [first]
        deadline = first.dequeued_at + max_wait_s
        while len(parked) < max_rows:
            remaining = deadline - time.perf_counter()
            if remaining <= 0:
                break
            try:
                p = self.queue.get(timeout=remaining)
            except queue.Empty:
                break
            p.dequeued_at = time.perf_counter()
            parked.append(p)
        return parked

    def top_up(self, parked: List[_ParkedRequest],
               max_rows: int) -> bool:
        """Absorb whatever is ALREADY queued into a pending batch, up to
        ``max_rows`` — no waiting. Called by the batcher while it is
        blocked on a full dispatch queue: rows that arrived meanwhile
        ride along at zero added latency instead of forming a tiny
        trailing batch (the continuous-batching half of the adaptive
        policy). Returns True when anything was taken."""
        took = False
        while len(parked) < max_rows:
            try:
                p = self.queue.get_nowait()
            except queue.Empty:
                break
            p.dequeued_at = time.perf_counter()
            parked.append(p)
            took = True
        return took

    def get_batch_adaptive(
            self, max_rows: int, max_wait_s: float,
            poll_s: float = 0.05,
    ) -> Tuple[DataTable, List[str], List[float]]:
        """``drain_parked`` packaged as (table, ids, queue-waits) for
        embedders that want the adaptive policy without managing parked
        requests themselves."""
        parked = self.drain_parked(max_rows, max_wait_s, poll_s)
        if not parked:
            return DataTable({"id": [], "request": []}), [], []
        return (DataTable({"id": [p.id for p in parked],
                           "request": [p.request for p in parked]}),
                [p.id for p in parked],
                [max(0.0, p.dequeued_at - p.enqueued_at)
                 for p in parked])

    def respond(self, rid: str, response: Dict[str, Any]) -> bool:
        """Reply through the held connection (ref:
        DistributedHTTPSource.scala:188 server.respond(batch, uuid, …))."""
        with self._lock:
            parked = self._pending.get(rid)
        if parked is None:
            return False
        parked.respond(response)
        return True

    def close(self) -> None:
        self._closed = True      # persistent connections shed + fold
        self.server.shutdown()
        self.server.server_close()


class _NoDefaultPipeline:
    """Placeholder active pipeline of a zoo-only engine (no default
    model): reaching it means a request bypassed the model-routing
    reject, which is a bug — fail loudly."""

    def transform(self, table):
        raise RuntimeError("engine has no default pipeline; requests "
                           "must name a model (X-Model header or "
                           "/models/<name@version> path)")


class PipelineHandle:
    """One immutable (pipeline, version) binding plus its in-flight
    batch count — the unit of the zero-downtime swap protocol. Every
    dispatched micro-batch carries the handle it was BUILT with, so a
    batch is always decoded, executed, retried, and answered by exactly
    one model version (the no-mixed-version-batch invariant), and a
    version's outstanding count reaching zero is the drain signal.

    ``controller`` and ``rescue_to`` are set only on canary handles by
    the lifecycle layer: canary batch outcomes feed the controller's
    breach detector, and a failing canary batch re-executes on
    ``rescue_to`` (the stable handle) so clients never eat a canary's
    faults.

    ``model_name``/``model_key`` are set only on zoo handles
    (serving/zoo.py): a model-routed batch carries the model identity
    through decode/execute/reply, so device spans and reply headers
    can audit exactly which ``name@version`` served each row."""

    __slots__ = ("pipeline", "version", "precision", "aot", "prepare",
                 "execute", "is_canary", "controller", "rescue_to",
                 "model_name", "model_key", "_outstanding", "_lock")

    def __init__(self, pipeline: Transformer, version: str,
                 is_canary: bool = False):
        from mmlspark_tpu.core.quantize import stage_precision
        self.pipeline = pipeline
        self.version = str(version)
        # serving-precision + AOT labels, captured ONCE at handle build
        # (json_scoring_pipeline forwards them from the model): every
        # healthz/metrics/swap-audit surface reads the handle, so a
        # rolling swap to a quantized or AOT-loaded model is auditable
        # and the canary comparison is visibly like-for-like (or not)
        self.precision = stage_precision(pipeline)
        self.aot = bool(getattr(pipeline, "aot", False))
        # optional two-stage split (duck-typed; absent on plain stages)
        self.prepare = getattr(pipeline, "prepare_batch", None)
        self.execute = getattr(pipeline, "execute_prepared", None)
        self.is_canary = bool(is_canary)
        self.controller = None
        self.rescue_to: Optional["PipelineHandle"] = None
        self.model_name: Optional[str] = None
        self.model_key: Optional[str] = None
        self._outstanding = 0
        self._lock = threading.Lock()

    def acquire(self) -> None:
        with self._lock:
            self._outstanding += 1

    def release(self) -> None:
        with self._lock:
            self._outstanding -= 1

    @property
    def outstanding(self) -> int:
        with self._lock:
            return self._outstanding


class _BatchTraceCtx:
    """Per-micro-batch tracing context, riding the dispatch item from
    the batcher to the worker (and through retries/rescues) so every
    stage lands spans on the right request traces.

    Batch-join semantics: ``batch_span`` creates ONE span that is
    shared by every member trace and ``links`` each request's root
    span — one decode/device span explains all N rows it served."""

    __slots__ = ("tracer", "traces", "by_rid", "primary", "roots",
                 "dispatched_at")

    def __init__(self, tracer, parked: List[_ParkedRequest]):
        self.tracer = tracer
        self.traces = []
        self.by_rid: Dict[str, Any] = {}
        self.roots = []
        # stamped when the batcher hands the item to the dispatch
        # queue; the FIRST device span starts here so the worker-wake
        # handoff is attributed instead of falling between spans
        self.dispatched_at: Optional[float] = None
        for p in parked:
            if p.trace is not None:
                self.traces.append(p.trace)
                self.by_rid[p.id] = p.trace
                self.roots.append(p.trace.root)
        self.primary = self.traces[0] if self.traces else None

    def batch_span(self, name: str, start: Optional[float] = None):
        if self.primary is None:
            return None
        span = self.tracer.start_span(name, self.primary,
                                      parent=self.primary.root,
                                      start=start)
        for root in self.roots:
            span.link(root.trace_id, root.span_id)
        for tr in self.traces[1:]:
            tr.add(span)
        return span

    def request_span(self, rid: str, name: str,
                     start: Optional[float] = None):
        tr = self.by_rid.get(rid)
        if tr is None:
            return None
        return self.tracer.start_span(name, tr, start=start)


class _PendingGroup:
    """One model's batch-in-formation on the continuous batcher:
    admitted requests accumulating toward ``batch_size`` rows or
    ``max_wait_ms`` age, whichever first. Groups form and dispatch
    independently per model — the continuous-batching unit."""

    __slots__ = ("reqs", "prio", "first_at")

    def __init__(self, prio: int, first_at: float):
        self.reqs: List[_ParkedRequest] = []
        self.prio = prio
        self.first_at = first_at


class ServingEngine:
    """The streaming loop: source → adaptive micro-batcher → user
    pipeline → sink (the structured-streaming query of ref:
    ServingImplicits.scala:10-50
    ``readStream.server()…writeStream.server()``).

    Request→device path (the serving hot path):

    1. **Adaptive micro-batcher** — one batcher thread drains the
       source queue, flushing a batch as soon as ``batch_size`` rows
       are collected OR ``max_wait_ms`` has elapsed since the batch's
       first request (bounded queueing delay; Clipper, NSDI'17).
    2. **Two-stage pipeline** — when the pipeline exposes the
       duck-typed ``prepare_batch``/``execute_prepared`` split (see
       ``json_scoring_pipeline``), the batcher ALSO runs the host
       decode/pad stage before handing the batch to a worker through a
       bounded dispatch queue, so the next batch's host work overlaps
       the current batch's device execution even at ``workers=1``.
    3. **Workers** — N threads pop prepared batches and drive the
       device + reply flush; ``workers > 1`` additionally overlaps one
       batch's device round trip with another's reply flush (jit
       dispatch is thread-safe). CONTRACT: pipeline.transform must be
       thread-safe under workers > 1 (TPUModel is; a Lambda closing
       over mutable state is only if it locks).

    The whole path is instrumented with latency histograms
    (queue-wait / decode / pipeline / respond, plus the model's own
    pad / device split) exported through ``metrics()`` and /healthz.
    """

    def __init__(self, source: HTTPSource,
                 pipeline: Optional[Transformer] = None,
                 reply_col: str = "reply", id_col: str = "id",
                 batch_size: int = 64,
                 content_type: str = "application/json",
                 error_col: str = "error", workers: int = 1,
                 max_wait_ms: float = 5.0, pipeline_depth: int = 2,
                 version: str = "v0", tracer=None,
                 tracing: Optional[bool] = None,
                 zoo=None, admission=None,
                 activation_timeout_s: float = 30.0,
                 zoo_enforce_interval_s: float = 1.0,
                 slo=None, flight_recorder=None,
                 slo_eval_interval_s: float = 0.25,
                 variants=None,
                 retry_after_max_s: float = 30.0):
        from mmlspark_tpu.core.metrics import WindowedCounter, \
            histogram_set
        from mmlspark_tpu.core import trace as trace_mod
        self.source = source
        # multi-model plane (serving/zoo.py + serving/admission.py):
        # with a zoo, requests carrying model=name@version route to
        # lazily-activated zoo handles; ``pipeline`` stays the default
        # for unkeyed requests (None = unkeyed requests answer 400)
        if pipeline is None and zoo is None:
            raise ValueError("ServingEngine needs a pipeline, a zoo, "
                             "or both")
        self.zoo = zoo
        self.admission = admission
        self.activation_timeout_s = float(activation_timeout_s)
        self._zoo_enforce_interval_s = float(zoo_enforce_interval_s)
        self._default_ok = pipeline is not None
        if pipeline is None:
            pipeline = _NoDefaultPipeline()
        # batcher-thread-only state: requests parked on a model that is
        # still activating (flushed by _poll_awaiting; bounded by the
        # source's parked-request table like every parked request)
        self._awaiting: Dict[str, List[_ParkedRequest]] = {}
        self._awaiting_since: Dict[str, float] = {}
        # SLO-adaptive variant routing (serving/variants.py): resolved
        # model keys pass through the selector's cached route table at
        # ingest; the selector's DECISION pass runs only on the
        # rate-gated batcher tick (enforced by check_adaptive_serving)
        self.variants = variants
        # continuous batcher state (batcher thread only): per-model
        # groups forming toward batch_size/max_wait_ms, plus the ready
        # lane of already-acquired chunks (cold-activation flushes)
        # waiting for an in-flight token. A slow model's group waiting
        # for a token no longer blocks any other model's dispatch.
        self._pending: Dict[Optional[str], _PendingGroup] = {}
        self._ready: List[List[Any]] = []   # [prio, first_at, handle, reqs]
        # dynamic Retry-After (satellite of the adaptive plane): shed
        # replies quote the live backlog / drain-rate estimate instead
        # of a constant, clamped to [1, retry_after_max_s]
        self.retry_after_max_s = max(1, int(retry_after_max_s))
        self._retry_after_s = self.source.retry_after_s
        self._drained_rows = WindowedCounter(bucket_s=1.0,
                                             horizon_s=120.0)
        self._retry_tick = 0.0
        # admission/routing rejections by reason (under _stats_lock):
        # quota, priority, no_model, unknown_model, load_failed,
        # activation_timeout
        self.rejections: Dict[str, int] = {}
        # request tracing: ``tracing`` overrides config
        # ``trace.enabled``; the tracer (and so the completed-trace
        # buffer) defaults to the process-wide one, so a fleet's
        # engines share one buffer and training spans land beside
        # serving spans. ``self.tracer is None`` == tracing off — the
        # hot path pays one attribute check.
        if tracing is None:
            from mmlspark_tpu.core import config as _config
            tracing = bool(_config.get("trace.enabled", True))
        self.tracer = (tracer if tracer is not None
                       else trace_mod.get_tracer()) if tracing else None
        if self.tracer is not None and not self.tracer.enabled:
            self.tracer = None
        # windowed SLO engine (core/slo.py): always on by default —
        # one sample per answered request at the HTTP handler, a
        # rate-gated burn-rate evaluation on the batcher tick, status
        # on /healthz + serving_slo_* on /metrics. ``slo=False``
        # disables; pass an SLOMonitor to share/customize objectives.
        if slo is None:
            from mmlspark_tpu.core.slo import SLOMonitor
            slo = SLOMonitor()
        elif slo is False:
            slo = None
        self.slo = slo
        self._slo_eval_interval_s = float(slo_eval_interval_s)
        # flight recorder (core/flightrecorder.py): the always-on
        # black box — defaults to the process-wide recorder so one
        # bundle tells the whole process's story. ``False`` disables.
        if flight_recorder is None:
            from mmlspark_tpu.core.flightrecorder import get_recorder
            flight_recorder = get_recorder()
        elif flight_recorder is False:
            flight_recorder = None
        self.flight_recorder = flight_recorder
        # hooks THIS engine installs on the monitor are remembered so
        # stop() can uninstall exactly them: a shared SLOMonitor
        # reused in a later engine must not keep routing bundles to a
        # stopped engine's recorder
        self._slo_hooks_installed: List[str] = []
        if self.slo is not None:
            if self.flight_recorder is not None and \
                    self.slo.on_fire is None:
                # SLO breach => auto-captured post-mortem bundle
                # (rate-limited inside the recorder)
                rec = self.flight_recorder
                self.slo.on_fire = (
                    lambda alert: rec.trigger(
                        f"slo_breach:{alert.name}"))
                self._slo_hooks_installed.append("on_fire")
            if zoo is not None and self.slo.record_event is None:
                # alert transitions land on the registry event
                # timeline next to SwapEvent/ZooEvent
                self.slo.record_event = zoo.record_event
                self._slo_hooks_installed.append("record_event")
        # versioned pipeline binding: batches carry the handle they
        # were built with, so a swap can cut over atomically (one
        # attribute store) while in-flight batches drain on their own
        # version — see serving/lifecycle.py
        self._active = PipelineHandle(pipeline, version)
        self._swap_lock = threading.Lock()   # one swap at a time
        self.swap_state = "idle"
        self.swaps_completed = 0
        self.swaps_rolled_back = 0
        self.swap_events: List[Any] = []
        self.reply_col = reply_col
        self.id_col = id_col
        self.batch_size = batch_size
        self.content_type = content_type
        self.error_col = error_col
        self.workers = max(1, int(workers))
        # batching policy: flush on batch_size rows OR max_wait_ms
        # elapsed since the batch's first request, whichever first
        self.max_wait_ms = float(max_wait_ms)
        # in-flight gating: at most workers + (pipeline_depth - 1)
        # batches past the batcher at once — every worker busy plus a
        # bounded run-ahead of prepared batches. While no token is
        # free (device saturated) the batcher keeps ABSORBING queued
        # requests into the pending batch, so occupancy rises exactly
        # when the device is the bottleneck; without the gate, a burst
        # dispatches as many tiny batches as there are slots and pays
        # the fixed per-batch cost once per row instead of per batch.
        self.pipeline_depth = max(1, int(pipeline_depth))
        self._inflight = threading.Semaphore(
            self.workers + self.pipeline_depth - 1)
        self._dispatch_q: "queue.Queue[Tuple]" = queue.Queue()
        self._stop = threading.Event()
        self._killed = threading.Event()   # chaos kill: no restart
        self._threads: List[threading.Thread] = []
        self._batcher: Optional[threading.Thread] = None
        self._threads_lock = threading.Lock()
        self._supervisor: Optional[threading.Thread] = None
        self.batches_processed = 0
        self.workers_restarted = 0
        self._stats_lock = threading.Lock()
        self.hists = histogram_set("queue_wait_ms", "decode_ms",
                                   "pipeline_ms", "respond_ms",
                                   "batch_rows")

    # -- versioned pipeline access ------------------------------------------

    @property
    def pipeline(self) -> Transformer:
        """The currently-active pipeline (latest cutover version)."""
        return self._active.pipeline

    @pipeline.setter
    def pipeline(self, pipeline: Transformer) -> None:
        # raw override (tests / embeddings): rebind the active handle in
        # place, keeping the version tag — the supported production path
        # is swap(), which warms up and canaries the incoming model.
        # Under _stats_lock like every other handle/state write, so
        # metrics()/healthz snapshots stay consistent.
        with self._stats_lock:
            self._active = PipelineHandle(pipeline, self._active.version)

    @property
    def model_version(self) -> str:
        return self._active.version

    def _route(self) -> PipelineHandle:
        """Pick the handle for the NEXT micro-batch: the active version,
        except during a canary phase when the swap controller diverts
        its configured fraction of batches to the incoming version."""
        active = self._active
        swap_ctl = self.__dict__.get("_swap_ctl")
        if swap_ctl is not None:
            try:
                return swap_ctl.route(active)
            except Exception:  # noqa: BLE001 — a sick controller must
                return active  # never take the serving path down
        return active

    def swap(self, pipeline: Transformer, version: str,
             warmup_example: Any = None, policy: Any = None):
        """Zero-downtime model swap: warm the incoming pipeline off the
        hot path, canary a fraction of live traffic through it, promote
        on a clean window or auto-roll-back on an error/latency breach.
        Blocks until the swap completes or rolls back; returns a
        ``SwapResult`` (see serving/lifecycle.py)."""
        from mmlspark_tpu.serving.lifecycle import execute_swap
        return execute_swap(self, pipeline, version,
                            warmup_example=warmup_example, policy=policy)

    def _respond_ok(self, rid: str, rep: Any,
                    handle: Optional[PipelineHandle] = None) -> None:
        body = rep if isinstance(rep, (bytes, str)) \
            else json.dumps(_to_jsonable(rep))
        headers = {"Content-Type": self.content_type}
        if handle is not None and handle.model_key is not None:
            # model-routed replies echo the serving identity so a
            # client (and the chaos drill) can audit that no reply ever
            # crossed models
            headers["X-Model"] = handle.model_key
        self.source.respond(rid, HTTPSchema.response(
            200, "OK", body if isinstance(body, bytes)
            else body.encode("utf-8"), headers))

    def _finish_request_trace(self, tctx: Optional[_BatchTraceCtx],
                              rid: str, t_answer: float,
                              error: bool = False) -> None:
        """Trace bookkeeping for one reply, BEFORE the respond() event
        fires: a ``respond`` span covering wait-for-my-turn in the
        answer loop + this row's flush, then the root closes at
        reply-enqueue. All trace writes happen before the handler
        thread (which buffers the finished trace) can wake."""
        if tctx is None:
            return
        span = tctx.request_span(rid, "respond", start=t_answer)
        if span is None:
            return
        if error:
            span.error()
        span.finish()
        root = tctx.by_rid[rid].root
        if error:
            root.error()
        root.finish()

    def _answer_output(self, out: DataTable, ids: List[str],
                       tctx: Optional[_BatchTraceCtx] = None,
                       handle: Optional[PipelineHandle] = None) -> None:
        """Answer one transformed batch, splitting per-row errors: a
        non-null ``error_col`` value means that row failed and gets a
        500 while its batchmates still get their 200s
        (ref: SimpleHTTPTransformer.scala:104-150 error-split pipeline)."""
        t_answer = time.perf_counter()
        replies = out[self.reply_col]
        out_ids = out[self.id_col]
        errors = (out[self.error_col]
                  if self.error_col in out.column_names else None)
        # per-row 500s echo the model identity too: a client auditing
        # routing must be able to attribute EVERY reply, not just 200s
        err_headers = ({"X-Model": handle.model_key}
                       if handle is not None
                       and handle.model_key is not None else None)
        answered = set()
        for i, (rid, rep) in enumerate(zip(out_ids, replies)):
            err = errors[i] if errors is not None else None
            if err is not None and err == err:  # non-null, non-NaN
                self._finish_request_trace(tctx, rid, t_answer,
                                           error=True)
                self.source.respond(rid, HTTPSchema.response(
                    500, f"row error: {err}", None, err_headers))
            else:
                self._finish_request_trace(tctx, rid, t_answer)
                self._respond_ok(rid, rep, handle)
            answered.add(rid)
        for rid in ids:
            if rid not in answered:
                self._finish_request_trace(tctx, rid, t_answer,
                                           error=True)
                self.source.respond(rid, HTTPSchema.response(
                    500, "row dropped by pipeline", None, err_headers))

    def process_one_batch(self, wait_s: float = 0.05) -> int:
        """Synchronous one-shot drain (fixed poll window) — kept for
        embedding/tests; a started engine runs the adaptive
        batcher/worker pipeline instead."""
        table, ids = self.source.get_batch(self.batch_size, wait_s)
        if not ids:
            return 0
        self._execute_batch(table, ids, None, self._active)
        return len(ids)

    def _device_span(self, tctx: Optional[_BatchTraceCtx],
                     handle: PipelineHandle, rows: int):
        """The batch-join device span: ONE span shared by every request
        trace in the micro-batch, linking their root spans and carrying
        the version/routing annotations the swap protocol needs to be
        debuggable. Returns (span, jit_miss_probe, misses_before)."""
        if tctx is None or tctx.primary is None:
            return None, None, None
        start = tctx.dispatched_at     # consumed once: a rescue/retry
        tctx.dispatched_at = None      # re-run starts its span at now
        ds = tctx.batch_span("device", start=start)
        ds.set("model_version", handle.version)
        if handle.model_key is not None:
            ds.set("model", handle.model_key)
        ds.set("rows", rows)
        if handle.is_canary:
            ds.set("canary", True)
        state = self.swap_state
        if state != "idle":
            ds.set("swap_state", state)
        bucket_for = getattr(handle.pipeline, "bucket_for", None)
        if callable(bucket_for):
            try:
                ds.set("bucket", int(bucket_for(rows)))
            except Exception:  # noqa: BLE001 — annotation only
                pass
        miss_fn = getattr(handle.pipeline, "jit_cache_miss_count", None)
        miss0 = None
        if callable(miss_fn):
            try:
                miss0 = int(miss_fn())
            except Exception:  # noqa: BLE001 — annotation only
                miss_fn = None
        return ds, miss_fn, miss0

    def _execute_batch(self, table: DataTable, ids: List[str],
                       prepped: Any,
                       handle: Optional[PipelineHandle] = None,
                       tctx: Optional[_BatchTraceCtx] = None) -> None:
        """Stage 2 of the pipeline: device execution + reply flush for
        one micro-batch (``prepped`` carries stage 1's decode output
        when the pipeline supports the split). The whole batch runs on
        ``handle``'s pipeline version — retries included — so no reply
        batch ever mixes model versions."""
        from mmlspark_tpu.core.trace import use_span
        if handle is None:
            handle = self._active
        # canary handles carry their controller; stable batches report
        # to whatever swap is in flight (the latency-delta baseline)
        ctl = handle.controller if handle.controller is not None \
            else self.__dict__.get("_swap_ctl")
        ds, miss_fn, miss0 = self._device_span(tctx, handle, len(ids))
        span_ctx = use_span(ds) if ds is not None \
            else contextlib.nullcontext()
        t0 = time.perf_counter()
        try:
            with span_ctx:
                if prepped is not None and handle.execute is not None:
                    out = handle.execute(table, prepped)
                else:
                    out = handle.pipeline.transform(table)
        except Exception as e:  # noqa: BLE001 — isolate the poison row(s)
            if ds is not None:
                ds.error(e).finish()
            if handle.is_canary and handle.rescue_to is not None:
                # a canary batch's faults are the SWAP's problem, not
                # the clients': record the strike and re-execute the
                # whole batch on the stable version (fresh decode — the
                # prepped payload may be the poisoned stage's output)
                log.warning("canary batch failed (%s); rescuing on %s",
                            e, handle.rescue_to.version)
                if ctl is not None:
                    ctl.observe(handle, ok=False, latency_ms=(
                        time.perf_counter() - t0) * 1e3, error=e)
                self._run_rescued(table, ids, handle.rescue_to, tctx)
                return
            log.warning("serving batch failed (%s); retrying per-row", e)
            if self.slo is not None and handle.model_key is not None:
                # per-model SLO stream (batch granularity): the failed
                # batch is this model's bad event even though per-row
                # retries may still answer some rows
                self.slo.record(False,
                                (time.perf_counter() - t0) * 1e3,
                                model=handle.model_key,
                                include_engine=False)
            self._process_rows_individually(table, ids, handle, tctx)
            with self._stats_lock:
                self.batches_processed += 1
            return
        dt_ms = (time.perf_counter() - t0) * 1e3
        if ds is not None:
            if miss_fn is not None:
                try:
                    ds.set("jit_cache_miss", bool(miss_fn() - miss0))
                except Exception:  # noqa: BLE001 — annotation only
                    pass
            ds.finish()
        if ctl is not None:
            # the controller discards row_errors for stable handles, so
            # only canary batches pay the error-column scan
            row_errors = (self._count_row_errors(out)
                          if handle.is_canary else 0)
            if (row_errors > 0 and handle.is_canary
                    and handle.rescue_to is not None):
                # row-level canary errors must not leak to clients
                # either: strike the canary, answer from stable. The
                # engine histogram is observed by the rescue run only —
                # one client batch, one pipeline_ms sample.
                ctl.observe(handle, ok=True, latency_ms=dt_ms,
                            row_errors=row_errors)
                self._run_rescued(table, ids, handle.rescue_to, tctx)
                return
            ctl.observe(handle, ok=True, latency_ms=dt_ms,
                        row_errors=row_errors)
        self.hists["pipeline_ms"].observe(dt_ms)
        if self.zoo is not None and handle.model_name is not None:
            # per-model latency (cardinality-capped — serving/zoo.py)
            self.zoo.observe_latency(handle.model_name, dt_ms)
        if self.slo is not None and handle.model_key is not None:
            # per-model SLO stream (engine-level totals come from the
            # HTTP handler; include_engine=False avoids double count)
            self.slo.record(True, dt_ms, model=handle.model_key,
                            include_engine=False)
        if self.variants is not None and handle.model_key is not None:
            # the selector's windowed latency/cost profile feed (O(1)
            # counter writes; decisions happen on the batcher tick)
            self.variants.observe(handle.model_key, dt_ms, len(ids))
        t1 = time.perf_counter()
        try:
            self._answer_output(out, ids, tctx, handle)
        except Exception as e:  # noqa: BLE001 — e.g. missing reply column
            log.warning("answering batch failed (%s); sending 500s", e)
            for rid in ids:
                self.source.respond(rid, HTTPSchema.response(
                    500, f"reply error: {e}", None))
        self.hists["respond_ms"].observe(
            (time.perf_counter() - t1) * 1e3)
        with self._stats_lock:
            self.batches_processed += 1

    def _run_rescued(self, table: DataTable, ids: List[str],
                     rescue: PipelineHandle,
                     tctx: Optional[_BatchTraceCtx] = None) -> None:
        """Re-execute a failed canary batch on the stable handle,
        COUNTED as in-flight on it: the swap's drain phase polls the
        old handle's outstanding count, so an untracked rescue could
        let the drain complete while this batch still runs on the old
        version. The trace context rides along — a rescued trace shows
        two device spans (the failed canary's and the stable rerun's),
        which is exactly the story a swap post-mortem needs."""
        rescue.acquire()
        try:
            self._execute_batch(table, ids, None, rescue, tctx)
        finally:
            rescue.release()

    def _count_row_errors(self, out: DataTable) -> int:
        """Non-null error_col rows in a transformed batch (the canary
        controller counts them against the incoming version)."""
        if self.error_col not in out.column_names:
            return 0
        errs = out[self.error_col]
        return sum(1 for e in errs if e is not None and e == e)

    def _process_rows_individually(self, table: DataTable,
                                   ids: List[str],
                                   handle: Optional[PipelineHandle] = None,
                                   tctx: Optional[_BatchTraceCtx] = None,
                                   ) -> None:
        """Batch-failure fallback: run each row alone so one poison
        request cannot 500 its batchmates (the per-row half of the
        reference's error isolation, SimpleHTTPTransformer.scala:104-150).
        Each retried row gets its OWN device span (retry=true) on its
        trace — the poison row's trace shows the failed batch span AND
        its lone-row verdict."""
        if handle is None:
            handle = self._active
        requests = table["request"]
        for rid, req in zip(ids, requests):
            row = DataTable({"id": [rid], "request": [req]})
            span = tctx.request_span(rid, "device") if tctx is not None \
                else None
            if span is not None:
                span.set("model_version", handle.version)
                span.set("rows", 1)
                span.set("retry", True)
            try:
                out = handle.pipeline.transform(row)
                if span is not None:
                    span.finish()
                self._answer_output(out, [rid], tctx, handle)
            except Exception as e:  # noqa: BLE001
                if span is not None:
                    span.error(e).finish()
                self.source.respond(rid, HTTPSchema.response(
                    500, f"pipeline error: {e}", None))

    def _build_item(self, parked: List[_ParkedRequest],
                    handle: PipelineHandle) -> Tuple:
        """Assemble + (optionally) decode one collected batch: the host
        half of the two-stage pipeline, run on the batcher thread.
        Tracing: each member request gets a ``queue_wait`` span
        (ingress enqueue → batch assembly, covering both the source
        queue AND the adaptive collect window) and the batch gets a
        shared ``decode`` span; both ride the returned item so the
        worker's device/respond spans land on the same traces."""
        table = DataTable({"id": [p.id for p in parked],
                           "request": [p.request for p in parked]})
        ids = [p.id for p in parked]
        tctx: Optional[_BatchTraceCtx] = None
        if self.tracer is not None:
            ctx = _BatchTraceCtx(self.tracer, parked)
            if ctx.primary is not None:
                tctx = ctx
                t_build = time.perf_counter()
                for p in parked:
                    if p.trace is not None:
                        self.tracer.start_span(
                            "queue_wait", p.trace,
                            start=p.enqueued_at).finish(t_build)
        prepped = None
        if handle.prepare is not None and handle.execute is not None:
            t0 = time.perf_counter()
            dspan = tctx.batch_span("decode", start=t0) \
                if tctx is not None else None
            if dspan is not None:
                dspan.set("rows", len(ids))
            try:
                prepped = handle.prepare(table)
                if dspan is not None:
                    codecs = getattr(prepped, "codecs", None)
                    if codecs:
                        dspan.set("codec",
                                  ",".join(sorted(codecs)))
                    dspan.finish()
                self.hists["decode_ms"].observe(
                    (time.perf_counter() - t0) * 1e3)
            except Exception as e:  # noqa: BLE001 — poison rows can die
                # in decode too: hand the batch over un-prepared so the
                # worker's per-row retry isolates the offender
                if dspan is not None:
                    dspan.error(e).finish()
                prepped = None
        # per-request codec rejects (columnar ingress, io/columnar.py):
        # a malformed or schema-mismatched body 400s exactly ITS
        # request — its trace finalizes as an error — while batch-mates
        # proceed to dispatch
        rejects = getattr(prepped, "rejects", None)
        if rejects:
            table, ids, tctx = self._apply_rejects(
                parked, table, ids, rejects, tctx)
            if not ids:
                return None   # nothing survived decode — no dispatch
        if tctx is not None:
            tctx.dispatched_at = time.perf_counter()
        return table, ids, prepped, handle, tctx

    def _apply_rejects(self, parked: List[_ParkedRequest],
                       table: DataTable, ids: List[str],
                       rejects: Dict[str, str], tctx):
        """Answer 400 for every codec-rejected request (finalizing its
        trace with error=true) and return the filtered (table, ids,
        trace-context) the surviving batch dispatches with."""
        kept: List[_ParkedRequest] = []
        for p in parked:
            msg = rejects.get(p.id)
            if msg is None:
                kept.append(p)
                continue
            if p.trace is not None:
                p.trace.root.set("codec_error", msg)
                p.trace.root.error()
            self.source.respond(p.id, HTTPSchema.response(
                400, "bad request",
                json.dumps({"error": msg}).encode("utf-8"),
                {"Content-Type": "application/json"}))
        keep_idx = [i for i, rid in enumerate(ids) if rid not in rejects]
        ids = [ids[i] for i in keep_idx]
        table = table._take_indices(np.asarray(keep_idx, dtype=np.int64))
        new_tctx = None
        if self.tracer is not None and kept:
            ctx = _BatchTraceCtx(self.tracer, kept)
            if ctx.primary is not None:
                new_tctx = ctx
        return table, ids, new_tctx

    def _batcher_loop(self):
        """Stage 1 of the pipeline: adaptive collect + (optional) host
        decode/pad, feeding the bounded dispatch queue. While a worker
        drives the device for batch N, this thread is already
        collecting and decoding batch N+1 — host work overlaps device
        work instead of serializing with it. While the dispatch queue
        is full (workers saturated), the pending batch keeps absorbing
        newly-queued requests up to batch_size, so batches grow toward
        full occupancy exactly when the device is the bottleneck.

        With a model zoo attached the plane is CONTINUOUS and
        MODEL-ROUTED (Orca-style iteration-level scheduling, OSDI'22,
        adapted to micro-batch granularity): every loop turn drains
        whatever is queued RIGHT NOW into per-model pending groups
        (admission + variant routing at ingest), then ``_pump``
        dispatches every group that is ready (full or aged past
        ``max_wait_ms``) for which an in-flight token is free —
        non-blocking, oldest-first within priority. A slow model's
        group waiting on a token no longer blocks another model's
        admission or dispatch (the old loop dispatched groups
        sequentially, BLOCKING on the token inside each one), and
        newly parked requests join their model's next dispatch slot
        the moment a pipeline-depth token frees. Batches still never
        mix models, and cold models still activate on the zoo's
        loader thread while their requests park in ``_awaiting``."""
        while not self._stop.is_set():
            busy = bool(self._pending) or bool(self._ready) \
                or bool(self._awaiting)
            try:
                if self.zoo is not None and busy:
                    # continuous mode: absorb what is already queued
                    # (bounded poll so pending work keeps pumping),
                    # never block batch-formation on a full drain
                    parked = self.source.drain_parked(
                        self.batch_size, 0.0, poll_s=0.002)
                    if parked:
                        self.source.top_up(parked, self.batch_size)
                else:
                    parked = self.source.drain_parked(
                        self.batch_size, self.max_wait_ms / 1e3)
            except Exception as e:  # noqa: BLE001 — keep collecting
                log.error("serving batcher error (continuing): %s", e)
                time.sleep(0.005)
                continue
            if self.slo is not None:
                # burn-rate evaluation tick: the batcher is the one
                # thread that is always awake (drain polls 50 ms even
                # idle), so alerts fire DURING a burn and resolve
                # after recovery without waiting for a scrape
                try:
                    self.slo.evaluate(
                        min_interval_s=self._slo_eval_interval_s)
                except Exception as e:  # noqa: BLE001 — keep serving
                    log.error("slo evaluate failed (continuing): %s", e)
            self._update_retry_after()
            if self.zoo is None:
                if parked:
                    self._dispatch_parked(parked)
                continue
            try:
                self._ingest(parked)
            except Exception as e:  # noqa: BLE001 — keep collecting
                # per-request rejects answer inside _ingest; a fault
                # here strands at most this drain's unrouted requests
                # on their reply timeout — the loop must keep serving
                log.error("request ingest failed (continuing): %s", e)
            try:
                now = time.perf_counter()
                for handle, chunk, prio in self._poll_awaiting():
                    # cold-activation flushes arrive pre-acquired and
                    # chunked; they queue in the ready lane stamped
                    # with their oldest member's dequeue time so the
                    # oldest-first pump ranks them fairly
                    self._ready.append(
                        [prio, min((p.dequeued_at for p in chunk),
                                   default=now), handle, chunk])
            except Exception as e:  # noqa: BLE001 — keep collecting
                log.error("awaiting poll failed (continuing): %s", e)
            try:
                # LRU eviction under memory pressure, rate-gated: the
                # batcher is the one thread that is always awake while
                # traffic flows (the loader also enforces after loads)
                self.zoo.enforce(
                    min_interval_s=self._zoo_enforce_interval_s)
            except Exception as e:  # noqa: BLE001 — eviction is
                # best-effort here; the loader's post-load enforce
                # and the next tick retry
                log.error("zoo enforce failed (continuing): %s", e)
            if self.variants is not None:
                # the variant plane's DECISION tick (rate-gated
                # inside the selector): profiles + burn alerts +
                # queue pressure in, a fresh cached route table out.
                # This is the ONLY place selection runs — never in
                # the HTTP handler (check_adaptive_serving).
                try:
                    self.variants.tick(pressure=self._pressure())
                except Exception as e:  # noqa: BLE001 — routing
                    # falls back to the last cached table
                    log.error("variant tick failed (continuing): %s",
                              e)
            try:
                self._pump()
            except Exception as e:  # noqa: BLE001 — keep collecting
                log.error("dispatch pump failed (continuing): %s", e)

    def _ingest(self, parked: List[_ParkedRequest]) -> None:
        """Admission-check + model-route newly drained requests into
        their per-model pending groups (batcher thread only). Routing
        happens BEFORE admission so unroutable requests answer 400/404
        without spending quota tokens; the variant selector's cached
        route table is applied here, once per request, as a dict
        lookup. Groups hold a zoo waiter for their key so a model with
        admitted-but-undispatched demand is never an eviction victim."""
        if not parked:
            return
        from mmlspark_tpu.serving.admission import request_identity
        from mmlspark_tpu.serving.zoo import model_key_of
        # one pressure sample per drained batch: the batcher is the
        # only consumer of both queues, so it cannot meaningfully
        # change within one ingest pass — no per-request qsize()
        pressure = self._pressure() if self.admission is not None else 0
        now = time.perf_counter()
        for p in parked:
            key = model_key_of(p.request)
            if key is None and not self._default_ok:
                self._reject_parked(
                    p, 400, "no_model",
                    "no model specified: set X-Model or POST "
                    "/models/<name@version>")
                continue
            if key is not None:
                # resolving here also merges bare-name and
                # name@latest requests into ONE dispatch group
                resolved = self.zoo.resolve(key)
                if resolved is None:
                    self._reject_parked(
                        p, 404, "unknown_model",
                        f"unknown model {key!r}; registered: "
                        f"{self.zoo.names_preview()}")
                    continue
                key = resolved
                if self.variants is not None:
                    # cached table read (O(1)); the reply's X-Model
                    # echoes the variant that actually served
                    key = self.variants.route(key)
            tenant, priority = request_identity(p.request)
            if self.admission is not None:
                verdict = self.admission.decide(tenant, priority,
                                                pressure)
                if verdict == "quota":
                    self._reject_parked(
                        p, 429, "quota",
                        f"tenant {tenant!r} over quota",
                        {"Retry-After": self._retry_header()})
                    continue
                if verdict == "priority":
                    self._reject_parked(
                        p, 503, "priority",
                        f"shed: engine saturated (priority {priority})",
                        {"Retry-After": self._retry_header()})
                    continue
            grp = self._pending.get(key)
            if grp is None:
                grp = _PendingGroup(priority, now)
                self._pending[key] = grp
                if key is not None:
                    # parked demand must survive until dispatch (the
                    # _awaiting discipline): without the hold, demand
                    # > capacity livelocks on load/evict/reload
                    self.zoo.add_waiter(key)
            grp.reqs.append(p)
            grp.prio = min(grp.prio, priority)

    def _drop_pending(self, key: Optional[str]) -> None:
        """Forget one pending group and release its zoo waiter hold."""
        self._pending.pop(key, None)
        if key is not None:
            self.zoo.remove_waiter(key)

    def _pump(self) -> None:
        """Dispatch every READY unit an in-flight token can cover,
        oldest-first within priority (batcher thread only). Units are
        ready-lane chunks (always dispatchable: handle in hand) and
        pending groups that are full or older than ``max_wait_ms``.
        The token acquire is NON-blocking: when the device is
        saturated the pump returns and groups keep absorbing arrivals
        — back-pressure becomes batch occupancy, exactly like the old
        top-up loop, but per model. Oldest-first ordering is the
        fairness bound: a continuously-fed hot model re-forms its
        group with a FRESH first_at after every dispatch, so a colder
        group's older timestamp wins the next free token — no group
        waits more than one token-release cycle behind hot traffic."""
        max_wait_s = self.max_wait_ms / 1e3
        while not self._stop.is_set():
            now = time.perf_counter()
            pick_ready = -1
            pick_key: Optional[str] = None
            best: Optional[Tuple[int, float]] = None
            for i, entry in enumerate(self._ready):
                rank = (entry[0], entry[1])
                if best is None or rank < best:
                    best, pick_ready, pick_key = rank, i, None
            for key, grp in self._pending.items():
                if len(grp.reqs) < self.batch_size \
                        and now - grp.first_at < max_wait_s:
                    continue        # still forming
                rank = (grp.prio, grp.first_at)
                if best is None or rank < best:
                    best, pick_ready, pick_key = rank, -1, key
            if best is None:
                return              # nothing ready
            if not self._inflight.acquire(blocking=False):
                return              # saturated: groups keep absorbing
            if pick_ready >= 0:
                entry = self._ready.pop(pick_ready)
                self._dispatch_now(entry[3], entry[2])
                continue
            grp = self._pending[pick_key]
            if pick_key is None:
                # default-pipeline group: version routing + handle
                # acquisition happen inside _dispatch_now
                chunk = grp.reqs[:self.batch_size]
                del grp.reqs[:self.batch_size]
                if grp.reqs:
                    grp.first_at = grp.reqs[0].dequeued_at
                else:
                    self._drop_pending(None)
                self._dispatch_now(chunk, None)
                continue
            try:
                handle, state, msg = self.zoo.acquire(pick_key)
            except Exception as e:  # noqa: BLE001 — e.g. the loader
                # thread failing to spawn; this group answers alone,
                # other groups (and the batcher) keep going
                self._inflight.release()
                for p in grp.reqs:
                    self._reject_parked(
                        p, 500, "routing_error",
                        f"model routing error for {pick_key!r}: {e}")
                self._drop_pending(pick_key)
                continue
            if state == "resident":
                chunk = grp.reqs[:self.batch_size]
                del grp.reqs[:self.batch_size]
                if grp.reqs:
                    grp.first_at = grp.reqs[0].dequeued_at
                else:
                    self._drop_pending(pick_key)
                self._dispatch_now(chunk, handle)
                continue
            self._inflight.release()    # no dispatch on this path
            if state == "loading":
                # hand the whole group to the awaiting table (its own
                # waiter hold + activation timeout); drop ours AFTER
                # so the model is never transiently waiter-free
                self._enqueue_awaiting(pick_key, grp.reqs)
                self._pending.pop(pick_key, None)
                self.zoo.remove_waiter(pick_key)
            elif state == "failed":
                for p in grp.reqs:
                    self._reject_parked(
                        p, 503, "load_failed",
                        f"model {pick_key!r} failed to load: {msg}",
                        {"Retry-After": self._retry_header(floor=5)})
                self._drop_pending(pick_key)
            else:   # unknown (e.g. deregistered while pending)
                for p in grp.reqs:
                    self._reject_parked(p, 404, "unknown_model", msg)
                self._drop_pending(pick_key)

    def _dispatch_now(self, parked: List[_ParkedRequest],
                      handle: Optional[PipelineHandle]) -> None:
        """Assemble + dispatch ONE micro-batch whose in-flight token is
        ALREADY held (the pump acquired it non-blocking). ``handle`` is
        None for the default (single-model) path — version routing and
        acquisition happen here — or a zoo handle that arrives ALREADY
        acquired (zoo.acquire bumps outstanding under the registry
        lock, atomically with the eviction scan)."""
        # token ownership transfers to the worker ONLY on a
        # successful put; any other exit (assembly failure, a
        # respond() error, a BaseException killing this thread)
        # must give it back, or each incident would permanently
        # shrink the engine's dispatch budget
        handed_off = False
        try:
            if handle is None:
                # version routing happens HERE, once per batch: the
                # handle rides with the item so decode, execution,
                # retries, and replies all use one model version.
                # acquire() BEFORE any other work, then re-check the
                # active handle: a cutover landing between route and
                # acquire would otherwise let the swap's drain poll
                # read outstanding==0 while this batch is still headed
                # for the old version.
                handle = self._route()
                handle.acquire()
                if not handle.is_canary and handle is not self._active:
                    handle.release()
                    handle = self._active   # stale route: follow cutover
                    handle.acquire()
            try:
                item = self._build_item(parked, handle)
            except Exception as e:  # noqa: BLE001
                log.error("batch assembly failed (%s); "
                          "dropping to 500s", e)
                for p in parked:
                    self.source.respond(p.id, HTTPSchema.response(
                        500, f"batch assembly error: {e}", None))
                return
            if item is None:
                # every request in the batch was codec-rejected
                # (each already answered 400); nothing to dispatch
                return
            self._dispatch_q.put(item)   # unbounded: tokens bound it
            handed_off = True
        finally:
            if not handed_off:
                # both the in-flight token AND the version handle
                # must come back on any non-dispatch exit
                if handle is not None:
                    handle.release()
                self._inflight.release()
        self._drained_rows.inc(len(parked))
        for p in parked:
            # dequeue stamp, not dispatch time: queue_wait must not
            # absorb the token wait or the decode stage (decode_ms
            # measures that) — the breakdown stays additive
            self.hists["queue_wait_ms"].observe(
                max(0.0, p.dequeued_at - p.enqueued_at) * 1e3)
        self.hists["batch_rows"].observe(float(len(parked)))

    def _dispatch_parked(self, parked: List[_ParkedRequest],
                         handle: Optional[PipelineHandle] = None) -> None:
        """Token-gate + assemble + dispatch ONE micro-batch (the
        single-model path; zoo engines go through the continuous
        ``_pump``). Waits for an in-flight token, topping the pending
        batch up from the queue meanwhile: back-pressure converts
        directly into batch occupancy instead of tiny trailing
        batches."""
        granted = False
        while not self._stop.is_set():
            if self._inflight.acquire(timeout=0.005):
                granted = True
                break
            if self.zoo is None and len(parked) < self.batch_size:
                try:
                    self.source.top_up(parked, self.batch_size)
                except Exception:  # noqa: BLE001 — source closing
                    pass
        if not granted:              # stopping — parked requests will
            if handle is not None:   # run out their reply timeout, but
                handle.release()     # the zoo handle must drain
            return
        self._dispatch_now(parked, handle)

    # -- model routing + admission (zoo engines; batcher thread only) -------

    def _pressure(self) -> int:
        """The admission layer's saturation signal: prepared batches
        queued behind busy workers PLUS requests backed up in the
        source queue PLUS the continuous batcher's admitted-but-
        undispatched backlog (pending groups + the ready lane). The
        dispatch queue alone is bounded by the in-flight token count
        (workers + pipeline_depth - 1, typically 2-3), which would
        leave the default tier limits unreachable; and the continuous
        batcher drains the source queue eagerly, so WITHOUT the
        pending/ready terms overload would hide in groups the old
        queue-depth signal never saw."""
        pressure = self._dispatch_q.qsize()
        try:
            pressure += self.source.queue.qsize()
        except Exception:  # noqa: BLE001 — source closing
            pass
        pressure += sum(len(g.reqs) for g in self._pending.values())
        pressure += sum(len(entry[3]) for entry in self._ready)
        return pressure

    def _retry_header(self, floor: int = 1) -> str:
        """The current drain-estimate Retry-After (seconds, as the
        header string) for shed replies; ``floor`` lifts paths with a
        known longer horizon (e.g. a failed load's retry window)."""
        return str(max(int(floor), self._retry_after_s))

    def _update_retry_after(self, now: Optional[float] = None) -> None:
        """Re-derive Retry-After from the live backlog / windowed
        drain rate (rate-gated; batcher thread). Shed replies then
        tell backoff-honoring clients when capacity should actually
        exist — backlog/rate seconds, clamped to [1,
        retry_after_max_s] — instead of a constant 1 s that invites
        an immediate re-stampede under a deep queue."""
        t = time.monotonic() if now is None else now
        if t - self._retry_tick < 0.5:
            return
        self._retry_tick = t
        backlog = self._pressure()
        if backlog <= 0:
            est = 1.0
        else:
            rate = self._drained_rows.rate(10.0)    # rows/s
            est = (backlog / rate) if rate > 0 \
                else float(self.retry_after_max_s)
        self._retry_after_s = int(
            min(max(1.0, math.ceil(est)), self.retry_after_max_s))
        # the HTTP handler's 503-shed path reads the source attribute
        self.source.retry_after_s = self._retry_after_s

    def _reject_parked(self, p: _ParkedRequest, code: int, reason: str,
                       message: str,
                       headers: Optional[Dict[str, str]] = None) -> None:
        """Answer one request rejected by admission/model routing,
        counting it by reason (``serving_admission_rejected_total``)."""
        with self._stats_lock:
            self.rejections[reason] = self.rejections.get(reason, 0) + 1
        if p.trace is not None:
            p.trace.root.set("rejected", reason)
        self.source.respond(p.id, HTTPSchema.response(
            code, message,
            json.dumps({"error": message}).encode("utf-8"),
            {"Content-Type": "application/json", **(headers or {})}))

    def _enqueue_awaiting(self, key: str,
                          group: List[_ParkedRequest]) -> None:
        lst = self._awaiting.setdefault(key, [])
        if not lst:
            self._awaiting_since[key] = time.monotonic()
            # register the parked demand with the zoo: an awaited
            # model must survive from activation to our flush poll,
            # or demand > capacity livelocks (load, evict before the
            # flush, reload, starve — see ModelZoo.add_waiter)
            self.zoo.add_waiter(key)
        lst.extend(group)

    def _drop_awaiting(self, key: str) -> None:
        """Forget a parked key (flushed or rejected) and release its
        zoo waiter hold so the model becomes evictable again."""
        self._awaiting.pop(key, None)
        self._awaiting_since.pop(key, None)
        self.zoo.remove_waiter(key)

    def _poll_awaiting(self) -> List[Tuple]:
        """Flush requests parked on cold models: activated models come
        back as dispatch groups (chunked to ``batch_size`` — every
        chunk gets its own acquired handle), failed/overdue activations
        answer 503."""
        if not self._awaiting:
            return []
        from mmlspark_tpu.serving.admission import request_identity
        out: List[Tuple] = []
        now = time.monotonic()
        for key in list(self._awaiting):
            try:
                handle, state, msg = self.zoo.acquire(key)
            except Exception as e:  # noqa: BLE001 — transient zoo
                # fault: the requests STAY parked (no handle leaked,
                # nothing unanswered) and the activation timeout still
                # bounds their wait
                log.error("zoo acquire failed for %s (still parked):"
                          " %s", key, e)
                continue
            group = self._awaiting[key]
            if state == "loading":
                if now - self._awaiting_since[key] \
                        <= self.activation_timeout_s:
                    continue            # keep waiting
                for p in group:
                    self._reject_parked(
                        p, 503, "activation_timeout",
                        f"model {key!r} still activating after "
                        f"{self.activation_timeout_s:.0f}s",
                        {"Retry-After": self._retry_header()})
            elif state == "resident":
                prio = min(request_identity(p.request)[1]
                           for p in group)
                chunks = [group[i:i + self.batch_size]
                          for i in range(0, len(group), self.batch_size)]
                out.append((handle, chunks[0], prio))
                for i in range(1, len(chunks)):
                    try:
                        h2, st2, _ = self.zoo.acquire(key)
                    except Exception:  # noqa: BLE001 — re-park
                        st2 = None
                    if st2 == "resident":
                        out.append((h2, chunks[i], prio))
                    else:   # can't happen while chunk 0 holds the
                        #     handle outstanding; guard anyway —
                        #     re-park this AND every later chunk
                        self._awaiting[key] = [
                            p for c in chunks[i:] for p in c]
                        self._awaiting_since[key] = now
                        break
                else:
                    self._drop_awaiting(key)
                continue
            else:   # failed / unknown (e.g. deregistered mid-wait)
                for p in group:
                    self._reject_parked(
                        p, 503, "load_failed",
                        f"model {key!r} failed to activate: {msg}",
                        {"Retry-After": self._retry_header(floor=5)})
            self._drop_awaiting(key)
        return out

    def _worker_loop(self):
        while not self._stop.is_set():
            try:
                item = self._dispatch_q.get(timeout=0.05)
            except queue.Empty:
                continue
            try:
                self._execute_batch(*item)
            except Exception as e:  # noqa: BLE001 — keep serving
                log.error("serving loop error (continuing): %s", e)
            finally:
                # token back even when the thread is dying (SystemExit
                # passes through): a leaked token would shrink the
                # engine's in-flight budget forever — and the version
                # handle must drain even on a crashed batch, or a swap
                # would wait on its outstanding count forever
                item[3].release()
                self._inflight.release()

    def _spawn_worker(self) -> threading.Thread:
        t = threading.Thread(target=self._worker_loop, daemon=True)
        t.start()
        return t

    def _spawn_batcher(self) -> threading.Thread:
        t = threading.Thread(target=self._batcher_loop, daemon=True)
        t.start()
        return t

    def _supervise(self, interval: float = 0.1):
        """Liveness watchdog: a worker or batcher thread that dies (a
        BaseException like SystemExit escaping the loop's Exception
        guard) is detected and respawned, so one crashed thread can't
        silently halve — or zero — the engine's throughput. Chaos kills
        (``kill()``) and normal ``stop()`` suppress restarts."""
        while not self._stop.wait(interval):
            with self._threads_lock:
                for i, t in enumerate(self._threads):
                    if t.is_alive() or self._stop.is_set():
                        continue
                    log.error("serving worker died; restarting")
                    self._threads[i] = self._spawn_worker()
                    with self._stats_lock:
                        self.workers_restarted += 1
                if (self._batcher is not None
                        and not self._batcher.is_alive()
                        and not self._stop.is_set()):
                    log.error("serving batcher died; restarting")
                    self._batcher = self._spawn_batcher()
                    with self._stats_lock:
                        self.workers_restarted += 1

    def is_alive(self) -> bool:
        """Engine liveness for /healthz: not killed, batcher running
        (when started), and at least one worker thread running."""
        if self._killed.is_set() or self._stop.is_set():
            return False
        with self._threads_lock:
            workers_ok = any(t.is_alive() for t in self._threads)
            batcher_ok = (self._batcher is None
                          or self._batcher.is_alive())
        return workers_ok and batcher_ok

    def _lifecycle_snapshot(self) -> Tuple[PipelineHandle, Dict[str, Any]]:
        """ONE consistent (handle, swap_state, counters) snapshot under
        ``_stats_lock`` — the lock every lifecycle writer (cutover,
        state transitions, counter bumps — see serving/lifecycle.py)
        holds. Reading these fields piecemeal raced a concurrent
        ``swap()``: a scrape could see the NEW version with the OLD
        swaps_completed count, or ``swap_state == idle`` with the
        not-yet-cut-over pipeline."""
        with self._stats_lock:
            active = self._active
            return active, {
                "batches_processed": self.batches_processed,
                "workers_restarted": self.workers_restarted,
                "model_version": active.version,
                "precision": active.precision,
                "aot": active.aot,
                "swap_state": self.swap_state,
                "swaps_completed": self.swaps_completed,
                "swaps_rolled_back": self.swaps_rolled_back,
            }

    def metrics(self) -> Dict[str, Any]:
        """Hot-path latency breakdown: engine histograms (queue wait,
        decode, pipeline, respond, batch occupancy) plus whatever the
        pipeline exposes through a duck-typed ``metrics`` hook
        (TPUModel adds its pad/device split and the jit-cache-miss
        counter). Exported on /healthz."""
        active, out = self._lifecycle_snapshot()
        out.update({k: h.summary() for k, h in self.hists.items()})
        with self._stats_lock:
            if self.rejections:
                out["rejections"] = dict(self.rejections)
        if self.zoo is not None:
            try:
                out["zoo"] = self.zoo.stats()
            except Exception:  # noqa: BLE001 — stats stay partial
                pass
        if self.admission is not None:
            try:
                out["admission"] = self.admission.stats()
            except Exception:  # noqa: BLE001 — stats stay partial
                pass
        swap_ctl = self.__dict__.get("_swap_ctl")
        if swap_ctl is not None:
            try:
                out["swap"] = swap_ctl.stats()
            except Exception:  # noqa: BLE001 — stats stay partial
                pass
        if self.slo is not None:
            try:
                out["slo"] = self.slo.status()
            except Exception:  # noqa: BLE001 — stats stay partial
                pass
        if self.variants is not None:
            # /healthz carries the currently-routed variant + last
            # step-down reason per logical model (satellite of the
            # adaptive plane: a degrade-to-int8 is operator-visible)
            try:
                out["variants"] = self.variants.status()
            except Exception:  # noqa: BLE001 — stats stay partial
                pass
        out["retry_after_s"] = self._retry_after_s
        stage = getattr(active.pipeline, "metrics", None)
        if callable(stage):
            try:
                out["pipeline_stage"] = stage()
            except Exception:  # noqa: BLE001 — stats stay partial
                pass
        return out

    def metrics_text(self) -> str:
        """Prometheus text exposition (format 0.0.4) of everything the
        engine knows: source/engine counters, the per-stage latency
        histograms with exact buckets, the lifecycle state as an
        ``_info`` series, the model's pad/device histograms and
        jit-cache-miss counter, drift gauges, and the process-wide
        GBDT/AutoML phase + trace-buffer families. Served on
        ``/metrics``."""
        from mmlspark_tpu.core.prometheus import (
            PromRenderer, pipeline_families, process_families,
        )
        r = PromRenderer()
        src = self.source
        with src._lock:
            seen, accepted = src.requests_seen, src.requests_accepted
            answered, rejected = src.requests_answered, \
                src.requests_rejected
            parked = len(src._pending)
        r.counter("serving_requests_seen_total",
                  "requests hitting the HTTP source", seen)
        r.counter("serving_requests_accepted_total",
                  "requests parked + enqueued", accepted)
        r.counter("serving_requests_answered_total",
                  "requests answered through the held connection",
                  answered)
        r.counter("serving_requests_rejected_total",
                  "requests shed with 503 + Retry-After", rejected)
        r.gauge("serving_parked_requests",
                "connections currently held open", parked)
        r.gauge("serving_queue_depth", "source queue depth",
                src.queue.qsize())
        active, snap = self._lifecycle_snapshot()
        r.counter("serving_batches_processed_total",
                  "micro-batches executed", snap["batches_processed"])
        r.counter("serving_workers_restarted_total",
                  "worker/batcher threads respawned by the supervisor",
                  snap["workers_restarted"])
        r.counter("serving_swaps_completed_total",
                  "model swaps promoted + cut over",
                  snap["swaps_completed"])
        r.counter("serving_swaps_rolled_back_total",
                  "model swaps rolled back", snap["swaps_rolled_back"])
        r.info("serving_model_info",
               "active model version, precision, aot, swap state (labels)",
               {"version": snap["model_version"],
                "precision": snap["precision"],
                "aot": "true" if snap["aot"] else "false",
                "swap_state": snap["swap_state"]})
        for name, hist in self.hists.items():
            r.histogram(f"serving_{name}",
                        "engine hot-path stage distribution", hist)
        ctl = self.__dict__.get("_swap_ctl")
        if ctl is not None:
            try:
                stats = ctl.stats()
                r.gauge("serving_canary_batches",
                        "canary batch outcomes for the swap in flight",
                        stats["canary_ok"], {"outcome": "ok"})
                r.sample("serving_canary_batches",
                         stats["canary_failed"], {"outcome": "failed"})
            except Exception:  # noqa: BLE001 — stats stay partial
                pass
        with self._stats_lock:
            rejections = dict(self.rejections)
        for reason in sorted(rejections):
            r.counter("serving_admission_rejected_total",
                      "requests rejected by admission/model routing "
                      "(quota, priority, no_model, unknown_model, "
                      "load_failed, activation_timeout)",
                      rejections[reason], {"reason": reason})
        if self.admission is not None:
            try:
                r.counter("serving_admission_admitted_total",
                          "requests admitted by the admission layer",
                          self.admission.stats()["admitted"])
            except Exception:  # noqa: BLE001 — stats stay partial
                pass
        if self.zoo is not None:
            from mmlspark_tpu.core.prometheus import zoo_families
            try:
                zoo_families(r, self.zoo)
            except Exception:  # noqa: BLE001 — stats stay partial
                pass
        if self.slo is not None:
            from mmlspark_tpu.core.prometheus import slo_families
            try:
                slo_families(r, self.slo)
            except Exception:  # noqa: BLE001 — stats stay partial
                pass
        if self.variants is not None:
            from mmlspark_tpu.core.prometheus import variant_families
            try:
                variant_families(r, self.variants)
            except Exception:  # noqa: BLE001 — stats stay partial
                pass
        r.gauge("serving_retry_after_s",
                "live drain-estimate Retry-After quoted on sheds",
                self._retry_after_s)
        cp = self.__dict__.get("controlplane")
        if cp is not None:
            from mmlspark_tpu.core.prometheus import (
                controlplane_families,
            )
            try:
                controlplane_families(r, cp)
            except Exception:  # noqa: BLE001 — stats stay partial
                pass
        pipeline_families(r, active.pipeline)
        process_families(r, tracer=self.tracer)
        return r.render()

    # -- trace export -------------------------------------------------------

    def traces(self, limit: Optional[int] = None) -> List[Any]:
        """Completed (tail-sampled) traces from this engine's buffer,
        oldest first."""
        if self.tracer is None:
            return []
        return self.tracer.buffer.traces(limit)

    def export_traces(self, limit: Optional[int] = None) -> Dict[str, Any]:
        """The buffer as Chrome trace-event JSON (the /debug/traces
        payload — save it and open in Perfetto). Carries a
        ``process_name`` metadata event naming this engine + pid, so
        merged multi-process exports (``core.trace.merge_chrome_traces``)
        render one labeled track group per engine process."""
        from mmlspark_tpu.core.trace import to_chrome_trace
        return to_chrome_trace(
            self.traces(limit),
            process_name=f"engine {self.source.address} "
                         f"pid={os.getpid()}")

    def _recorder_key(self) -> str:
        return f"engine@{self.source.address}"

    def start(self) -> "ServingEngine":
        with self._threads_lock:
            self._batcher = self._spawn_batcher()
            self._threads = [self._spawn_worker()
                             for _ in range(self.workers)]
        self._supervisor = threading.Thread(target=self._supervise,
                                            daemon=True)
        self._supervisor.start()
        self.source.health_probe = self.is_alive
        self.source.metrics_probe = self.metrics
        self.source.tracer = self.tracer
        self.source.trace_probe = self.export_traces
        self.source.prom_probe = self.metrics_text
        self.source.slo = self.slo
        rec = self.flight_recorder
        if rec is not None:
            # the black box sees this engine's traces, SLO state, the
            # lifecycle/zoo event timelines, and a metrics snapshot;
            # keys carry the address so stop() can detach cleanly
            key = self._recorder_key()
            rec.attach_tracer(
                self.tracer,
                label=f"engine {self.source.address} pid={os.getpid()}",
                key=f"{key}:tracer")
            if self.slo is not None:
                rec.attach_slo(key, self.slo)
            rec.add_event_source(f"{key}:swap_events",
                                 lambda: self.swap_events)
            if self.zoo is not None:
                # keyed per engine (a shared zoo re-attaches under each
                # engine's key) so stop()'s prefix detach releases it
                rec.add_event_source(f"{key}:registry_events",
                                     lambda: self.zoo.events)
            rec.add_stats_source(key, self.metrics)
            self.source.bundle_probe = (
                lambda limit=None: rec.dump_bundle(
                    reason="http_request", trace_limit=limit))
        return self

    def kill(self, close_source: bool = True) -> None:
        """Chaos hook: simulate a crashed engine — workers exit and are
        NOT restarted. ``close_source=True`` also drops the listener
        (clients see connection-refused, the crashed-process shape);
        ``close_source=False`` keeps accepting but never replies (the
        stalled-engine shape: parked requests run out their timeout)."""
        self._killed.set()
        self._stop.set()
        if close_source:
            self.source.close()

    def stop(self) -> None:
        self._stop.set()
        if self.flight_recorder is not None:
            # drop this engine's recorder hooks (a process recorder
            # outlives engines; stale closures would leak them)
            self.flight_recorder.detach(self._recorder_key())
        if self.slo is not None:
            # uninstall exactly the monitor hooks THIS engine wired:
            # a shared monitor handed to a later engine must re-wire
            # to that engine's recorder/zoo, not keep ours
            for hook in self._slo_hooks_installed:
                setattr(self.slo, hook, None)
            self._slo_hooks_installed = []
        if self._supervisor is not None:
            self._supervisor.join(timeout=5)
        with self._threads_lock:
            threads = list(self._threads)
            if self._batcher is not None:
                threads.append(self._batcher)
        for t in threads:
            t.join(timeout=5)
        if self.zoo is not None:
            # release this engine's parked-demand holds: a shared zoo
            # must not carry dead engines' waiters (they would exempt
            # models from eviction forever)
            for key in list(self._awaiting):
                self.zoo.remove_waiter(key)
            self._awaiting.clear()
            self._awaiting_since.clear()
            # same for the continuous batcher's pending groups, and
            # the ready lane's acquired-but-undispatched handles (the
            # batcher thread is joined above — no races): an
            # unreleased handle would pin its model's outstanding
            # count above zero forever
            for key in list(self._pending):
                if key is not None:
                    self.zoo.remove_waiter(key)
            self._pending.clear()
            for entry in self._ready:
                if entry[2] is not None:
                    entry[2].release()
            self._ready.clear()
        try:
            self.source.close()
        except Exception:  # noqa: BLE001 — already closed by kill()
            pass


def serve_model(pipeline: Optional[Transformer] = None,
                host: str = "127.0.0.1",
                port: int = 8899, batch_size: int = 64,
                reply_col: str = "reply",
                workers: int = 1, max_wait_ms: float = 5.0,
                pipeline_depth: int = 2,
                version: str = "v0", tracer=None,
                tracing: Optional[bool] = None,
                zoo=None, admission=None,
                slo=None, flight_recorder=None,
                slo_eval_interval_s: float = 0.25,
                variants=None) -> ServingEngine:
    """One-call serving: the ``.server()`` DSL analog
    (ref: ServingImplicits.scala:10-50). Batches flush on
    ``batch_size`` rows or ``max_wait_ms`` elapsed, whichever first;
    the batcher thread decodes/pads the next batch while a worker
    drives the device for the current one. ``workers`` > 1 additionally
    overlaps device round-trips; the pipeline's ``transform`` must then
    be thread-safe (TPUModel is)."""
    source = HTTPSource(host=host, port=port)
    return ServingEngine(source, pipeline, reply_col=reply_col,
                         batch_size=batch_size, workers=workers,
                         max_wait_ms=max_wait_ms,
                         pipeline_depth=pipeline_depth,
                         version=version, tracer=tracer,
                         tracing=tracing, zoo=zoo,
                         admission=admission, slo=slo,
                         flight_recorder=flight_recorder,
                         slo_eval_interval_s=slo_eval_interval_s,
                         variants=variants,
                         ).start()
