"""Serving engine: HTTP source/sink with reply-by-uuid routing.

TPU-native re-creation of Spark Serving
(ref: src/io/http/src/main/scala/HTTPSource.scala:48-178 single-node
source/sink; DistributedHTTPSource.scala:33-472 per-executor
JVMSharedServer with batch-indexed request routing and reply-by-uuid;
PartitionConsolidator.scala:17).

Design: each serving host runs one threaded HTTP server (the
JVMSharedServer analog). Accepted requests park their connection and
enqueue (uuid, request-struct); the serving engine drains the queue into
DataTable micro-batches, runs the user pipeline (whose heavy stages are
jitted/sharded on the TPU mesh), and the sink answers each row back
through the SAME host's held connection — the reply-routing invariant of
the reference (replies must flow through the host that accepted the
request, DistributedHTTPSource.scala:188-192). On a multi-host mesh, run
one ServingEngine per host behind any TCP load balancer; model state is
replicated by jax, no cross-host reply routing is ever needed.
"""

from __future__ import annotations

import json
import queue
import threading
import time
import uuid as uuid_lib
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from mmlspark_tpu.core.logging_utils import get_logger
from mmlspark_tpu.core.stage import Transformer
from mmlspark_tpu.core.table import DataTable
from mmlspark_tpu.io.http import HTTPSchema, _jsonable as _to_jsonable

log = get_logger("serving")


class SharedVariable:
    """Process-wide lazily-initialized shared value
    (ref: io/http SharedVariable.scala double-checked lazy singleton)."""

    def __init__(self, factory: Callable[[], Any]):
        self._factory = factory
        self._value = None
        self._have = False
        self._lock = threading.Lock()

    def get(self) -> Any:
        if not self._have:
            with self._lock:
                if not self._have:
                    self._value = self._factory()
                    self._have = True
        return self._value


class SharedSingleton:
    """Keyed process-wide singletons (ref: SharedSingleton.scala)."""

    _instances: Dict[str, Any] = {}
    _lock = threading.Lock()

    @classmethod
    def get_or_create(cls, key: str, factory: Callable[[], Any]) -> Any:
        with cls._lock:
            if key not in cls._instances:
                cls._instances[key] = factory()
            return cls._instances[key]


class _ParkedRequest:
    """A request whose connection is held open until respond()."""

    def __init__(self, rid: str, request_struct: Dict[str, Any]):
        self.id = rid
        self.request = request_struct
        self._event = threading.Event()
        self.response: Optional[Dict[str, Any]] = None

    def respond(self, response: Dict[str, Any]) -> None:
        self.response = response
        self._event.set()

    def wait(self, timeout: float) -> Optional[Dict[str, Any]]:
        if self._event.wait(timeout):
            return self.response
        return None


class HTTPSource:
    """One host's HTTP server + request queue
    (ref: HTTPSource.scala:48-138; JVMSharedServer
    DistributedHTTPSource.scala:96-246 incl. port scanning and
    requestsSeen/Accepted counters)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 8899,
                 api_path: str = "/", max_queue: int = 10_000,
                 reply_timeout: float = 60.0, port_scan: int = 20,
                 max_parked: Optional[int] = None,
                 retry_after_s: int = 1):
        self.api_path = api_path
        self.queue: "queue.Queue[_ParkedRequest]" = queue.Queue(max_queue)
        self.requests_seen = 0
        self.requests_accepted = 0
        self.requests_answered = 0
        self.requests_rejected = 0
        # the parked-request table is BOUNDED: a stalled engine must shed
        # load with 503 + Retry-After, not hold thousands of connections
        # hostage until reply_timeout (the load-shedding half of the
        # Tail-at-Scale story). Default bound = the queue bound.
        self.max_parked = max_parked if max_parked is not None else max_queue
        self.retry_after_s = max(1, int(retry_after_s))
        # set by ServingEngine.start(): () -> bool engine liveness; the
        # /healthz endpoint folds it into its verdict
        self.health_probe: Optional[Callable[[], bool]] = None
        self._pending: Dict[str, _ParkedRequest] = {}
        self._lock = threading.Lock()
        source = self

        class Handler(BaseHTTPRequestHandler):
            def _send_json(self, code: int, payload: Dict[str, Any],
                           headers: Optional[Dict[str, str]] = None):
                body = json.dumps(payload).encode("utf-8")
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def _shed(self, reason: str):
                with source._lock:
                    source.requests_rejected += 1
                self._send_json(
                    503, {"error": reason,
                          "retry_after": source.retry_after_s},
                    {"Retry-After": str(source.retry_after_s)})

            def do_GET(self):  # noqa: N802 (http.server API)
                path_only = self.path.split("?", 1)[0].rstrip("/")
                if path_only != "/healthz":
                    self.send_error(404, f"unknown path {path_only}")
                    return
                healthy = True
                if source.health_probe is not None:
                    try:
                        healthy = bool(source.health_probe())
                    except Exception:  # noqa: BLE001 — probe crash = sick
                        healthy = False
                with source._lock:
                    stats = {
                        "status": "ok" if healthy else "unhealthy",
                        "seen": source.requests_seen,
                        "accepted": source.requests_accepted,
                        "answered": source.requests_answered,
                        "rejected": source.requests_rejected,
                        "parked": len(source._pending),
                        "queue_depth": source.queue.qsize(),
                    }
                self._send_json(200 if healthy else 503, stats)

            def do_POST(self):  # noqa: N802 (http.server API)
                with source._lock:
                    source.requests_seen += 1
                path_only = self.path.split("?", 1)[0]
                if source.api_path not in ("/", "") and \
                        path_only.rstrip("/") != source.api_path.rstrip("/"):
                    self.send_error(404, f"unknown path {path_only}")
                    return
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length) if length else b""
                req = HTTPSchema.request(
                    self.path, "POST", body,
                    {k: v for k, v in self.headers.items()})
                parked = _ParkedRequest(uuid_lib.uuid4().hex, req)
                with source._lock:
                    if len(source._pending) >= source.max_parked:
                        shed = True
                    else:
                        source._pending[parked.id] = parked
                        shed = False
                if shed:
                    self._shed("parked-request table full")
                    return
                try:
                    source.queue.put_nowait(parked)
                    with source._lock:
                        source.requests_accepted += 1
                except queue.Full:
                    with source._lock:
                        source._pending.pop(parked.id, None)
                    self._shed("queue full")
                    return
                resp = parked.wait(reply_timeout)
                with source._lock:
                    source._pending.pop(parked.id, None)
                if resp is None:
                    self.send_error(504, "serving timeout")
                    return
                code = resp["statusLine"]["statusCode"]
                entity = resp.get("entity") or b""
                if isinstance(entity, str):
                    entity = entity.encode("utf-8")
                self.send_response(code)
                # framing/hop-by-hop headers are computed by this server;
                # forwarding pipeline-supplied ones would duplicate/conflict
                _framing = {"content-length", "transfer-encoding",
                            "connection"}
                for k, v in (resp.get("headers") or {}).items():
                    if k.lower() not in _framing:
                        self.send_header(k, v)
                self.send_header("Content-Length", str(len(entity)))
                self.end_headers()
                self.wfile.write(entity)
                with source._lock:
                    source.requests_answered += 1

            def log_message(self, *a):  # silence default stderr logging
                pass

        class Server(ThreadingHTTPServer):
            request_queue_size = 128  # listen backlog for bursty clients
            daemon_threads = True

        last_err: Optional[Exception] = None
        for p in range(port, port + port_scan):
            try:
                self.server = Server((host, p), Handler)
                self.port = p
                break
            except OSError as e:  # port taken — scan upward (ref :234)
                last_err = e
        else:
            raise OSError(f"no free port in [{port}, {port+port_scan}): "
                          f"{last_err}")
        self.address = f"http://{host}:{self.port}"
        self._thread = threading.Thread(target=self.server.serve_forever,
                                        daemon=True)
        self._thread.start()
        log.info("serving source listening on %s", self.address)

    def get_batch(self, max_rows: int = 64,
                  wait_s: float = 0.05) -> Tuple[DataTable, List[str]]:
        """Drain up to max_rows parked requests into a table
        (ref: HTTPSource.getBatch)."""
        parked: List[_ParkedRequest] = []
        deadline = time.time() + wait_s
        while len(parked) < max_rows:
            remaining = deadline - time.time()
            if remaining <= 0 and parked:
                break
            try:
                parked.append(self.queue.get(
                    timeout=max(remaining, 0.001)))
            except queue.Empty:
                break
        if not parked:
            return DataTable({"id": [], "request": []}), []
        return (DataTable({"id": [p.id for p in parked],
                           "request": [p.request for p in parked]}),
                [p.id for p in parked])

    def respond(self, rid: str, response: Dict[str, Any]) -> bool:
        """Reply through the held connection (ref:
        DistributedHTTPSource.scala:188 server.respond(batch, uuid, …))."""
        with self._lock:
            parked = self._pending.get(rid)
        if parked is None:
            return False
        parked.respond(response)
        return True

    def close(self) -> None:
        self.server.shutdown()
        self.server.server_close()


class ServingEngine:
    """The streaming loop: source → user pipeline → sink
    (the structured-streaming query of ref: ServingImplicits.scala:10-50
    ``readStream.server()…writeStream.server()``)."""

    def __init__(self, source: HTTPSource, pipeline: Transformer,
                 reply_col: str = "reply", id_col: str = "id",
                 batch_size: int = 64,
                 content_type: str = "application/json",
                 error_col: str = "error", workers: int = 1):
        self.source = source
        self.pipeline = pipeline
        self.reply_col = reply_col
        self.id_col = id_col
        self.batch_size = batch_size
        self.content_type = content_type
        self.error_col = error_col
        # workers > 1 drains the queue from N loop threads, so batch
        # N+1 assembles (and its replies flush) while batch N's device
        # round-trip is in flight — the accelerator round-trip otherwise
        # serializes the whole engine (jit dispatch is thread-safe).
        # CONTRACT: pipeline.transform must itself be thread-safe under
        # workers > 1 (TPUModel is; a Lambda closing over mutable state
        # is only if it locks)
        self.workers = max(1, int(workers))
        self._stop = threading.Event()
        self._killed = threading.Event()   # chaos kill: no restart
        self._threads: List[threading.Thread] = []
        self._threads_lock = threading.Lock()
        self._supervisor: Optional[threading.Thread] = None
        self.batches_processed = 0
        self.workers_restarted = 0
        self._stats_lock = threading.Lock()

    def _respond_ok(self, rid: str, rep: Any) -> None:
        body = rep if isinstance(rep, (bytes, str)) \
            else json.dumps(_to_jsonable(rep))
        self.source.respond(rid, HTTPSchema.response(
            200, "OK", body if isinstance(body, bytes)
            else body.encode("utf-8"),
            {"Content-Type": self.content_type}))

    def _answer_output(self, out: DataTable, ids: List[str]) -> None:
        """Answer one transformed batch, splitting per-row errors: a
        non-null ``error_col`` value means that row failed and gets a
        500 while its batchmates still get their 200s
        (ref: SimpleHTTPTransformer.scala:104-150 error-split pipeline)."""
        replies = out[self.reply_col]
        out_ids = out[self.id_col]
        errors = (out[self.error_col]
                  if self.error_col in out.column_names else None)
        answered = set()
        for i, (rid, rep) in enumerate(zip(out_ids, replies)):
            err = errors[i] if errors is not None else None
            if err is not None and err == err:  # non-null, non-NaN
                self.source.respond(rid, HTTPSchema.response(
                    500, f"row error: {err}", None))
            else:
                self._respond_ok(rid, rep)
            answered.add(rid)
        for rid in ids:
            if rid not in answered:
                self.source.respond(rid, HTTPSchema.response(
                    500, "row dropped by pipeline", None))

    def process_one_batch(self, wait_s: float = 0.05) -> int:
        table, ids = self.source.get_batch(self.batch_size, wait_s)
        if not ids:
            return 0
        try:
            out = self.pipeline.transform(table)
        except Exception as e:  # noqa: BLE001 — isolate the poison row(s)
            log.warning("serving batch failed (%s); retrying per-row", e)
            self._process_rows_individually(table, ids)
            with self._stats_lock:
                self.batches_processed += 1
            return len(ids)
        try:
            self._answer_output(out, ids)
        except Exception as e:  # noqa: BLE001 — e.g. missing reply column
            log.warning("answering batch failed (%s); sending 500s", e)
            for rid in ids:
                self.source.respond(rid, HTTPSchema.response(
                    500, f"reply error: {e}", None))
        with self._stats_lock:
            self.batches_processed += 1
        return len(ids)

    def _process_rows_individually(self, table: DataTable,
                                   ids: List[str]) -> None:
        """Batch-failure fallback: run each row alone so one poison
        request cannot 500 its batchmates (the per-row half of the
        reference's error isolation, SimpleHTTPTransformer.scala:104-150)."""
        requests = table["request"]
        for rid, req in zip(ids, requests):
            row = DataTable({"id": [rid], "request": [req]})
            try:
                out = self.pipeline.transform(row)
                self._answer_output(out, [rid])
            except Exception as e:  # noqa: BLE001
                self.source.respond(rid, HTTPSchema.response(
                    500, f"pipeline error: {e}", None))

    def _worker_loop(self):
        while not self._stop.is_set():
            try:
                n = self.process_one_batch()
            except Exception as e:  # noqa: BLE001 — keep serving
                log.error("serving loop error (continuing): %s", e)
                n = 0
            if n == 0:
                time.sleep(0.005)

    def _spawn_worker(self) -> threading.Thread:
        t = threading.Thread(target=self._worker_loop, daemon=True)
        t.start()
        return t

    def _supervise(self, interval: float = 0.1):
        """Liveness watchdog: a worker thread that dies (a BaseException
        like SystemExit escaping the loop's Exception guard) is detected
        and respawned, so one crashed drainer can't silently halve — or
        zero — the engine's throughput. Chaos kills (``kill()``) and
        normal ``stop()`` suppress restarts."""
        while not self._stop.wait(interval):
            with self._threads_lock:
                for i, t in enumerate(self._threads):
                    if t.is_alive() or self._stop.is_set():
                        continue
                    log.error("serving worker died; restarting")
                    self._threads[i] = self._spawn_worker()
                    with self._stats_lock:
                        self.workers_restarted += 1

    def is_alive(self) -> bool:
        """Engine liveness for /healthz: not killed and at least one
        drainer thread running."""
        if self._killed.is_set() or self._stop.is_set():
            return False
        with self._threads_lock:
            return any(t.is_alive() for t in self._threads)

    def start(self) -> "ServingEngine":
        with self._threads_lock:
            self._threads = [self._spawn_worker()
                             for _ in range(self.workers)]
        self._supervisor = threading.Thread(target=self._supervise,
                                            daemon=True)
        self._supervisor.start()
        self.source.health_probe = self.is_alive
        return self

    def kill(self, close_source: bool = True) -> None:
        """Chaos hook: simulate a crashed engine — workers exit and are
        NOT restarted. ``close_source=True`` also drops the listener
        (clients see connection-refused, the crashed-process shape);
        ``close_source=False`` keeps accepting but never replies (the
        stalled-engine shape: parked requests run out their timeout)."""
        self._killed.set()
        self._stop.set()
        if close_source:
            self.source.close()

    def stop(self) -> None:
        self._stop.set()
        if self._supervisor is not None:
            self._supervisor.join(timeout=5)
        with self._threads_lock:
            threads = list(self._threads)
        for t in threads:
            t.join(timeout=5)
        try:
            self.source.close()
        except Exception:  # noqa: BLE001 — already closed by kill()
            pass


def serve_model(pipeline: Transformer, host: str = "127.0.0.1",
                port: int = 8899, batch_size: int = 64,
                reply_col: str = "reply",
                workers: int = 1) -> ServingEngine:
    """One-call serving: the ``.server()`` DSL analog
    (ref: ServingImplicits.scala:10-50). ``workers`` > 1 overlaps the
    accelerator round-trip of one micro-batch with the assembly of the
    next; the pipeline's ``transform`` must then be thread-safe
    (TPUModel is)."""
    source = HTTPSource(host=host, port=port)
    return ServingEngine(source, pipeline, reply_col=reply_col,
                         batch_size=batch_size, workers=workers).start()
