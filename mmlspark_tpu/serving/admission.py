"""SLO-aware admission control for the multi-model serving plane.

The source's bounded parked-table/queue shedding (PR 1/2) protects the
*process*; this layer protects *tenants and tiers from each other* on
top of it: one hot tenant, or a burst of requests for a cold model
mid-activation, must not starve everyone else's SLO.

Two mechanisms, both decided per request on the batcher thread before
any model work happens:

- **Per-tenant quotas** — a token bucket per tenant (``X-Tenant``
  header; absent = ``"default"``): sustained ``rate_per_s`` with a
  ``burst`` allowance. Over-quota requests answer **429** with
  ``Retry-After`` — the tenant's problem, not back-pressure, so the
  fleet client does NOT fail them over to another replica (which would
  just spend the tenant's quota fleet-wide).
- **Priority-tiered shedding** — requests carry ``X-Priority`` (0 =
  high, 1 = normal, 2 = low; absent = 1, values clamp into [0, 2]).
  When the engine shows pressure (prepared batches queued behind busy
  workers plus the source-queue backlog — cold-activation storms and
  hot-model bursts both surface here), tiers shed lowest-first at
  their configured pressure limits,
  answering **503 + Retry-After** exactly like the existing load
  shedding. Default: only priority 2 sheds (above pressure 8); tiers
  0/1 never shed here — the source's own bounds still protect the
  process.

The controller is shared across a fleet's engines (quotas are
fleet-wide, like the model zoo) and thread-safe.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional, Tuple

DEFAULT_TENANT = "default"

PRIORITY_HIGH = 0
PRIORITY_NORMAL = 1
PRIORITY_LOW = 2

# pressure limit per priority tier: a request sheds when the engine's
# dispatch pressure EXCEEDS its tier's limit (None = never shed here)
DEFAULT_PRESSURE_LIMITS: Dict[int, Optional[int]] = {
    PRIORITY_HIGH: None, PRIORITY_NORMAL: None, PRIORITY_LOW: 8}


def header_get(request: Optional[Dict[str, Any]], name: str
               ) -> Optional[str]:
    """Case-insensitive header lookup on a request struct — the ONE
    header-scan implementation (model routing, tenant identity, and
    trace propagation all route through ``core.trace.header_get``, so
    header handling cannot diverge between carriers). This wrapper
    adds the request-struct unwrap and the str() coercion admission
    callers rely on."""
    from mmlspark_tpu.core.trace import header_get as _scan
    value = _scan((request or {}).get("headers") or {}, name)
    return None if value is None else str(value)


def request_identity(request: Optional[Dict[str, Any]]
                     ) -> Tuple[str, int]:
    """(tenant, priority) from a request struct's headers
    (case-insensitive ``X-Tenant`` / ``X-Priority``)."""
    tenant = (header_get(request, "x-tenant") or "").strip() \
        or DEFAULT_TENANT
    priority = PRIORITY_NORMAL
    raw = header_get(request, "x-priority")
    if raw is not None:
        try:
            priority = int(raw.strip())
        except ValueError:
            pass                     # malformed header: keep the default
    return tenant, max(PRIORITY_HIGH, min(PRIORITY_LOW, priority))


class TenantQuota:
    """Token bucket: ``rate_per_s`` sustained, ``burst`` peak (defaults
    to max(1, rate)). Thread-safe; no release bookkeeping — admission
    spends a token, time refills them."""

    def __init__(self, rate_per_s: float, burst: Optional[float] = None):
        self.rate = float(rate_per_s)
        self.burst = float(burst) if burst is not None \
            else max(1.0, self.rate)
        self._tokens = self.burst
        self._t = time.monotonic()
        self._lock = threading.Lock()

    def try_take(self) -> bool:
        with self._lock:
            # clock read INSIDE the lock: a stale `now` from a racing
            # caller would apply a negative refill delta and regress
            # the bucket clock
            now = time.monotonic()
            self._tokens = min(
                self.burst, self._tokens + (now - self._t) * self.rate)
            self._t = now
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            return False


class AdmissionController:
    """Per-tenant quotas + priority-tiered shedding (module docstring).

    ``quotas`` maps tenant -> ``TenantQuota`` (or a plain number,
    taken as rate_per_s); ``default_quota`` applies to tenants not
    listed (None = unlimited). ``priority_pressure_limits`` overrides
    ``DEFAULT_PRESSURE_LIMITS`` per tier.
    """

    # bounded per-tenant stats: beyond this many distinct tenants the
    # rest aggregate under "_other" (the metric-cardinality discipline)
    MAX_TENANT_STATS = 64

    def __init__(self,
                 quotas: Optional[Dict[str, Any]] = None,
                 default_quota: Optional[Any] = None,
                 priority_pressure_limits:
                 Optional[Dict[int, Optional[int]]] = None):
        def as_quota(q):
            return q if isinstance(q, TenantQuota) or q is None \
                else TenantQuota(q)

        self.quotas: Dict[str, TenantQuota] = {
            t: as_quota(q) for t, q in (quotas or {}).items()}
        self.default_quota = as_quota(default_quota)
        self.priority_pressure_limits = dict(DEFAULT_PRESSURE_LIMITS)
        if priority_pressure_limits:
            self.priority_pressure_limits.update(priority_pressure_limits)
        self.admitted = 0
        self.shed: Dict[str, int] = {}          # reason -> count
        self._tenant_shed: Dict[str, int] = {}  # tenant -> count (capped)
        self._lock = threading.Lock()

    def decide(self, tenant: str, priority: int,
               pressure: int) -> Optional[str]:
        """Admission verdict for one request: None (admitted),
        ``"priority"`` (tier sheds at this pressure -> 503), or
        ``"quota"`` (tenant bucket empty -> 429)."""
        limit = self.priority_pressure_limits.get(
            priority, self.priority_pressure_limits.get(PRIORITY_LOW))
        if limit is not None and pressure > limit:
            self._record_shed("priority", tenant)
            return "priority"
        quota = self.quotas.get(tenant, self.default_quota)
        if quota is not None and not quota.try_take():
            self._record_shed("quota", tenant)
            return "quota"
        with self._lock:
            self.admitted += 1
        return None

    def _record_shed(self, reason: str, tenant: str) -> None:
        with self._lock:
            self.shed[reason] = self.shed.get(reason, 0) + 1
            if tenant not in self._tenant_shed \
                    and len(self._tenant_shed) >= self.MAX_TENANT_STATS:
                tenant = "_other"
            self._tenant_shed[tenant] = \
                self._tenant_shed.get(tenant, 0) + 1

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {"admitted": self.admitted,
                    "shed": dict(self.shed),
                    "shed_by_tenant": dict(self._tenant_shed)}
