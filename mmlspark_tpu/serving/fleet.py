"""Multi-host serving: one engine per host + partition consolidation.

The reference's DistributedHTTPSource runs one JVMSharedServer per
executor with batch-indexed request routing and reply-by-uuid
(ref: src/io/http/src/main/scala/DistributedHTTPSource.scala:33-472);
PartitionConsolidator funnels many partitions' rows into one stream per
executor for rate-limited resources (PartitionConsolidator.scala:17,103).

TPU-native shape: model state is replicated by jax, so serving hosts are
independent — each runs one ServingEngine and any TCP load balancer
fronts them. ``ServingFleet`` manages N engines (the one-process
simulation of that deployment and the orchestration utility on a real
host group); the genuinely cross-process deployment — one engine per OS
process with reply-routing and per-process counters — is exercised by
tests/serving_worker.py + tests/test_distributed.py
(test_cross_process_serving_fleet). ``PartitionConsolidator`` keeps each
process's own row range of a table, funneling work to exactly one
consumer per host.
"""

from __future__ import annotations

import http.client
import io
import itertools
import json
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from collections import deque
from concurrent.futures import (
    FIRST_COMPLETED, Future, TimeoutError as _FutureTimeout,
    wait as _futures_wait,
)
from typing import Any, Dict, List, Optional

from mmlspark_tpu.core.logging_utils import get_logger
from mmlspark_tpu.core.params import IntParam
from mmlspark_tpu.core.schema import Schema
from mmlspark_tpu.core.stage import Transformer
from mmlspark_tpu.core.table import DataTable
from mmlspark_tpu.serving.server import HTTPSource, ServingEngine
from mmlspark_tpu.utils.resilience import CircuitBreaker

log = get_logger("serving.fleet")

# sentinel: "this batch should ride HTTP instead" from the shm rung —
# distinct from any engine reply (which is always a dict)
_SHM_DECLINED = object()


class ServingUnavailable(RuntimeError):
    """Every candidate engine failed at the transport level (or was
    skipped by an open circuit). ``attempts`` is the per-engine log:
    ``[{"engine": i, "address": ..., "error": ..., "skipped": bool}]`` —
    the typed replacement for leaking raw urllib errors to callers."""

    def __init__(self, attempts: List[Dict[str, Any]]):
        self.attempts = list(attempts)
        detail = "; ".join(
            f"{a['address']}: {a['error']}" for a in self.attempts)
        super().__init__(
            f"no serving engine available after "
            f"{len(self.attempts)} attempt(s): {detail or 'none tried'}")


def json_scoring_pipeline(model, field: str = "features",
                          reply_field: str = "prediction",
                          drift_monitor=None, reply_col: str = None,
                          batch_size: int = 256):
    """The standard model-behind-HTTP pipeline: decode JSON request
    bodies ``{field: [floats]}``, score the micro-batch through
    ``model`` (a TPUModel whose inputCol is ``field``), reply
    ``{reply_field: argmax}`` per row. One implementation shared by the
    serving bench, the throughput floor test, and user deployments —
    the serving-side analog of ServingImplicits' request parsing
    (ref: ServingImplicits.scala).

    ``model`` may also be a fitted **PipelineModel** (or an already-
    compiled ``FusedPipelineModel``): request bodies are then RAW ROW
    objects ``{col: value, ...}`` — strings and token lists included —
    and the whole pipeline scores end-to-end through the fused XLA
    program (core/fusion.py): host featurization kernels run on the
    batcher thread (``prepare_batch``), the fused device program plus
    reply build run on a worker (``execute_prepared``), micro-batches
    pad to the pow-2 shape buckets, and ``warmup``/
    ``jit_cache_misses``/``bucket_for`` keep the lifecycle swap and
    tracing contracts identical to the single-model path. See
    ``_FusedPipelineScorer``.

    Both paths speak the COLUMNAR ingress protocol alongside JSON
    (io/columnar.py, docs/columnar_ingress.md): a request whose
    Content-Type negotiates msgpack-columns or Arrow IPC carries typed
    column buffers for ANY number of rows — decode is a zero-copy
    buffer view, assembly concatenates columns without per-row Python
    objects, and the reply carries one value per row. JSON stays the
    bit-parity oracle; a body that fails its negotiated codec is 400d
    alone while batch-mates proceed.

    The returned stage exposes the ServingEngine two-stage split:
    ``prepare_batch`` (codec negotiate + decode + column assembly —
    pure host work the batcher thread runs while the device executes
    the previous batch) and ``execute_prepared`` (model forward +
    reply build, run by a worker). ``transform`` remains the
    single-stage fallback — the per-row poison-isolation retry and
    non-pipelined embeddings use it.

    ``drift_monitor`` (a ``core.metrics.DriftMonitor``) makes the stage
    observe every decoded feature batch, so per-feature mean/var/null
    drift vs the fit-time statistics rides along in ``metrics()`` and
    /healthz. The stage also forwards the model's ``warmup`` hook so
    the lifecycle swap protocol can pre-compile every serving bucket
    off the hot path."""
    import numpy as np
    from mmlspark_tpu.core.fusion import FusedPipelineModel
    from mmlspark_tpu.core.stage import PipelineModel
    from mmlspark_tpu.stages.basic import Lambda

    if isinstance(model, (PipelineModel, FusedPipelineModel)):
        if drift_monitor is not None:
            # losing drift detection silently on the pipeline path
            # would be worse than refusing: the fused plan fetches only
            # the reply column, so there is no assembled feature matrix
            # to observe. Attach monitoring to a pipeline stage instead.
            raise ValueError(
                "drift_monitor is not supported for pipeline scoring "
                "(the fused plan never materializes the feature "
                "matrix); observe drift inside the pipeline instead")
        return _FusedPipelineScorer(
            model, reply_field=reply_field, reply_col=reply_col,
            batch_size=batch_size).stage()

    from mmlspark_tpu.core.metrics import (
        ingress_decode_histogram, ingress_histograms,
    )
    from mmlspark_tpu.io import columnar as CIN

    # feature dim confirmed by the last SUCCESSFUL score: columnar
    # requests with a mismatching width 400 instead of poisoning the
    # micro-batch. Learned only after success, so a bad first request
    # can never teach the scorer the wrong width.
    _state = {"dim": None}

    def decode(table: DataTable) -> CIN.PreparedBatch:
        """Per-request codec negotiation + decode + column assembly:
        JSON bodies stay the bit-parity oracle (same parse, same f32
        cast as always); columnar bodies become zero-copy (rows, dim)
        views concatenated without any per-row Python object. Requests
        that fail their negotiated codec land in ``rejects`` — the
        engine 400s exactly those and dispatches the rest."""
        reqs = table["request"]
        ids = (list(table["id"]) if "id" in table.column_names
               else [str(i) for i in range(len(reqs))])
        hists = ingress_histograms()
        t_neg = time.perf_counter()
        codecs = [CIN.negotiate(r.get("headers")) for r in reqs]
        hists["negotiate"].observe(
            (time.perf_counter() - t_neg) * 1e3)
        segs: List["np.ndarray"] = []
        spans: List[tuple] = []
        rejects: Dict[str, str] = {}
        counts: Dict[str, int] = {}
        pos = 0
        ref_dim = _state["dim"]
        for rid, r, codec in zip(ids, reqs, codecs):
            t0 = time.perf_counter()
            try:
                if codec == "json":
                    row = json.loads(r["entity"].decode())
                    feat = np.asarray(row[field], dtype=np.float32)
                    if feat.ndim != 1:
                        raise CIN.CodecError(
                            f"{field!r} must be a flat number list")
                    seg = feat[None, :]
                else:
                    batch = CIN.decode_columnar(codec, r["entity"])
                    col = batch.columns.get(field)
                    if col is None:
                        raise CIN.CodecError(
                            f"missing column {field!r}")
                    col = np.asarray(col)
                    if col.ndim != 2:
                        raise CIN.CodecError(
                            f"{field!r} must be (rows, dim); "
                            f"got shape {col.shape}")
                    seg = np.asarray(col, dtype=np.float32)
                d = seg.shape[1]
                if ref_dim is None:
                    ref_dim = d       # within-batch reference
                elif d != ref_dim:
                    raise CIN.CodecError(
                        f"feature dim {d} != expected {ref_dim}")
            except Exception as e:  # noqa: BLE001 — reject THIS request
                rejects[rid] = f"{type(e).__name__}: {e}"
                continue
            ingress_decode_histogram(codec).observe(
                (time.perf_counter() - t0) * 1e3)
            if seg.shape[0]:
                segs.append(seg)
            spans.append((pos, pos + seg.shape[0], codec))
            pos += seg.shape[0]
            counts[codec] = counts.get(codec, 0) + 1
        t_asm = time.perf_counter()
        if not segs:
            feats = np.zeros((0, ref_dim or 0), dtype=np.float32)
        elif len(segs) == 1:
            feats = segs[0]   # zero-copy: the request-body view itself
        else:
            feats = np.concatenate(segs, axis=0)
        hists["assemble"].observe(
            (time.perf_counter() - t_asm) * 1e3)
        return CIN.PreparedBatch(feats, rejects, spans, counts)

    def execute(table: DataTable, prepped) -> DataTable:
        if isinstance(prepped, np.ndarray):
            # legacy embedders handing a raw feature matrix
            prepped = CIN.PreparedBatch(
                prepped, spans=[(i, i + 1, "json")
                                for i in range(prepped.shape[0])])
        feats = prepped.payload
        if feats.shape[0] == 0:
            # every surviving request carried zero rows
            return table.with_column(
                "reply", [{reply_field: []} for _ in prepped.spans])
        scored = model.transform(DataTable({field: feats}))
        # drift counts SERVED batches, observed exactly once AFTER a
        # successful score: a failed batch re-runs through the per-row
        # retry / canary-rescue paths (which call transform -> execute
        # again), so observing in decode would double-count precisely
        # when the system is under the stress the monitor watches for
        if drift_monitor is not None:
            drift_monitor.observe(feats)
        # reply values: a TPUModel emits a score matrix (reply the
        # argmax class); a fitted estimator model (linear/GBDT — the
        # continuous-training refit path serves these directly) already
        # emits one prediction per row
        try:
            out_col = model.get("outputCol")
        except Exception:  # noqa: BLE001 — not a TPUModel-style stage
            out_col = None
        if out_col is not None and out_col in scored.column_names:
            preds = np.asarray(scored[out_col]).argmax(-1)
        else:
            get_pcol = getattr(model, "get_prediction_col", None)
            pcol = get_pcol() if callable(get_pcol) else "prediction"
            preds = np.asarray(scored[pcol])
        _state["dim"] = feats.shape[1]

        def scalar(v):
            f = float(v)
            return int(f) if f.is_integer() else f

        replies = []
        for s, e, codec in prepped.spans:
            if codec == "json":
                replies.append({reply_field: scalar(preds[s])})
            else:
                # columnar requests reply one value PER ROW they carried
                replies.append(
                    {reply_field: [scalar(p) for p in preds[s:e]]})
        return table.with_column("reply", replies)

    def handle(table: DataTable) -> DataTable:
        prepped = decode(table)
        if prepped.rejects:
            # single-stage callers (per-row retry, embedders) have no
            # reject channel: surface the codec error as the row error
            raise CIN.CodecError("; ".join(prepped.rejects.values()))
        return execute(table, prepped)

    lam = Lambda.apply(handle)
    lam.prepare_batch = decode
    lam.execute_prepared = execute
    # the wrapped model itself: the continuous-training control plane
    # (serving/controlplane.py) shadow-scores candidates through
    # pipeline.model.predict/transform, and refit hooks warm-start
    # from the live model
    lam.model = model
    # pad/device hists + jit_cache_misses — TPUModel has the hook;
    # other Model types serve fine without it
    stage_metrics = getattr(model, "metrics", None)
    if callable(stage_metrics) or drift_monitor is not None:
        def metrics_hook():
            out = dict(stage_metrics()) if callable(stage_metrics) else {}
            if drift_monitor is not None:
                out["drift"] = drift_monitor.summary()
            return out
        lam.metrics = metrics_hook
    # warmup forwards to the model (TPUModel compiles every bucket);
    # the swap protocol calls it before cutover
    model_warmup = getattr(model, "warmup", None)
    if callable(model_warmup):
        lam.warmup = model_warmup
    # observability hooks the engine duck-types: raw histogram objects
    # (the Prometheus /metrics renderer needs exact buckets, not
    # summaries), the compile-cache counter (device spans flag
    # jit_cache_miss per batch; /metrics exports the total), the shape
    # bucket a batch pads to (span annotation), and the drift monitor
    # (drift gauges on /metrics)
    model_hists = getattr(model, "histograms", None)
    if callable(model_hists):
        lam.histograms = model_hists
    if hasattr(model, "jit_cache_misses"):
        lam.jit_cache_miss_count = lambda: model.jit_cache_misses
    model_bucket = getattr(model, "bucket_for", None)
    if callable(model_bucket):
        lam.bucket_for = model_bucket
    # per-model device residency (summed across mesh devices) — the
    # zoo's measured eviction cost for this stage (serving/zoo.py
    # _duck_bytes); a sharded model reports its true split footprint
    model_rb = getattr(model, "resident_bytes", None)
    if callable(model_rb):
        lam.resident_bytes = model_rb
    if drift_monitor is not None:
        lam.drift_monitor = drift_monitor
    # precision/aot labels ride the stage into the PipelineHandle so
    # healthz/serving_model_info/SwapEvent can audit a quantized or
    # AOT-loaded rollout (see serving/lifecycle.py)
    from mmlspark_tpu.core.quantize import stage_precision
    lam.precision = stage_precision(model)
    lam.aot = bool(getattr(model, "aot", False))
    return lam


def json_row_scoring_pipeline(pipeline, reply_col: str = "prediction"):
    """Serve an arbitrary TABULAR pipeline behind HTTP: each request
    body is a JSON object of column values (one row); bodies batch into
    a DataTable, run through ``pipeline.transform``, and the
    ``reply_col`` value answers each request. This is what
    ``mmlspark-tpu serve`` wraps saved models with — any fitted
    pipeline becomes an HTTP scorer with no Python written
    (ref: ServingImplicits.scala request parsing; the CLI is the
    R-wrapper-capability analog)."""
    import numpy as np
    from mmlspark_tpu.stages.basic import Lambda

    def handle(table: DataTable) -> DataTable:
        rows = [json.loads(r["entity"].decode())
                for r in table["request"]]
        data = DataTable.from_rows(rows)
        scored = pipeline.transform(data)
        if reply_col not in scored:
            raise KeyError(
                f"reply column {reply_col!r} not in scored table; "
                f"have {scored.column_names}")
        vals = scored[reply_col]
        return table.with_column(
            "reply", [v.item() if isinstance(v, np.generic) else v
                      for v in vals])

    return Lambda.apply(handle)


class _FusedPipelineScorer:
    """Serve a fitted pipeline end-to-end through its fused XLA program
    (the pipeline branch of ``json_scoring_pipeline``).

    Request bodies are raw row objects; the two-stage engine split maps
    onto the fusion plan: ``prepare_batch`` (batcher thread) decodes
    JSON, runs the plan's host-stage prefix and the first fused
    segment's host Feed kernels (string codes / token hashing — the
    PR 4 columnar paths), and edge-pads every feed up to the pow-2
    shape bucket; ``execute_prepared`` (worker thread) dispatches the
    fused program with DONATED input buffers and does exactly one D2H
    fetch of the reply column. The plan is pruned to the reply column
    (``final_needed``), so nothing else is ever fetched.

    Serving contracts forwarded to the engine: ``warmup`` compiles
    every bucket's program off the hot path (lifecycle swap),
    ``jit_cache_miss_count`` is the recompile guard the device spans
    annotate, ``bucket_for`` labels spans, and ``metrics`` exposes the
    fusion plan + DeviceTable stats on /healthz."""

    def __init__(self, pipeline, reply_field: str = "prediction",
                 reply_col: str = None, batch_size: int = 256):
        import numpy as np
        from mmlspark_tpu.core.fusion import FusedPipelineModel
        from mmlspark_tpu.io import columnar as CIN
        self.np = np
        self.cin = CIN
        self.fused = pipeline if isinstance(pipeline, FusedPipelineModel) \
            else pipeline.fused(batch_size=batch_size)
        self.reply_field = reply_field
        self.reply_col = reply_col or self._default_reply_col()
        self._row_names: List[str] = []
        self._names_lock = threading.Lock()
        # pre-pinned, per-bucket reused host staging buffers for the
        # edge-pad copy (io/columnar.py StagingPool); the padded buffer
        # is handed to the donated fused dispatch
        self._staging = CIN.StagingPool()
        # per-column trailing shapes CONFIRMED by the last successful
        # batch — the schema-mismatch guard's trusted reference, so a
        # wrong-shaped request that happens to decode FIRST in a
        # micro-batch cannot get its well-formed batch-mates rejected
        # (only the very first batch ever falls back to first-seen)
        self._confirmed_shapes: Dict[str, tuple] = {}
        # D2H fetches per scored batch (the "at most one device round
        # trip" guarantee, asserted by tests): bumped once per fetch
        self.device_roundtrips = 0
        self.batches_scored = 0

    def _default_reply_col(self) -> str:
        for stage in reversed(self.fused.get_stages()):
            get_pred = getattr(stage, "get_prediction_col", None)
            if callable(get_pred):
                return get_pred()
        return "prediction"

    # -- decode --------------------------------------------------------------

    def _decode_requests(self, table: DataTable):
        """Per-request negotiate + decode: JSON bodies parse to row
        dicts (the oracle), columnar bodies decode to zero-copy
        ``ColumnarBatch`` views. Returns ``(decoded, spans, rejects,
        codec_counts)`` where ``decoded``/``spans`` cover only the
        SURVIVING requests (rejects keyed by request id)."""
        from mmlspark_tpu.core.metrics import (
            ingress_decode_histogram, ingress_histograms,
        )
        import time as _time
        CIN = self.cin
        reqs = table["request"]
        ids = (list(table["id"]) if "id" in table.column_names
               else [str(i) for i in range(len(reqs))])
        t_neg = _time.perf_counter()
        codecs = [CIN.negotiate(r.get("headers")) for r in reqs]
        ingress_histograms()["negotiate"].observe(
            (_time.perf_counter() - t_neg) * 1e3)
        decoded: List[Any] = []
        spans: List[tuple] = []
        rejects: Dict[str, str] = {}
        counts: Dict[str, int] = {}
        # trusted reference first (shapes the last SUCCESSFUL batch
        # scored with); unseen columns fall back to first-seen within
        # this batch
        ref_shapes: Dict[str, tuple] = dict(self._confirmed_shapes)
        pos = 0
        for rid, r, codec in zip(ids, reqs, codecs):
            t0 = _time.perf_counter()
            try:
                if codec == "json":
                    item = json.loads(r["entity"].decode())
                    if not isinstance(item, dict):
                        raise CIN.CodecError(
                            "JSON request body must be a row object")
                    n = 1
                else:
                    item = CIN.decode_columnar(codec, r["entity"])
                    n = item.n_rows
                    # schema-mismatch isolation: a request whose column
                    # widths disagree with its batch-mates 400s alone
                    # instead of breaking the whole concatenation
                    for name, col in item.columns.items():
                        if not isinstance(col, self.np.ndarray):
                            continue
                        ref = ref_shapes.get(name)
                        if ref is None:
                            ref_shapes[name] = col.shape[1:]
                        elif col.shape[1:] != ref:
                            raise CIN.CodecError(
                                f"column {name!r} shape {col.shape[1:]}"
                                f" != batch shape {ref}")
            except Exception as e:  # noqa: BLE001 — reject THIS request
                rejects[rid] = f"{type(e).__name__}: {e}"
                continue
            ingress_decode_histogram(codec).observe(
                (_time.perf_counter() - t0) * 1e3)
            decoded.append(item)
            spans.append((pos, pos + n, codec))
            pos += n
            counts[codec] = counts.get(codec, 0) + 1
        return decoded, spans, rejects, counts, ref_shapes

    def _assemble(self, decoded: List[Any], total_rows: int) -> DataTable:
        """One batch table from per-request decoded items — columns
        concatenate buffer views; NO per-row dicts are built for
        columnar requests. Column ORDER is pinned, growing: first-seen
        order keeps the schema signature — and so the compiled fused
        programs — from churning with clients' key ordering, while a
        key the first batch happened to omit is APPENDED when it first
        appears (one replan/compile, never a silently dropped field)."""
        CIN = self.cin
        with self._names_lock:
            known = set(self._row_names)
            for item in decoded:
                keys = (item.columns if isinstance(item, CIN.ColumnarBatch)
                        else item)
                for k in keys:
                    if k not in known:
                        self._row_names.append(k)
                        known.add(k)
            names = list(self._row_names)
        return DataTable({n: CIN.assemble_column(decoded, n, total_rows)
                          for n in names})

    def _pad(self, name: str, arr, bucket: int):
        # edge-pad with copies of the last row into the REUSED staging
        # buffer: valid inputs, so normalization/log paths can't
        # NaN-poison (TPUModel discipline); no per-batch allocation
        return self._staging.pad(name, self.np.asarray(arr), bucket)

    # -- the two-stage split -------------------------------------------------

    def prepare(self, table: DataTable):
        from mmlspark_tpu.core.fusion import (
            FusedSegment, load_column_f32, pipeline_histograms,
        )
        from mmlspark_tpu.core.metrics import ingress_histograms
        import time as _time
        t0 = _time.perf_counter()
        decoded, spans, rejects, codecs, shapes = \
            self._decode_requests(table)
        total = spans[-1][1] if spans else 0
        if total == 0:
            # nothing decodable (all rejected and/or zero-row batches)
            return self.cin.PreparedBatch(("empty",), rejects, spans,
                                          codecs)
        t_asm = _time.perf_counter()
        raw = self._assemble(decoded, total)
        ingress_histograms()["assemble"].observe(
            (_time.perf_counter() - t_asm) * 1e3)
        envelope = self.cin.PreparedBatch(None, rejects, spans, codecs,
                                          meta={"shapes": shapes})
        plan = self.fused.plan_for(raw.schema,
                                   final_needed={self.reply_col})
        cur = raw
        seg_idx = None
        for i, step in enumerate(plan.steps):
            if isinstance(step, FusedSegment):
                seg_idx = i
                break
            cur = step.stage.transform(cur)
        if seg_idx is None:
            # no device segment anywhere: cur IS the scored table —
            # execute() must only read the reply out of it
            envelope.payload = ("host", plan, cur, total)
            return envelope
        seg = plan.steps[seg_idx]
        n = len(cur)
        bucket = self.fused.bucket_for(n)
        has_tail = seg_idx + 1 < len(plan.steps)
        if has_tail and n < bucket:
            # multi-segment/trailing-stage plans: pad the TABLE itself
            # (edge rows) so every downstream segment sees the bucket
            # shape too — otherwise each distinct micro-batch size
            # would retrace the tail segments on the hot path. The
            # single-tail-segment hot path below pads only the feeds.
            idx = self.np.concatenate(
                [self.np.arange(n),
                 self.np.full(bucket - n, n - 1, dtype=self.np.int64)])
            cur = cur._take_indices(idx)
        t_pad = _time.perf_counter()
        feeds: Dict[str, Any] = {}
        for col in seg.external_reads:
            feeds[col] = self._pad(col, load_column_f32(cur, col), bucket)
        for feed in seg.feeds:
            feeds[feed.name] = self._pad(feed.name, feed.load(cur),
                                         bucket)
        ingress_histograms()["pad"].observe(
            (_time.perf_counter() - t_pad) * 1e3)
        pipeline_histograms()["prepare"].observe(
            (_time.perf_counter() - t0) * 1e3)
        envelope.payload = ("fused", plan, cur, n, seg_idx, feeds)
        return envelope

    def _commit_shapes(self, prepped) -> None:
        """Latch this batch's per-column shapes as the trusted
        mismatch-guard reference — called only AFTER a successful
        score, so a bad batch can never teach the guard wrong widths
        (attribute store is atomic; last writer wins)."""
        shapes = prepped.meta.get("shapes")
        if shapes:
            self._confirmed_shapes = shapes

    def execute(self, table: DataTable, prepped) -> DataTable:
        import time as _time
        from mmlspark_tpu.core.fusion import pipeline_histograms
        spans = prepped.spans
        payload = prepped.payload
        if payload[0] == "empty":
            # every surviving request carried zero rows
            self.batches_scored += 1
            return table.with_column(
                "reply", [{self.reply_field: []} for _ in spans])
        if payload[0] == "host":
            # prepare() already ran every (host) step — re-executing
            # the plan would double-transform non-idempotent stages
            _, plan, cur, n = payload
            self.batches_scored += 1
            self._commit_shapes(prepped)
            return self._reply(table, self.np.asarray(
                cur[self.reply_col])[:n], spans)
        _, plan, cur, n, seg_idx, feeds = payload
        seg = plan.steps[seg_idx]
        t0 = _time.perf_counter()
        consts = seg.consts_list(plan.device_table)
        # donated feeds: the padded batch is consumed exactly once, XLA
        # may alias it for activations (accelerator backends)
        out = seg.compiled(donate=True)(consts, feeds)
        tail = plan.steps[seg_idx + 1:]
        if not tail and self.reply_col in out:
            # the hot path: ONE device round trip — fetch the reply
            # column, slice the padding off
            vals = self.np.asarray(out[self.reply_col])[:n]
            self.device_roundtrips += 1
            self.batches_scored += 1
            pipeline_histograms()["device"].observe(
                (_time.perf_counter() - t0) * 1e3)
            self._commit_shapes(prepped)
            return self._reply(table, vals, spans)
        # general tail (multi-segment / trailing host stages): fold the
        # segment's live outputs back — at FULL bucket length, so the
        # tail segments keep seeing padded shapes and never retrace per
        # batch size (prepare() padded `cur` itself for this case) —
        # and continue the plan; one round trip per remaining segment.
        # The pad rows slice off at the reply, exactly once.
        for col in seg.writes_live:
            # len(cur) == bucket when prepare() padded the table (real
            # tail), == n when the tail is empty (reply from cur)
            val = self.np.asarray(out[col])[:len(cur)]
            cast = seg.out_cast(col)
            if cast is not None:
                val = val.astype(cast)
            cur = cur.with_column(col, val, seg.out_field(col, val))
        self.device_roundtrips += 1
        for step in tail:
            from mmlspark_tpu.core.fusion import FusedSegment
            if isinstance(step, FusedSegment):
                env = step.build_env(cur, plan.device_table)
                out2 = step.compiled(donate=False)(
                    step.consts_list(plan.device_table), env)
                cur = plan._materialize(cur, step, out2)
                self.device_roundtrips += 1
            else:
                cur = step.stage.transform(cur)
        self.batches_scored += 1
        pipeline_histograms()["device"].observe(
            (_time.perf_counter() - t0) * 1e3)
        self._commit_shapes(prepped)
        return self._reply(table,
                           self.np.asarray(cur[self.reply_col])[:n],
                           spans)

    def _reply(self, table: DataTable, vals, spans) -> DataTable:
        def jsonify(v):
            if self.np.ndim(v) >= 1:
                # vector reply columns (probability / rawPrediction)
                return [float(x) for x in self.np.asarray(v).ravel()]
            v = float(v)
            return int(v) if v.is_integer() else v

        out = []
        for s, e, codec in spans:
            if codec == "json":
                # the oracle shape: one scalar reply per request row
                out.append({self.reply_field: jsonify(vals[s])})
            else:
                # columnar requests reply one value PER ROW they carried
                out.append({self.reply_field:
                            [jsonify(v) for v in vals[s:e]]})
        return table.with_column("reply", out)

    def transform(self, table: DataTable) -> DataTable:
        """Single-stage fallback (per-row poison retry, embeddings)."""
        prepped = self.prepare(table)
        if prepped.rejects:
            # single-stage callers have no reject channel: surface the
            # codec error as the row error (the engine's main path 400s
            # rejects before dispatch, so this only fires for embedders)
            raise self.cin.CodecError(
                "; ".join(prepped.rejects.values()))
        return self.execute(table, prepped)

    # -- serving hooks -------------------------------------------------------

    def warmup(self, example, sizes: Optional[List[int]] = None) -> int:
        """Compile every bucket's fused program through the EXACT
        serving path (prepare/execute with bucket padding + donation),
        so a lifecycle swap reaches the hot path fully warm. Runs
        through the shared bucket loop (core/warmup.py), so each
        bucket's compile wall lands in the ``model_warmup_ms``
        histogram on /metrics — near-zero for AOT-loaded pipelines."""
        from mmlspark_tpu.core.warmup import (
            warmup_buckets, warn_warmup_example,
        )
        from mmlspark_tpu.io.http import _jsonable
        table = example if isinstance(example, DataTable) \
            else DataTable(dict(example))
        if len(table) == 0:
            raise ValueError("warmup needs at least one example row")
        # PR 11 footnote, enforced: an all-None column (or a column set
        # that disagrees with live traffic's pinned request keys) would
        # warm programs no live batch matches — warn NOW, actionably,
        # instead of silently recompiling on the first live batch
        with self._names_lock:
            live = list(self._row_names)
        warn_warmup_example(table, live_columns=live or None)
        body = [json.dumps({k: _jsonable(v) for k, v in row.items()}
                           ).encode() for row in table.rows()]

        def run_bucket(b: int) -> None:
            reqs = [{"entity": body[i % len(body)]} for i in range(b)]
            req_table = DataTable({"id": [str(i) for i in range(b)],
                                   "request": reqs})
            self.execute(req_table, self.prepare(req_table))

        return warmup_buckets(run_bucket,
                              sizes or self.fused.bucket_sizes(),
                              lambda: self.fused.jit_cache_misses)

    def jit_cache_miss_count(self) -> int:
        return self.fused.jit_cache_misses

    def bucket_for(self, rows: int) -> int:
        return self.fused.bucket_for(rows)

    def metrics(self) -> Dict[str, Any]:
        return self.fused.metrics()

    def stage(self):
        """Package as the Lambda stage the ServingEngine consumes, with
        the duck-typed two-stage split + lifecycle/observability hooks
        attached (the same contract the TPUModel path exposes)."""
        from mmlspark_tpu.stages.basic import Lambda
        lam = Lambda.apply(self.transform)
        lam.prepare_batch = self.prepare
        lam.execute_prepared = self.execute
        lam.warmup = self.warmup
        lam.metrics = self.metrics
        lam.jit_cache_miss_count = self.jit_cache_miss_count
        lam.bucket_for = self.bucket_for
        lam.resident_bytes = self.fused.resident_bytes
        lam.precision = self.fused.precision
        lam.aot = bool(self.fused.aot)
        lam.scorer = self
        return lam


# engine-reported statuses worth failing over for: overload/shedding
# (503 + Retry-After), serving timeout (504), and gateway-ish 502.
# Anything else 4xx/5xx is the REQUEST's problem (poison row -> 500) and
# must surface to the caller unchanged — retrying it on another replica
# would just poison that one too. 429 is deliberately NOT here: the
# admission layer's tenant quotas (serving/admission.py) are fleet-wide,
# so replaying an over-quota request on the next replica would only
# spend the tenant's tokens everywhere — the 429 surfaces to the caller.
_FAILOVER_CODES = frozenset({502, 503, 504})


class ServingFleet:
    """N serving engines over one pipeline — one per host in a real
    deployment, N ports on one host in simulation/tests. Replies always
    flow through the engine that accepted the request (the reference's
    reply-routing invariant, DistributedHTTPSource.scala:188-192).

    The client side (``post``) is a resilient stand-in for an external
    load balancer: round-robin with a per-engine ``CircuitBreaker`` (a
    dead or shedding engine stops receiving traffic after
    ``failure_threshold`` failures until ``breaker_cooldown`` elapses),
    failover of idempotent scoring requests onto the next replica, and
    optional request hedging (Dean & Barroso, *The Tail at Scale*): when
    ``hedge_percentile`` is set, a request still unanswered after that
    latency percentile fires a duplicate on another replica and the first
    reply wins."""

    def __init__(self, pipeline=None, n_engines: int = 2,
                 host: str = "127.0.0.1", base_port: int = 18700,
                 batch_size: int = 64, reply_col: str = "reply",
                 workers: int = 1,
                 failure_threshold: int = 3,
                 breaker_cooldown: float = 2.0,
                 hedge_percentile: Optional[float] = None,
                 hedge_min_s: float = 0.02,
                 max_parked: Optional[int] = None,
                 max_wait_ms: float = 5.0,
                 pipeline_depth: int = 2,
                 version: str = "v0", tracer=None,
                 tracing: Optional[bool] = None,
                 zoo=None, admission=None,
                 slo=None, flight_recorder=None,
                 shm_transport: bool = False):
        # the multi-model plane: ONE zoo (and one admission controller)
        # shared by every engine — models are process-resident, so the
        # device-memory budget and tenant quotas are fleet-wide
        self.zoo = zoo
        self.admission = admission
        self._init_client(tracer=tracer, tracing=tracing,
                          hedge_percentile=hedge_percentile,
                          hedge_min_s=hedge_min_s)
        self.shm_transport = bool(shm_transport)
        port = base_port
        try:
            for _ in range(n_engines):
                source = HTTPSource(host=host, port=port,
                                    max_parked=max_parked)
                port = source.port + 1      # skip whatever port-scan used
                try:
                    engine = ServingEngine(
                        source, pipeline, reply_col=reply_col,
                        batch_size=batch_size, workers=workers,
                        max_wait_ms=max_wait_ms,
                        pipeline_depth=pipeline_depth,
                        version=version, tracer=self.tracer,
                        tracing=self.tracer is not None,
                        zoo=zoo, admission=admission,
                        slo=slo, flight_recorder=flight_recorder).start()
                except Exception:
                    source.close()   # don't orphan the bound port
                    raise
                self.engines.append(engine)
        except Exception:
            # partial construction must not leak threads/bound ports
            self.stop_all()
            raise
        self._build_breakers(failure_threshold, breaker_cooldown)
        log.info("fleet of %d engines: %s", n_engines, self.addresses)

    def _init_client(self, tracer=None, tracing: Optional[bool] = None,
                     hedge_percentile: Optional[float] = None,
                     hedge_min_s: float = 0.02) -> None:
        """Client-side state shared by the in-process fleet and the
        remote-address client (``connect``)."""
        from mmlspark_tpu.core import trace as trace_mod
        # ONE tracer across the fleet: every engine's completed traces
        # land in the same tail-sampled buffer, so fleet.traces() is
        # the whole fleet's story (default: the process-wide tracer)
        if tracing is None:
            from mmlspark_tpu.core import config as _config
            tracing = bool(_config.get("trace.enabled", True))
        self.tracer = (tracer if tracer is not None
                       else trace_mod.get_tracer()) if tracing else None
        if self.tracer is not None and not self.tracer.enabled:
            self.tracer = None
        self.engines: List[ServingEngine] = []
        self._remote_addresses: Optional[List[str]] = None
        self.transport_errors = 0
        self.hedged_requests = 0
        self._stats_lock = threading.Lock()
        self.hedge_percentile = hedge_percentile
        self.hedge_min_s = hedge_min_s
        self._latencies: "deque[float]" = deque(maxlen=256)
        self._probe_lock = threading.Lock()   # single-flight all-open probe
        # columnar-ingress negotiation memory: flips False after a
        # columnar POST was rejected AND its JSON retry succeeded (a
        # JSON-only engine) so later post_columns calls skip the
        # doomed columnar attempt (the stale-conn retry discipline:
        # pay the discovery once, remember the verdict). The verdict
        # is a COOLDOWN, not a life sentence — a transient 500 that
        # happened to mimic a negotiation failure must not degrade
        # the client to per-row JSON forever, so after
        # ``columnar_retry_cooldown_s`` the next call re-probes the
        # columnar path (and resets the flag on success).
        self._columnar_ok = True
        self.columnar_retry_cooldown_s = 60.0
        self._columnar_retry_at = 0.0
        # shared-memory transport negotiation: the SAME cooldown
        # discipline, one more rung up the ladder. shm -> HTTP+msgpack
        # -> per-row JSON; each rung remembers a rejection for a
        # cooldown, then re-probes. The shm rung only exists when the
        # client opted in (co-located deployments; io/shm.py).
        # the fleet-wide placement plane (serving/placement.py);
        # attach_placement wires a controller in
        self.placement = None
        self.shm_transport = False
        self._shm_ok = True
        self.shm_retry_cooldown_s = 60.0
        self._shm_retry_at = 0.0
        self._shm_ring = None
        self._shm_lock = threading.Lock()
        self._shm_fallbacks = 0
        # itertools.count: next() is atomic under the GIL, so
        # concurrent client threads can't tear the round-robin
        self._next = itertools.count()
        self.breakers: List[CircuitBreaker] = []
        # windowed demand (requests via post, rows via post_columns):
        # the autoscaler's control signal — demand_rate() per engine
        # against its scale-up/-down watermarks (serving/autoscale.py)
        from mmlspark_tpu.core.metrics import WindowedCounter
        self._demand = WindowedCounter(bucket_s=1.0, horizon_s=600.0)
        # dynamic membership (autoscaler join/leave): mutations are
        # serialized under this lock; in-flight posts read addresses/
        # breakers without it — post() treats a membership-race index
        # error as one more failover attempt, so the worst case is a
        # retried leg, never a wrong reply
        self._membership_lock = threading.Lock()
        self.engines_added = 0
        self.engines_removed = 0

    def _build_breakers(self, failure_threshold: int,
                        breaker_cooldown: float) -> None:
        # remembered so engines joining later (add_engine) get
        # breakers with the fleet's configured budget
        self._breaker_params = (int(failure_threshold),
                                float(breaker_cooldown))
        self.breakers = [
            CircuitBreaker(failure_threshold=failure_threshold,
                           cooldown=breaker_cooldown,
                           name=f"engine{i}@{addr}")
            for i, addr in enumerate(self.addresses)]
        # an opening circuit is exactly the moment evidence matters:
        # auto-capture a flight-recorder bundle (rate-limited) on the
        # closed->open transition of any engine's breaker. on_open is
        # a single slot, so ONE recorder gets the hook — the fleet's
        # engines share one (the constructor arg or the process-wide
        # default), so take the first engine's.
        rec = next((e.flight_recorder for e in self.engines
                    if getattr(e, "flight_recorder", None) is not None),
                   None)
        if rec is not None:
            for breaker in self.breakers:
                breaker.on_open = (
                    lambda b, _rec=rec: _rec.trigger(
                        f"circuit_open:{b.name}"))

    @classmethod
    def connect(cls, addresses: List[str],
                failure_threshold: int = 3,
                breaker_cooldown: float = 2.0,
                hedge_percentile: Optional[float] = None,
                hedge_min_s: float = 0.02,
                tracer=None,
                tracing: Optional[bool] = None,
                wait_ready_s: float = 0.0,
                ready_poll_timeout_s: float = 1.0,
                shm_transport: bool = False) -> "ServingFleet":
        """A CLIENT-ONLY fleet over engines that live in OTHER
        processes (or hosts): the same round-robin + circuit-breaking
        + failover + hedging client, pointed at explicit addresses
        instead of in-process engines. This is the multi-process
        deployment shape (one OS process per engine — the ROADMAP
        sharded-serving direction): each leg injects the traceparent
        context, so a request that retries/hedges across processes
        still reassembles into ONE trace from the engines' exported
        buffers (``core.trace.merge_chrome_traces``).

        ``wait_ready_s`` > 0 runs a STARTUP probe: poll each address's
        ``/healthz`` with backoff until it answers or the budget runs
        out. Engine processes spawn slowly (a replica pays its Python/
        jax import before it listens), and without the probe the first
        real requests against a not-yet-listening worker burn the
        breaker's whole failure budget — the fleet opens the circuit
        of an engine that was never down, then serves degraded until
        the cooldown. Probe failures touch NO breaker (breakers are
        built after the wait); addresses still unreachable when the
        budget ends just log — the normal breaker/failover path owns
        them from there.

        Engine-management surfaces (``rolling_swap``, ``metrics``,
        ``kill_engine``) are inert on a connected client — scrape the
        remote engines' own ``/metrics``/``/healthz`` instead."""
        fleet = cls.__new__(cls)
        fleet.zoo = None
        fleet.admission = None
        fleet._init_client(tracer=tracer, tracing=tracing,
                           hedge_percentile=hedge_percentile,
                           hedge_min_s=hedge_min_s)
        fleet.shm_transport = bool(shm_transport)
        fleet._remote_addresses = [str(a).rstrip("/") for a in addresses]
        if not fleet._remote_addresses:
            raise ValueError("connect() needs at least one address")
        if wait_ready_s > 0:
            fleet._wait_ready(wait_ready_s, ready_poll_timeout_s)
        fleet._build_breakers(failure_threshold, breaker_cooldown)
        log.info("fleet client connected to %d remote engines: %s",
                 len(fleet._remote_addresses), fleet.addresses)
        return fleet

    def _wait_ready(self, budget_s: float,
                    probe_timeout_s: float = 1.0,
                    addresses: Optional[List[str]] = None) -> List[str]:
        """Bounded startup probe: poll every address's /healthz under
        ONE shared deadline with jittered backoff (utils/resilience
        discipline) until each answers anything at all — an HTTP
        status means the process is listening, which is all the probe
        establishes. Returns the addresses that never came up (logged;
        callers' breakers take over)."""
        from mmlspark_tpu.utils.resilience import Deadline, RetryPolicy
        deadline = Deadline.after(float(budget_s))
        policy = RetryPolicy(max_attempts=1_000_000, base_delay=0.05,
                             multiplier=1.5, max_delay=0.5,
                             name="fleet.wait_ready")
        pending = list(addresses if addresses is not None
                       else self._remote_addresses)
        not_ready: List[str] = []
        for addr in pending:

            def probe(_addr=addr):
                timeout = max(0.05,
                              deadline.clamp(float(probe_timeout_s)))
                try:
                    with urllib.request.urlopen(f"{_addr}/healthz",
                                                timeout=timeout):
                        pass
                except urllib.error.HTTPError:
                    pass   # an HTTP status = listening; ready enough

            try:
                if deadline.expired:
                    # budget spent on earlier addresses: one immediate
                    # probe each, no backoff — a worker that came up
                    # meanwhile must not be written off unprobed
                    probe()
                else:
                    policy.call(probe, deadline=deadline)
            except Exception:  # noqa: BLE001 — budget spent / refused
                not_ready.append(addr)
        if not_ready:
            log.warning(
                "fleet.connect: %d/%d engines not listening after "
                "%.1fs startup probe (%s); their breakers will own "
                "them from here", len(not_ready), len(pending),
                budget_s, ", ".join(not_ready))
        return not_ready

    @property
    def addresses(self) -> List[str]:
        if self._remote_addresses is not None:
            return list(self._remote_addresses)
        return [e.source.address for e in self.engines]

    # -- transport ---------------------------------------------------------

    # keep-alive connection pool: one persistent HTTPConnection per
    # (thread, engine address). thread-local => no locking, and a
    # connection is never shared across concurrent requests
    _conn_pool = threading.local()

    @classmethod
    def _pooled_conn(cls, addr: str,
                     timeout: float) -> "http.client.HTTPConnection":
        conns = getattr(cls._conn_pool, "conns", None)
        if conns is None:
            conns = cls._conn_pool.conns = {}
        conn = conns.get(addr)
        if conn is None:
            u = urllib.parse.urlsplit(addr)
            conn = http.client.HTTPConnection(u.hostname, u.port,
                                              timeout=timeout)
            conns[addr] = conn
        conn.timeout = timeout
        if conn.sock is not None:
            conn.sock.settimeout(timeout)
        return conn

    @classmethod
    def _drop_conn(cls, addr: str) -> None:
        conns = getattr(cls._conn_pool, "conns", {})
        conn = conns.pop(addr, None)
        if conn is not None:
            try:
                conn.close()
            except Exception:  # noqa: BLE001
                pass

    @classmethod
    def _http_post(cls, addr: str, body: bytes, timeout: float,
                   replayable: bool = True, pooled: bool = True,
                   content_type: str = "application/json",
                   extra_headers: Optional[Dict[str, str]] = None,
                   ) -> Dict[str, Any]:
        """POST over a pooled keep-alive connection (HTTP/1.1): the
        serving hot path pays no TCP handshake and spawns no server
        thread per request. App-level statuses surface as
        ``urllib.error.HTTPError`` (the breaker/failover contract).

        A pooled connection the server closed while idle fails either
        on the SEND or — when the buffered write slips through before
        the RST — as RemoteDisconnected on the response; both retry
        once on a fresh connection, else a whole healthy fleet looks
        down after an idle gap (every thread-local conn went stale at
        once). The response-phase retry could re-execute a request the
        engine processed but never answered, so it is gated on
        ``replayable`` (post's ``idempotent`` flag). ``pooled=False``
        uses a one-shot connection closed before return — for spawned
        hedge threads, whose thread-local pool would otherwise leak
        one connection per call. Other failures propagate — the
        caller's failover policy decides."""
        import time as _time
        t0 = _time.perf_counter()
        headers = {"Content-Type": content_type, **(extra_headers or {})}
        for attempt in (0, 1):
            if pooled:
                conn = cls._pooled_conn(addr, timeout)
            else:
                u = urllib.parse.urlsplit(addr)
                conn = http.client.HTTPConnection(u.hostname, u.port,
                                                  timeout=timeout)
                headers = dict(headers, Connection="close")

            def _discard():
                if pooled:
                    cls._drop_conn(addr)
                else:
                    try:
                        conn.close()
                    except Exception:  # noqa: BLE001
                        pass

            fresh = conn.sock is None
            try:
                conn.request("POST", "/", body, headers)
            except Exception:
                _discard()
                if fresh or attempt:
                    raise
                continue   # stale keep-alive socket: one fresh retry
            try:
                resp = conn.getresponse()
                data = resp.read()
                if not pooled or resp.will_close:
                    _discard()
            except (http.client.RemoteDisconnected,
                    http.client.BadStatusLine):
                _discard()
                if fresh or attempt or not replayable:
                    raise
                continue   # idle-closed socket ate the send: retry
            except Exception:
                _discard()
                raise
            if resp.status >= 400:
                if (resp.status == 503 and resp.will_close
                        and not fresh and not attempt):
                    # a closed source draining its old persistent
                    # connections (shed + Connection: close): nothing
                    # was processed — reconnect once; a fresh connect
                    # reaches whatever now owns the port
                    continue
                raise urllib.error.HTTPError(
                    addr, resp.status, resp.reason,
                    dict(resp.getheaders()), io.BytesIO(data))
            return {"body": json.loads(data),
                    "latency": _time.perf_counter() - t0}
        raise RuntimeError("unreachable")   # loop always returns/raises

    @staticmethod
    def _submit(fn, *args) -> "Future":
        """Run ``fn`` on a fresh DAEMON thread, returning a Future.
        Deliberately not a ThreadPoolExecutor: its non-daemon workers
        are joined by the atexit hook, so an abandoned hedge leg stuck
        against a stalled engine would block interpreter exit for its
        whole transport timeout; daemon threads also can't starve each
        other the way a fixed-size pool full of zombie legs can."""
        fut: "Future" = Future()

        def run():
            if not fut.set_running_or_notify_cancel():
                return
            try:
                fut.set_result(fn(*args))
            except BaseException as e:  # noqa: BLE001 — future protocol
                fut.set_exception(e)

        threading.Thread(target=run, daemon=True,
                         name="fleet-hedge").start()
        return fut

    def _hedge_threshold(self) -> Optional[float]:
        if self.hedge_percentile is None:
            return None
        with self._stats_lock:
            if len(self._latencies) < 16:
                return None
            lat = sorted(self._latencies)
        idx = min(len(lat) - 1,
                  int(self.hedge_percentile / 100.0 * len(lat)))
        return max(self.hedge_min_s, lat[idx])

    def _record_latency(self, dt: float) -> None:
        with self._stats_lock:
            self._latencies.append(dt)

    def _classify_and_record(self, breaker: CircuitBreaker,
                             err: Optional[BaseException]) -> None:
        """Breaker bookkeeping for one transport outcome: success, or an
        app-level HTTP status (engine alive and answering — e.g. a
        poison row's 500), counts as healthy; failover statuses and
        transport failures count against the engine."""
        if err is None or (isinstance(err, urllib.error.HTTPError)
                           and err.code not in _FAILOVER_CODES):
            breaker.record_success()
        else:
            breaker.record_failure()

    # -- client-side tracing -------------------------------------------------

    def _client_trace(self, name: str):
        """One trace per logical client call. Inside an active span
        (``core.trace.use_span``) the new root CONTINUES that trace as
        a child, so an embedder's own spans, the client legs, and the
        remote engines' server spans all share one trace id."""
        if self.tracer is None:
            return None
        from mmlspark_tpu.core.trace import current_span
        cur = current_span()
        return self.tracer.new_trace(
            name,
            trace_id=cur.trace_id if cur is not None else None,
            parent_id=cur.span_id if cur is not None else None)

    def _leg_span(self, trace, i: int, hedge: bool = False,
                  probe: bool = False):
        """One client leg span + the propagation headers it must carry.
        Every leg of one logical post — retries, failovers, hedges —
        is a SIBLING under the same root, so the fan-out renders as one
        trace; the remote engine parents its server span on the leg's
        span id (Tracer.inject/extract)."""
        if trace is None:
            return None, None
        span = self.tracer.start_span("client.post", trace,
                                      parent=trace.root)
        span.set("engine", i)
        span.set("address", self.addresses[i])
        if hedge:
            span.set("hedge", True)
        if probe:
            span.set("probe", True)
        return span, self.tracer.inject(span)

    @staticmethod
    def _merged_headers(extra_headers: Optional[Dict[str, str]],
                        inject: Optional[Dict[str, str]]
                        ) -> Optional[Dict[str, str]]:
        if not inject:
            return extra_headers
        return {**(extra_headers or {}), **inject}

    # serializes leg-span verdicts: a hedge winner cancelling the
    # loser races the loser's own done-callback (they run on different
    # threads); without the lock the same span could be labeled BOTH
    # cancelled and error, or a genuinely failed leg could lose its
    # error to a concurrent cancel. Critical sections are a few
    # attribute stores — one class-wide lock is cheap and sufficient.
    _leg_lock = threading.Lock()

    @staticmethod
    def _mark_root_http(trace, code: int) -> None:
        """The client root's verdict for an app-level HTTP status —
        the server-side shed-vs-error discipline (the shared
        ``core.trace.SHED_STATUSES`` policy): back-pressure statuses
        are shed=true, only real 5xx are errors. A hot tenant's quota
        429s must not flood the client tracer's protected tail ring."""
        if trace is None:
            return
        from mmlspark_tpu.core.trace import SHED_STATUSES
        trace.root.set("http_status", code)
        if code in SHED_STATUSES:
            trace.root.set("shed", True)
        elif code >= 500:
            trace.root.error()

    def _finish_leg(self, span, err: Optional[BaseException]) -> None:
        """Close one leg span for its own outcome — UNLESS the leg was
        already marked cancelled (it lost a hedge race: the winner
        closed it; its late real outcome must not rewrite the
        verdict). Quota/shed HTTP statuses mark the leg shed, not
        error (the root discipline, per leg)."""
        if span is None:
            return
        from mmlspark_tpu.core.trace import SHED_STATUSES
        with self._leg_lock:
            if span.end is not None or span.attrs.get("cancelled"):
                return
            if isinstance(err, urllib.error.HTTPError) and \
                    err.code in SHED_STATUSES:
                span.set("shed", True)
                span.set("http_status", err.code)
            elif err is not None:
                span.error(err)
            span.finish()

    @classmethod
    def _cancel_leg(cls, span) -> None:
        """Mark a hedge loser: ``cancelled=true``, NOT error — the leg
        was abandoned because its sibling answered first, which is the
        hedge working as designed, not a failure (the shed-vs-error
        distinction applied to client spans: 'cancelled' must not
        flood error dashboards or the protected tail ring)."""
        if span is None:
            return
        with cls._leg_lock:
            if span.end is None:
                span.set("cancelled", True)
                span.finish()

    def _attempt(self, i: int, body: bytes, timeout: float, tried: set,
                 allow_hedge: bool,
                 content_type: str = "application/json",
                 extra_headers: Optional[Dict[str, str]] = None,
                 trace=None) -> Dict[str, Any]:
        """One logical attempt against engine ``i``, hedged onto another
        replica if allowed and the reply is slower than the hedge
        threshold. ALL breaker recording happens here — for a hedged
        primary the outcome is recorded when its leg actually finishes
        (a stalled primary must still open its circuit even though the
        hedge rescued the request). Raises the (winning) transport
        error on failure. Each leg carries its own traceparent headers
        (per-leg client spans under ``trace``)."""
        breaker = self.breakers[i]
        addr = self.addresses[i]
        threshold = self._hedge_threshold() if allow_hedge else None
        if threshold is None or threshold >= timeout:
            span, inj = self._leg_span(trace, i)
            try:
                # allow_hedge carries post()'s idempotent flag: only
                # idempotent requests may transparently replay a
                # response-phase stale-connection failure
                result = self._http_post(
                    addr, body, timeout, replayable=allow_hedge,
                    content_type=content_type,
                    extra_headers=self._merged_headers(extra_headers,
                                                       inj))
            except Exception as e:
                self._classify_and_record(breaker, e)
                self._finish_leg(span, e)
                raise
            self._classify_and_record(breaker, None)
            self._finish_leg(span, None)
            return result
        import time as _time
        start = _time.monotonic()
        # hedge legs run on spawned one-shot threads: pooled=False, or
        # each call would strand a keep-alive conn in a dead thread's
        # local storage (hedging only runs for idempotent requests)
        span1, inj1 = self._leg_span(trace, i)
        f1 = self._submit(self._http_post, addr, body, timeout,
                          True, False, content_type,
                          self._merged_headers(extra_headers, inj1))
        f1.add_done_callback(
            lambda f: (self._classify_and_record(breaker, f.exception()),
                       self._finish_leg(span1, f.exception())))
        try:
            return f1.result(timeout=threshold)
        except _FutureTimeout:
            pass                       # slow — fire the hedge
        # allow() (not a bare state check) so a half-open replica's
        # probe budget also gates hedge traffic — a barely-recovered
        # engine must not get a thundering herd of hedges
        j = next((k for k in range(len(self.breakers))
                  if k != i and k not in tried
                  and self.breakers[k].allow()),
                 None)
        if j is None:
            return f1.result(
                timeout=max(0.001, start + timeout - _time.monotonic()))
        with self._stats_lock:
            self.hedged_requests += 1
        tried.add(j)   # the hedge consumed replica j for this request
        span2, inj2 = self._leg_span(trace, j, hedge=True)
        f2 = self._submit(self._http_post, self.addresses[j], body,
                          timeout, True, False, content_type,
                          self._merged_headers(extra_headers, inj2))
        f2.add_done_callback(
            lambda f: (self._classify_and_record(self.breakers[j],
                                                 f.exception()),
                       self._finish_leg(span2, f.exception())))
        pending = {f1, f2}
        first_error: Optional[BaseException] = None
        while pending:
            remaining = start + timeout - _time.monotonic()
            if remaining <= 0:
                raise TimeoutError(
                    f"hedged request to {addr} timed out after {timeout}s")
            done, pending = _futures_wait(
                pending, timeout=remaining, return_when=FIRST_COMPLETED)
            if not done:
                raise TimeoutError(
                    f"hedged request to {addr} timed out after {timeout}s")
            for f in done:
                err = f.exception()
                if err is None:
                    # the sibling leg LOSES: mark it cancelled (not
                    # error) — but only while it is genuinely still in
                    # flight. A leg that already COMPLETED (e.g. both
                    # futures landed in one wait round) gets its real
                    # verdict from its own done-callback; cancelling
                    # it would erase a true transport error.
                    loser_f, loser_span = ((f2, span2) if f is f1
                                           else (f1, span1))
                    if not loser_f.done():
                        self._cancel_leg(loser_span)
                    return f.result()
                first_error = first_error or err
        raise first_error  # both legs failed — surface the primary's

    # -- the client --------------------------------------------------------

    @staticmethod
    def _route_headers(model: Optional[str], tenant: Optional[str],
                       priority: Optional[int],
                       headers: Optional[Dict[str, str]]
                       ) -> Optional[Dict[str, str]]:
        """The model-routing/admission headers (serving/zoo.py +
        serving/admission.py) as one merged extra-header dict."""
        out = dict(headers or {})
        if model is not None:
            out["X-Model"] = str(model)
        if tenant is not None:
            out["X-Tenant"] = str(tenant)
        if priority is not None:
            out["X-Priority"] = str(int(priority))
        return out or None

    def post(self, payload: Any, timeout: float = 30.0,
             idempotent: bool = True,
             content_type: str = "application/json",
             model: Optional[str] = None,
             tenant: Optional[str] = None,
             priority: Optional[int] = None,
             headers: Optional[Dict[str, str]] = None) -> Dict[str, Any]:
        """Failover-aware round-robin client — the stand-in for an
        external load balancer in tests/examples.

        Engines whose circuit is open are skipped; transport failures
        and overload statuses (429/502/503/504) fail over to the next
        replica when ``idempotent`` (scoring requests are). When every
        candidate fails, raises ``ServingUnavailable`` carrying the
        per-engine attempt log. Application-level HTTP errors (e.g. a
        poison row's 500) propagate unchanged — as do admission 429s
        (a tenant's empty quota is fleet-wide; replaying the request
        on another replica would just spend it there too).

        ``model``/``tenant``/``priority`` ride as the multi-model
        plane's routing headers (``X-Model``/``X-Tenant``/
        ``X-Priority``); ``headers`` adds arbitrary extras."""
        body = payload if isinstance(payload, bytes) \
            else json.dumps(payload).encode()
        extra_headers = self._route_headers(model, tenant, priority,
                                            headers)
        self._demand.inc(1.0)    # the autoscaler's windowed signal
        n = len(self.addresses)
        start = next(self._next)
        order = [(start + k) % n for k in range(n)]
        if self.placement is not None and model:
            # the placement plane: assigned engines first (round-robin
            # WITHIN the replica set), the rest of the fleet behind
            # them — a stale plan or a dying replica set falls through
            # to any engine, where the zoo's lazy activation takes over
            self.placement.record_request(model)
            self.placement.rebuild()        # rate-limited internally
            preferred = [i for i in self.placement.engines_for(model)
                         if 0 <= i < n]
            if preferred:
                k = start % len(preferred)
                head = preferred[k:] + preferred[:k]
                order = head + [i for i in order if i not in set(head)]
        max_tries = n if idempotent else 1
        attempts: List[Dict[str, Any]] = []
        tried: set = set()
        # the client-side trace of this LOGICAL request: every leg
        # (failover, hedge, probe) is a sibling client span under this
        # root, and each leg's traceparent headers make the remote
        # engine's server spans children of that leg — one trace id
        # across processes
        trace = self._client_trace("fleet.post")
        try:
            for i in order:
                if len(tried) >= max_tries:
                    break
                if i in tried:
                    continue   # already consumed as a hedge leg
                breaker = self.breakers[i]
                if not breaker.allow():
                    attempts.append(
                        {"engine": i, "address": self.addresses[i],
                         "error": "circuit open", "skipped": True})
                    continue
                tried.add(i)
                try:
                    # _attempt owns ALL breaker recording (incl. hedges)
                    result = self._attempt(i, body, timeout, tried,
                                           allow_hedge=idempotent,
                                           content_type=content_type,
                                           extra_headers=extra_headers,
                                           trace=trace)
                except urllib.error.HTTPError as e:
                    if e.code in _FAILOVER_CODES:
                        attempts.append(
                            {"engine": i, "address": self.addresses[i],
                             "error": f"HTTP {e.code}", "skipped": False})
                        continue
                    # app-level error: the engine is alive and
                    # answering — the request itself is at fault.
                    # Surface it unchanged.
                    self._mark_root_http(trace, e.code)
                    raise
                except Exception as e:  # noqa: BLE001 — URLError/...
                    with self._stats_lock:
                        self.transport_errors += 1
                    attempts.append(
                        {"engine": i, "address": self.addresses[i],
                         "error": f"{type(e).__name__}: {e}",
                         "skipped": False})
                    continue
                self._record_latency(result["latency"])
                if trace is not None:
                    # failovers = legs that actually RAN and failed
                    # before this one; circuit-open skips produced no
                    # client leg and must not inflate the count the
                    # perfetto walkthrough pairs with sibling legs
                    failovers = len([a for a in attempts
                                     if not a.get("skipped")])
                    if failovers:
                        trace.root.set("failovers", failovers)
                return result["body"]
            if not tried and order:
                # every circuit open: last-resort probe of the
                # round-robin head so the fleet can rediscover a
                # recovered engine even before the breaker cooldown
                # elapses. SINGLE-FLIGHT: only one caller at a time
                # pays the probe's timeout against a possibly-stalled
                # engine; everyone else fails fast — the whole point of
                # an open circuit during a total outage.
                if not self._probe_lock.acquire(blocking=False):
                    attempts.append(
                        {"engine": order[0],
                         "address": self.addresses[order[0]],
                         "error": "circuit open (probe in flight)",
                         "skipped": True})
                    raise ServingUnavailable(attempts)
                try:
                    return self._probe(order[0], body, timeout, attempts,
                                       idempotent, content_type,
                                       extra_headers, trace=trace)
                finally:
                    self._probe_lock.release()
            raise ServingUnavailable(attempts)
        except ServingUnavailable:
            if trace is not None:
                trace.root.error("no serving engine available")
            raise
        finally:
            if trace is not None:
                self.tracer.finish(trace)

    def _probe(self, i: int, body: bytes, timeout: float,
               attempts: List[Dict[str, Any]],
               replayable: bool = True,
               content_type: str = "application/json",
               extra_headers: Optional[Dict[str, str]] = None,
               trace=None) -> Dict[str, Any]:
        """The all-circuits-open last-resort probe of engine ``i``."""
        span, inj = self._leg_span(trace, i, probe=True)
        extra_headers = self._merged_headers(extra_headers, inj)
        try:
            result = self._http_post(self.addresses[i], body, timeout,
                                     replayable=replayable,
                                     content_type=content_type,
                                     extra_headers=extra_headers)
        except urllib.error.HTTPError as e:
            self._finish_leg(span, e)
            if e.code not in _FAILOVER_CODES:
                # engine alive and answering: the post() contract —
                # app-level errors (a poison row's 500) propagate
                # unchanged — holds on the probe path too, and an
                # answering engine force-closes its breaker
                self._mark_root_http(trace, e.code)
                self.breakers[i].reset()
                raise
            self.breakers[i].record_failure()
            attempts.append(
                {"engine": i, "address": self.addresses[i],
                 "error": f"HTTP {e.code}", "skipped": False})
            raise ServingUnavailable(attempts) from e
        except Exception as e:  # noqa: BLE001 — URLError/timeout/...
            self._finish_leg(span, e)
            with self._stats_lock:
                self.transport_errors += 1
            attempts.append(
                {"engine": i, "address": self.addresses[i],
                 "error": f"{type(e).__name__}: {e}", "skipped": False})
            raise ServingUnavailable(attempts) from e
        # a real scored reply while OPEN: force the breaker closed
        self._finish_leg(span, None)
        self.breakers[i].reset()
        self._record_latency(result["latency"])
        return result["body"]

    def post_columns(self, columns: Dict[str, Any],
                     timeout: float = 30.0, codec: str = "msgpack",
                     idempotent: bool = True,
                     model: Optional[str] = None,
                     tenant: Optional[str] = None,
                     priority: Optional[int] = None) -> Dict[str, Any]:
        """The pooled COLUMNAR client: typed columns (numpy arrays /
        string lists / token lists, any row count) encode ONCE as a
        columnar record batch and ride the same keep-alive pool,
        failover, and hedging as ``post`` — fleet-internal hops use the
        zero-copy ingress path end to end. The reply carries one value
        per row: ``{"prediction": [...]}``.

        Negotiation fallback: an old/JSON-only engine rejects the
        columnar body (it cannot decode it); the client then replays
        the SAME rows as JSON oracle requests, and — once that retry
        succeeds — remembers the verdict so later calls skip the
        doomed columnar attempt (the PR 2 stale-connection retry
        discipline applied to content negotiation)."""
        from mmlspark_tpu.io import columnar as CIN
        # demand is measured in ROWS: the nested post() counts the one
        # HTTP request, this adds the rest of the batch so a columnar
        # client's load registers at its true weight
        rows = 0
        for v in columns.values():
            try:
                rows = max(rows, len(v))
            except TypeError:
                pass
        if rows > 1:
            self._demand.inc(float(rows - 1))
        if self.shm_transport and (
                self._shm_ok
                or time.monotonic() >= self._shm_retry_at):
            result = self._post_columns_shm(columns, timeout,
                                            idempotent, model=model,
                                            tenant=tenant,
                                            priority=priority)
            if result is not _SHM_DECLINED:
                return result
        try_columnar = (self._columnar_ok
                        or time.monotonic() >= self._columnar_retry_at)
        if try_columnar:
            body, ct = CIN.encode_columns(columns, codec=codec)
            try:
                result = self.post(body, timeout=timeout,
                                   idempotent=idempotent,
                                   content_type=ct, model=model,
                                   tenant=tenant, priority=priority)
                self._columnar_ok = True   # (re-)probe succeeded
                return result
            except urllib.error.HTTPError as e:
                # 400: codec reject; 415: an explicit media-type no;
                # 500: a pre-columnar engine whose JSON decode choked
                # on the binary body. Anything else is not a
                # negotiation problem — surface it.
                if e.code not in (400, 415, 500):
                    raise
                log.warning("columnar POST rejected (HTTP %d); "
                            "retrying as JSON", e.code)
        out = self._post_columns_json(columns, timeout, idempotent,
                                      model=model, tenant=tenant,
                                      priority=priority)
        if try_columnar:
            # the JSON replay succeeded where columnar failed: treat
            # the engine as JSON-only for a cooldown, then re-probe —
            # a transient 500 must not pin the slow path forever
            self._columnar_ok = False
            self._columnar_retry_at = (time.monotonic()
                                       + self.columnar_retry_cooldown_s)
            log.warning("engine speaks JSON only; using the JSON "
                        "fallback path for %.0fs before re-probing",
                        self.columnar_retry_cooldown_s)
        return out

    def _ensure_shm_ring(self):
        """Lazily create this client's shared-memory ring (io/shm.py);
        the client OWNS the segment and unlinks it in stop_all/
        close_shm."""
        with self._shm_lock:
            if self._shm_ring is None:
                from mmlspark_tpu.io import shm as SHM
                self._shm_ring = SHM.ShmRing()
            return self._shm_ring

    def _shm_declined(self, cooldown: bool) -> Any:
        """Record one shm->HTTP fallback; with ``cooldown`` the shm
        rung stays down for ``shm_retry_cooldown_s`` (negotiation
        verdict), without it the next call retries shm immediately
        (transient local condition: ring full, frame too big)."""
        with self._stats_lock:
            self._shm_fallbacks += 1
        if cooldown:
            self._shm_ok = False
            self._shm_retry_at = (time.monotonic()
                                  + self.shm_retry_cooldown_s)
            log.warning("engine does not accept the shm transport; "
                        "using HTTP bodies for %.0fs before re-probing",
                        self.shm_retry_cooldown_s)
        return _SHM_DECLINED

    def _post_columns_shm(self, columns: Dict[str, Any],
                          timeout: float, idempotent: bool,
                          model: Optional[str] = None,
                          tenant: Optional[str] = None,
                          priority: Optional[int] = None) -> Any:
        """The shared-memory rung: frame the columns into a ring slot
        (one staged copy, no body bytes) and post only the tiny control
        message. Returns ``_SHM_DECLINED`` when this batch should ride
        HTTP instead (ring full / frame too big / engine rejected the
        codec); ``ServingUnavailable`` and app-level errors propagate —
        they are not negotiation failures."""
        from mmlspark_tpu.io import shm as SHM
        try:
            ring = self._ensure_shm_ring()
            ctrl, ct, token = ring.write(columns)
        except (SHM.ShmBackpressure, SHM.ShmCapacity):
            return self._shm_declined(cooldown=False)
        except Exception:  # noqa: BLE001 — no /dev/shm, perms, ...
            return self._shm_declined(cooldown=True)
        clean = True
        try:
            result = self.post(ctrl, timeout=timeout,
                               idempotent=idempotent,
                               content_type=ct, model=model,
                               tenant=tenant, priority=priority)
            self._shm_ok = True   # (re-)probe succeeded
            return result
        except urllib.error.HTTPError as e:
            # the engine REPLIED (it is done with the slot): 400/415 =
            # cannot attach / stale / explicit no; 500 = a pre-shm
            # engine that parsed the control message as an ordinary
            # JSON request and choked at the app level — all three are
            # negotiation verdicts, fall back (the columnar-rung
            # discipline); other app-level errors surface unchanged
            if e.code in (400, 415, 500):
                return self._shm_declined(cooldown=True)
            raise
        except Exception:
            # transport failure / total outage: an engine may still be
            # mid-read on the slot — quarantine it, don't reuse soon
            clean = False
            raise
        finally:
            ring.release(token, clean=clean)

    # -- dynamic membership (the autoscaler's join/leave surface) -----------

    def demand_rate(self, window_s: float = 30.0) -> float:
        """Client-observed demand (rows/s, JSON posts counting 1) over
        the trailing window — the autoscaler's control signal."""
        return self._demand.rate(float(window_s))

    def add_engine(self, address: str,
                   wait_ready_s: float = 0.0) -> int:
        """Join one engine to a CONNECTED fleet's rotation and return
        its index. ``wait_ready_s`` > 0 runs the startup probe against
        the new address first (the ``connect`` discipline: a slow
        starter must not burn its fresh breaker's failure budget).
        Membership mutations serialize under ``_membership_lock``;
        the breaker appends BEFORE the address so a concurrently
        routing ``post`` never indexes past the breaker list."""
        if self._remote_addresses is None:
            raise RuntimeError(
                "add_engine joins remote engines; in-process fleets "
                "are fixed at construction")
        addr = str(address).rstrip("/")
        if wait_ready_s > 0:
            self._wait_ready(float(wait_ready_s), addresses=[addr])
        ft, cd = self._breaker_params
        with self._membership_lock:
            if addr in self._remote_addresses:
                return self._remote_addresses.index(addr)
            idx = len(self._remote_addresses)
            self.breakers.append(CircuitBreaker(
                failure_threshold=ft, cooldown=cd,
                name=f"engine{idx}@{addr}"))
            self._remote_addresses.append(addr)
            self.engines_added += 1
        if self.placement is not None:
            # rebalance the placement plane over the new width
            self.placement.set_n_engines(len(self.addresses),
                                         reason=f"join:{addr}")
        log.info("fleet: engine %s joined (now %d engines)", addr,
                 idx + 1)
        return idx

    def remove_engine(self, address: str) -> None:
        """Drop one engine from a CONNECTED fleet's rotation (the
        address shrinks BEFORE the breaker list — the mirror of
        ``add_engine``'s ordering — so racing posts never index past
        either). The engine process itself is NOT touched: retiring a
        live engine is the autoscaler's drain-before-retire job
        (serving/autoscale.py), which only stops a process after this
        removal AND a drained /healthz."""
        if self._remote_addresses is None:
            raise RuntimeError(
                "remove_engine is for connected fleets; in-process "
                "fleets are fixed at construction")
        addr = str(address).rstrip("/")
        with self._membership_lock:
            if addr not in self._remote_addresses:
                raise ValueError(f"unknown engine address {addr!r}")
            i = self._remote_addresses.index(addr)
            del self._remote_addresses[i]
            del self.breakers[i]
            self.engines_removed += 1
        if self.placement is not None:
            self.placement.set_n_engines(len(self.addresses),
                                         reason=f"leave:{addr}")
        log.info("fleet: engine %s left (now %d engines)", addr,
                 len(self.addresses))

    def attach_placement(self, controller=None, **kwargs):
        """Wire a fleet-wide ``PlacementController`` (serving/
        placement.py) into the client: model-keyed posts route to the
        model's assigned engines first. Pass a controller, or kwargs to
        build one over this fleet's zoo and engine count. Returns the
        controller."""
        if controller is None:
            from mmlspark_tpu.serving.placement import (
                PlacementController,
            )
            controller = PlacementController(
                self.zoo, n_engines=len(self.addresses), **kwargs)
        self.placement = controller
        return controller

    def close_shm(self) -> None:
        """Unlink this client's shm ring (owner side) and drop any
        engine-side attachments living in this process."""
        with self._shm_lock:
            ring, self._shm_ring = self._shm_ring, None
        if ring is not None:
            ring.close()
        import sys
        shm_mod = sys.modules.get("mmlspark_tpu.io.shm")
        if shm_mod is not None:
            shm_mod.close_attachments()

    def _post_columns_json(self, columns: Dict[str, Any],
                           timeout: float,
                           idempotent: bool,
                           model: Optional[str] = None,
                           tenant: Optional[str] = None,
                           priority: Optional[int] = None
                           ) -> Dict[str, Any]:
        """The negotiation fallback: replay the columns as per-row JSON
        oracle requests, merging the scalar replies into the columnar
        reply shape (one list per reply key)."""
        from mmlspark_tpu.io.columnar import columns_to_rows
        merged: Dict[str, List[Any]] = {}
        for row in columns_to_rows(columns):
            body = self.post(row, timeout=timeout, idempotent=idempotent,
                             model=model, tenant=tenant,
                             priority=priority)
            for k, v in body.items():
                merged.setdefault(k, []).append(v)
        return merged

    # -- observability -----------------------------------------------------

    def health(self, timeout: float = 2.0) -> List[Dict[str, Any]]:
        """Poll every engine's /healthz (in-process or remote);
        unreachable engines report ``{"reachable": False, ...}``."""
        out = []
        for addr in self.addresses:
            url = f"{addr}/healthz"
            try:
                with urllib.request.urlopen(url, timeout=timeout) as r:
                    out.append({"reachable": True,
                                **json.loads(r.read())})
            except urllib.error.HTTPError as err:
                try:
                    out.append({"reachable": True,
                                **json.loads(err.read())})
                except Exception:  # noqa: BLE001
                    out.append({"reachable": True,
                                "status": f"HTTP {err.code}"})
            except Exception as err:  # noqa: BLE001
                out.append({"reachable": False,
                            "error": f"{type(err).__name__}: {err}"})
        return out

    def metrics(self) -> Dict[str, Any]:
        """Fleet-wide latency breakdown: per-engine snapshots plus an
        aggregate merging every engine's histograms (the bench/ops
        view). Engine histograms merge exactly (same bucket layout);
        the pipeline-stage metrics come from engine 0 — fleet engines
        share one pipeline object, so its counters are already
        fleet-wide."""
        from mmlspark_tpu.core.metrics import LatencyHistogram
        per_engine = [e.metrics() for e in self.engines]
        aggregate: Dict[str, Any] = {}
        if self.engines:
            for key in self.engines[0].hists:
                aggregate[key] = LatencyHistogram.merged(
                    [e.hists[key] for e in self.engines]).summary()
            stage = per_engine[0].get("pipeline_stage")
            if stage is not None:
                aggregate["pipeline_stage"] = stage
        aggregate["batches_processed"] = sum(
            m["batches_processed"] for m in per_engine)
        # lifecycle rollup: per-engine versions/states plus the fleet
        # swap counters (the ops view of a rolling upgrade in flight)
        aggregate["model_versions"] = [
            m.get("model_version") for m in per_engine]
        aggregate["precisions"] = [
            m.get("precision") for m in per_engine]
        aggregate["aot"] = [m.get("aot") for m in per_engine]
        aggregate["swap_states"] = [
            m.get("swap_state") for m in per_engine]
        aggregate["swaps_completed"] = sum(
            m.get("swaps_completed", 0) for m in per_engine)
        aggregate["swaps_rolled_back"] = sum(
            m.get("swaps_rolled_back", 0) for m in per_engine)
        return {"engines": per_engine, "aggregate": aggregate}

    def traces(self, limit: Optional[int] = None,
               raw: bool = False) -> Any:
        """The fleet's completed (tail-sampled) traces. Default: Chrome
        trace-event JSON (save to a file, open in Perfetto); pass
        ``raw=True`` for the Trace objects. Engines share one tracer,
        so this is every engine's traffic on one timeline."""
        if self.tracer is None:
            from mmlspark_tpu.core.trace import to_chrome_trace
            return [] if raw else to_chrome_trace([])
        traces = self.tracer.buffer.traces(limit)
        if raw:
            return traces
        from mmlspark_tpu.core.trace import to_chrome_trace
        return to_chrome_trace(traces)

    def metrics_text(self) -> str:
        """Fleet-wide Prometheus text exposition: per-engine counters
        (labeled ``engine="<i>"``), the merged cross-engine latency
        histograms, fleet client counters (failover/hedging), and the
        process-wide phase/trace families. Each engine also serves its
        own ``/metrics``; this is the aggregate the ops view scrapes."""
        from mmlspark_tpu.core.metrics import LatencyHistogram
        from mmlspark_tpu.core.prometheus import (
            PromRenderer, pipeline_families, process_families,
        )
        r = PromRenderer()
        for i, e in enumerate(self.engines):
            src = e.source
            with src._lock:
                seen, answered, rejected = (
                    src.requests_seen, src.requests_answered,
                    src.requests_rejected)
            labels = {"engine": str(i)}
            r.counter("serving_requests_seen_total",
                      "requests hitting the HTTP source", seen, labels)
            r.counter("serving_requests_answered_total",
                      "requests answered", answered, labels)
            r.counter("serving_requests_rejected_total",
                      "requests shed", rejected, labels)
            _, snap = e._lifecycle_snapshot()
            r.counter("serving_batches_processed_total",
                      "micro-batches executed",
                      snap["batches_processed"], labels)
            r.counter("serving_swaps_completed_total",
                      "model swaps completed",
                      snap["swaps_completed"], labels)
            r.counter("serving_swaps_rolled_back_total",
                      "model swaps rolled back",
                      snap["swaps_rolled_back"], labels)
            r.info("serving_model_info",
                   "active model version, precision, aot, swap state "
                   "per engine",
                   {**labels, "version": snap["model_version"],
                    "precision": snap["precision"],
                    "aot": "true" if snap["aot"] else "false",
                    "swap_state": snap["swap_state"]})
            with e._stats_lock:
                rejections = dict(e.rejections)
            for reason in sorted(rejections):
                r.counter("serving_admission_rejected_total",
                          "requests rejected by admission/model routing",
                          rejections[reason],
                          {**labels, "reason": reason})
            if e.slo is not None:
                from mmlspark_tpu.core.prometheus import slo_families
                try:
                    # per-engine SLO families (engine label): each
                    # engine's burn state is its own — a fleet is
                    # degraded engine by engine
                    slo_families(r, e.slo, labels)
                except Exception:  # noqa: BLE001 — stats stay partial
                    pass
        if self.zoo is not None:
            # ONE zoo across the fleet: its families render once, not
            # per engine (the per-model label space stays capped)
            from mmlspark_tpu.core.prometheus import zoo_families
            try:
                zoo_families(r, self.zoo)
            except Exception:  # noqa: BLE001 — stats stay partial
                pass
        if self.placement is not None:
            # the placement plane is fleet-level by construction: one
            # controller, one family set
            from mmlspark_tpu.core.prometheus import placement_families
            try:
                placement_families(r, self.placement)
            except Exception:  # noqa: BLE001 — stats stay partial
                pass
        if self.engines:
            for key in self.engines[0].hists:
                merged = LatencyHistogram.merged(
                    [e.hists[key] for e in self.engines])
                r.histogram(f"serving_{key}",
                            "fleet-merged hot-path stage distribution",
                            merged)
            # fleet engines share one pipeline object, so its hooks
            # (model hists, jit misses, drift) are already fleet-wide
            pipeline_families(r, self.engines[0].pipeline)
        with self._stats_lock:
            transport, hedged, shm_fb = (self.transport_errors,
                                         self.hedged_requests,
                                         self._shm_fallbacks)
        r.counter("serving_fleet_transport_errors_total",
                  "client-side transport failures", transport)
        r.counter("serving_fleet_hedged_requests_total",
                  "tail-latency hedge requests fired", hedged)
        r.gauge("serving_fleet_engines",
                "engines currently in the routing rotation",
                len(self.addresses))
        r.gauge("serving_fleet_demand_rate",
                "client-observed demand over the trailing 30s "
                "(rows/s; JSON posts count 1)", self.demand_rate())
        auto = self.__dict__.get("autoscaler")
        if auto is not None:
            from mmlspark_tpu.core.prometheus import autoscale_families
            try:
                autoscale_families(r, auto)
            except Exception:  # noqa: BLE001 — stats stay partial
                pass
        # shared-memory transport: process-wide counters (io/shm.py) —
        # rendered only once the transport has actually loaded, so a
        # fleet that never negotiated shm pays no import
        import sys as _sys
        shm_mod = _sys.modules.get("mmlspark_tpu.io.shm")
        if shm_mod is not None or shm_fb:
            st = shm_mod.stats() if shm_mod is not None else {}
            att = (shm_mod.attached_count()
                   if shm_mod is not None else 0)
            r.gauge("serving_shm_segments",
                    "shared-memory segments this process maps "
                    "(owned ring + engine-side attachments)",
                    att + (1 if self._shm_ring is not None else 0))
            r.counter("serving_shm_batches_total",
                      "columnar batches carried over shared memory",
                      st.get("batches", 0))
            r.counter("serving_shm_bytes_total",
                      "columnar frame bytes placed in shared memory",
                      st.get("bytes", 0))
            r.counter("serving_shm_stale_slots_total",
                      "shm decodes rejected by a generation mismatch",
                      st.get("gen_mismatch", 0))
            r.counter("serving_shm_segments_reaped_total",
                      "dead owners' segments unlinked by a survivor",
                      st.get("reaped", 0))
            r.counter("serving_shm_fallbacks_total",
                      "batches that fell back from shm to HTTP bodies",
                      shm_fb)
        process_families(r, tracer=self.tracer)
        return r.render()

    def counters(self) -> Dict[str, int]:
        return {
            "seen": sum(e.source.requests_seen for e in self.engines),
            "accepted": sum(e.source.requests_accepted
                            for e in self.engines),
            "answered": sum(e.source.requests_answered
                            for e in self.engines),
            "rejected": sum(e.source.requests_rejected
                            for e in self.engines),
            "transport_errors": self.transport_errors,
            "hedged": self.hedged_requests,
            "workers_restarted": sum(e.workers_restarted
                                     for e in self.engines),
            "swaps_completed": sum(e.swaps_completed
                                   for e in self.engines),
            "swaps_rolled_back": sum(e.swaps_rolled_back
                                     for e in self.engines),
        }

    # -- model lifecycle ---------------------------------------------------

    def _failover_pressure(self) -> bool:
        """True while the fleet looks stressed: any ALIVE engine's
        circuit is open (dead engines' circuits stay open by design and
        must not stall a rolling upgrade forever)."""
        for e, b in zip(self.engines, self.breakers):
            if e.is_alive() and b.state == CircuitBreaker.OPEN:
                return True
        return False

    def rolling_swap(self, pipeline, version: str,
                     warmup_example=None, policy=None,
                     pressure_timeout_s: float = 30.0,
                     ) -> Dict[str, Any]:
        """Upgrade the fleet to ``pipeline``@``version`` one engine at a
        time (zero downtime: each engine keeps serving through its own
        warmup/canary/cutover — see serving/lifecycle.py).

        Between engines the rollout PAUSES while the fleet shows
        failover pressure (an alive engine's circuit open), bounded by
        ``pressure_timeout_s`` per engine. Dead engines are skipped. A
        rollback anywhere STOPS the rollout — a version that breached
        one engine's canary must not march across the rest. Returns a
        per-engine outcome report plus the aggregate verdict."""
        outcomes: List[Dict[str, Any]] = []
        completed = rolled_back = 0
        for i, engine in enumerate(self.engines):
            if not engine.is_alive():
                outcomes.append({"engine": i,
                                 "address": engine.source.address,
                                 "outcome": "skipped_dead"})
                continue
            deadline = time.monotonic() + pressure_timeout_s
            while self._failover_pressure() and \
                    time.monotonic() < deadline:
                time.sleep(0.05)   # pause the rollout, keep serving
            if self._failover_pressure():
                log.warning("rolling_swap: proceeding on engine %d "
                            "despite failover pressure (%.1fs budget "
                            "spent)", i, pressure_timeout_s)
            try:
                res = engine.swap(pipeline, version,
                                  warmup_example=warmup_example,
                                  policy=policy)
            except Exception as e:  # noqa: BLE001 — e.g. engine died
                # between the liveness check and the swap
                outcomes.append({"engine": i,
                                 "address": engine.source.address,
                                 "outcome": "error",
                                 "reason": f"{type(e).__name__}: {e}"})
                continue
            if res.completed:
                completed += 1
                outcomes.append({"engine": i,
                                 "address": engine.source.address,
                                 "outcome": "completed"})
            else:
                rolled_back += 1
                outcomes.append({"engine": i,
                                 "address": engine.source.address,
                                 "outcome": "rolled_back",
                                 "reason": res.reason})
                log.warning("rolling_swap: %s rolled back on engine %d "
                            "(%s); halting the rollout", version, i,
                            res.reason)
                break
        return {"version": version, "completed": completed,
                "rolled_back": rolled_back, "engines": outcomes,
                "ok": rolled_back == 0 and completed > 0}

    def kill_engine(self, index: int, close_source: bool = True) -> None:
        """Chaos hook: crash (or stall, with ``close_source=False``) one
        engine mid-load; the breaker + failover path must absorb it."""
        self.engines[index].kill(close_source=close_source)

    def stop_all(self) -> None:
        for e in self.engines:
            e.stop()
        self.close_shm()


class PartitionConsolidator(Transformer):
    """Funnel a table to one stream per host
    (ref: PartitionConsolidator.scala:17 — many partitions feeding one
    connection-holding consumer per executor).

    In a multi-process ``jax.distributed`` job each process keeps only
    its own contiguous row range (consolidating that host's partitions
    into one table); single-process it coalesces the table's shards into
    one. ``hostCount``/``hostIndex`` override auto-detection for tests."""

    hostCount = IntParam("total hosts (0 = auto from jax.distributed)",
                         default=0)
    hostIndex = IntParam("this host's index (-1 = auto)", default=-1)

    def transform(self, table: DataTable) -> DataTable:
        count = self.get("hostCount")
        index = self.get("hostIndex")
        from mmlspark_tpu.parallel import distributed as dist
        if count <= 0 or index < 0:
            # delegate to the training-side feeder so serving and
            # training always agree on the host-sharding rule
            info = dist.host_info()
            if count <= 0:
                count = info.process_count
            if index < 0:
                index = info.process_index
        if index >= count:
            raise ValueError(
                f"hostIndex {index} out of range for hostCount {count}")
        if count <= 1:
            # consolidate: downstream shard-aware consumers must see ONE
            # logical partition (that is this stage's whole purpose)
            return table.repartition(1)
        return dist.shard_table_for_host(
            table, dist.HostInfo(process_index=index, process_count=count,
                                 local_device_count=0,
                                 global_device_count=0)).repartition(1)

    def transform_schema(self, schema: Schema) -> Schema:
        return schema
