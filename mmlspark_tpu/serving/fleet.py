"""Multi-host serving: one engine per host + partition consolidation.

The reference's DistributedHTTPSource runs one JVMSharedServer per
executor with batch-indexed request routing and reply-by-uuid
(ref: src/io/http/src/main/scala/DistributedHTTPSource.scala:33-472);
PartitionConsolidator funnels many partitions' rows into one stream per
executor for rate-limited resources (PartitionConsolidator.scala:17,103).

TPU-native shape: model state is replicated by jax, so serving hosts are
independent — each runs one ServingEngine and any TCP load balancer
fronts them. ``ServingFleet`` manages N engines (the one-process
simulation of that deployment and the orchestration utility on a real
host group); the genuinely cross-process deployment — one engine per OS
process with reply-routing and per-process counters — is exercised by
tests/serving_worker.py + tests/test_distributed.py
(test_cross_process_serving_fleet). ``PartitionConsolidator`` keeps each
process's own row range of a table, funneling work to exactly one
consumer per host.
"""

from __future__ import annotations

import itertools
import json
import urllib.request
from typing import Any, Dict, List

from mmlspark_tpu.core.logging_utils import get_logger
from mmlspark_tpu.core.params import IntParam
from mmlspark_tpu.core.schema import Schema
from mmlspark_tpu.core.stage import Transformer
from mmlspark_tpu.core.table import DataTable
from mmlspark_tpu.serving.server import HTTPSource, ServingEngine

log = get_logger("serving.fleet")


def json_scoring_pipeline(model, field: str = "features",
                          reply_field: str = "prediction"):
    """The standard model-behind-HTTP pipeline: decode JSON request
    bodies ``{field: [floats]}``, score the micro-batch through
    ``model`` (a TPUModel whose inputCol is ``field``), reply
    ``{reply_field: argmax}`` per row. One implementation shared by the
    serving bench, the throughput floor test, and user deployments —
    the serving-side analog of ServingImplicits' request parsing
    (ref: ServingImplicits.scala)."""
    import numpy as np
    from mmlspark_tpu.stages.basic import Lambda

    def handle(table: DataTable) -> DataTable:
        feats = np.stack([
            np.asarray(json.loads(r["entity"].decode())[field],
                       dtype=np.float32)
            for r in table["request"]])
        scored = model.transform(DataTable({field: feats}))
        preds = np.asarray(scored[model.get("outputCol")]).argmax(-1)
        return table.with_column(
            "reply", [{reply_field: int(p)} for p in preds])

    return Lambda.apply(handle)


def json_row_scoring_pipeline(pipeline, reply_col: str = "prediction"):
    """Serve an arbitrary TABULAR pipeline behind HTTP: each request
    body is a JSON object of column values (one row); bodies batch into
    a DataTable, run through ``pipeline.transform``, and the
    ``reply_col`` value answers each request. This is what
    ``mmlspark-tpu serve`` wraps saved models with — any fitted
    pipeline becomes an HTTP scorer with no Python written
    (ref: ServingImplicits.scala request parsing; the CLI is the
    R-wrapper-capability analog)."""
    import numpy as np
    from mmlspark_tpu.stages.basic import Lambda

    def handle(table: DataTable) -> DataTable:
        rows = [json.loads(r["entity"].decode())
                for r in table["request"]]
        data = DataTable.from_rows(rows)
        scored = pipeline.transform(data)
        if reply_col not in scored:
            raise KeyError(
                f"reply column {reply_col!r} not in scored table; "
                f"have {scored.column_names}")
        vals = scored[reply_col]
        return table.with_column(
            "reply", [v.item() if isinstance(v, np.generic) else v
                      for v in vals])

    return Lambda.apply(handle)


class ServingFleet:
    """N serving engines over one pipeline — one per host in a real
    deployment, N ports on one host in simulation/tests. Replies always
    flow through the engine that accepted the request (the reference's
    reply-routing invariant, DistributedHTTPSource.scala:188-192)."""

    def __init__(self, pipeline, n_engines: int = 2,
                 host: str = "127.0.0.1", base_port: int = 18700,
                 batch_size: int = 64, reply_col: str = "reply",
                 workers: int = 1):
        self.engines: List[ServingEngine] = []
        port = base_port
        try:
            for _ in range(n_engines):
                source = HTTPSource(host=host, port=port)
                port = source.port + 1      # skip whatever port-scan used
                try:
                    engine = ServingEngine(source, pipeline,
                                           reply_col=reply_col,
                                           batch_size=batch_size,
                                           workers=workers).start()
                except Exception:
                    source.close()   # don't orphan the bound port
                    raise
                self.engines.append(engine)
        except Exception:
            # partial construction must not leak threads/bound ports
            self.stop_all()
            raise
        # itertools.count: next() is atomic under the GIL, so
        # concurrent client threads can't tear the round-robin
        self._next = itertools.count()
        log.info("fleet of %d engines: %s", n_engines, self.addresses)

    @property
    def addresses(self) -> List[str]:
        return [e.source.address for e in self.engines]

    def post(self, payload: Any, timeout: float = 30.0) -> Dict[str, Any]:
        """Round-robin client — the stand-in for an external load
        balancer in tests/examples."""
        addr = self.addresses[next(self._next) % len(self.engines)]
        body = payload if isinstance(payload, bytes) \
            else json.dumps(payload).encode()
        req = urllib.request.Request(
            addr, data=body, headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return json.loads(r.read())

    def counters(self) -> Dict[str, int]:
        return {
            "seen": sum(e.source.requests_seen for e in self.engines),
            "accepted": sum(e.source.requests_accepted
                            for e in self.engines),
            "answered": sum(e.source.requests_answered
                            for e in self.engines),
        }

    def stop_all(self) -> None:
        for e in self.engines:
            e.stop()


class PartitionConsolidator(Transformer):
    """Funnel a table to one stream per host
    (ref: PartitionConsolidator.scala:17 — many partitions feeding one
    connection-holding consumer per executor).

    In a multi-process ``jax.distributed`` job each process keeps only
    its own contiguous row range (consolidating that host's partitions
    into one table); single-process it coalesces the table's shards into
    one. ``hostCount``/``hostIndex`` override auto-detection for tests."""

    hostCount = IntParam("total hosts (0 = auto from jax.distributed)",
                         default=0)
    hostIndex = IntParam("this host's index (-1 = auto)", default=-1)

    def transform(self, table: DataTable) -> DataTable:
        count = self.get("hostCount")
        index = self.get("hostIndex")
        from mmlspark_tpu.parallel import distributed as dist
        if count <= 0 or index < 0:
            # delegate to the training-side feeder so serving and
            # training always agree on the host-sharding rule
            info = dist.host_info()
            if count <= 0:
                count = info.process_count
            if index < 0:
                index = info.process_index
        if index >= count:
            raise ValueError(
                f"hostIndex {index} out of range for hostCount {count}")
        if count <= 1:
            # consolidate: downstream shard-aware consumers must see ONE
            # logical partition (that is this stage's whole purpose)
            return table.repartition(1)
        return dist.shard_table_for_host(
            table, dist.HostInfo(process_index=index, process_count=count,
                                 local_device_count=0,
                                 global_device_count=0)).repartition(1)

    def transform_schema(self, schema: Schema) -> Schema:
        return schema
