"""Fleet-wide zoo placement plane: which engine processes serve which
``model@version``.

PR 13's ``ModelZoo`` made each engine a demand-driven cache; this
module adds the FLEET-level controller above it. One
``PlacementController`` watches per-model request demand (windowed
rates, ``core.metrics.WindowedCounter``) and each model's residency
cost (the zoo's ``cost_bytes`` accounting, itself fed by the duck-typed
``resident_bytes()`` hook), and assigns every demanded model to a set
of engine indices:

- **hot models get replicas** — a model carrying a dominant share of
  the windowed demand is assigned to proportionally many engines (at
  least 2 once it clears ``hot_share`` of traffic, up to the fleet
  size);
- **cold models get exactly one** — a model with a trickle of demand
  stays servable without spending residency on every engine;
- **assignment is residency-aware** — replicas land on the engines
  with the least assigned bytes (balanced packing), and sticky: a
  model keeps its current engines while the plan still wants that many
  replicas (minimal churn per rebuild);
- **one loader activation feeds N engines** — the fleet's engines
  share ONE zoo, so assigning a model to more engines never re-loads
  it; the plan only spreads the TRAFFIC.

``ServingFleet.attach_placement`` wires the controller into the
client: model-keyed requests route to the model's assigned engines
first, with the full round-robin order BEHIND them — a stale plan
(new model, engine death, pre-first-rebuild) falls back to any engine,
where the zoo's lazy activation takes over; those fallbacks are
counted (``serving_placement_stale_routes_total``).

Eviction sees FLEET-GLOBAL demand: ``evict_coldest`` offers the zoo
the least-demanded victims first, and the zoo's own invariants (never
a model with outstanding batches, parked waiters, or a pin — anywhere
in the fleet, since the zoo is shared) arbitrate each offer.

Every placement decision lands as an ordered ``PlacementEvent`` on the
registry timeline (``zoo.record_event``), interleaved with the Swap
and Zoo events by time — one audit trail tells the whole story.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from mmlspark_tpu.core.metrics import LatencyHistogram, WindowedCounter
from mmlspark_tpu.core.logging_utils import get_logger

log = get_logger("serving.placement")

# per-model replica series rendered with their own label; overflow
# folds into model="_other" (the LabelledHistograms cap discipline)
REPLICA_LABEL_CAP = 16


class PlacementEvent:
    """One placement decision on the registry timeline (the SwapEvent /
    ZooEvent discipline): ``assign`` / ``unassign`` carry the engine
    delta for one model, ``rebuild`` summarizes a whole plan pass."""

    def __init__(self, kind: str, model: str, version: str = "",
                 reason: str = "",
                 stats: Optional[Dict[str, Any]] = None):
        self.kind = kind          # 'assign' | 'unassign' | 'rebuild'
        self.model = model
        self.version = version
        self.reason = reason
        self.stats = dict(stats or {})
        self.at = time.time()

    def __repr__(self) -> str:
        extra = f", reason={self.reason!r}" if self.reason else ""
        if "engines" in self.stats:
            extra += f", engines={self.stats['engines']}"
        return f"PlacementEvent({self.kind}, {self.model!r}{extra})"


class PlacementController:
    """Demand- and residency-aware assignment of models to engine
    indices (see module docstring).

    ``record_request`` is the hot-path hook (one windowed-counter inc);
    ``rebuild`` recomputes the plan (called by the fleet opportunistically
    or by an ops loop); ``engines_for`` answers routing. All methods
    are thread-safe."""

    def __init__(self, zoo, n_engines: int,
                 demand_window_s: float = 60.0,
                 hot_share: float = 0.5,
                 max_replicas: Optional[int] = None,
                 rebuild_min_interval_s: float = 1.0):
        if n_engines < 1:
            raise ValueError("placement needs at least one engine")
        self.zoo = zoo
        self.n_engines = int(n_engines)
        self.demand_window_s = float(demand_window_s)
        self.hot_share = float(hot_share)
        self.max_replicas = (int(max_replicas) if max_replicas
                             else self.n_engines)
        self.rebuild_min_interval_s = float(rebuild_min_interval_s)
        self._lock = threading.Lock()
        self._demand: Dict[str, WindowedCounter] = {}
        self._assignments: Dict[str, Tuple[int, ...]] = {}
        self._dead: set = set()          # engines excluded from plans
        self._last_rebuild = 0.0
        self.rebuilds = 0
        self.stale_routes = 0
        self.rebuild_hist = LatencyHistogram(unit="ms")

    # -- demand -------------------------------------------------------------

    def record_request(self, model: str) -> None:
        """One model-keyed request arrived (the fleet client calls this
        on every routed post)."""
        key = str(model)
        with self._lock:
            c = self._demand.get(key)
            if c is None:
                c = self._demand[key] = WindowedCounter(bucket_s=1.0)
        c.inc()

    def demand_rate(self, model: str) -> float:
        """Requests/s for ``model`` over the demand window."""
        with self._lock:
            c = self._demand.get(str(model))
        return c.rate(self.demand_window_s) if c is not None else 0.0

    # -- engine liveness ----------------------------------------------------

    def mark_engine_dead(self, index: int) -> None:
        """Exclude an engine from future plans (and rebuild now so its
        replicas reassign). The fleet's breakers still own short-term
        failover; this is the placement-plane reaction to a confirmed
        death (SIGKILL chaos, decommission)."""
        with self._lock:
            self._dead.add(int(index))
        self.rebuild(force=True, reason=f"engine{index}_dead")

    def mark_engine_alive(self, index: int) -> None:
        with self._lock:
            self._dead.discard(int(index))

    def set_n_engines(self, n: int, reason: str = "resize") -> None:
        """Resize the engine universe (the autoscaler's join/leave
        hook) and rebuild the plan NOW: after a join, hot models fan
        out onto the new replica; after a leave, its assignments
        reassign before the next routed post. A default-capped
        ``max_replicas`` (== the old width) follows the resize; an
        explicit cap is the operator's and stays. Liveness marks for
        engines beyond the new width are dropped — index ``i`` of a
        future fleet is a different process."""
        n = int(n)
        if n < 1:
            raise ValueError("placement needs at least one engine")
        with self._lock:
            if n == self.n_engines:
                return
            if self.max_replicas == self.n_engines:
                self.max_replicas = n
            self.n_engines = n
            self._dead = {i for i in self._dead if i < n}
        self.rebuild(force=True, reason=reason)

    # -- the plan -----------------------------------------------------------

    def _zoo_costs(self) -> Dict[str, int]:
        """model and model@version -> residency cost (the zoo's
        ``cost_bytes``, fed by artifact sizes / metadata / duck-typed
        ``resident_bytes()``)."""
        costs: Dict[str, int] = {}
        if self.zoo is None:
            return costs
        try:
            rows = self.zoo.stats().get("models", [])
        except Exception:  # noqa: BLE001 — stats stay best-effort
            return costs
        for row in rows:
            cost = int(row.get("cost_bytes", 0))
            costs[f"{row['model']}@{row['version']}"] = cost
            # bare-name routing resolves to the latest version; keep
            # the first (most-recently-used-ordered) row's cost
            costs.setdefault(row["model"], cost)
        return costs

    def _replicas_wanted(self, rate: float, total_rate: float,
                         alive: int) -> int:
        """Demand share -> replica count: every demanded model gets
        one; a model above ``hot_share`` of the windowed demand gets at
        least two; shares scale proportionally up to the alive-engine
        count (and ``max_replicas``)."""
        cap = max(1, min(alive, self.max_replicas))
        if total_rate <= 0 or rate <= 0:
            return 1
        share = rate / total_rate
        wanted = max(1, round(share * alive))
        if share >= self.hot_share:
            wanted = max(2, wanted)
        return min(cap, wanted)

    def rebuild(self, force: bool = False,
                reason: str = "demand") -> Dict[str, Tuple[int, ...]]:
        """Recompute the fleet plan. Rate-limited by
        ``rebuild_min_interval_s`` unless ``force``. Returns the new
        assignment map (model -> engine indices). Emits the per-model
        assign/unassign deltas and one rebuild summary onto the
        registry timeline."""
        now = time.monotonic()
        t0 = time.perf_counter()
        with self._lock:
            if not force and now < self._last_rebuild \
                    + self.rebuild_min_interval_s:
                return dict(self._assignments)
            self._last_rebuild = now
            alive_engines = [i for i in range(self.n_engines)
                             if i not in self._dead]
            if not alive_engines:
                alive_engines = list(range(self.n_engines))
            rates = {key: c.rate(self.demand_window_s)
                     for key, c in self._demand.items()}
            old = dict(self._assignments)
        costs = self._zoo_costs()
        total_rate = sum(rates.values())
        # residency-aware balanced packing: engines accumulate the
        # bytes of what they're assigned; each model's replicas land on
        # the least-loaded engines, sticky to their current homes
        load = {i: 0.0 for i in alive_engines}
        plan: Dict[str, Tuple[int, ...]] = {}
        for key in sorted(rates, key=lambda k: (-rates[k], k)):
            wanted = self._replicas_wanted(rates[key], total_rate,
                                           len(alive_engines))
            cost = float(costs.get(key, 0)) or 1.0
            current = [i for i in old.get(key, ()) if i in load]
            chosen = current[:wanted]
            for i in sorted(load, key=lambda e: (load[e], e)):
                if len(chosen) >= wanted:
                    break
                if i not in chosen:
                    chosen.append(i)
            chosen = sorted(chosen)
            for i in chosen:
                load[i] += cost
            plan[key] = tuple(chosen)
        with self._lock:
            self._assignments = dict(plan)
            self.rebuilds += 1
        ms = (time.perf_counter() - t0) * 1e3
        self.rebuild_hist.observe(ms)
        self._record_deltas(old, plan, reason, ms, total_rate)
        return dict(plan)

    def _record_deltas(self, old: Dict[str, Tuple[int, ...]],
                       new: Dict[str, Tuple[int, ...]],
                       reason: str, ms: float,
                       total_rate: float) -> None:
        record = getattr(self.zoo, "record_event", None)
        if record is None:
            return
        for key in sorted(set(old) | set(new)):
            before, after = set(old.get(key, ())), set(new.get(key, ()))
            if before == after:
                continue
            name, _, version = key.partition("@")
            gained, lost = sorted(after - before), sorted(before - after)
            if gained:
                record(PlacementEvent(
                    "assign", name, version, reason=reason,
                    stats={"engines": gained,
                           "replicas": len(after)}))
            if lost:
                record(PlacementEvent(
                    "unassign", name, version, reason=reason,
                    stats={"engines": lost,
                           "replicas": len(after)}))
        record(PlacementEvent(
            "rebuild", "_fleet", reason=reason,
            stats={"models": len(new), "ms": ms,
                   "demand_rps": round(total_rate, 3)}))

    # -- routing ------------------------------------------------------------

    def engines_for(self, model: str) -> List[int]:
        """The model's assigned engine indices (empty = not in the
        plan: the caller routes to any engine and the zoo lazily
        activates; counted as a stale route)."""
        with self._lock:
            assigned = self._assignments.get(str(model))
            if assigned:
                return list(assigned)
            self.stale_routes += 1
            return []

    def replica_counts(self) -> Dict[str, int]:
        with self._lock:
            return {k: len(v) for k, v in self._assignments.items()}

    def assignments(self) -> Dict[str, Tuple[int, ...]]:
        with self._lock:
            return dict(self._assignments)

    # -- fleet-global eviction ----------------------------------------------

    def evict_coldest(self, keep: int = 1,
                      reason: str = "placement_cold") -> Optional[str]:
        """Offer the zoo the least-demanded models as eviction victims
        (coldest first), keeping at least ``keep`` assigned models
        untouched. The ZOO arbitrates every offer — a model with
        outstanding batches, parked waiters, or a pin anywhere in the
        fleet refuses (returns False) and the next-coldest is offered.
        Returns the evicted spec, or None when nothing was evictable."""
        if self.zoo is None:
            return None
        with self._lock:
            rates = {key: c.rate(self.demand_window_s)
                     for key, c in self._demand.items()}
        candidates = sorted(rates, key=lambda k: (rates[k], k))
        if keep > 0:
            candidates = candidates[:max(0, len(candidates) - keep)]
        for spec in candidates:
            try:
                if self.zoo.evict(spec, reason=reason):
                    log.info("placement: evicted cold model %s "
                             "(%.3f req/s fleet-wide)", spec,
                             rates[spec])
                    return spec
            except KeyError:
                continue       # demand for a never-registered spec
        return None

    # -- observability ------------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "models": len(self._assignments),
                "assignments": sum(len(v) for v in
                                   self._assignments.values()),
                "rebuilds": self.rebuilds,
                "stale_routes": self.stale_routes,
                "dead_engines": sorted(self._dead),
                "demand_rps": {
                    k: round(c.rate(self.demand_window_s), 3)
                    for k, c in self._demand.items()},
            }
