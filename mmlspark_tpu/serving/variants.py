"""SLO-adaptive variant selection: route each logical model to the
cheapest physical variant that still meets its latency objective.

The zoo (serving/zoo.py) already stores multiple physical variants of
one logical model — f32 vs int8 (core/quantize.py), single-device vs
mesh-sharded (serving/sharded.py), AOT vs traced — but routing was
static: a request named ``model@version`` always got that executable.
This module makes the runtime pick (INFaaS; Romero et al., ATC '21):

- **Declared ladder.** ``declare(logical, variants=[...], slo_ms=...)``
  registers an ordered variant ladder for one logical model: rung 0 is
  the preferred/full-fidelity variant, later rungs are the cheaper
  tiers the operator is willing to degrade onto (int8, a smaller
  mesh). The ladder order IS the degradation policy — written down
  once, by a human, instead of inferred per incident.
- **Windowed profiles.** Every scored batch feeds a per-variant
  windowed latency/cost profile (``observe``, wired from the engine's
  existing per-model batch-latency feed): p99 over a trailing window,
  measured device-ms/row as the default cost signal, and the variant's
  cold-start cost from the zoo's activation timing. A declared
  ``cost`` (chip-seconds, $/1k rows — whatever the operator's unit is)
  overrides the measured signal; ``cost_source`` records which one a
  decision used.
- **Selection.** Among the OPEN rungs (0..floor), serve the cheapest
  variant whose profiled p99 meets ``slo_ms`` — preferring resident
  variants on ties (activating a cold variant mid-incident spends the
  cold-start exactly when there is no headroom for it).
- **Graceful degradation.** When the SLO engine reports a fast burn or
  admission reports queue pressure, the floor opens one cheaper rung
  per decide tick — load degrades onto cheaper variants BEFORE
  priority shedding fires. When the burn resolves and pressure clears,
  the floor closes one rung per ``hold_s`` (hysteresis: a flapping
  burn must not flap the fleet's executables).
- **Decisions are rate-gated and cached.** ``tick`` (the batcher's
  rate-gated control tick, next to ``slo.evaluate`` and
  ``zoo.enforce``) recomputes the route table; the per-request path is
  one dict lookup (``route``). ``tools/check_fusion_kernels.py
  check_adaptive_serving`` proves statically that no selection ever
  runs in the HTTP handler.

Every transition lands as a ``VariantEvent`` on the registry timeline
(``zoo.record_event``), interleaved with Swap/Zoo/Placement events by
time, and the active variant + last step-down reason surface on
``/healthz`` and ``serving_variant_*`` Prometheus families.

Eviction safety is inherited, not re-implemented: routing to a variant
goes through the zoo's ``acquire`` (outstanding bumped under the
registry lock) and the engine's pending-group waiter holds, so a
variant carrying traffic is never an eviction victim.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from mmlspark_tpu.core.logging_utils import get_logger
from mmlspark_tpu.core.metrics import WindowedCounter, WindowedHistogram

log = get_logger("serving.variants")

# variant-labeled Prometheus series rendered per declared logical
# model; declarations are operator-made and small, but the render cap
# keeps a scripted declare-loop from exploding the scrape
VARIANT_LABEL_CAP = 16


class VariantEvent:
    """One variant-plane decision on the registry timeline (the
    SwapEvent / ZooEvent / PlacementEvent discipline). ``declare``
    records the ladder, ``step_down``/``step_up`` move the degradation
    floor (reason carries why), ``select`` is a cost/profile-driven
    re-route within the open rungs."""

    def __init__(self, kind: str, model: str, variant: str = "",
                 reason: str = "",
                 stats: Optional[Dict[str, Any]] = None):
        self.kind = kind      # 'declare'|'step_down'|'step_up'|'select'
        self.model = model    # the LOGICAL model name
        self.variant = variant            # the chosen variant key
        self.reason = reason
        self.stats = dict(stats or {})
        self.at = time.time()

    def __repr__(self) -> str:
        extra = f", reason={self.reason!r}" if self.reason else ""
        return (f"VariantEvent({self.kind}, {self.model!r} -> "
                f"{self.variant!r}{extra})")


class VariantProfile:
    """Windowed latency/cost profile of ONE physical variant. Fed a
    (batch latency ms, rows) sample per scored batch; answers p99 and
    measured ms/row over a trailing window."""

    __slots__ = ("key", "declared_cost", "hist", "ms_sum", "rows_sum",
                 "batches")

    def __init__(self, key: str, declared_cost: Optional[float] = None):
        self.key = key
        self.declared_cost = (float(declared_cost)
                              if declared_cost is not None else None)
        # 1 s buckets: profile windows are tens of seconds, and the
        # selector must see a load ramp within a tick or two
        self.hist = WindowedHistogram(bucket_s=1.0, horizon_s=600.0)
        self.ms_sum = WindowedCounter(bucket_s=1.0, horizon_s=600.0)
        self.rows_sum = WindowedCounter(bucket_s=1.0, horizon_s=600.0)
        self.batches = 0

    def observe(self, ms: float, rows: int,
                now: Optional[float] = None) -> None:
        self.hist.observe(float(ms), now=now)
        self.ms_sum.inc(float(ms), now=now)
        self.rows_sum.inc(float(max(1, rows)), now=now)
        self.batches += 1

    def p99(self, window_s: float,
            now: Optional[float] = None) -> Optional[float]:
        """Profiled p99 batch latency, or None with no samples in the
        window (an unprofiled variant is a DIFFERENT fact than a fast
        one — the policy treats them differently)."""
        if self.hist.count(window_s, now=now) <= 0:
            return None
        return self.hist.percentile(99, window_s, now=now)

    def ms_per_row(self, window_s: float,
                   now: Optional[float] = None) -> Optional[float]:
        rows = self.rows_sum.total(window_s, now=now)
        if rows <= 0:
            return None
        return self.ms_sum.total(window_s, now=now) / rows

    def cost(self, window_s: float,
             now: Optional[float] = None
             ) -> "tuple[Optional[float], str]":
        """(cost, source): the declared cost when the operator pinned
        one, else the measured ms/row, else None (unprofiled)."""
        if self.declared_cost is not None:
            return self.declared_cost, "declared"
        measured = self.ms_per_row(window_s, now=now)
        if measured is not None:
            return measured, "measured"
        return None, "unprofiled"


class _Ladder:
    """Mutable selector state for one logical model (selector lock)."""

    __slots__ = ("name", "variants", "slo_ms", "floor", "active_idx",
                 "last_reason", "last_change_at", "clear_since",
                 "step_downs", "step_ups", "selects")

    def __init__(self, name: str, variants: List[str], slo_ms: float):
        self.name = name
        self.variants = list(variants)     # rung 0 = preferred
        self.slo_ms = float(slo_ms)
        self.floor = 0                     # open rungs: 0..floor
        self.active_idx = 0
        self.last_reason = ""
        self.last_change_at = 0.0
        self.clear_since: Optional[float] = None
        self.step_downs = 0
        self.step_ups = 0
        self.selects = 0


class VariantSelector:
    """Cached, rate-gated variant routing over a zoo's variant sets
    (see module docstring).

    Hot-path contract: ``route`` and ``observe`` are O(1) dict/counter
    operations safe on the batcher thread; ``tick`` (the decision
    pass) is rate-gated like ``zoo.enforce`` and runs ONLY on the
    batcher's control tick — never in the per-request HTTP handler
    (enforced by the ``check_adaptive_serving`` audit)."""

    def __init__(self, zoo, slo=None,
                 window_s: float = 30.0,
                 decide_interval_s: float = 0.5,
                 hold_s: float = 3.0,
                 pressure_limit: int = 32,
                 record_event=None):
        self.zoo = zoo
        self.slo = slo
        self.window_s = float(window_s)
        self.decide_interval_s = float(decide_interval_s)
        self.hold_s = float(hold_s)
        self.pressure_limit = int(pressure_limit)
        # default: the zoo's registry timeline — one audit trail
        self.record_event = (record_event if record_event is not None
                             else getattr(zoo, "record_event", None))
        self._lock = threading.Lock()
        self._ladders: Dict[str, _Ladder] = {}
        self._profiles: Dict[str, VariantProfile] = {}
        # the CACHE the hot path reads: every declared variant key (and
        # the logical bare name) -> the active variant key. Replaced
        # wholesale under the lock; reads are lock-free dict lookups.
        self._routes: Dict[str, str] = {}
        self._last_tick = 0.0
        self.events: List[VariantEvent] = []

    # -- declaration --------------------------------------------------------

    def declare(self, logical: str, variants: List[str], slo_ms: float,
                costs: Optional[List[Optional[float]]] = None) -> None:
        """Declare one logical model's variant ladder. ``variants`` are
        zoo specs (``name@version``; bare names resolve) ordered from
        the preferred/full-fidelity rung down to the cheapest tier the
        operator will degrade onto. ``slo_ms`` is the model's latency
        objective (profiled p99 must stay under it). ``costs``
        optionally pins per-variant declared costs (one unit for the
        whole ladder) — a list aligned with ``variants`` or a mapping
        keyed by spec; unpinned variants use their measured ms/row."""
        if len(variants) < 1:
            raise ValueError("a variant ladder needs at least one rung")
        if isinstance(costs, dict):
            costs = [costs.get(spec) for spec in variants]
        if costs is not None and len(costs) != len(variants):
            raise ValueError("costs must align with variants")
        keys: List[str] = []
        for spec in variants:
            key = self.zoo.resolve(spec) if self.zoo is not None else spec
            if key is None:
                raise KeyError(f"variant {spec!r} is not registered")
            keys.append(key)
        with self._lock:
            if logical in self._ladders:
                raise ValueError(
                    f"ladder for {logical!r} already declared")
            ladder = _Ladder(logical, keys, slo_ms)
            self._ladders[logical] = ladder
            for i, key in enumerate(keys):
                self._profiles.setdefault(
                    key, VariantProfile(
                        key, costs[i] if costs is not None else None))
            self._rebuild_routes_locked()
        self._emit(VariantEvent(
            "declare", logical, keys[0],
            stats={"variants": list(keys), "slo_ms": float(slo_ms)}))

    def declared(self) -> List[str]:
        with self._lock:
            return list(self._ladders)

    # -- hot-path feeds (batcher thread; O(1)) ------------------------------

    def route(self, key: Optional[str]) -> Optional[str]:
        """The per-request lookup: a declared variant key (or logical
        name) maps to the ladder's ACTIVE variant; anything else passes
        through unchanged. Pure cache read — decisions happen in
        ``tick``."""
        if key is None:
            return None
        return self._routes.get(key, key)

    def observe(self, key: str, ms: float, rows: int = 1,
                now: Optional[float] = None) -> None:
        """One scored batch on variant ``key`` (the engine's per-model
        batch-latency feed). Unknown keys are ignored — only declared
        variants carry profiles."""
        prof = self._profiles.get(key)
        if prof is not None:
            prof.observe(ms, rows, now=now)

    # -- the rate-gated decision tick ---------------------------------------

    def tick(self, pressure: int = 0,
             now: Optional[float] = None,
             min_interval_s: Optional[float] = None) -> bool:
        """One control-tick decision pass (the batcher calls this next
        to ``slo.evaluate``/``zoo.enforce``). Rate-gated by
        ``decide_interval_s``; returns True when a pass actually ran."""
        t = time.monotonic() if now is None else now
        gate = (self.decide_interval_s if min_interval_s is None
                else float(min_interval_s))
        with self._lock:
            if gate > 0 and t - self._last_tick < gate:
                return False
            self._last_tick = t
            burn_reason = self._burn_reason_locked()
            changed = False
            for ladder in self._ladders.values():
                changed |= self._decide_locked(ladder, pressure,
                                               burn_reason, t)
            if changed:
                self._rebuild_routes_locked()
        return True

    def _burn_reason_locked(self) -> Optional[str]:
        """The SLO engine's degradation signal: any active FAST-burn
        alert (engine-level or on a declared variant's stream). Slow
        burns do not move executables — they page humans."""
        if self.slo is None:
            return None
        try:
            active = self.slo.alerts.active()
        except Exception:  # noqa: BLE001 — a sick monitor must never
            return None    # take the variant plane down
        for alert in active:
            if "fast" in alert.rule:
                return f"fast_burn:{alert.slo}"
        return None

    def _decide_locked(self, ladder: _Ladder, pressure: int,
                       burn_reason: Optional[str], now: float) -> bool:
        degraded = burn_reason is not None \
            or pressure >= self.pressure_limit
        reason = burn_reason or "queue_pressure"
        changed = False
        if degraded:
            ladder.clear_since = None
            if ladder.floor < len(ladder.variants) - 1:
                # one rung per decide tick: bounded degradation rate
                ladder.floor += 1
                ladder.last_reason = reason
                ladder.last_change_at = now
                ladder.step_downs += 1
                changed = True
                self._emit(VariantEvent(
                    "step_down", ladder.name,
                    ladder.variants[ladder.floor], reason=reason,
                    stats={"floor": ladder.floor,
                           "pressure": int(pressure)}))
        else:
            if ladder.clear_since is None:
                ladder.clear_since = now
            elif now - ladder.clear_since >= self.hold_s \
                    and ladder.floor > 0:
                # hysteretic recovery: one rung per hold_s of clean air
                ladder.floor -= 1
                ladder.clear_since = now
                ladder.last_change_at = now
                ladder.step_ups += 1
                changed = True
                self._emit(VariantEvent(
                    "step_up", ladder.name,
                    ladder.variants[ladder.floor], reason="recovered",
                    stats={"floor": ladder.floor}))
        best = self._choose_locked(ladder, now)
        if best != ladder.active_idx:
            ladder.active_idx = best
            ladder.selects += 1
            ladder.last_change_at = now
            changed = True
            prof = self._profiles[ladder.variants[best]]
            cost, src = prof.cost(self.window_s)
            self._emit(VariantEvent(
                "select", ladder.name, ladder.variants[best],
                reason=ladder.last_reason or "cost",
                stats={"rung": best, "cost": cost,
                       "cost_source": src}))
        return changed

    def _choose_locked(self, ladder: _Ladder, now: float) -> int:
        """Pick the active rung among the open ones (0..floor): the
        cheapest variant whose profiled p99 meets the SLO. Unprofiled
        rungs count as meeting (they only become reachable when the
        floor opened — degradation is how a cheaper tier first earns a
        profile), but rank after profiled ones on cost ties; cold
        (non-resident) rungs rank last — paying an activation
        mid-incident is the wrong moment."""
        meeting: List[tuple] = []
        fallback: List[tuple] = []
        for i in range(ladder.floor + 1):
            key = ladder.variants[i]
            prof = self._profiles[key]
            p99 = prof.p99(self.window_s)
            cost, src = prof.cost(self.window_s)
            resident = self._resident(key)
            # sort key: cost first (None = unprofiled ranks after any
            # measured/declared cost), then warm-before-cold, then the
            # ladder's declared preference
            rank = (cost if cost is not None else float("inf"),
                    0 if resident else 1, i)
            if p99 is None or p99 <= ladder.slo_ms:
                meeting.append(rank)
            else:
                fallback.append((p99, 0 if resident else 1, i))
        if meeting:
            return min(meeting)[-1]
        if fallback:
            return min(fallback)[-1]     # best-effort: lowest p99
        return ladder.floor

    def _resident(self, key: str) -> bool:
        if self.zoo is None:
            return True
        try:
            status = self.zoo.entry_status(key)
        except Exception:  # noqa: BLE001 — residency is advisory
            return True
        return bool(status) and status.get("state") == "resident"

    def _rebuild_routes_locked(self) -> None:
        routes: Dict[str, str] = {}
        for ladder in self._ladders.values():
            active = ladder.variants[ladder.active_idx]
            routes[ladder.name] = active       # bare logical name
            for key in ladder.variants:
                routes[key] = active
        self._routes = routes   # atomic swap: readers never see a mix

    def _emit(self, event: VariantEvent) -> None:
        self.events.append(event)
        if self.record_event is not None:
            try:
                self.record_event(event)
            except Exception:  # noqa: BLE001 — the timeline is
                pass           # best-effort; routing must not die

    # -- observability ------------------------------------------------------

    def status(self) -> Dict[str, Any]:
        """The /healthz payload: per-logical active variant, rung,
        degradation floor, last step-down reason, and each rung's
        profile (p99, cost + cost_source, residency, cold-start ms)."""
        with self._lock:
            ladders = list(self._ladders.values())
        out: Dict[str, Any] = {}
        for ladder in ladders:
            rungs = []
            for i, key in enumerate(ladder.variants):
                prof = self._profiles[key]
                p99 = prof.p99(self.window_s)
                cost, src = prof.cost(self.window_s)
                entry = None
                if self.zoo is not None:
                    try:
                        entry = self.zoo.entry_status(key)
                    except Exception:  # noqa: BLE001
                        entry = None
                rungs.append({
                    "variant": key, "rung": i,
                    "open": i <= ladder.floor,
                    "p99_ms": (round(p99, 2)
                               if p99 is not None else None),
                    "cost": (round(cost, 4)
                             if cost is not None else None),
                    "cost_source": src,
                    "state": (entry or {}).get("state", "unknown"),
                    "activation_ms": (entry or {}).get("activation_ms"),
                })
            out[ladder.name] = {
                "active": ladder.variants[ladder.active_idx],
                "rung": ladder.active_idx,
                "floor": ladder.floor,
                "slo_ms": ladder.slo_ms,
                "last_step_down_reason": ladder.last_reason,
                "step_downs": ladder.step_downs,
                "step_ups": ladder.step_ups,
                "selects": ladder.selects,
                "variants": rungs,
            }
        return out

    def stats(self) -> Dict[str, Any]:
        """Counter totals for the ``serving_variant_*`` families."""
        with self._lock:
            ladders = list(self._ladders.values())
        return {
            "declared": len(ladders),
            "step_downs": sum(x.step_downs for x in ladders),
            "step_ups": sum(x.step_ups for x in ladders),
            "selects": sum(x.selects for x in ladders),
            "degraded": sum(1 for x in ladders if x.active_idx > 0),
        }
