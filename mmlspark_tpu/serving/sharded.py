"""Mesh-sharded serving: pjit-compiled inference over a named mesh.

Serving so far ran replicated single-device models — one replica = one
chip, the fleet scales out. This module makes the serving path
MESH-NATIVE (the ROADMAP sharded-serving item): serving programs compile
once per shape bucket as ``jit`` with **explicit**
``in_shardings``/``out_shardings`` over a ``parallel/mesh.py`` mesh and
donated input buffers — the standard sharded-inference shape of GSPMD
(Xu et al., 2021) and *Efficiently Scaling Transformer Inference*
(Pope et al., 2022). Three placements, one per serving family:

- **Pipeline families** (``data_shard_pipeline``): fused
  Featurize→model programs (core/fusion.py) shard the BATCH dim over
  the ``data`` axis; per-stage consts replicate (or shard per an
  explicit per-op spec); ``DeviceTable`` ships every column/feed/const
  straight into its declared placement. Bit-identical to the
  single-device program — batch-dim sharding never changes a row's
  math.
- **Tensor parallelism** (``tensor_shard_model``): a ``TPUModel`` whose
  weight matrices shard across the ``model`` axis
  (``auto_weight_specs``: largest divisible dim, small leaves stay
  replicated) with inputs/outputs replicated — XLA inserts the
  collectives. This is how a model whose weights exceed one device's
  memory serves from the mesh: per-device resident bytes stay below
  the total weight bytes (``device_residency`` proves it).
- **Sequence parallelism** (``seq_shard_lm``): the Transformer-LM zoo
  model scores LONG CONTEXTS with its sequence dim sharded over the
  ``seq`` axis, reusing the existing ring/Ulysses attention
  (parallel/ring_attention.py) inside ``shard_map`` — weights
  replicated, the attention collective is the only cross-shard
  traffic.

Every sharded program declares its shardings explicitly — never
inferred from operand placement (tools/check_fusion_kernels.py
``check_sharded_serving`` audits the jit call sites). On this CPU
container the mesh is simulated with
``XLA_FLAGS=--xla_force_host_platform_device_count=8`` (tests/conftest
forces it; ``serving/aot.py``'s runner re-forces it in fresh processes
from the artifact manifest).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mmlspark_tpu.core.fusion import (
    FusedPipelineModel, SegmentSharding, fuse, register_kernel,
)
from mmlspark_tpu.parallel import mesh as mesh_lib

DATA_AXIS = mesh_lib.DATA_AXIS
MODEL_AXIS = mesh_lib.MODEL_AXIS
SEQ_AXIS = mesh_lib.SEQ_AXIS

# weight leaves smaller than this stay replicated under
# auto_weight_specs: sharding a bias vector buys nothing and costs a
# collective; the big matrices (embeddings, Dense kernels) are where
# per-device memory goes
DEFAULT_MIN_SHARD_BYTES = 1 << 15


def serving_mesh(axes: Optional[Dict[str, int]] = None) -> Mesh:
    """The serving mesh: all devices on the ``data`` axis by default
    (``axes`` overrides, e.g. ``{"model": 8}`` for tensor parallelism
    or ``{"seq": 8}`` for long-context scoring)."""
    return mesh_lib.make_mesh(axes or {DATA_AXIS: -1})


# ---------------------------------------------------------------------------
# placement rules
# ---------------------------------------------------------------------------


def auto_weight_specs(weights: Any, mesh: Mesh, axis: str = MODEL_AXIS,
                      min_shard_bytes: int = DEFAULT_MIN_SHARD_BYTES,
                      ) -> Any:
    """Per-leaf ``PartitionSpec`` tree: shard each weight leaf's
    LARGEST dim that divides the axis size (ties break toward the
    first), replicate leaves smaller than ``min_shard_bytes`` or with
    no divisible dim — the naive-sharding rule of SNIPPETS [3], which
    is exactly what fitting an oversized model onto N chips needs."""
    n = int(mesh.shape[axis])

    def spec_for(leaf) -> P:
        arr = np.asarray(leaf) if not hasattr(leaf, "shape") else leaf
        shape = tuple(getattr(arr, "shape", ()))
        nbytes = int(getattr(arr, "nbytes",
                             np.asarray(arr).nbytes if shape else 0))
        if not shape or nbytes < min_shard_bytes:
            return P()
        divisible = [(d, i) for i, d in enumerate(shape) if d % n == 0]
        if not divisible:
            return P()
        _, dim = max(divisible, key=lambda t: (t[0], -t[1]))
        parts: list = [None] * len(shape)
        parts[dim] = axis
        return P(*parts)

    return jax.tree_util.tree_map(spec_for, weights)


def device_residency(obj: Any) -> Dict[str, Any]:
    """Per-device resident bytes of a served model's device state.

    ``obj`` is a ``TPUModel`` (weights ship if they haven't yet), a
    ``FusedPipelineModel`` (DeviceTable consts + cached columns), or a
    plain pytree of jax arrays. Returns ``{"per_device_bytes",
    "max_device_bytes", "total_bytes", "devices"}`` — the
    too-big-for-one-device proof is ``max_device_bytes <
    total_logical_bytes`` (and the eviction-cost signal the zoo sums
    is ``total_bytes`` across the mesh)."""
    per: Dict[str, int] = {}

    def add(leaf) -> None:
        shards = getattr(leaf, "addressable_shards", None)
        if not shards:
            return
        # all-or-nothing per leaf (the fusion._shard_bytes contract):
        # a donated/deleted buffer must not leave a partial per-device
        # count behind
        try:
            counts = [(str(s.device), int(s.data.nbytes))
                      for s in shards]
        except Exception:  # noqa: BLE001 — donated/deleted buffer
            return
        for key, nbytes in counts:
            per[key] = per.get(key, 0) + nbytes

    if hasattr(obj, "_weights_on_device"):          # TPUModel
        tree = obj._weights_on_device()
        for leaf in jax.tree_util.tree_leaves(tree):
            add(leaf)
    elif isinstance(obj, FusedPipelineModel):
        with obj._plan_lock:
            plans = list(obj._plans.values())
        for plan in plans:
            dt = plan.device_table
            with dt._lock:
                trees = [t for _, t in dt._consts.values()]
                cols = [a for p_ in dt._tables.values()
                        for a in p_.values()]
            for tree in trees:
                for leaf in jax.tree_util.tree_leaves(tree):
                    add(leaf)
            for arr in cols:
                add(arr)
    else:                                           # pytree of arrays
        for leaf in jax.tree_util.tree_leaves(obj):
            add(leaf)
    total = sum(per.values())
    return {
        "per_device_bytes": per,
        "max_device_bytes": max(per.values()) if per else 0,
        "total_bytes": total,
        "devices": len(per),
    }


# ---------------------------------------------------------------------------
# the three serving placements
# ---------------------------------------------------------------------------


def data_shard_pipeline(pipeline: Any, mesh: Optional[Mesh] = None,
                        data_axis: str = DATA_AXIS,
                        const_specs: Optional[Dict[str, Any]] = None,
                        batch_size: int = 256) -> FusedPipelineModel:
    """Compile a fitted pipeline for mesh-sharded fused serving: every
    shape bucket's program jits with explicit batch-dim
    ``in_shardings``/``out_shardings`` over ``data_axis`` and donated
    inputs; ``DeviceTable`` consts replicate (``const_specs`` shards
    named ops' tables). Drop-in for ``fuse()`` — same serving
    discipline (buckets, warmup, jit_cache_misses), bit-identical
    outputs."""
    mesh = mesh if mesh is not None else serving_mesh()
    fused = pipeline if isinstance(pipeline, FusedPipelineModel) \
        else fuse(pipeline, batch_size=batch_size)
    return fused.shard(mesh, data_axis=data_axis,
                       const_specs=const_specs)


def tensor_shard_model(model: Any, mesh: Optional[Mesh] = None,
                       axis: str = MODEL_AXIS,
                       min_shard_bytes: int = DEFAULT_MIN_SHARD_BYTES,
                       weight_specs: Any = None) -> Any:
    """Tensor-parallel serving for a ``TPUModel`` too big for one
    device: weights shard across ``axis`` (``auto_weight_specs`` unless
    an explicit spec tree is given), inputs/outputs replicate, and the
    forward jits with those shardings declared — XLA inserts the
    collectives (GSPMD). Returns the model, configured in place."""
    mesh = mesh if mesh is not None else serving_mesh({axis: -1})
    if weight_specs is None:
        weight_specs = auto_weight_specs(model.get("weights"), mesh,
                                         axis=axis,
                                         min_shard_bytes=min_shard_bytes)
    return model.set_sharding(mesh, weight_specs=weight_specs,
                              in_spec=P(), out_spec=P())


class _SeqShardedApply:
    """Picklable seq-parallel LM forward: ``shard_map`` over the
    ``seq`` axis around a seq-axis-aware ``networks.Transformer``
    (ring/Ulysses attention inside — parallel/ring_attention.py).
    Weights replicate at the shard_map boundary; the attention
    collective is the only cross-shard traffic (the
    ``seq_parallel_apply`` contract, packaged as a TPUModel modelFn).

    The mesh itself is NOT pickled (Device handles are process-local):
    ``__getstate__`` keeps only the axis sizes and the fn rebuilds the
    mesh from the loading process's devices on first call — the AOT
    fallback path in a fresh replica just works."""

    int_input = True   # consumes token ids, not float features

    def __init__(self, module, mesh: Mesh, axis: str = SEQ_AXIS):
        self.module = module
        self.axis = str(axis)
        self.mesh_axes = {str(k): int(v) for k, v in mesh.shape.items()}
        self._mesh = mesh
        self._fn = None

    def __getstate__(self):
        return {"module": self.module, "axis": self.axis,
                "mesh_axes": self.mesh_axes}

    def __setstate__(self, state):
        self.module = state["module"]
        self.axis = state["axis"]
        self.mesh_axes = state["mesh_axes"]
        self._mesh = None
        self._fn = None

    @property
    def mesh(self) -> Mesh:
        if self._mesh is None:
            self._mesh = mesh_lib.make_mesh(dict(self.mesh_axes))
        return self._mesh

    def _build(self):
        if self._fn is not None:
            return self._fn
        from mmlspark_tpu.utils.jax_compat import shard_map
        module, axis = self.module, self.axis
        out_spec = (P(None, axis) if module.num_classes == 0 else P())

        def apply(vars_, toks):
            return module.apply(vars_, toks)

        self._fn = shard_map(apply, mesh=self.mesh,
                             in_specs=(P(), P(None, axis)),
                             out_specs=out_spec, check_vma=False)
        return self._fn

    def __call__(self, weights, inputs: Dict[str, jnp.ndarray]):
        toks = list(inputs.values())[0]
        variables = weights if (isinstance(weights, dict)
                                and "params" in weights) \
            else {"params": weights}
        return self._build()(variables, toks)


register_kernel(_SeqShardedApply.__call__, "sharded.seq_lm_apply")


def seq_shard_lm(module, variables: Any, mesh: Optional[Mesh] = None,
                 seq_axis: str = SEQ_AXIS, **model_kw) -> Any:
    """Serve a ``networks.Transformer`` with its SEQUENCE dim sharded
    over the mesh — long-context scoring through the existing
    ring/Ulysses attention. ``module`` must carry ``seq_axis=seq_axis``
    (build it so); token ids arrive ``[B, T]`` with ``T`` divisible by
    the axis size. Returns a ``TPUModel`` whose jitted forward declares
    tokens ``P(None, seq_axis)`` in/out (LM head) or replicated out
    (classifier head) — the serving discipline (buckets, warmup,
    donation, jit_cache_misses) is unchanged."""
    from mmlspark_tpu.models.tpu_model import TPUModel
    mesh = mesh if mesh is not None else serving_mesh({seq_axis: -1})
    if getattr(module, "seq_axis", None) != seq_axis:
        raise ValueError(
            f"module.seq_axis is {getattr(module, 'seq_axis', None)!r}; "
            f"build the Transformer with seq_axis={seq_axis!r} so its "
            f"attention runs the ring/Ulysses collective")
    fn = _SeqShardedApply(module, mesh, axis=seq_axis)
    if not (isinstance(variables, dict) and "params" in variables):
        variables = {"params": variables}
    model = TPUModel(modelFn=fn, weights=dict(variables), **model_kw)
    out_spec = (P(None, seq_axis) if module.num_classes == 0 else P())
    return model.set_sharding(mesh, weight_specs=P(),
                              in_spec=P(None, seq_axis),
                              out_spec=out_spec)


def assert_serves_from_mesh(model: Any,
                            ) -> Tuple[int, int]:
    """The too-big-for-one-device assertion, packaged: returns
    ``(max_device_bytes, total_logical_bytes)`` and raises when any
    single device holds the full weight set (i.e. the model is NOT
    actually sharded)."""
    res = device_residency(model)
    total_logical = int(sum(
        int(np.asarray(a).nbytes) if not hasattr(a, "nbytes")
        else int(a.nbytes)
        for a in jax.tree_util.tree_leaves(
            model.get("weights") if hasattr(model, "get") else model)))
    if res["max_device_bytes"] >= total_logical:
        raise AssertionError(
            f"model is not sharded: one device holds "
            f"{res['max_device_bytes']} bytes >= the full "
            f"{total_logical}-byte weight set")
    return res["max_device_bytes"], total_logical
