"""Streaming HTTP serving.

Analog of Spark Serving (ref: src/io/http/src/main/scala/HTTPSource.scala,
DistributedHTTPSource.scala, ServingImplicits.scala,
PartitionConsolidator.scala).
"""

from mmlspark_tpu.serving.aot import (
    export_model, load_model, read_manifest,
)
from mmlspark_tpu.serving.fleet import (
    PartitionConsolidator, ServingFleet, ServingUnavailable,
    json_row_scoring_pipeline, json_scoring_pipeline,
)
from mmlspark_tpu.serving.lifecycle import (
    CanaryPolicy, ModelRegistry, SwapEvent, SwapInProgress, SwapResult,
)
from mmlspark_tpu.serving.server import (
    HTTPSource, PipelineHandle, ServingEngine, SharedSingleton,
    SharedVariable, serve_model,
)

__all__ = ["CanaryPolicy", "HTTPSource", "ModelRegistry",
           "PartitionConsolidator", "PipelineHandle", "ServingEngine",
           "ServingFleet", "ServingUnavailable", "SharedSingleton",
           "SharedVariable", "SwapEvent", "SwapInProgress", "SwapResult",
           "export_model", "json_row_scoring_pipeline",
           "json_scoring_pipeline", "load_model", "read_manifest",
           "serve_model"]
