"""Streaming HTTP serving.

Analog of Spark Serving (ref: src/io/http/src/main/scala/HTTPSource.scala,
DistributedHTTPSource.scala, ServingImplicits.scala,
PartitionConsolidator.scala).
"""

from mmlspark_tpu.serving.fleet import (
    PartitionConsolidator, ServingFleet, json_scoring_pipeline,
)
from mmlspark_tpu.serving.server import (
    HTTPSource, ServingEngine, SharedSingleton, SharedVariable, serve_model,
)

__all__ = ["HTTPSource", "PartitionConsolidator", "ServingEngine",
           "ServingFleet", "SharedSingleton", "SharedVariable",
           "json_scoring_pipeline", "serve_model"]
