"""Streaming HTTP serving.

Analog of Spark Serving (ref: src/io/http/src/main/scala/HTTPSource.scala,
DistributedHTTPSource.scala, ServingImplicits.scala,
PartitionConsolidator.scala).
"""

from mmlspark_tpu.serving.fleet import (
    PartitionConsolidator, ServingFleet, ServingUnavailable,
    json_row_scoring_pipeline, json_scoring_pipeline,
)
from mmlspark_tpu.serving.server import (
    HTTPSource, ServingEngine, SharedSingleton, SharedVariable, serve_model,
)

__all__ = ["HTTPSource", "PartitionConsolidator", "ServingEngine",
           "ServingFleet", "ServingUnavailable", "SharedSingleton",
           "SharedVariable", "json_row_scoring_pipeline",
           "json_scoring_pipeline", "serve_model"]
