"""Streaming HTTP serving.

Analog of Spark Serving (ref: src/io/http/src/main/scala/HTTPSource.scala,
DistributedHTTPSource.scala, ServingImplicits.scala).
"""

from mmlspark_tpu.serving.server import (
    HTTPSource, ServingEngine, SharedSingleton, SharedVariable, serve_model,
)

__all__ = ["HTTPSource", "ServingEngine", "SharedSingleton",
           "SharedVariable", "serve_model"]
