"""Streaming HTTP serving.

Analog of Spark Serving (ref: src/io/http/src/main/scala/HTTPSource.scala,
DistributedHTTPSource.scala, ServingImplicits.scala,
PartitionConsolidator.scala).
"""

from mmlspark_tpu.serving.admission import (
    AdmissionController, TenantQuota,
)
from mmlspark_tpu.serving.controlplane import (
    ContinuousTrainer, GatePolicy, IngestDriver, PromoteEvent,
    QuarantineEvent, RefitPolicy, RetrainEvent, ShadowEvent,
    TriggerPolicy,
)
from mmlspark_tpu.serving.aot import (
    export_model, load_model, read_manifest,
)
from mmlspark_tpu.serving.fleet import (
    PartitionConsolidator, ServingFleet, ServingUnavailable,
    json_row_scoring_pipeline, json_scoring_pipeline,
)
from mmlspark_tpu.serving.lifecycle import (
    CanaryPolicy, ModelRegistry, SwapEvent, SwapInProgress, SwapResult,
)
from mmlspark_tpu.serving.server import (
    HTTPSource, PipelineHandle, ServingEngine, SharedSingleton,
    SharedVariable, serve_model,
)
from mmlspark_tpu.serving.zoo import (
    ModelZoo, ZooEvent, model_key_of,
)
from mmlspark_tpu.core.flightrecorder import (
    FlightRecorder, get_recorder,
)
from mmlspark_tpu.core.slo import (
    Alert, AlertEvent, AlertLog, BurnRateRule, SLO, SLOMonitor,
)

# mesh-sharded serving (serving/sharded.py) resolves lazily: it pulls
# core.fusion and therefore jax, and `import mmlspark_tpu.serving`
# must stay host-only cheap (the PR 9 import discipline)
_SHARDED_EXPORTS = frozenset({
    "assert_serves_from_mesh", "auto_weight_specs",
    "data_shard_pipeline", "device_residency", "seq_shard_lm",
    "serving_mesh", "tensor_shard_model",
})

# the multi-host fabric (serving/placement.py, io/shm.py) resolves
# lazily too: shm pulls numpy + the columnar codecs, and neither
# belongs on the import path of a client that never opts in
_FABRIC_EXPORTS = {
    "PlacementController": ("mmlspark_tpu.serving.placement",),
    "PlacementEvent": ("mmlspark_tpu.serving.placement",),
    "ShmRing": ("mmlspark_tpu.io.shm",),
    "shm_available": ("mmlspark_tpu.io.shm",),
    # the SLO-adaptive plane (variant selection + fleet autoscaling)
    # rides the same lazy path: most clients never opt in
    "VariantSelector": ("mmlspark_tpu.serving.variants",),
    "VariantEvent": ("mmlspark_tpu.serving.variants",),
    "FleetAutoscaler": ("mmlspark_tpu.serving.autoscale",),
    "AutoscaleEvent": ("mmlspark_tpu.serving.autoscale",),
}


def __getattr__(name):
    if name in _SHARDED_EXPORTS:
        from mmlspark_tpu.serving import sharded as _sharded
        return getattr(_sharded, name)
    if name in _FABRIC_EXPORTS:
        import importlib
        mod = importlib.import_module(_FABRIC_EXPORTS[name][0])
        return getattr(mod, name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")


__all__ = ["AdmissionController", "Alert", "AlertEvent", "AlertLog",
           "AutoscaleEvent",
           "BurnRateRule", "CanaryPolicy", "ContinuousTrainer",
           "FlightRecorder", "GatePolicy", "HTTPSource",
           "IngestDriver",
           "ModelRegistry", "ModelZoo", "PartitionConsolidator",
           "PipelineHandle", "PlacementController", "PlacementEvent",
           "PromoteEvent", "QuarantineEvent",
           "RefitPolicy", "RetrainEvent",
           "SLO", "SLOMonitor", "ServingEngine",
           "ServingFleet", "ServingUnavailable", "ShadowEvent",
           "SharedSingleton",
           "SharedVariable", "SwapEvent", "SwapInProgress", "SwapResult",
           "TenantQuota", "TriggerPolicy", "VariantEvent",
           "VariantSelector", "ZooEvent", "FleetAutoscaler",
           "assert_serves_from_mesh",
           "auto_weight_specs",
           "data_shard_pipeline", "device_residency", "export_model",
           "get_recorder", "json_row_scoring_pipeline",
           "json_scoring_pipeline", "load_model", "model_key_of",
           "read_manifest", "seq_shard_lm", "serve_model",
           "serving_mesh", "shm_available", "ShmRing",
           "tensor_shard_model"]
