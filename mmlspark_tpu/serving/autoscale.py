"""Load-driven fleet autoscaling: spawn and retire engine processes
off windowed demand, with bounded scale rates and a drain-before-
retire discipline.

The serving fleet's width was fixed at ``connect`` time; this module
closes the loop the ROADMAP's adaptive-serving item asks for. A
``FleetAutoscaler`` watches the client-observed demand rate
(``ServingFleet.demand_rate`` — a windowed counter fed by every
``post``/``post_columns``) and keeps per-engine demand inside a
watermark band:

- **Scale up** when demand/engine exceeds ``up_rate`` — the
  ``spawner`` callback starts one engine process (the
  ``tests/serving_worker.py`` machinery in tests and the bench), the
  new address passes the fleet's STARTUP PROBE before joining the
  rotation (the ``connect`` discipline: a slow starter must not burn
  its fresh breaker's failure budget), and the placement controller
  rebalances over the new width (``set_n_engines``) so hot models fan
  out onto the new replica.
- **Scale down** when demand/engine falls under ``down_rate`` —
  always through ``_drain_and_stop``: the engine leaves the routing
  rotation FIRST, then its ``/healthz`` is polled until parked
  connections and queue depth hit zero (bounded by
  ``drain_timeout_s``), and only then does the process stop. The
  ``check_adaptive_serving`` audit proves statically that rotation
  removal and process stop happen nowhere else — a scale-down can
  shed capacity, never in-flight requests.
- **Bounded rates + hysteresis.** At most one engine joins or leaves
  per decision, decisions are separated by ``cooldown_s`` (joins) /
  ``down_cooldown_s`` (leaves, longer by default), and the fleet
  width stays inside [``min_engines``, ``max_engines``]. Engines the
  autoscaler did not spawn are never retired — the operator's
  baseline capacity is not the controller's to take.

Every decision lands as an ``AutoscaleEvent`` (on the registry
timeline too when a zoo's ``record_event`` is wired), and
``serving_autoscale_*`` Prometheus families render through the
fleet's ``metrics_text``.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request
from typing import Any, Callable, Dict, List, Optional, Tuple

from mmlspark_tpu.core.logging_utils import get_logger

log = get_logger("serving.autoscale")


class AutoscaleEvent:
    """One autoscaler decision on the timeline (the VariantEvent /
    PlacementEvent discipline)."""

    def __init__(self, kind: str, address: str = "", reason: str = "",
                 stats: Optional[Dict[str, Any]] = None):
        self.kind = kind    # 'scale_up'|'scale_down'|'drain_timeout'
        self.address = address
        self.reason = reason
        self.stats = dict(stats or {})
        self.at = time.time()

    def __repr__(self) -> str:
        return (f"AutoscaleEvent({self.kind}, {self.address!r}, "
                f"reason={self.reason!r})")


class FleetAutoscaler:
    """Watermark controller over a CONNECTED ``ServingFleet``.

    ``spawner()`` starts one engine process and returns
    ``(address, stop_handle)`` — the handle is a zero-arg callable, or
    an object with ``terminate``/``kill`` (a ``subprocess.Popen``).
    The autoscaler owns the processes it spawned (retires newest
    first) and ONLY those."""

    def __init__(self, fleet, spawner: Callable[[], Tuple[str, Any]],
                 min_engines: int = 1,
                 max_engines: int = 4,
                 up_rate: float = 100.0,
                 down_rate: Optional[float] = None,
                 window_s: float = 10.0,
                 cooldown_s: float = 5.0,
                 down_cooldown_s: Optional[float] = None,
                 startup_probe_s: float = 60.0,
                 drain_timeout_s: float = 10.0,
                 record_event=None):
        if min_engines < 1:
            raise ValueError("min_engines must be >= 1")
        if max_engines < min_engines:
            raise ValueError("max_engines must be >= min_engines")
        self.fleet = fleet
        self.spawner = spawner
        self.min_engines = int(min_engines)
        self.max_engines = int(max_engines)
        self.up_rate = float(up_rate)
        # default low watermark well under half the high one: a fleet
        # that just scaled up must not immediately qualify for scale-
        # down (the hysteresis band)
        self.down_rate = (float(down_rate) if down_rate is not None
                          else self.up_rate * 0.3)
        if self.down_rate >= self.up_rate:
            raise ValueError("down_rate must sit below up_rate "
                             "(the hysteresis band)")
        self.window_s = float(window_s)
        self.cooldown_s = float(cooldown_s)
        self.down_cooldown_s = (float(down_cooldown_s)
                                if down_cooldown_s is not None
                                else self.cooldown_s * 2)
        self.startup_probe_s = float(startup_probe_s)
        self.drain_timeout_s = float(drain_timeout_s)
        self.record_event = record_event
        self._lock = threading.Lock()
        # addresses this autoscaler spawned, join order; only these
        # are retire candidates (newest-first)
        self._owned: List[str] = []
        self._stoppers: Dict[str, Any] = {}
        self._last_change = 0.0
        self._thread: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()
        self.scale_ups = 0
        self.scale_downs = 0
        self.drain_timeouts = 0
        self.spawn_failures = 0
        self.events: List[AutoscaleEvent] = []
        # the fleet's /metrics renders serving_autoscale_* through us
        fleet.autoscaler = self

    # -- the control loop ---------------------------------------------------

    def start(self, interval_s: float = 1.0) -> "FleetAutoscaler":
        """Run ``tick`` on a daemon thread every ``interval_s``."""
        if self._thread is not None:
            return self
        self._stop_evt.clear()

        def loop():
            while not self._stop_evt.wait(interval_s):
                try:
                    self.tick()
                except Exception as e:  # noqa: BLE001 — a sick
                    # controller must not take the fleet down
                    log.error("autoscaler tick failed (continuing): %s",
                              e)

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="fleet-autoscaler")
        self._thread.start()
        return self

    def stop(self) -> None:
        """Stop the control loop (spawned engines keep serving; use
        ``close`` to also retire them)."""
        self._stop_evt.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5)

    def close(self, drain: bool = True) -> None:
        """Stop the loop AND retire every spawned engine (newest
        first), each through the drain path unless ``drain=False``
        (teardown in tests where the fleet is going away anyway)."""
        self.stop()
        with self._lock:
            owned = list(reversed(self._owned))
        for addr in owned:
            try:
                self._drain_and_stop(addr, reason="close",
                                     drain=drain)
            except Exception as e:  # noqa: BLE001 — keep retiring
                log.warning("close: retiring %s failed: %s", addr, e)

    def tick(self, now: Optional[float] = None) -> Optional[str]:
        """One control decision: compare windowed demand/engine to the
        watermark band, move the fleet width AT MOST one engine, and
        respect the cooldowns. Returns 'scale_up'/'scale_down'/None
        (what happened), for tests and manual driving."""
        t = time.monotonic() if now is None else now
        n = len(self.fleet.addresses)
        demand = self.fleet.demand_rate(self.window_s)
        per_engine = demand / max(1, n)
        with self._lock:
            since_change = t - self._last_change
        if per_engine > self.up_rate and n < self.max_engines:
            if since_change < self.cooldown_s:
                return None         # bounded scale rate
            return self._scale_up(demand, per_engine, t)
        if per_engine < self.down_rate and n > self.min_engines:
            if since_change < self.down_cooldown_s:
                return None
            with self._lock:
                victim = self._owned[-1] if self._owned else None
            if victim is None or victim not in self.fleet.addresses:
                return None         # nothing of ours to retire
            self._drain_and_stop(
                victim,
                reason=f"demand {per_engine:.1f}/engine < "
                       f"{self.down_rate:.1f}")
            with self._lock:
                self._last_change = t
            return "scale_down"
        return None

    # -- scale up -----------------------------------------------------------

    def _scale_up(self, demand: float, per_engine: float,
                  t: float) -> Optional[str]:
        try:
            address, stopper = self.spawner()
        except Exception as e:  # noqa: BLE001 — spawn failed; the
            # fleet keeps serving at its current width
            self.spawn_failures += 1
            log.error("autoscaler spawn failed: %s", e)
            return None
        try:
            # startup probe BEFORE rotation (fleet.add_engine probes):
            # first real traffic must not eat the new breaker's budget
            self.fleet.add_engine(address,
                                  wait_ready_s=self.startup_probe_s)
        except Exception as e:  # noqa: BLE001 — never-joined process
            # must not leak
            self.spawn_failures += 1
            self._stop_proc(stopper)
            log.error("autoscaler join of %s failed: %s", address, e)
            return None
        with self._lock:
            self._owned.append(address)
            self._stoppers[address] = stopper
            self._last_change = t
            self.scale_ups += 1
        self._emit(AutoscaleEvent(
            "scale_up", address,
            reason=f"demand {per_engine:.1f}/engine > "
                   f"{self.up_rate:.1f}",
            stats={"demand_rate": round(demand, 1),
                   "engines": len(self.fleet.addresses)}))
        log.info("autoscaler: %s joined (demand %.1f/engine)",
                 address, per_engine)
        return "scale_up"

    # -- scale down: THE drain path -----------------------------------------

    def _drain_and_stop(self, address: str, reason: str,
                        drain: bool = True) -> None:
        """Retire ONE engine safely: out of the rotation first (no new
        requests route to it), wait for its parked connections and
        queue to empty, then stop the process. This is the only place
        the autoscaler removes an engine or stops a process — enforced
        statically by ``check_adaptive_serving``."""
        try:
            self.fleet.remove_engine(address)
        except ValueError:
            pass    # already out of the rotation (e.g. double close)
        if drain and not self._wait_drained(address):
            self.drain_timeouts += 1
            self._emit(AutoscaleEvent(
                "drain_timeout", address,
                reason=f"not drained after {self.drain_timeout_s:.0f}s;"
                       " stopping anyway (requests already answered or"
                       " timed out)"))
        with self._lock:
            stopper = self._stoppers.pop(address, None)
            if address in self._owned:
                self._owned.remove(address)
            self.scale_downs += 1
        self._stop_proc(stopper)
        self._emit(AutoscaleEvent(
            "scale_down", address, reason=reason,
            stats={"engines": len(self.fleet.addresses)}))
        log.info("autoscaler: %s drained + retired (%s)", address,
                 reason)

    def _wait_drained(self, address: str) -> bool:
        """Poll the engine's own /healthz until it holds no parked
        connections and its queue is empty (it is out of the rotation,
        so the counts only fall), bounded by ``drain_timeout_s``."""
        deadline = time.monotonic() + self.drain_timeout_s
        while time.monotonic() < deadline:
            try:
                with urllib.request.urlopen(f"{address}/healthz",
                                            timeout=2.0) as resp:
                    health = json.loads(resp.read())
                if health.get("parked", 1) == 0 \
                        and health.get("queue_depth", 1) == 0:
                    return True
            except Exception:  # noqa: BLE001 — engine already gone
                return True    # counts as drained: nothing listening
            time.sleep(0.05)
        return False

    @staticmethod
    def _stop_proc(stopper: Any) -> None:
        """Stop one spawned engine's process handle: a callable, or a
        Popen-shaped object (terminate, bounded wait, then kill)."""
        if stopper is None:
            return
        if callable(stopper):
            stopper()
            return
        stopper.terminate()
        try:
            stopper.wait(timeout=5)
        except Exception:  # noqa: BLE001 — stuck in shutdown
            stopper.kill()

    # -- observability ------------------------------------------------------

    def _emit(self, event: AutoscaleEvent) -> None:
        self.events.append(event)
        if self.record_event is not None:
            try:
                self.record_event(event)
            except Exception:  # noqa: BLE001 — timeline best-effort
                pass

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            owned = len(self._owned)
        return {
            "engines": len(self.fleet.addresses),
            "owned": owned,
            "min_engines": self.min_engines,
            "max_engines": self.max_engines,
            "up_rate": self.up_rate,
            "down_rate": self.down_rate,
            "demand_rate": self.fleet.demand_rate(self.window_s),
            "scale_ups": self.scale_ups,
            "scale_downs": self.scale_downs,
            "drain_timeouts": self.drain_timeouts,
            "spawn_failures": self.spawn_failures,
        }
