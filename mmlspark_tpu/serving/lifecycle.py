"""Versioned model lifecycle: registry, hot swap, canary, rollback.

The reference's serving layer is a *streaming* web service (ref:
src/io/http DistributedHTTPSource.scala — the query keeps running while
batches flow), but our engines bind one fitted pipeline at start. This
module closes the gap: a ``ModelRegistry`` of version-tagged pipelines
and an atomic, chaos-proof swap protocol on ``ServingEngine`` /
``ServingFleet`` so a model refreshed by ``partial_fit`` /
``Booster.boost_more`` replaces the live one without dropping traffic.

Swap state machine (exported as ``engine.swap_state``):

    idle -> warming -> canary -> draining -> idle      (completed)
                 \\         \\
                  +-> rolled_back (warmup failed/stalled, canary breach,
                      decision timeout, engine death)

- **warming**: the incoming pipeline's ``warmup`` hook compiles every
  serving shape bucket OFF the hot path (on a sacrificial thread with a
  timeout — a stalled warmup rolls the swap back instead of wedging
  it). Zero ``jit_cache_misses`` during or after cutover.
- **canary**: the batcher routes ``CanaryPolicy.fraction`` of
  micro-batches to the incoming version. Every batch carries its
  ``PipelineHandle``, so no reply batch ever mixes versions. A failing
  canary batch is *rescued* — re-executed on the stable version — so
  clients never eat a canary's faults; the failure still counts against
  the canary through a ``CircuitBreaker`` (consecutive-failure AND
  window-failure-rate breach, the same machinery the fleet client uses
  per engine). Latency is watched through per-version
  ``LatencyHistogram``s: a canary p50 beyond ``latency_ratio`` x the
  stable p50 is also a breach.
- **draining**: cutover is ONE attribute store (``engine._active``);
  batches already dispatched on the old handle drain on the old
  version (its ``outstanding`` count reaching zero ends the phase).
- **rolled_back** surfaces a typed ``SwapEvent`` carrying the reason
  and the canary stats at the moment of the decision.

``ServingFleet.rolling_swap`` runs the protocol engine-by-engine,
pausing while the fleet shows failover pressure (open circuits), and
stops marching a version that rolled back anywhere.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from mmlspark_tpu.core.logging_utils import get_logger
from mmlspark_tpu.core.metrics import LatencyHistogram
from mmlspark_tpu.serving.server import PipelineHandle, ServingEngine
from mmlspark_tpu.utils.resilience import CircuitBreaker

log = get_logger("serving.lifecycle")

# swap_state values (engine.swap_state / healthz)
IDLE = "idle"
WARMING = "warming"
CANARY = "canary"
DRAINING = "draining"
ROLLED_BACK = "rolled_back"


class SwapInProgress(RuntimeError):
    """A second swap was requested while one is already running."""


class SwapEvent:
    """Typed lifecycle event: one completed or rolled-back swap.

    Carries each side's serving precision (f32/int8) and the incoming
    model's AOT flag, so a rollout to a quantized or AOT-loaded model
    is auditable from the event log alone — and a canary that compared
    int8 against f32 is visible as exactly that."""

    def __init__(self, kind: str, from_version: str, to_version: str,
                 reason: str = "", stats: Optional[Dict[str, Any]] = None,
                 from_precision: str = "f32", to_precision: str = "f32",
                 from_aot: bool = False, to_aot: bool = False):
        self.kind = kind                    # 'completed' | 'rolled_back'
        self.from_version = from_version
        self.to_version = to_version
        self.reason = reason
        self.stats = dict(stats or {})
        self.from_precision = str(from_precision)
        self.to_precision = str(to_precision)
        self.from_aot = bool(from_aot)
        self.to_aot = bool(to_aot)
        self.at = time.time()

    def __repr__(self) -> str:
        extra = f", reason={self.reason!r}" if self.reason else ""
        if (self.from_precision != self.to_precision
                or self.from_aot != self.to_aot):
            extra += (f", {self.from_precision}"
                      f"{'+aot' if self.from_aot else ''} -> "
                      f"{self.to_precision}"
                      f"{'+aot' if self.to_aot else ''}")
        return (f"SwapEvent({self.kind}, {self.from_version!r} -> "
                f"{self.to_version!r}{extra})")


class SwapResult:
    """What ``engine.swap`` returns: the outcome plus its event."""

    def __init__(self, completed: bool, event: SwapEvent):
        self.completed = completed
        self.rolled_back = not completed
        self.event = event
        self.reason = event.reason

    def __repr__(self) -> str:
        state = "completed" if self.completed else "rolled_back"
        return f"SwapResult({state}, {self.event!r})"


class CanaryPolicy:
    """Rollback-policy knobs for one swap.

    - ``fraction``: share of micro-batches routed to the incoming
      version during the canary phase (0 disables the canary — direct
      cutover after warmup).
    - ``min_batches``: clean canary batches required to promote.
    - ``consecutive_failures`` / ``error_rate`` (+ ``min_calls``,
      ``window``): the CircuitBreaker breach thresholds — either
      N failures in a row, or the windowed failure rate, rolls back.
    - ``latency_ratio``: canary p50 beyond this multiple of the stable
      p50 (after ``min_batches`` canary observations AND at least as
      many stable ones) is a breach; ``None`` disables the check.
    - ``decision_timeout_s``: no promote/breach decision within this
      wall budget rolls back (the safe default — e.g. an engine killed
      mid-swap stops producing canary observations).
    - ``warmup_timeout_s``: warmup hook budget; a stalled warmup rolls
      back instead of wedging the swap.
    - ``drain_timeout_s``: bound on waiting for old-version in-flight
      batches after cutover (expiry logs; cutover already happened).
    """

    def __init__(self, fraction: float = 0.25, min_batches: int = 8,
                 consecutive_failures: int = 3,
                 error_rate: float = 0.34, min_calls: int = 3,
                 window: int = 20,
                 latency_ratio: Optional[float] = None,
                 decision_timeout_s: float = 30.0,
                 warmup_timeout_s: float = 60.0,
                 drain_timeout_s: float = 30.0):
        if not 0.0 <= fraction <= 1.0:
            raise ValueError(f"fraction must be in [0, 1]: {fraction}")
        self.fraction = float(fraction)
        self.min_batches = int(min_batches)
        self.consecutive_failures = int(consecutive_failures)
        self.error_rate = float(error_rate)
        self.min_calls = int(min_calls)
        self.window = int(window)
        self.latency_ratio = latency_ratio
        self.decision_timeout_s = float(decision_timeout_s)
        self.warmup_timeout_s = float(warmup_timeout_s)
        self.drain_timeout_s = float(drain_timeout_s)


class ModelRegistry:
    """Version-tagged model store feeding the swap protocol.

    Versions are insertion-ordered; ``previous(v)`` answers "what do we
    roll back to" and the registry records every ``SwapEvent`` handed
    to ``record_event`` so ops can audit the lifecycle history.
    ``events`` keeps the newest ``events_cap`` records — swaps alone
    would never fill it, but the model zoo logs every
    activate/evict on the same timeline, and a churning cache in an
    always-on process must not grow the audit log forever.
    Thread-safe."""

    events_cap = 4096

    def __init__(self):
        self._versions: Dict[str, Any] = {}
        self._order: List[str] = []
        self._meta: Dict[str, Dict[str, Any]] = {}
        self.events: List[SwapEvent] = []
        self._lock = threading.Lock()

    def register(self, version: str, pipeline: Any,
                 metadata: Optional[Dict[str, Any]] = None) -> None:
        from mmlspark_tpu.core.quantize import stage_precision
        meta = dict(metadata or {})
        # precision/aot recorded at registration (explicit metadata
        # wins): the registry is the audit trail a quantized/AOT
        # rollout is traced back through
        meta.setdefault("precision", stage_precision(pipeline))
        meta.setdefault("aot", bool(getattr(pipeline, "aot", False)))
        with self._lock:
            if version in self._versions:
                raise ValueError(f"version {version!r} already registered")
            self._versions[version] = pipeline
            self._order.append(version)
            self._meta[version] = meta

    def get(self, version: str) -> Any:
        with self._lock:
            if version not in self._versions:
                raise KeyError(f"unknown model version {version!r}; "
                               f"have {self._order}")
            return self._versions[version]

    def _entry_locked(self, version: str
                      ) -> "Tuple[Any, str, Dict[str, Any]]":
        """(served object, state, metadata) for one version — caller
        holds ``self._lock``. Base registries hold materialized
        pipelines, so state is always ``"registered"``; ``ModelZoo``
        overrides this with its load/evict lifecycle (and a
        ``PipelineHandle`` in the first slot when resident)."""
        return (self._versions[version], "registered",
                dict(self._meta.get(version, {})))

    def lookup(self, version: str) -> "Tuple[Any, str, Dict[str, Any]]":
        """ONE consistent ``(handle, state, metadata)`` triple under
        the registry lock — the ``engine._lifecycle_snapshot``
        discipline applied to registry reads. A reader racing a
        concurrent register/load/evict must never see a half-updated
        entry (e.g. state ``resident`` with no handle, or metadata
        from a different lifecycle step than the state)."""
        with self._lock:
            if version not in self._versions:
                raise KeyError(f"unknown model version {version!r}; "
                               f"have {self._order}")
            return self._entry_locked(version)

    def list(self) -> List[Dict[str, Any]]:
        """Every version's ``{version, state, metadata, loaded}`` as
        ONE consistent snapshot under the registry lock, in insertion
        order (the ``lookup`` consistency contract, registry-wide)."""
        with self._lock:
            out = []
            for v in self._order:
                obj, state, meta = self._entry_locked(v)
                out.append({"version": v, "state": state,
                            "metadata": meta,
                            "loaded": obj is not None})
            return out

    def metadata(self, version: str) -> Dict[str, Any]:
        with self._lock:
            return dict(self._meta.get(version, {}))

    def versions(self) -> List[str]:
        with self._lock:
            return list(self._order)

    def latest(self) -> str:
        with self._lock:
            if not self._order:
                raise KeyError("registry is empty")
            return self._order[-1]

    def previous(self, version: str) -> Optional[str]:
        with self._lock:
            if version not in self._order:
                return None
            i = self._order.index(version)
            return self._order[i - 1] if i > 0 else None

    def record_event(self, event: SwapEvent) -> None:
        with self._lock:
            self.events.append(event)
            if len(self.events) > self.events_cap:
                del self.events[:len(self.events) - self.events_cap]


class SwapController:
    """The canary-phase brain: routes a fraction of batches to the
    incoming handle, scores every canary outcome through a
    CircuitBreaker + per-version latency histograms, and resolves to
    'promote' or 'breach:<reason>'. Installed on the engine as
    ``_swap_ctl`` for the duration of the canary phase only."""

    def __init__(self, stable: PipelineHandle, canary: PipelineHandle,
                 policy: CanaryPolicy):
        self.stable = stable
        self.canary = canary
        self.policy = policy
        # breach detector: consecutive failures OR windowed failure
        # rate — identical machinery to the fleet's per-engine breakers
        self.breaker = CircuitBreaker(
            failure_threshold=policy.consecutive_failures,
            failure_rate=policy.error_rate,
            window=policy.window, min_calls=policy.min_calls,
            cooldown=3600.0,          # a tripped canary never half-opens
            name=f"canary:{canary.version}")
        self.canary_hist = LatencyHistogram()
        self.stable_hist = LatencyHistogram()
        self.canary_ok = 0
        self.canary_failed = 0
        self.canary_row_errors = 0
        self.last_error: Optional[str] = None
        # deterministic fractional pacing (error-diffusion accumulator):
        # the long-run canary share equals ``fraction`` EXACTLY for any
        # value in (0, 1] — a rounded stride would send 100% of traffic
        # to the canary for any fraction above 2/3, and a random draw
        # could starve a low-fraction canary for a long unlucky streak
        self._acc = 0.0
        self._lock = threading.Lock()
        self._decided = threading.Event()
        self.decision: Optional[str] = None    # 'promote' | 'breach:...'
        canary.controller = self
        canary.rescue_to = stable

    # -- routing (batcher thread) -------------------------------------------

    def route(self, active: PipelineHandle) -> PipelineHandle:
        if self._decided.is_set() or self.policy.fraction <= 0:
            return active
        with self._lock:
            self._acc += self.policy.fraction
            take = self._acc >= 1.0
            if take:
                self._acc -= 1.0
        return self.canary if take else active

    # -- outcome scoring (worker threads) -----------------------------------

    def observe(self, handle: PipelineHandle, ok: bool,
                latency_ms: float, row_errors: int = 0,
                error: Optional[BaseException] = None) -> None:
        if handle is self.stable or not handle.is_canary:
            self.stable_hist.observe(latency_ms)
            return
        if handle is not self.canary or self._decided.is_set():
            return                    # stale handle / already resolved
        self.canary_hist.observe(latency_ms)
        failed = (not ok) or row_errors > 0
        with self._lock:
            if failed:
                self.canary_failed += 1
                self.canary_row_errors += int(row_errors)
                if error is not None:
                    self.last_error = f"{type(error).__name__}: {error}"
        if failed:
            self.breaker.record_failure()
            if self.breaker.state != CircuitBreaker.CLOSED:
                # the reason carries observed-vs-threshold so the
                # SwapEvent / rollback line / quarantine bundle is
                # self-explanatory without cross-referencing the policy
                with self._lock:
                    nf, ok = self.canary_failed, self.canary_ok
                calls = nf + ok
                rate = nf / max(calls, 1)
                self._resolve(
                    f"breach:error_rate observed={rate:.2f} "
                    f"({nf}/{calls} failed) >= "
                    f"threshold={self.policy.error_rate:.2f} or "
                    f"{self.policy.consecutive_failures} consecutive")
            return
        self.breaker.record_success()
        with self._lock:
            self.canary_ok += 1
            enough = self.canary_ok >= self.policy.min_batches
        latency_reason = self._latency_breached()
        if latency_reason is not None:
            self._resolve(latency_reason)
        elif enough:
            self._resolve("promote")

    def _latency_breached(self) -> Optional[str]:
        """None while healthy, else the full ``breach:latency ...``
        reason with observed p50s vs the allowed ratio."""
        ratio = self.policy.latency_ratio
        if ratio is None:
            return None
        c, s = self.canary_hist.summary(), self.stable_hist.summary()
        if c.get("count", 0) < self.policy.min_batches or \
                s.get("count", 0) < self.policy.min_batches:
            return None
        if c["p50"] > ratio * max(s["p50"], 1e-9):
            return (f"breach:latency canary_p50={c['p50']:.2f}ms > "
                    f"allowed={ratio:.2f}x stable_p50="
                    f"{s['p50']:.2f}ms")
        return None

    def _resolve(self, decision: str) -> None:
        with self._lock:
            if self.decision is None:
                self.decision = decision
        self._decided.set()

    def wait_decision(self, timeout: float) -> str:
        """Block until promote/breach, else a timeout breach (the safe
        default: an engine that stopped producing canary observations
        — killed mid-swap, starved of traffic — must not promote)."""
        if not self._decided.wait(timeout):
            with self._lock:
                ok, nf = self.canary_ok, self.canary_failed
            self._resolve(
                f"breach:decision_timeout after {timeout:.0f}s "
                f"(canary_ok={ok}/{self.policy.min_batches} needed, "
                f"failed={nf})")
        return self.decision or "breach:decision_timeout"

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            out = {
                "canary_version": self.canary.version,
                "stable_version": self.stable.version,
                "canary_ok": self.canary_ok,
                "canary_failed": self.canary_failed,
                "canary_row_errors": self.canary_row_errors,
                "decision": self.decision,
            }
        out["canary_p50_ms"] = self.canary_hist.summary().get("p50")
        out["stable_p50_ms"] = self.stable_hist.summary().get("p50")
        # the policy thresholds the decision was judged against, so the
        # SwapEvent stats pair every observed value with its limit
        out["thresholds"] = {
            "error_rate": self.policy.error_rate,
            "consecutive_failures": self.policy.consecutive_failures,
            "min_batches": self.policy.min_batches,
            "latency_ratio": self.policy.latency_ratio,
            "decision_timeout_s": self.policy.decision_timeout_s,
        }
        if self.last_error:
            out["last_error"] = self.last_error
        return out


def _run_warmup(pipeline: Any, example: Any, timeout_s: float,
                ) -> Optional[str]:
    """Run the pipeline's duck-typed ``warmup`` hook on a sacrificial
    daemon thread with a wall budget. Returns None on success, else the
    failure reason. A hung warmup leaks its (daemon) thread — the price
    of not wedging the swap on a stalled compile."""
    hook: Optional[Callable] = getattr(pipeline, "warmup", None)
    if hook is None:
        return None
    if example is None:
        # hooks that need an example can't run without one; treat a
        # missing example as "skip warmup" only when the hook accepts
        # zero arguments, else fail loudly — a silent skip would let
        # the first live batch pay the compile the swap promised to
        # pre-pay
        import inspect
        try:
            sig = inspect.signature(hook)
            required = [p for p in sig.parameters.values()
                        if p.default is inspect.Parameter.empty
                        and p.kind in (p.POSITIONAL_ONLY,
                                       p.POSITIONAL_OR_KEYWORD)]
        except (TypeError, ValueError):
            required = []
        if required:
            return ("warmup_failed: pipeline.warmup requires an example "
                    "but none was passed to swap()")
    outcome: Dict[str, Any] = {}
    done = threading.Event()

    def run():
        try:
            outcome["result"] = (hook(example) if example is not None
                                 else hook())
        except Exception as e:  # noqa: BLE001 — reported as the reason
            outcome["error"] = f"{type(e).__name__}: {e}"
        finally:
            done.set()

    threading.Thread(target=run, daemon=True,
                     name="lifecycle-warmup").start()
    if not done.wait(timeout_s):
        return f"warmup_timeout: no result within {timeout_s}s"
    if "error" in outcome:
        return f"warmup_failed: {outcome['error']}"
    return None


def execute_swap(engine: ServingEngine, pipeline: Any, version: str,
                 warmup_example: Any = None,
                 policy: Optional[CanaryPolicy] = None,
                 registry: Optional[ModelRegistry] = None) -> SwapResult:
    """The swap protocol on one engine (see module docstring). Blocks
    until the swap completes or rolls back."""
    policy = policy or CanaryPolicy()
    if not engine._swap_lock.acquire(blocking=False):
        raise SwapInProgress(
            f"engine {engine.source.address} is already mid-swap "
            f"(state {engine.swap_state})")
    try:
        old = engine._active
        from_version = old.version
        from mmlspark_tpu.core.quantize import stage_precision
        precisions = {"from_precision": old.precision,
                      "to_precision": stage_precision(pipeline),
                      "from_aot": old.aot,
                      "to_aot": bool(getattr(pipeline, "aot", False))}

        def rolled_back(reason: str,
                        stats: Optional[Dict[str, Any]] = None
                        ) -> SwapResult:
            # state + counter move together under the stats lock, so a
            # concurrent metrics()/healthz scrape can never see
            # rolled_back state with the old rollback count (the
            # consistent-snapshot contract of engine._lifecycle_snapshot)
            with engine._stats_lock:
                engine.swap_state = ROLLED_BACK
                engine.swaps_rolled_back += 1
            event = SwapEvent("rolled_back", from_version, version,
                              reason=reason, stats=stats, **precisions)
            engine.swap_events.append(event)
            if registry is not None:
                registry.record_event(event)
            log.warning("swap %s -> %s ROLLED BACK on %s: %s",
                        from_version, version, engine.source.address,
                        reason)
            recorder = getattr(engine, "flight_recorder", None)
            if recorder is not None:
                # a rollback is exactly the moment the evidence matters:
                # auto-capture a post-mortem bundle (rate-limited) with
                # the canary's traces, the alert/event timeline, and the
                # windowed series at the decision point
                try:
                    recorder.trigger(
                        f"swap_rollback:{from_version}->{version}:"
                        f"{reason}")
                except Exception:  # noqa: BLE001 — capture is
                    pass           # best-effort, never blocks rollback
            return SwapResult(False, event)

        if not engine.is_alive():
            return rolled_back("engine_dead")

        # -- warming: compile every bucket OFF the hot path -----------------
        with engine._stats_lock:
            engine.swap_state = WARMING
        reason = _run_warmup(pipeline, warmup_example,
                             policy.warmup_timeout_s)
        if reason is not None:
            return rolled_back(reason)

        # -- canary: a fraction of live batches on the new version ----------
        stats: Dict[str, Any] = {}
        if policy.fraction > 0 and policy.min_batches > 0:
            canary = PipelineHandle(pipeline, version, is_canary=True)
            ctl = SwapController(old, canary, policy)
            engine._swap_ctl = ctl
            with engine._stats_lock:
                engine.swap_state = CANARY
            try:
                decision = ctl.wait_decision(policy.decision_timeout_s)
                stats = ctl.stats()
            finally:
                engine._swap_ctl = None
            if decision != "promote":
                return rolled_back(decision, stats)

        # -- draining: atomic cutover, old version drains -------------------
        new_handle = PipelineHandle(pipeline, version)
        with engine._stats_lock:
            # THE cutover: handle + state flip in one locked block —
            # batchers read _active lock-free (a plain ref load), but a
            # metrics()/healthz snapshot sees version and swap_state
            # move together instead of piecemeal
            engine._active = new_handle
            engine.swap_state = DRAINING
        deadline = time.monotonic() + policy.drain_timeout_s
        while old.outstanding > 0 and time.monotonic() < deadline:
            time.sleep(0.005)
        if old.outstanding > 0:
            log.warning(
                "swap %s -> %s: %d old-version batch(es) still in "
                "flight after %.1fs drain budget (cutover already "
                "done; they will answer on %s)", from_version, version,
                old.outstanding, policy.drain_timeout_s, from_version)
        with engine._stats_lock:
            engine.swap_state = IDLE
            engine.swaps_completed += 1
        event = SwapEvent("completed", from_version, version, stats=stats,
                          **precisions)
        engine.swap_events.append(event)
        if registry is not None:
            registry.record_event(event)
        log.info("swap %s -> %s completed on %s", from_version, version,
                 engine.source.address)
        return SwapResult(True, event)
    finally:
        engine._swap_lock.release()
