"""AOT-compiled serving executables: kill the replica cold-start.

Every fleet replica used to pay per-bucket JIT tracing at process start
(``warmup()`` — trace + XLA-compile one program per pow-2 shape bucket)
before it could serve its first request. This module moves that work to
**model-export time**: ``export_model`` lowers and compiles every
(bucket, program) pair once, serializes the StableHLO executables
(``jax.export``) plus the weights/scales to a versioned artifact
directory, and seeds a persistent XLA compilation cache next to them —
so ``load_model`` on a fresh replica is *deserialize and go*: no Python
tracing of the model, and the XLA compile of each deserialized program
is a disk hit. Measured as ``cold_start_to_first_200_ms`` in the
serving bench (``bench.py --scenarios coldstart``) and floor-pinned in
``tests/test_perf_floors.py``.

Artifact layout (``<dir>/``)::

    manifest.json        # kind, version, precision, buckets, backend,
                         # jax version, serve hints — human-readable
    programs.pkl         # [(key, input avals, serialized executable)]
    weights.pkl          # np weights pytree (incl. int8 scales)
    model_fn.pkl         # lazy fallback for shapes the artifact
                         # never saw (tpu_model kind only)
    pipeline.pkl         # the fitted stage list (pipeline kind only)
    example.pkl          # warmup/calibration example rows
    example_request.json # one ready-to-POST request body
    xla_cache/           # persistent compilation cache, seeded at
                         # export with the LOAD-side compiles

Two artifact kinds:

- ``tpu_model`` — a ``TPUModel`` (f32 or int8-quantized): one exported
  program per bucket. ``load_model`` returns an ``AOTTPUModel`` whose
  compiled-call dispatch hits the pre-compiled executable by input
  signature — **zero JIT traces at request time**; an unseen shape
  falls back to jit (lazily unpickling the model fn) and counts a
  ``jit_cache_miss`` like any other recompile.
- ``pipeline`` — a fitted ``PipelineModel``/``FusedPipelineModel``
  served through the fused scorer: one exported program per
  (bucket, fused segment) of the SERVING plan. ``load_model`` rebuilds
  the fused pipeline and installs the executables on its segments.

AOT programs are **single-device** (one replica = one chip; the fleet
replicates — mesh-sharded serving is the separate ROADMAP item), and
``precision``/``aot`` ride the manifest into ``serving_model_info`` so
a rolling swap to an AOT/int8 replica is auditable on /metrics.

The format field records ``jax_export`` when ``jax.export`` is
available; otherwise export falls back to ``trace_cache`` — no
serialized programs, but the artifact's seeded compilation cache still
turns the load-side compiles into disk hits while tracing re-runs.
Everything here imports jax lazily so the cold-start runner
(``python -m mmlspark_tpu.serving.aot``) can stamp its clock before
paying the import.
"""

from __future__ import annotations

import contextlib
import json
import os
import pickle
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

ARTIFACT_VERSION = 1
FORMAT_JAX_EXPORT = "jax_export"
FORMAT_TRACE_CACHE = "trace_cache"

_MANIFEST = "manifest.json"
_PROGRAMS = "programs.pkl"
_WEIGHTS = "weights.pkl"
_MODEL_FN = "model_fn.pkl"
_PIPELINE = "pipeline.pkl"
_EXAMPLE = "example.pkl"
_EXAMPLE_REQUEST = "example_request.json"
_XLA_CACHE = "xla_cache"
# sharded artifacts only: the mesh axes + PartitionSpecs the programs
# were exported with (jax.export carries shardings; the load side must
# rebuild the same mesh shape and place inputs to match)
_SHARDING = "sharding.pkl"


def _jax_export():
    """jax.export when this jax has it, else None (trace-cache mode)."""
    try:
        import jax.export as je
        if hasattr(je, "export") and hasattr(je, "deserialize"):
            return je
    except Exception:  # noqa: BLE001 — any import failure = unsupported
        pass
    return None


def input_signature(inputs: Dict[str, Any]) -> Tuple:
    """Shape/dtype signature of a named-array dict — the key the
    per-bucket executables dispatch on (sorted, so env/feed dict
    ordering can never alias two programs)."""
    return tuple(sorted((k, tuple(np.shape(v)), str(np.asarray(v).dtype)
                         if not hasattr(v, "dtype") else str(v.dtype))
                        for k, v in inputs.items()))


def _avals_of(tree):
    """Pytree of arrays -> picklable pytree of (shape, dtype-str)."""
    import jax
    return jax.tree_util.tree_map(
        lambda a: (tuple(a.shape), str(a.dtype)), tree)


def _is_aval_leaf(x) -> bool:
    return (isinstance(x, tuple) and len(x) == 2
            and isinstance(x[0], tuple))


def _avals_to_structs(tree, shardings=None):
    """The inverse: (shape, dtype) leaves -> ShapeDtypeStruct leaves.
    ``shardings`` (a single Sharding applied to every leaf, or a
    matching pytree) attaches the placement — sharded programs must be
    lowered against sharding-carrying avals or jax.export resolves a
    1-device context and refuses the multi-device call."""
    import jax
    if shardings is None:
        return jax.tree_util.tree_map(
            lambda leaf: jax.ShapeDtypeStruct(leaf[0], np.dtype(leaf[1])),
            tree, is_leaf=_is_aval_leaf)
    from jax.sharding import Sharding
    if isinstance(shardings, Sharding):
        return jax.tree_util.tree_map(
            lambda leaf: jax.ShapeDtypeStruct(
                leaf[0], np.dtype(leaf[1]), sharding=shardings),
            tree, is_leaf=_is_aval_leaf)
    return jax.tree_util.tree_map(
        lambda leaf, s: jax.ShapeDtypeStruct(
            leaf[0], np.dtype(leaf[1]), sharding=s),
        tree, shardings, is_leaf=_is_aval_leaf)


def _model_sharding_blob(model) -> Optional[Dict[str, Any]]:
    """The picklable description of a TPUModel's sharding (None when
    unsharded): mesh axes + the PartitionSpec trees. Device handles
    never enter the artifact — the load side rebuilds the mesh from
    its own processes' devices."""
    sh = getattr(model, "_sharding", None) or \
        getattr(model, "sharding", None)
    if not isinstance(sh, dict):
        return None
    return {
        "kind": "tpu_model",
        "axes": {str(k): int(v) for k, v in sh["mesh"].shape.items()},
        "weight_specs": sh["weight_specs"],
        "in_spec": sh["in_spec"],
        "out_spec": sh["out_spec"],
    }


def _load_sharding_blob(art_dir: str) -> Optional[Dict[str, Any]]:
    path = os.path.join(art_dir, _SHARDING)
    if not os.path.exists(path):
        return None
    with open(path, "rb") as f:
        return pickle.load(f)


def _rebuild_mesh(axes: Dict[str, int]):
    from mmlspark_tpu.parallel import mesh as mesh_lib
    return mesh_lib.make_mesh({k: int(v) for k, v in axes.items()})


@contextlib.contextmanager
def _artifact_cache(art_dir: str):
    """Point jax's persistent compilation cache into the artifact for
    the duration (export seeds it; load hits it), restoring the
    caller's cache config after. Best-effort: a jax without the knobs
    — or an artifact on a read-only mount (cache READS still work) —
    still exports/loads, just without (re)seeding the disk cache.

    NOTE: the cache redirection is process-global for the duration, so
    a compile racing on another thread during this window caches into
    the artifact instead of the operator's configured dir (harmless but
    surprising). Load artifacts BEFORE initiating a swap on a live
    engine rather than from inside a serving callback."""
    import jax
    cache_dir = os.path.join(art_dir, _XLA_CACHE)
    try:
        os.makedirs(cache_dir, exist_ok=True)
    except OSError:
        # read-only artifact with no pre-seeded cache dir: compile
        # without the disk cache rather than failing the load
        if not os.path.isdir(cache_dir):
            yield
            return
    old = {}
    knobs = {"jax_compilation_cache_dir": cache_dir,
             "jax_persistent_cache_min_entry_size_bytes": -1,
             "jax_persistent_cache_min_compile_time_secs": 0.0}
    try:
        for k, v in knobs.items():
            try:
                old[k] = getattr(jax.config, k)
                jax.config.update(k, v)
            except Exception:  # noqa: BLE001 — knob missing on old jax
                pass
        _reset_cc()
        yield
    finally:
        for k, v in old.items():
            try:
                jax.config.update(k, v)
            except Exception:  # noqa: BLE001
                pass
        _reset_cc()


def _reset_cc() -> None:
    """Drop jax's lazily-initialized compilation-cache singleton so a
    cache-dir change mid-process actually takes effect."""
    try:
        from jax._src import compilation_cache as _cc
        _cc.reset_cache()
    except Exception:  # noqa: BLE001 — private API drift: cache just
        pass           # stays bound to the first dir it saw


def _single_device_mesh():
    import jax
    from mmlspark_tpu.parallel import mesh as mesh_lib
    return mesh_lib.make_mesh({"data": 1}, devices=[jax.devices()[0]])


def _write_manifest(out_dir: str, manifest: Dict[str, Any]) -> None:
    with open(os.path.join(out_dir, _MANIFEST), "w") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)


def read_manifest(art_dir: str) -> Dict[str, Any]:
    with open(os.path.join(art_dir, _MANIFEST)) as f:
        return json.load(f)


# ---------------------------------------------------------------------------
# export
# ---------------------------------------------------------------------------


def export_model(model, example, out_dir: str, version: str = "v0",
                 ) -> Dict[str, Any]:
    """Export ``model`` + every (bucket, program) pair to ``out_dir``.

    ``model`` is a ``TPUModel`` (f32 or ``quantize()``d) or a fitted
    ``PipelineModel``/``FusedPipelineModel``; ``example`` is the same
    representative-row table/dict ``warmup`` takes. Returns the written
    manifest. Export compiles every program once (trace + XLA) — that
    is the point: replicas loading the artifact never do."""
    from mmlspark_tpu.core.fusion import FusedPipelineModel
    from mmlspark_tpu.core.stage import PipelineModel
    from mmlspark_tpu.models.tpu_model import TPUModel
    os.makedirs(out_dir, exist_ok=True)
    if isinstance(model, TPUModel):
        return _export_tpu_model(model, example, out_dir, version)
    if isinstance(model, (PipelineModel, FusedPipelineModel)):
        return _export_pipeline(model, example, out_dir, version)
    raise TypeError(
        f"cannot AOT-export {type(model).__name__}: expected TPUModel, "
        f"PipelineModel, or FusedPipelineModel")


class _CaptureRun:
    """Stand-in for a TPUModel's jitted forward during export: records
    every (weights, inputs) call so export sees EXACTLY the arrays the
    real transform path builds (coercion, padding, sharding, dtype
    casts included), while still computing through jit so transform's
    readback works. ``jitted`` is supplied by the caller so a SHARDED
    model's capture computes through the same explicit-shardings jit
    the live replica would."""

    def __init__(self, jitted: Callable):
        self.jitted = jitted
        self.calls: List[Tuple[Any, Dict[str, Any]]] = []

    def __call__(self, weights, inputs):
        self.calls.append((weights, inputs))
        return self.jitted(weights, inputs)


def _export_tpu_model(model, example, out_dir: str,
                      version: str) -> Dict[str, Any]:
    import jax
    from mmlspark_tpu.core.table import DataTable
    from mmlspark_tpu.models.tpu_model import TPUModel
    je = _jax_export()
    table = example if isinstance(example, DataTable) \
        else DataTable(dict(example))
    if len(table) == 0:
        raise ValueError("export needs at least one example row")

    # export clone: a SINGLE-device mesh for plain models (one replica
    # = one chip; the fleet replicates), or the model's OWN mesh
    # sharding for sharded models — jax.export carries the declared
    # shardings, so a multi-chip replica loads the artifact and serves
    # from its mesh with zero traces, exactly like a single-chip one
    sharding_blob = _model_sharding_blob(model)
    clone = TPUModel(modelFn=model.get("modelFn"),
                     weights=model.get("weights"),
                     feedDict=model.get("feedDict"),
                     fetchDict=model.get("fetchDict"),
                     batchSize=model.get("batchSize"),
                     computeDtype=model.get("computeDtype"),
                     inputCol=model.get("inputCol"),
                     outputCol=model.get("outputCol"),
                     precision=model.get("precision"))
    if sharding_blob is not None:
        clone.set_sharding(_rebuild_mesh(sharding_blob["axes"]),
                           weight_specs=sharding_blob["weight_specs"],
                           in_spec=sharding_blob["in_spec"],
                           out_spec=sharding_blob["out_spec"])
    else:
        clone.set_mesh(_single_device_mesh())

    model_fn = clone.get("modelFn")

    def run(weights, inputs):
        out = model_fn(weights, inputs)
        if not isinstance(out, dict):
            out = {"output": out}
        return out

    def make_jit():
        if sharding_blob is not None:
            return clone._jit_sharded(run, donate=())
        return jax.jit(run)

    def load_shardings(rec):
        """The (weights, inputs) sharding trees the LOAD side lowers
        against (None/None when unsharded)."""
        if sharding_blob is None:
            return None, None
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P
        mesh = clone._sharding["mesh"]
        w_sh = jax.tree_util.tree_map(
            lambda _leaf, s: NamedSharding(mesh, s),
            rec["weights_avals"], sharding_blob["weight_specs"],
            is_leaf=_is_aval_leaf)
        return w_sh, NamedSharding(mesh, sharding_blob["in_spec"])

    capture = _CaptureRun(make_jit())
    clone._jitted["run"] = capture      # transform uses it verbatim
    records: List[Dict[str, Any]] = []
    with _artifact_cache(out_dir):
        for b in clone.bucket_sizes():
            idx = np.resize(np.arange(len(table)), b)
            clone.transform(table._take_indices(idx))
        seen = set()
        for weights_dev, inputs in capture.calls:
            sig = input_signature(inputs)
            if sig in seen:
                continue
            seen.add(sig)
            rec = {"key": sig, "weights_avals": _avals_of(weights_dev),
                   "inputs_avals": _avals_of(inputs)}
            if je is not None:
                exp = je.export(make_jit())(weights_dev, inputs)
                rec["blob"] = exp.serialize()
                # seed the cache with the LOAD-side compile (the
                # deserialized module's HLO differs from the jit
                # trace's, so the load path needs its own entry)
                w_sh, in_sh = load_shardings(rec)
                jax.jit(je.deserialize(rec["blob"]).call).lower(
                    _avals_to_structs(rec["weights_avals"], w_sh),
                    _avals_to_structs(rec["inputs_avals"],
                                      in_sh)).compile()
            records.append(rec)

    with open(os.path.join(out_dir, _PROGRAMS), "wb") as f:
        pickle.dump(records, f)
    host_weights = jax.tree_util.tree_map(np.asarray,
                                          model.get("weights"))
    with open(os.path.join(out_dir, _WEIGHTS), "wb") as f:
        pickle.dump(host_weights, f)
    with open(os.path.join(out_dir, _MODEL_FN), "wb") as f:
        pickle.dump(model.get("modelFn"), f)
    example_cols = {c: np.asarray(table[c][:1]).tolist()
                    if isinstance(table[c], np.ndarray)
                    else list(table[c][:1]) for c in table.column_names}
    with open(os.path.join(out_dir, _EXAMPLE), "wb") as f:
        pickle.dump(example_cols, f)
    field = list(clone._feeds().values())[0]
    req = {field: np.asarray(table[field][:1]).ravel().tolist()}
    with open(os.path.join(out_dir, _EXAMPLE_REQUEST), "w") as f:
        json.dump(req, f)
    manifest = {
        "artifact_version": ARTIFACT_VERSION,
        "kind": "tpu_model",
        "format": FORMAT_JAX_EXPORT if je is not None
        else FORMAT_TRACE_CACHE,
        "version": version,
        "precision": model.get("precision"),
        "buckets": clone.bucket_sizes(),
        "programs": len(records),
        "batch_size": int(model.get("batchSize")),
        "compute_dtype": model.get("computeDtype"),
        "int_input": bool(getattr(model.get("modelFn"), "int_input",
                                  False)),
        "feeds": clone._feeds(),
        "fetches": clone._fetches(),
        "serve": {"field": field},
        "backend": _backend(),
        "jax_version": _jax_version(),
    }
    if sharding_blob is not None:
        manifest["sharded"] = True
        manifest["mesh"] = sharding_blob["axes"]
        with open(os.path.join(out_dir, _SHARDING), "wb") as f:
            pickle.dump(sharding_blob, f)
    _write_manifest(out_dir, manifest)
    return manifest


def _segment_shardings(seg):
    """A sharded FusedSegment's (consts, env) in-sharding trees — the
    same placement ``FusedSegment._jit_sharded`` declares."""
    sh = seg.sharding
    return ([sh.const_sharding(op.name) for op in seg.ops],
            sh.env_sharding())


def _segment_record_shardings(seg, rec):
    """The sharding trees a record's avals lower against at LOAD time
    (None/None for unsharded segments). Prefix shardings expand to
    full trees so ``_avals_to_structs`` can zip leaf-for-leaf."""
    import jax
    from jax.sharding import Sharding
    if seg.sharding is None:
        return None, None
    consts_in, env_sh = _segment_shardings(seg)
    full = []
    for sh_i, avals_i in zip(consts_in, rec["consts_avals"]):
        if isinstance(sh_i, Sharding):
            full.append(jax.tree_util.tree_map(
                lambda _leaf, _s=sh_i: _s, avals_i,
                is_leaf=_is_aval_leaf))
        else:
            full.append(sh_i)
    return full, env_sh


@contextlib.contextmanager
def _capture_segment_calls():
    """Export-time hook: wrap ``FusedSegment.compiled`` so every fused
    dispatch records (segment, consts, env) — the exact arrays the
    serving path builds (bucket padding included)."""
    from mmlspark_tpu.core import fusion as FZ
    orig = FZ.FusedSegment.compiled
    calls: List[Tuple[Any, Any, Dict[str, Any]]] = []

    def wrapper(self, donate):
        real = orig(self, donate)

        def capture(consts, env):
            calls.append((self, consts, env))
            return real(consts, env)

        return capture

    FZ.FusedSegment.compiled = wrapper
    try:
        yield calls
    finally:
        FZ.FusedSegment.compiled = orig


def _export_pipeline(pipeline, example, out_dir: str,
                     version: str) -> Dict[str, Any]:
    import jax
    from mmlspark_tpu.core.fusion import FusedPipelineModel
    from mmlspark_tpu.core.table import DataTable
    from mmlspark_tpu.serving.fleet import _FusedPipelineScorer
    je = _jax_export()
    fused = pipeline if isinstance(pipeline, FusedPipelineModel) \
        else pipeline.fused()
    table = example if isinstance(example, DataTable) \
        else DataTable(dict(example))
    if len(table) == 0:
        raise ValueError("export needs at least one example row")
    scorer = _FusedPipelineScorer(fused, batch_size=fused.batch_size)

    with _artifact_cache(out_dir), _capture_segment_calls() as calls:
        scorer.warmup(table)
        if not calls:
            raise ValueError(
                "nothing to AOT-export: the serving plan has no fused "
                "segment (host-only pipelines have no compiled programs "
                "to serialize)")
        # resolve each captured segment to its step index in the
        # serving plan (the plan load_model will rebuild)
        plan = None
        for p in fused._plans.values():
            if any(step is calls[0][0] for step in p.steps):
                plan = p
                break
        if plan is None:
            raise RuntimeError("serving plan not found after warmup")
        records = []
        seen = set()
        for seg, consts, env in calls:
            step = next(i for i, s in enumerate(plan.steps) if s is seg)
            sig = seg.env_signature(env)
            if (step, sig) in seen:
                continue
            seen.add((step, sig))
            rec = {"step": step, "key": sig,
                   "consts_avals": _avals_of(consts),
                   "env_avals": _avals_of(env)}
            if je is not None:
                fn = seg._make_fn(count_traces=False)
                if seg.sharding is not None:
                    # mesh-sharded segment: export the same explicit-
                    # shardings program the live replica runs (the env
                    # arrays captured here are already placed per spec)
                    consts_in, env_sh = _segment_shardings(seg)
                    jitted = jax.jit(fn, in_shardings=(consts_in,
                                                       env_sh),
                                     out_shardings=env_sh)
                else:
                    jitted = jax.jit(fn)
                exp = je.export(jitted)(consts, env)
                rec["blob"] = exp.serialize()
                c_sh, e_sh = _segment_record_shardings(seg, rec)
                jax.jit(je.deserialize(rec["blob"]).call).lower(
                    _avals_to_structs(rec["consts_avals"], c_sh),
                    _avals_to_structs(rec["env_avals"], e_sh)).compile()
            records.append(rec)

    with open(os.path.join(out_dir, _PROGRAMS), "wb") as f:
        pickle.dump(records, f)
    with open(os.path.join(out_dir, _PIPELINE), "wb") as f:
        pickle.dump({"stages": fused.get_stages(),
                     "in_schema": plan.in_schema,
                     "final_needed": plan.final_needed,
                     "reply_col": scorer.reply_col,
                     "row_names": list(scorer._row_names)}, f)
    rows = [dict(zip(table.column_names,
                     (table[c][0] for c in table.column_names)))]
    with open(os.path.join(out_dir, _EXAMPLE), "wb") as f:
        pickle.dump({c: [table[c][0]] for c in table.column_names}, f)
    from mmlspark_tpu.io.http import _jsonable
    with open(os.path.join(out_dir, _EXAMPLE_REQUEST), "w") as f:
        json.dump({k: _jsonable(v) for k, v in rows[0].items()}, f)
    manifest = {
        "artifact_version": ARTIFACT_VERSION,
        "kind": "pipeline",
        "format": FORMAT_JAX_EXPORT if je is not None
        else FORMAT_TRACE_CACHE,
        "version": version,
        "precision": fused.precision,
        "buckets": fused.bucket_sizes(),
        "programs": len(records),
        "batch_size": int(fused.batch_size),
        "serve": {"reply_col": scorer.reply_col},
        "backend": _backend(),
        "jax_version": _jax_version(),
    }
    if fused.sharding is not None:
        sh = fused.sharding
        axes = {str(k): int(v) for k, v in sh.mesh.shape.items()}
        manifest["sharded"] = True
        manifest["mesh"] = axes
        with open(os.path.join(out_dir, _SHARDING), "wb") as f:
            pickle.dump({"kind": "pipeline", "axes": axes,
                         "data_axis": sh.data_axis,
                         "const_specs": sh.const_specs}, f)
    _write_manifest(out_dir, manifest)
    return manifest


def _backend() -> str:
    import jax
    return jax.default_backend()


def _jax_version() -> str:
    import jax
    return jax.__version__


# ---------------------------------------------------------------------------
# load
# ---------------------------------------------------------------------------


class _LazyModelFn:
    """Placeholder model fn on an AOT-loaded model: carries the traced
    model's ``int_input`` flag (the transform path needs it to coerce
    feeds) without unpickling — calling it means the lazy fallback
    failed to load."""

    def __init__(self, int_input: bool):
        self.int_input = bool(int_input)

    def __call__(self, *a, **kw):
        raise RuntimeError("AOT placeholder model fn invoked; the "
                           "fallback failed to load")


def _compile_record(rec, avals_args) -> Optional[Callable]:
    """One serialized program -> a callable executable (pre-compiled at
    load — the only XLA work a replica does, and a cache disk-hit when
    the artifact's seeded xla_cache rode along)."""
    import jax
    je = _jax_export()
    if "blob" not in rec or je is None:
        return None
    exp = je.deserialize(rec["blob"])
    return jax.jit(exp.call).lower(*avals_args).compile()


def load_model(art_dir: str):
    """Rebuild a served model from an AOT artifact: deserialize the
    pre-compiled (bucket, program) executables and return a model that
    serves with ZERO jit traces at request time. Returns an
    ``AOTTPUModel`` (tpu_model kind) or a ``FusedPipelineModel`` with
    AOT programs installed (pipeline kind); both carry
    ``aot=True`` + the artifact's ``precision`` for the
    serving_model_info labels, and slot straight into
    ``json_scoring_pipeline`` / ``ServingEngine.swap``."""
    manifest = read_manifest(art_dir)
    if manifest["kind"] == "tpu_model":
        return _load_tpu_model(art_dir, manifest)
    if manifest["kind"] == "pipeline":
        return _load_pipeline(art_dir, manifest)
    raise ValueError(f"unknown artifact kind {manifest['kind']!r}")


_AOT_MODEL_CLS = None


def _aot_model_class():
    """The AOTTPUModel class, built once on first load (TPUModel pulls
    in jax, which this module keeps out of import time)."""
    global _AOT_MODEL_CLS
    if _AOT_MODEL_CLS is not None:
        return _AOT_MODEL_CLS
    from mmlspark_tpu.models.tpu_model import TPUModel

    class AOTTPUModel(TPUModel):
        """TPUModel whose compiled-call dispatch goes straight to the
        artifact's pre-compiled executables (by input signature). The
        model fn is NOT loaded — an unseen shape lazily unpickles it,
        traces, and counts a jit_cache_miss like any recompile."""

        def _post_init(self):
            super()._post_init()
            self.aot = True
            self._aot_programs: Dict[Tuple, Callable] = {}
            self._artifact_dir: Optional[str] = None

        def _fallback(self) -> Callable:
            # check-then-set under the model's init lock: two workers
            # hitting unseen shapes at once must not both unpickle —
            # the second set("modelFn") would wipe _jitted and re-trace
            # every fallback shape the first already compiled. The jit
            # build itself happens in super()._compiled() OUTSIDE this
            # block (the lock is not reentrant).
            with self._init_lock:
                if isinstance(self.get("modelFn"), (_LazyModelFn,
                                                    type(None))):
                    path = os.path.join(self._artifact_dir, _MODEL_FN)
                    if not os.path.exists(path):
                        raise RuntimeError(
                            "AOT artifact has no model_fn fallback and "
                            "this input shape was never exported")
                    with open(path, "rb") as f:
                        self.set("modelFn", pickle.load(f))
            return super()._compiled()

        def _compiled(self) -> Callable:
            progs = self._aot_programs
            if not progs:
                return self._fallback()
            model = self

            def dispatch(weights, inputs):
                prog = progs.get(input_signature(inputs))
                if prog is not None:
                    return prog(weights, inputs)
                return model._fallback()(weights, inputs)

            return dispatch

    _AOT_MODEL_CLS = AOTTPUModel
    return AOTTPUModel


def _model_kwargs(manifest: Dict[str, Any],
                  weights: Any) -> Dict[str, Any]:
    """The ONE manifest -> TPUModel constructor mapping, shared by
    ``load_model`` and the cold-start runner's trace-mode rebuild so
    the two replicas being compared are configured identically."""
    return dict(
        weights=weights, batchSize=manifest["batch_size"],
        computeDtype=manifest.get("compute_dtype", "float32"),
        feedDict=manifest.get("feeds"),
        fetchDict=manifest.get("fetches"),
        inputCol=manifest["serve"]["field"],
        outputCol=list(manifest["fetches"])[0],
        precision=manifest.get("precision", "f32"))


def _load_tpu_model(art_dir: str, manifest: Dict[str, Any]):
    with open(os.path.join(art_dir, _WEIGHTS), "rb") as f:
        weights = pickle.load(f)
    with open(os.path.join(art_dir, _PROGRAMS), "rb") as f:
        records = pickle.load(f)
    model = _aot_model_class()(
        modelFn=_LazyModelFn(manifest.get("int_input", False)),
        **_model_kwargs(manifest, weights))
    model._artifact_dir = art_dir
    sharding_blob = _load_sharding_blob(art_dir) \
        if manifest.get("sharded") else None
    w_sh = in_sh = None
    if sharding_blob is not None:
        # the multi-chip replica: same mesh shape, this process's
        # devices; the unseen-shape jit fallback is sharded too
        mesh = _rebuild_mesh(sharding_blob["axes"])
        model.set_sharding(mesh,
                           weight_specs=sharding_blob["weight_specs"],
                           in_spec=sharding_blob["in_spec"],
                           out_spec=sharding_blob["out_spec"])
        import jax
        from jax.sharding import NamedSharding
        in_sh = NamedSharding(mesh, sharding_blob["in_spec"])
    else:
        model.set_mesh(_single_device_mesh())
    with _artifact_cache(art_dir):
        for rec in records:
            if sharding_blob is not None and w_sh is None:
                # one NamedSharding tree serves every record: the
                # specs and mesh never change between buckets
                w_sh = jax.tree_util.tree_map(
                    lambda _leaf, s: NamedSharding(mesh, s),
                    rec["weights_avals"],
                    sharding_blob["weight_specs"],
                    is_leaf=_is_aval_leaf)
            co = _compile_record(
                rec, (_avals_to_structs(rec["weights_avals"], w_sh),
                      _avals_to_structs(rec["inputs_avals"], in_sh)))
            if co is not None:
                model._aot_programs[tuple(map(tuple, rec["key"]))] = co
    if not model._aot_programs:
        # trace-cache format: programs re-trace through the fallback,
        # but compiles hit the artifact's seeded cache. Load the fn
        # eagerly and warm every bucket here (still off the hot path).
        model._fallback()
        with open(os.path.join(art_dir, _EXAMPLE), "rb") as f:
            example = pickle.load(f)
        with _artifact_cache(art_dir):
            model.warmup(example)
    return model


def _load_pipeline(art_dir: str, manifest: Dict[str, Any]):
    from mmlspark_tpu.core.fusion import FusedPipelineModel, FusedSegment
    with open(os.path.join(art_dir, _PIPELINE), "rb") as f:
        meta = pickle.load(f)
    with open(os.path.join(art_dir, _PROGRAMS), "rb") as f:
        records = pickle.load(f)
    fused = FusedPipelineModel(meta["stages"],
                               batch_size=manifest["batch_size"])
    sharding_blob = _load_sharding_blob(art_dir) \
        if manifest.get("sharded") else None
    if sharding_blob is not None:
        fused.shard(_rebuild_mesh(sharding_blob["axes"]),
                    data_axis=sharding_blob["data_axis"],
                    const_specs=sharding_blob.get("const_specs"))
    plan = fused.plan_for(meta["in_schema"], meta["final_needed"])
    with _artifact_cache(art_dir):
        for rec in records:
            step = plan.steps[rec["step"]]
            if not isinstance(step, FusedSegment):
                raise RuntimeError(
                    f"artifact step {rec['step']} is not a fused segment"
                    f" in the rebuilt plan — stage list drifted")
            c_sh, e_sh = _segment_record_shardings(step, rec)
            co = _compile_record(
                rec, (_avals_to_structs(rec["consts_avals"], c_sh),
                      _avals_to_structs(rec["env_avals"], e_sh)))
            if co is not None:
                step.install_aot({tuple(map(tuple, rec["key"])): co})
    fused.aot = True
    return fused


# ---------------------------------------------------------------------------
# cold-start runner (bench + floor test drive this as a fresh process)
# ---------------------------------------------------------------------------


def _force_mesh_devices(manifest: Dict[str, Any]) -> None:
    """A sharded artifact needs as many devices as its export mesh.
    On a CPU host (tests/bench: the forced-host-device-count recipe)
    give this process enough VIRTUAL cpu devices BEFORE first backend
    use; on a real accelerator the topology is what it is and a
    mismatch surfaces as jax.export's own count error."""
    mesh = manifest.get("mesh")
    if not mesh:
        return
    import math
    platforms = os.environ.get("JAX_PLATFORMS", "")
    if "cpu" not in platforms.split(","):
        return
    from mmlspark_tpu.utils.jax_compat import set_cpu_device_count
    set_cpu_device_count(math.prod(int(v) for v in mesh.values()))


def _coldstart(art_dir: str, mode: str, port: int,
               t0: float) -> Dict[str, Any]:
    """Build a serving replica from the artifact and time process-start
    -> first HTTP 200. ``mode='aot'`` loads the pre-compiled
    executables; ``mode='trace'`` rebuilds the model from weights +
    model fn and pays the per-bucket trace+compile warmup — today's
    trace-at-startup replica, the baseline the AOT path retires."""
    import urllib.request
    manifest = read_manifest(art_dir)
    _force_mesh_devices(manifest)
    if mode == "aot":
        model = load_model(art_dir)
    elif manifest["kind"] == "pipeline":
        from mmlspark_tpu.core.fusion import FusedPipelineModel
        with open(os.path.join(art_dir, _PIPELINE), "rb") as f:
            meta = pickle.load(f)
        model = FusedPipelineModel(meta["stages"],
                                   batch_size=manifest["batch_size"])
        blob = _load_sharding_blob(art_dir) \
            if manifest.get("sharded") else None
        if blob is not None:
            # the trace-mode baseline replica shards the same way the
            # AOT one does — the two cold starts being compared differ
            # ONLY in where the compiles come from
            model.shard(_rebuild_mesh(blob["axes"]),
                        data_axis=blob["data_axis"],
                        const_specs=blob.get("const_specs"))
    else:
        from mmlspark_tpu.models.tpu_model import TPUModel
        with open(os.path.join(art_dir, _WEIGHTS), "rb") as f:
            weights = pickle.load(f)
        with open(os.path.join(art_dir, _MODEL_FN), "rb") as f:
            model_fn = pickle.load(f)
        model = TPUModel(modelFn=model_fn,
                         **_model_kwargs(manifest, weights))
        blob = _load_sharding_blob(art_dir) \
            if manifest.get("sharded") else None
        if blob is not None:
            model.set_sharding(_rebuild_mesh(blob["axes"]),
                               weight_specs=blob["weight_specs"],
                               in_spec=blob["in_spec"],
                               out_spec=blob["out_spec"])
        else:
            model.set_mesh(_single_device_mesh())

    from mmlspark_tpu.core.table import DataTable
    from mmlspark_tpu.serving.fleet import json_scoring_pipeline
    from mmlspark_tpu.serving.server import HTTPSource, ServingEngine
    kwargs = {} if manifest["kind"] == "pipeline" \
        else {"field": manifest["serve"]["field"]}
    stage = json_scoring_pipeline(model, **kwargs)
    # warm through the SERVING path (the production replica discipline:
    # the swap protocol's warmup hook). AOT mode pays signature-hits;
    # trace mode pays the per-bucket trace+compile this module retires.
    with open(os.path.join(art_dir, _EXAMPLE), "rb") as f:
        example = pickle.load(f)
    warmup = getattr(stage, "warmup", None)
    if callable(warmup):
        warmup(DataTable(dict(example))
               if manifest["kind"] == "pipeline" else example)
    t_ready = time.perf_counter()
    source = HTTPSource(port=port)
    engine = ServingEngine(source, stage, batch_size=64,
                           version=manifest.get("version", "v0"),
                           tracing=False).start()
    with open(os.path.join(art_dir, _EXAMPLE_REQUEST), "rb") as f:
        body = f.read()
    misses_before = int(model.jit_cache_misses)
    req = urllib.request.Request(source.address, data=body,
                                 headers={"Content-Type":
                                          "application/json"})
    with urllib.request.urlopen(req, timeout=60) as resp:
        code = resp.status
        resp.read()
    t_200 = time.perf_counter()
    request_traces = int(model.jit_cache_misses) - misses_before
    engine.stop()
    return {
        "mode": mode,
        "ok": code == 200,
        "cold_start_to_first_200_ms": round((t_200 - t0) * 1e3, 1),
        "model_ready_ms": round((t_ready - t0) * 1e3, 1),
        "first_request_ms": round((t_200 - t_ready) * 1e3, 1),
        "jit_traces_total": int(model.jit_cache_misses),
        "jit_traces_at_request_time": request_traces,
        "precision": manifest.get("precision", "f32"),
        "format": manifest.get("format"),
        "backend": _backend(),
    }


def main(argv: Optional[List[str]] = None) -> int:
    # the clock starts HERE — before jax/flax/model imports, which are
    # all lazy in this module precisely so a fresh replica's import
    # cost lands inside the measured window for BOTH modes
    t0 = time.perf_counter()
    import argparse
    ap = argparse.ArgumentParser(
        description="AOT serving artifact cold-start runner")
    ap.add_argument("artifact", help="artifact directory (export_model)")
    ap.add_argument("--mode", choices=["aot", "trace"], default="aot")
    ap.add_argument("--port", type=int, default=18980)
    args = ap.parse_args(argv)
    out = _coldstart(args.artifact, args.mode, args.port, t0)
    print(json.dumps(out))
    return 0 if out["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
