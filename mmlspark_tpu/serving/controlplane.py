"""Closed-loop continuous training: drift -> refit -> shadow -> canary.

The reference's serving layer was a *streaming* service (ref:
src/io/http DistributedHTTPSource.scala): models live behind live
traffic indefinitely, so a model fit once is a model drifting forever.
Every mechanism this loop needs already exists in the codebase —
``DriftMonitor`` (core/metrics.py), incremental refits via
``partial_fit``/``boost_more``, the canary swap protocol with
auto-rollback (serving/lifecycle.py), SLO burn-rate alerts + the
flight recorder (core/slo.py, core/flightrecorder.py), and the bounded
``ReplayWindow`` over chunked ingest (io/ooc.py). This module is the
*control plane* that connects them into one supervised loop
(the TFX production lesson, Baylor et al. KDD'17: continuous training
is only safe with automated validation gates and rollback on EVERY
path):

::

            +--------------------- idle <--------------------+
            | trigger (drift breach | SLO burn alert)        |
            v                                                |
        refitting --(retries exhausted)--> idle/degraded     |
            | partial_fit / boost_more on the replay window  |
            v                                                |
        shadowing --(gate FAIL)--> quarantine (+ bundle) ----+
            | candidate vs baseline on the freshest traffic  |
            v                                                |
        promoting --(canary breach)--> quarantine (+ bundle)-+
            | execute_swap: warmup -> canary -> cutover      |
            +--- promoted ----------------------------------+

Design rules (audited by ``tools/check_fusion_kernels.py``'s
``check_control_loop``):

- **One transition funnel.** Every ``self.state`` write goes through
  ``_transition``, and ``_transition`` records a timeline event — the
  registry event log (next to ``SwapEvent``/``ZooEvent``/
  ``AlertEvent``) is a complete, ordered record of every decision the
  loop ever made.
- **Dedicated trainer thread.** Refits and shadow validation run ONLY
  on the ``controlplane-trainer`` thread — never on the engine's
  batcher or worker threads. Training work on the serving hot path is
  the failure mode this loop exists to prevent.
- **Training death never takes serving down.** Repeated refit failures
  open a circuit (state ``degraded``); a dead trainer thread degrades
  ``/healthz`` (still HTTP 200) — in both cases the engine keeps
  serving the frozen model untouched.
- **Quarantine keeps the evidence.** A candidate that fails the gate
  (or rolls back in canary) is never promoted; its gate verdict and a
  flight-recorder bundle are retained on the trainer
  (``quarantined[version]``) and a ``QuarantineEvent`` lands on the
  timeline.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from mmlspark_tpu.core.logging_utils import get_logger
from mmlspark_tpu.core.metrics import controlplane_histograms
from mmlspark_tpu.io.ooc import ChunkedTable, ReplayWindow
from mmlspark_tpu.serving.lifecycle import (
    CanaryPolicy, ModelRegistry, execute_swap,
)

log = get_logger("serving.controlplane")

# loop states (trainer.state / healthz controlplane.state)
IDLE = "idle"
REFITTING = "refitting"
SHADOWING = "shadowing"
PROMOTING = "promoting"
DEGRADED = "degraded"
STOPPED = "stopped"

_TRAINER_THREAD_NAME = "controlplane-trainer"


class _ControlEvent:
    """Base typed record for one control-loop decision. Shares the
    ``SwapEvent``/``ZooEvent`` duck-typed shape (``kind``/``at``/
    ``version``/``reason``/``stats``) so the flight recorder and the
    registry timeline render all five families side by side."""

    def __init__(self, kind: str, version: str = "",
                 reason: str = "",
                 stats: Optional[Dict[str, Any]] = None):
        self.kind = kind
        self.version = version
        self.reason = reason
        self.stats = dict(stats or {})
        self.at = time.time()

    def __repr__(self) -> str:
        extra = f", reason={self.reason!r}" if self.reason else ""
        v = f", {self.version!r}" if self.version else ""
        return f"{type(self).__name__}({self.kind}{v}{extra})"


class RetrainEvent(_ControlEvent):
    """Loop + refit lifecycle: ``loop_started``/``loop_stopped``,
    ``triggered``, ``refit_ok``/``refit_failed``, ``circuit_open``/
    ``circuit_closed``, ``trainer_error``."""


class ShadowEvent(_ControlEvent):
    """Shadow validation: ``shadow_pass``/``shadow_fail`` with the full
    gate verdict in ``stats``."""


class PromoteEvent(_ControlEvent):
    """Promotion: ``promote_started`` (gate passed, canary launching)
    and ``promoted`` (cutover complete)."""


class QuarantineEvent(_ControlEvent):
    """A candidate rejected by the gate or rolled back in canary —
    never promoted; ``stats`` carries the verdict summary and the
    evidence bundle stays on ``trainer.quarantined[version]``."""


class TriggerPolicy:
    """When the loop launches a refit.

    - drift floors: a ``DriftMonitor`` summary breaching
      ``max_mean_delta_sigma`` (|mean shift| in fit-time sigma units),
      ``max_var_ratio``, or ``max_null_rate`` triggers.
    - ``watch_slo_alerts``: an active SLO burn-rate alert triggers.
    - ``min_drift_rows``: drift verdicts on fewer observed rows are
      noise, not a trigger.
    - ``min_window_rows``: no refit until the replay window holds at
      least this many labeled rows.
    - ``cooldown_s``: quiet period after any completed cycle (promoted,
      quarantined, or failed) before the next trigger fires.
    """

    def __init__(self, max_mean_delta_sigma: float = 3.0,
                 max_var_ratio: Optional[float] = 16.0,
                 max_null_rate: float = 0.01,
                 watch_slo_alerts: bool = True,
                 min_drift_rows: int = 64,
                 min_window_rows: int = 64,
                 cooldown_s: float = 5.0):
        self.max_mean_delta_sigma = float(max_mean_delta_sigma)
        self.max_var_ratio = (None if max_var_ratio is None
                              else float(max_var_ratio))
        self.max_null_rate = float(max_null_rate)
        self.watch_slo_alerts = bool(watch_slo_alerts)
        self.min_drift_rows = int(min_drift_rows)
        self.min_window_rows = int(min_window_rows)
        self.cooldown_s = float(cooldown_s)


class GatePolicy:
    """The shadow-validation floors a candidate must clear before it
    may even *canary* (the verifyResult discipline applied to refits).

    - ``shadow_rows``: freshest window rows to score both sides on.
    - ``min_rows``: fewer shadow rows than this fails the gate (no
      promote on thin evidence — the decision-timeout discipline).
    - ``max_nan_rate``: non-finite candidate predictions above this
      fraction fail (a NaN-poisoned refit dies here).
    - ``max_divergence``: candidate-vs-baseline disagreement rate
      (classification) or normalized mean absolute delta (regression)
      above this fails — a candidate that rewrites most answers is a
      different model, not a refresh, and needs a human.
    - ``min_quality_delta``: candidate quality minus baseline quality
      (accuracy, or negative RMSE) must be at least this (default
      allows a small regression; a label-flipped refit craters it).
    """

    def __init__(self, shadow_rows: int = 512, min_rows: int = 32,
                 max_nan_rate: float = 0.0,
                 max_divergence: float = 0.5,
                 min_quality_delta: float = -0.02):
        self.shadow_rows = int(shadow_rows)
        self.min_rows = int(min_rows)
        self.max_nan_rate = float(max_nan_rate)
        self.max_divergence = float(max_divergence)
        self.min_quality_delta = float(min_quality_delta)


class RefitPolicy:
    """Fault tolerance of the refit step itself.

    - ``max_attempts`` / ``backoff_s`` (doubling): transient refit
      failures retry with backoff inside one cycle.
    - ``circuit_after``: consecutive FAILED CYCLES that open the
      circuit — the loop stops trying (state ``degraded``, serving
      continues frozen) until ``circuit_reset_s`` elapses, then
      half-opens for one probe cycle.
    """

    def __init__(self, max_attempts: int = 3, backoff_s: float = 0.2,
                 circuit_after: int = 3,
                 circuit_reset_s: float = 30.0):
        self.max_attempts = max(1, int(max_attempts))
        self.backoff_s = float(backoff_s)
        self.circuit_after = max(1, int(circuit_after))
        self.circuit_reset_s = float(circuit_reset_s)


class IngestDriver:
    """Feeds micro-batches from a chunk source into a ``ReplayWindow``
    on its own daemon thread — the live labeled-data stream of the
    continuous loop (labels arrive out of band of serving traffic).

    ``source`` is a zero-arg factory of chunks (the ``ChunkedTable``
    factory contract) or a ``ChunkedTable``; ``interval_s`` paces the
    feed. The driver loops the source when ``loop=True`` (soak
    harnesses) and stops at stream end otherwise."""

    def __init__(self, source: Any, window: ReplayWindow,
                 interval_s: float = 0.0, loop: bool = False,
                 on_chunk: Optional[Callable[[Any], None]] = None):
        if isinstance(source, ChunkedTable):
            self._factory = source._factory
        elif callable(source):
            self._factory = source
        else:
            raise TypeError("IngestDriver needs a ChunkedTable or a "
                            "zero-arg chunk factory")
        self.window = window
        self.interval_s = float(interval_s)
        self.loop = bool(loop)
        self.on_chunk = on_chunk
        self.chunks_fed = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "IngestDriver":
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="controlplane-ingest")
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.is_set():
            for chunk in self._factory():
                if self._stop.is_set():
                    return
                self.window.append(chunk)
                self.chunks_fed += 1
                if self.on_chunk is not None:
                    try:
                        self.on_chunk(chunk)
                    except Exception:  # noqa: BLE001 — observer only
                        pass
                if self.interval_s > 0:
                    self._stop.wait(self.interval_s)
            if not self.loop:
                return

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5)


class ContinuousTrainer:
    """The long-running control loop on one ``ServingEngine``.

    ``refit`` is the model-family hook: a callable
    ``(window: ChunkedTable, active_pipeline) -> candidate_pipeline``
    that runs the incremental update (``partial_fit`` for linear
    models, ``boost_more`` for GBDT) over the replay window and wraps
    the result for serving (e.g. ``json_scoring_pipeline``). It runs
    ONLY on the trainer thread.

    Recovery is idempotent: version names are derived from the
    registry (``{prefix}-N`` past the highest already registered), so
    a trainer restarted after an engine crash resumes the sequence
    instead of colliding; ``state_dict()``/``load_state()`` carry the
    counters and quarantine verdicts across restarts.
    """

    history_cap = 1024

    def __init__(self, engine, refit: Callable[[ChunkedTable, Any], Any],
                 window: Optional[ReplayWindow] = None,
                 registry: Optional[ModelRegistry] = None,
                 drift_monitor: Any = None,
                 triggers: Optional[TriggerPolicy] = None,
                 gate: Optional[GatePolicy] = None,
                 refit_policy: Optional[RefitPolicy] = None,
                 canary: Optional[CanaryPolicy] = None,
                 warmup_example: Any = None,
                 version_prefix: str = "ct",
                 poll_interval_s: float = 0.25,
                 features_col: str = "features",
                 label_col: str = "label",
                 predict_fn: Optional[Callable] = None,
                 quality_fn: Optional[Callable] = None,
                 state: Optional[Dict[str, Any]] = None):
        self.engine = engine
        self.refit = refit
        self.window = window if window is not None else ReplayWindow()
        self.registry = registry if registry is not None \
            else ModelRegistry()
        # None = resolve dynamically from the ACTIVE pipeline at every
        # check (serving/fleet.py attaches the monitor the serving path
        # observes into) — so a promoted candidate carrying a fresh
        # monitor rebuilt from the window takes over the watch
        self.drift_monitor = drift_monitor
        self.triggers = triggers or TriggerPolicy()
        self.gate = gate or GatePolicy()
        self.refit_policy = refit_policy or RefitPolicy()
        self.canary = canary or CanaryPolicy()
        self.warmup_example = warmup_example
        self.version_prefix = str(version_prefix)
        self.poll_interval_s = float(poll_interval_s)
        self.features_col = features_col
        self.label_col = label_col
        self.predict_fn = predict_fn
        self.quality_fn = quality_fn

        self.state = IDLE
        self.history: List[_ControlEvent] = []
        self.quarantined: Dict[str, Dict[str, Any]] = {}
        self.refits = 0
        self.refit_failures = 0
        self.promotions = 0
        self.quarantines = 0
        self.cycles = 0
        self.consecutive_failures = 0
        self.circuit_open = False
        self.last_trigger: Optional[str] = None
        self._version_counter = 0
        self._cooldown_until = 0.0
        self._circuit_opened_at = 0.0
        self._forced_trigger: Optional[str] = None
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._die = threading.Event()    # chaos: abrupt thread death
        self._thread: Optional[threading.Thread] = None
        self._started = False
        self._stopped = False
        self._hists = controlplane_histograms()
        if state:
            self.load_state(state)

    # -- state persistence / idempotent recovery ----------------------------

    def state_dict(self) -> Dict[str, Any]:
        """Loop state that survives an engine restart (verdicts only —
        bundles and pipelines stay with the process that made them)."""
        with self._lock:
            return {
                "version_counter": self._version_counter,
                "refits": self.refits,
                "refit_failures": self.refit_failures,
                "promotions": self.promotions,
                "quarantines": self.quarantines,
                "cycles": self.cycles,
                "consecutive_failures": self.consecutive_failures,
                "circuit_open": self.circuit_open,
                "quarantined": {v: {"verdict": q.get("verdict"),
                                    "at": q.get("at")}
                                for v, q in self.quarantined.items()},
            }

    def load_state(self, state: Dict[str, Any]) -> None:
        with self._lock:
            self._version_counter = max(
                self._version_counter,
                int(state.get("version_counter", 0)))
            self.refits = int(state.get("refits", self.refits))
            self.refit_failures = int(
                state.get("refit_failures", self.refit_failures))
            self.promotions = int(
                state.get("promotions", self.promotions))
            self.quarantines = int(
                state.get("quarantines", self.quarantines))
            self.cycles = int(state.get("cycles", self.cycles))
            self.consecutive_failures = int(
                state.get("consecutive_failures",
                          self.consecutive_failures))
            self.circuit_open = bool(
                state.get("circuit_open", self.circuit_open))
            if self.circuit_open:
                self._circuit_opened_at = time.monotonic()
            for v, q in dict(state.get("quarantined", {})).items():
                self.quarantined.setdefault(v, dict(q))

    def _sync_version_counter(self) -> None:
        """Fast-forward the version counter past every ``{prefix}-N``
        already in the registry — restart-idempotent version naming."""
        prefix = self.version_prefix + "-"
        highest = 0
        for v in self.registry.versions():
            if v.startswith(prefix):
                try:
                    highest = max(highest, int(v[len(prefix):]))
                except ValueError:
                    continue
        with self._lock:
            self._version_counter = max(self._version_counter, highest)

    def _next_version(self) -> str:
        with self._lock:
            self._version_counter += 1
            n = self._version_counter
        return f"{self.version_prefix}-{n}"

    # -- lifecycle ----------------------------------------------------------

    def _recorder_key(self) -> str:
        return f"controlplane@{self.engine.source.address}"

    def start(self) -> "ContinuousTrainer":
        if self._started:
            return self
        self._started = True
        self._sync_version_counter()
        # register the baseline version so previous()/rollback anchors
        # exist even before the first promote
        base = self.engine._active
        if base.version not in self.registry.versions():
            try:
                self.registry.register(base.version, base.pipeline,
                                       metadata={"baseline": True})
            except ValueError:
                pass    # registered concurrently — fine
        self.engine.controlplane = self
        self.engine.source.controlplane_probe = self.status
        rec = getattr(self.engine, "flight_recorder", None)
        if rec is not None:
            key = self._recorder_key()
            # quarantine/rollback bundles carry the loop's own decision
            # timeline + status (the gate verdict travels in both)
            rec.add_event_source(f"{key}:events", lambda: self.history)
            rec.add_stats_source(key, self.status)
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name=_TRAINER_THREAD_NAME)
        self._transition(IDLE, RetrainEvent(
            "loop_started", reason="continuous training loop up",
            stats={"window_rows": self.window.rows}))
        self._thread.start()
        return self

    def stop(self) -> None:
        if not self._started or self._stopped:
            return
        self._stopped = True
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=10)
        rec = getattr(self.engine, "flight_recorder", None)
        if rec is not None:
            try:
                rec.detach(self._recorder_key())
            except Exception:  # noqa: BLE001 — best-effort detach
                pass
        self._transition(STOPPED, RetrainEvent(
            "loop_stopped", stats={"cycles": self.cycles,
                                   "promotions": self.promotions,
                                   "quarantines": self.quarantines}))

    def kill_trainer(self) -> None:
        """Chaos hook: make the trainer thread die abruptly (no
        transition, no cleanup) — the training-death drill. Serving
        must continue frozen; ``/healthz`` shows the control plane
        degraded."""
        self._die.set()

    # -- ingest -------------------------------------------------------------

    def ingest(self, chunk: Any) -> None:
        """Append one labeled micro-batch to the replay window (the
        inline alternative to an ``IngestDriver``)."""
        self.window.append(chunk)

    # -- the transition funnel (audited) ------------------------------------

    def _transition(self, state: str, event: _ControlEvent) -> None:
        """THE single state-write funnel: every loop state change lands
        its typed event on the registry timeline in the same breath.
        ``check_control_loop`` (tools/check_fusion_kernels.py) rejects
        any ``self.state`` write outside this method and any
        ``_transition`` body that stops recording."""
        with self._lock:
            self.state = state
        self._record(event)

    def _record(self, event: _ControlEvent) -> None:
        self.history.append(event)
        if len(self.history) > self.history_cap:
            del self.history[:len(self.history) - self.history_cap]
        try:
            self.registry.record_event(event)
        except Exception:  # noqa: BLE001 — the loop never dies on a
            pass           # full/broken audit log

    # -- triggers -----------------------------------------------------------

    def trigger_now(self, reason: str = "manual") -> None:
        """Queue one cycle regardless of drift/SLO state (the loop
        still runs it on the trainer thread, through the same gate)."""
        self._forced_trigger = reason

    def _monitor(self) -> Any:
        if self.drift_monitor is not None:
            return self.drift_monitor
        return getattr(self.engine._active.pipeline,
                       "drift_monitor", None)

    def _check_triggers(self) -> Optional[str]:
        forced = self._forced_trigger
        if forced is not None:
            self._forced_trigger = None
            return f"forced:{forced}"
        tp = self.triggers
        mon = self._monitor()
        if mon is not None:
            try:
                s = mon.summary()
            except Exception:  # noqa: BLE001 — a sick monitor must not
                s = {"rows": 0}  # kill the loop
            if s.get("rows", 0) >= tp.min_drift_rows:
                delta = s.get("max_abs_mean_delta_sigma", 0.0)
                if delta >= tp.max_mean_delta_sigma:
                    return (f"drift:mean_delta_sigma={delta:.2f}"
                            f">={tp.max_mean_delta_sigma:.2f}"
                            f" (feature={s.get('worst_feature')})")
                ratio = s.get("max_var_ratio", 1.0)
                if tp.max_var_ratio is not None and \
                        ratio >= tp.max_var_ratio:
                    return (f"drift:var_ratio={ratio:.2f}"
                            f">={tp.max_var_ratio:.2f}")
                nulls = s.get("null_rate", 0.0)
                if nulls >= tp.max_null_rate > 0:
                    return (f"drift:null_rate={nulls:.4f}"
                            f">={tp.max_null_rate:.4f}")
        slo = getattr(self.engine, "slo", None)
        if tp.watch_slo_alerts and slo is not None:
            try:
                active = slo.alerts.active()
            except Exception:  # noqa: BLE001
                active = []
            if active:
                a = active[0]
                return (f"slo:{a.name} burn_short={a.burn_short:.1f} "
                        f"burn_long={a.burn_long:.1f}")
        return None

    # -- the loop (trainer thread only) -------------------------------------

    def _loop(self) -> None:
        while not self._stop.is_set():
            if self._die.is_set():
                return    # chaos: abrupt death, no cleanup
            try:
                self._tick()
            except Exception as e:  # noqa: BLE001 — the loop survives
                # anything a cycle throws past its own handling
                log.warning("controlplane tick error: %s", e)
                self._record(RetrainEvent(
                    "trainer_error", reason=f"{type(e).__name__}: {e}"))
            self._stop.wait(self.poll_interval_s)

    def _tick(self) -> None:
        now = time.monotonic()
        if self.circuit_open:
            rp = self.refit_policy
            if now - self._circuit_opened_at < rp.circuit_reset_s:
                return
            # half-open: allow one probe cycle
            self.circuit_open = False
            self._transition(IDLE, RetrainEvent(
                "circuit_closed",
                reason=f"half-open probe after "
                       f"{rp.circuit_reset_s:.0f}s"))
        if now < self._cooldown_until:
            return
        if self.window.rows < self.triggers.min_window_rows:
            return
        reason = self._check_triggers()
        if reason is None:
            return
        self.last_trigger = reason
        self._cycle(reason)
        self._cooldown_until = time.monotonic() + \
            self.triggers.cooldown_s

    def _cycle(self, reason: str) -> None:
        """One full drift->refit->shadow->canary cycle. Runs on the
        trainer thread only (allowlisted in check_control_loop)."""
        self.cycles += 1
        version = self._next_version()
        snapshot = self.window.snapshot()
        self._transition(REFITTING, RetrainEvent(
            "triggered", version=version, reason=reason,
            stats={"window_rows": snapshot.num_rows}))
        baseline = self.engine._active
        t0 = time.perf_counter()
        try:
            candidate = self._run_refit(snapshot, baseline.pipeline)
        except Exception as e:  # noqa: BLE001 — a refit that exhausted
            # its retries fails the CYCLE, not the loop (and never
            # touches serving)
            self.refit_failures += 1
            self.consecutive_failures += 1
            fail = RetrainEvent(
                "refit_failed", version=version,
                reason=f"{type(e).__name__}: {e}",
                stats={"attempts": self.refit_policy.max_attempts,
                       "consecutive_failures":
                           self.consecutive_failures})
            if self.consecutive_failures >= \
                    self.refit_policy.circuit_after:
                self.circuit_open = True
                self._circuit_opened_at = time.monotonic()
                self._record(fail)
                self._transition(DEGRADED, RetrainEvent(
                    "circuit_open",
                    reason=f"{self.consecutive_failures} consecutive "
                           f"refit failures; serving frozen model "
                           f"{baseline.version}",
                    stats={"frozen_version": baseline.version}))
            else:
                self._transition(IDLE, fail)
            return
        self.refits += 1
        self.consecutive_failures = 0
        refit_ms = (time.perf_counter() - t0) * 1000.0
        self._hists["refit"].observe(refit_ms)
        self._transition(SHADOWING, RetrainEvent(
            "refit_ok", version=version,
            stats={"refit_ms": round(refit_ms, 2),
                   "window_rows": snapshot.num_rows}))
        verdict = self._shadow_and_gate(candidate, baseline.pipeline,
                                        version)
        if not verdict["pass"]:
            self._quarantine(version, verdict)
            return
        self._record(ShadowEvent("shadow_pass", version=version,
                                 stats=verdict))
        # gate passed: register + canary. Registration happens BEFORE
        # the swap so the registry can answer previous() for rollback
        # and the timeline shows intent even if the canary breaches.
        try:
            self.registry.register(version, candidate,
                                   metadata={"trigger": reason,
                                             "gate": verdict})
        except ValueError:
            pass    # already registered (restart replay) — idempotent
        self._transition(PROMOTING, PromoteEvent(
            "promote_started", version=version, reason="gate_pass",
            stats={"divergence": verdict["divergence"],
                   "quality_delta": verdict["quality_delta"]}))
        t1 = time.perf_counter()
        result = execute_swap(self.engine, candidate, version,
                              warmup_example=self.warmup_example,
                              policy=self.canary,
                              registry=self.registry)
        self._hists["promote"].observe(
            (time.perf_counter() - t1) * 1000.0)
        if result.completed:
            self.promotions += 1
            # restart the drift watch: if the refit hook attached a
            # fresh monitor to the candidate this resets a clean slate;
            # if the old monitor is still active, clearing its running
            # stats stops the SAME shift re-triggering every cooldown
            mon = self._monitor()
            if mon is not None and callable(getattr(mon, "reset",
                                                    None)):
                mon.reset()
            self._transition(IDLE, PromoteEvent(
                "promoted", version=version,
                stats={"swap": result.event.stats}))
        else:
            # the canary's auto-rollback already restored the stable
            # version; quarantine the candidate with the swap evidence
            verdict = dict(verdict)
            verdict.update({"pass": False,
                            "reason": f"canary:{result.reason}",
                            "swap_stats": result.event.stats})
            self._quarantine(version, verdict)

    def _run_refit(self, snapshot: ChunkedTable,
                   active_pipeline: Any) -> Any:
        """The incremental refit with bounded retries + backoff.
        Trainer thread only (allowlisted)."""
        rp = self.refit_policy
        delay = rp.backoff_s
        last: Optional[BaseException] = None
        for attempt in range(rp.max_attempts):
            if self._stop.is_set() or self._die.is_set():
                break
            try:
                candidate = self.refit(snapshot, active_pipeline)
                if candidate is None:
                    raise ValueError("refit returned None")
                return candidate
            except Exception as e:  # noqa: BLE001 — retried, then
                last = e            # surfaced to _cycle
                log.warning("refit attempt %d/%d failed: %s",
                            attempt + 1, rp.max_attempts, e)
                if attempt + 1 < rp.max_attempts:
                    self._stop.wait(delay)
                    delay *= 2
        raise last if last is not None else \
            RuntimeError("refit aborted")

    # -- shadow scoring + the gate ------------------------------------------

    def _predict(self, pipeline: Any, X: np.ndarray) -> np.ndarray:
        if self.predict_fn is not None:
            return np.asarray(self.predict_fn(pipeline, X))
        model = getattr(pipeline, "model", None)
        if model is not None:
            p = getattr(model, "predict", None)
            if callable(p):
                return np.asarray(p(X))
            tr = getattr(model, "transform", None)
            if callable(tr):
                from mmlspark_tpu.core.table import DataTable
                fcol = self.features_col
                get_f = getattr(model, "get_features_col", None)
                if callable(get_f):
                    try:
                        fcol = get_f()
                    except Exception:  # noqa: BLE001
                        pass
                out = tr(DataTable({fcol: np.asarray(X)}))
                pcol = "prediction"
                get_p = getattr(model, "get_prediction_col", None)
                if callable(get_p):
                    try:
                        pcol = get_p()
                    except Exception:  # noqa: BLE001
                        pass
                return np.asarray(out[pcol])
        p = getattr(pipeline, "predict", None)
        if callable(p):
            return np.asarray(p(X))
        raise ValueError(
            "cannot shadow-score this pipeline: expose .model with "
            "predict/transform, a .predict, or pass predict_fn=")

    def _quality(self, pred: np.ndarray, y: np.ndarray,
                 classification: bool) -> float:
        if self.quality_fn is not None:
            return float(self.quality_fn(pred, y))
        pred = np.asarray(pred, dtype=np.float64).ravel()[:len(y)]
        finite = np.isfinite(pred)
        if classification:
            # non-finite predictions count as wrong, not as absent
            return float(np.mean((pred == y) & finite))
        err = np.where(finite, pred - y, np.inf)
        return -float(np.sqrt(np.mean(err ** 2)))

    def _shadow_and_gate(self, candidate: Any, baseline: Any,
                         version: str) -> Dict[str, Any]:
        """Score candidate vs baseline on the freshest window rows and
        compute the gate verdict. Trainer thread only (allowlisted).
        Never raises: an exception IS a failing verdict."""
        g = self.gate
        t0 = time.perf_counter()
        tracer = getattr(self.engine, "tracer", None)
        try:
            from mmlspark_tpu.core.table import DataTable
            chunks = self.window.tail(g.shadow_rows)
            if not chunks:
                return {"pass": False, "reason": "gate:no_shadow_rows",
                        "shadow_rows": 0, "divergence": None,
                        "nan_rate": None, "quality_delta": None}
            tail = chunks[0] if len(chunks) == 1 \
                else DataTable.concat(chunks)
            from mmlspark_tpu.core.table import features_matrix
            X = features_matrix(tail, self.features_col)
            y = np.asarray(tail[self.label_col], dtype=np.float64) \
                if self.label_col in tail else None
            if len(X) > g.shadow_rows:
                X = X[-g.shadow_rows:]
                if y is not None:
                    y = y[-g.shadow_rows:]

            def score() -> Dict[str, Any]:
                pc = np.asarray(self._predict(candidate, X),
                                dtype=np.float64).ravel()[:len(X)]
                pb = np.asarray(self._predict(baseline, X),
                                dtype=np.float64).ravel()[:len(X)]
                return {"pc": pc, "pb": pb}

            if tracer is not None:
                with tracer.trace_block(
                        "controlplane.shadow",
                        attrs={"candidate": version,
                               "rows": int(len(X))}):
                    preds = score()
            else:
                preds = score()
            pc, pb = preds["pc"], preds["pb"]
            self._hists["shadow"].observe(
                (time.perf_counter() - t0) * 1000.0)

            t1 = time.perf_counter()
            nan_rate = float(np.mean(~np.isfinite(pc))) if len(pc) \
                else 1.0
            classification = bool(
                y is not None and
                np.allclose(y, np.round(y), atol=1e-9))
            finite_both = np.isfinite(pc) & np.isfinite(pb)
            if classification:
                # disagreement rate; a non-finite candidate answer
                # disagrees by definition
                divergence = float(np.mean(
                    (pc != pb) | ~np.isfinite(pc)))
            else:
                scale = float(np.std(pb[finite_both])) if \
                    finite_both.any() else 0.0
                diff = np.abs(np.where(np.isfinite(pc), pc, np.inf)
                              - pb)
                divergence = float(np.mean(diff)) / (scale + 1e-9)
            verdict: Dict[str, Any] = {
                "shadow_rows": int(len(X)),
                "nan_rate": round(nan_rate, 6),
                "divergence": round(divergence, 6),
                "classification": classification,
            }
            if y is not None:
                qc = self._quality(pc, y, classification)
                qb = self._quality(pb, y, classification)
                verdict.update(
                    quality_candidate=round(qc, 6),
                    quality_baseline=round(qb, 6),
                    quality_delta=round(qc - qb, 6))
            else:
                verdict.update(quality_candidate=None,
                               quality_baseline=None,
                               quality_delta=None)
            # floors, most-specific first — the verdict names exactly
            # which floor failed with observed-vs-threshold values (the
            # rollback-reason discipline)
            if len(X) < g.min_rows:
                verdict.update(
                    **{"pass": False},
                    reason=f"gate:thin_evidence rows={len(X)}"
                           f"<{g.min_rows}")
            elif nan_rate > g.max_nan_rate:
                verdict.update(
                    **{"pass": False},
                    reason=f"gate:nan_rate={nan_rate:.4f}"
                           f">{g.max_nan_rate:.4f}")
            elif verdict["quality_delta"] is not None and \
                    verdict["quality_delta"] < g.min_quality_delta:
                verdict.update(
                    **{"pass": False},
                    reason=f"gate:quality_delta="
                           f"{verdict['quality_delta']:.4f}"
                           f"<{g.min_quality_delta:.4f} (candidate "
                           f"{verdict['quality_candidate']} vs "
                           f"baseline {verdict['quality_baseline']})")
            elif divergence > g.max_divergence:
                verdict.update(
                    **{"pass": False},
                    reason=f"gate:divergence={divergence:.4f}"
                           f">{g.max_divergence:.4f}")
            else:
                verdict.update(**{"pass": True}, reason="gate:pass")
            self._hists["gate"].observe(
                (time.perf_counter() - t1) * 1000.0)
            return verdict
        except Exception as e:  # noqa: BLE001 — a shadow that cannot
            # score is a FAILING verdict, never a promoted unknown
            return {"pass": False,
                    "reason": f"gate:shadow_error "
                              f"{type(e).__name__}: {e}",
                    "shadow_rows": 0, "divergence": None,
                    "nan_rate": None, "quality_delta": None}

    # -- quarantine ---------------------------------------------------------

    def _quarantine(self, version: str,
                    verdict: Dict[str, Any]) -> None:
        """Reject the candidate, keep the evidence: QuarantineEvent on
        the timeline (verdict in ``stats``), then a flight-recorder
        bundle captured AFTER the event lands so the bundle's own
        timeline contains the verdict it documents."""
        self.quarantines += 1
        reason = str(verdict.get("reason", "gate:fail"))
        stats = {k: v for k, v in verdict.items()
                 if isinstance(v, (int, float, str, bool))
                 or v is None}
        self._transition(IDLE, QuarantineEvent(
            "quarantined", version=version, reason=reason,
            stats=stats))
        bundle = None
        rec = getattr(self.engine, "flight_recorder", None)
        if rec is not None:
            try:
                bundle = rec.dump_bundle(
                    reason=f"quarantine:{version}:{reason}")
            except Exception:  # noqa: BLE001 — evidence is
                bundle = None  # best-effort
            try:
                rec.trigger(f"quarantine:{version}:{reason}")
            except Exception:  # noqa: BLE001
                pass
        self.quarantined[version] = {
            "verdict": verdict, "bundle": bundle, "at": time.time()}
        log.warning("candidate %s QUARANTINED: %s", version, reason)

    # -- observability ------------------------------------------------------

    def status(self) -> Dict[str, Any]:
        """The /healthz ``controlplane`` block: loop state, health, and
        counters. ``degraded`` is True while training is unhealthy —
        circuit open or trainer thread dead — with serving frozen."""
        t = self._thread
        alive = bool(t is not None and t.is_alive())
        with self._lock:
            state = self.state
            counter = self._version_counter
        degraded = bool(
            self._started and not self._stopped
            and (self.circuit_open or not alive))
        now = time.monotonic()
        return {
            "state": state,
            "degraded": degraded,
            "trainer_alive": alive,
            "circuit_open": self.circuit_open,
            "cycles": self.cycles,
            "refits": self.refits,
            "refit_failures": self.refit_failures,
            "consecutive_failures": self.consecutive_failures,
            "promotions": self.promotions,
            "quarantines": self.quarantines,
            "version_counter": counter,
            "last_trigger": self.last_trigger,
            "cooldown_remaining_s": round(
                max(0.0, self._cooldown_until - now), 3),
            "window": self.window.stats(),
        }
