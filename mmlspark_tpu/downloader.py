"""Model zoo: sha256-verified schemas, repos, downloader.

TPU-native analog of the reference's downloader component
(ref: src/downloader/src/main/scala/ModelDownloader.scala:37-209,
Schema.scala:54): a repo is a directory (local or remote) holding an
``index.json`` of model schemas plus one weight blob per model; every
fetch verifies the blob's sha256 against the schema before returning, and
remote fetches retry with backoff (ref: FaultToleranceUtils
ModelDownloader.scala:37-50).

Weights are stored as flax msgpack bytes (``flax.serialization``) next to
a JSON network spec (see models/networks.build_network) — the
TPU-idiomatic replacement for CNTK's binary graph files: the graph is a
declarative spec, the weights a pytree blob.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Any, Dict, Iterator, List, Optional

from mmlspark_tpu.core.logging_utils import get_logger

log = get_logger("downloader")

DEFAULT_CACHE = os.path.expanduser("~/.mmlspark_tpu/models")


class ModelSchema:
    """Schema of a zoo model (ref: downloader Schema.scala:54-100)."""

    def __init__(self, name: str, dataset: str = "", model_type: str = "",
                 uri: str = "", sha256: str = "", size: int = 0,
                 input_shape: Optional[List[int]] = None,
                 num_layers: int = 0,
                 layer_names: Optional[List[str]] = None,
                 network_spec: Optional[Dict[str, Any]] = None):
        self.name = name
        self.dataset = dataset
        self.model_type = model_type
        self.uri = uri
        self.sha256 = sha256
        self.size = int(size)
        self.input_shape = list(input_shape or [])
        self.num_layers = int(num_layers)
        self.layer_names = list(layer_names or [])
        self.network_spec = dict(network_spec or {})

    def to_json(self) -> Dict[str, Any]:
        return {"name": self.name, "dataset": self.dataset,
                "model_type": self.model_type, "uri": self.uri,
                "sha256": self.sha256, "size": self.size,
                "input_shape": self.input_shape,
                "num_layers": self.num_layers,
                "layer_names": self.layer_names,
                "network_spec": self.network_spec}

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "ModelSchema":
        return ModelSchema(**d)

    def __repr__(self):
        return f"ModelSchema({self.name!r}, dataset={self.dataset!r})"


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def retry_with_backoff(fn, times: int = 3, base_delay: float = 0.5,
                       no_retry: tuple = ()):
    """ref: FaultToleranceUtils.retryWithTimeout
    (ModelDownloader.scala:37-50). Exception types in ``no_retry``
    re-raise immediately — deterministic failures (4xx client errors)
    must not burn the backoff budget.

    Back-compat shim over the unified ``utils.resilience.RetryPolicy``
    (exponential backoff + full jitter)."""
    from mmlspark_tpu.utils.resilience import RetryPolicy
    if not isinstance(no_retry, tuple):      # bare class, like `except`
        no_retry = (no_retry,)
    return RetryPolicy(max_attempts=times, base_delay=base_delay,
                       no_retry=no_retry,
                       name="downloader").call(fn)


class LocalRepo:
    """Directory-backed model repo (ref: HDFSRepo Schema analog —
    ModelDownloader.scala:54-123): ``index.json`` + ``<name>.msgpack``."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(path, exist_ok=True)

    def _index_path(self) -> str:
        return os.path.join(self.path, "index.json")

    def _load_index(self) -> Dict[str, Dict[str, Any]]:
        if not os.path.exists(self._index_path()):
            return {}
        with open(self._index_path()) as f:
            return json.load(f)

    def list_schemas(self) -> Iterator[ModelSchema]:
        for d in self._load_index().values():
            yield ModelSchema.from_json(d)

    def get_schema(self, name: str) -> ModelSchema:
        idx = self._load_index()
        if name not in idx:
            raise KeyError(
                f"model {name!r} not in repo {self.path}; "
                f"have {sorted(idx)}")
        return ModelSchema.from_json(idx[name])

    def blob_path(self, schema: ModelSchema) -> str:
        return os.path.join(self.path, f"{schema.name}.msgpack")

    def read_blob(self, schema: ModelSchema, verify: bool = True) -> bytes:
        path = self.blob_path(schema)
        if verify and _sha256(path) != schema.sha256:
            raise IOError(
                f"sha256 mismatch for {schema.name}: file {path} corrupt "
                f"(ref behavior: ModelDownloader verifies hash on fetch)")
        with open(path, "rb") as f:
            return f.read()

    def publish(self, name: str, network_spec: Dict[str, Any],
                variables: Any = None, dataset: str = "",
                model_type: str = "",
                input_shape: Optional[List[int]] = None,
                layer_names: Optional[List[str]] = None,
                blob: Optional[bytes] = None) -> ModelSchema:
        """Add a model to the repo (the zoo-maintainer path). Pass either
        a flax ``variables`` pytree or pre-serialized ``blob`` bytes."""
        blob_path = os.path.join(self.path, f"{name}.msgpack")
        blob, schema = _blob_and_schema(
            name, network_spec, variables, blob, f"file://{blob_path}",
            dataset, model_type, input_shape, layer_names)
        with open(blob_path, "wb") as f:
            f.write(blob)
        idx = self._load_index()
        idx[name] = schema.to_json()
        with open(self._index_path(), "w") as f:
            json.dump(idx, f, indent=1)
        return schema


def _blob_and_schema(name, network_spec, variables, blob, uri,
                     dataset, model_type, input_shape, layer_names):
    """Shared publish assembly for every repo flavor: serialize the
    variables when no blob is given, hash, and build the ModelSchema."""
    if blob is None:
        from flax import serialization
        blob = serialization.to_bytes(variables)
    schema = ModelSchema(
        name=name, dataset=dataset, model_type=model_type,
        uri=uri, sha256=hashlib.sha256(blob).hexdigest(),
        size=len(blob), input_shape=input_shape,
        layer_names=layer_names, network_spec=network_spec)
    return blob, schema


class HTTPRepo:
    """HTTP(S)-backed model repo — the remote half of the reference's
    downloader (ref: ModelDownloader.scala:54-124 HDFSRepo/DefaultModelRepo:
    remote URI fetch, sha256 verify, retry with backoff). Expects the same
    layout LocalRepo publishes: ``<base>/index.json`` + ``<name>.msgpack``.
    """

    handles_retries = True   # this repo owns its whole retry policy

    def __init__(self, base_url: str, retries: int = 3):
        self.base_url = base_url.rstrip("/")
        self._fs = None
        self.retries = retries

    def _filesystem(self):
        from mmlspark_tpu.utils.filesystem import (
            HTTPFileSystem, WebDAVFileSystem, scheme_of,
        )
        if self._fs is None:
            # single transport attempt per try — OUR retry loop wraps
            # fetch+verify together so corrupted-but-200 downloads are
            # also re-fetched, without multiplying attempts
            cls = WebDAVFileSystem if scheme_of(self.base_url).startswith(
                "webdav") else HTTPFileSystem
            self._fs = cls(retries=1)
        return self._fs

    def _fetch(self, rel: str) -> bytes:
        fs = self._filesystem()
        url = f"{self.base_url}/{rel}"
        return retry_with_backoff(lambda: fs.read_bytes(url),
                                  times=self.retries)

    def _load_index(self) -> Dict[str, Dict[str, Any]]:
        return json.loads(self._fetch("index.json").decode())

    def list_schemas(self) -> Iterator[ModelSchema]:
        for d in self._load_index().values():
            yield ModelSchema.from_json(d)

    def get_schema(self, name: str) -> ModelSchema:
        idx = self._load_index()
        if name not in idx:
            raise KeyError(
                f"model {name!r} not in repo {self.base_url}; "
                f"have {sorted(idx)}")
        return ModelSchema.from_json(idx[name])

    def read_blob(self, schema: ModelSchema, verify: bool = True) -> bytes:
        fs = self._filesystem()
        url = f"{self.base_url}/{schema.name}.msgpack"

        def fetch_and_verify() -> bytes:
            blob = fs.read_bytes(url)
            if verify and hashlib.sha256(blob).hexdigest() != schema.sha256:
                raise IOError(
                    f"sha256 mismatch for {schema.name} fetched from "
                    f"{self.base_url} (corrupt or tampered download)")
            return blob

        # hash failures re-fetch too: a truncated 200 body is transient
        return retry_with_backoff(fetch_and_verify, times=self.retries)

    def publish(self, name: str, network_spec: Dict[str, Any],
                variables: Any = None, dataset: str = "",
                model_type: str = "",
                input_shape: Optional[List[int]] = None,
                layer_names: Optional[List[str]] = None,
                blob: Optional[bytes] = None) -> ModelSchema:
        """Publish to a WRITABLE remote repo (``webdav://`` base_url —
        the HDFSRepo-publish analog, ref: ModelDownloader.scala:54-124).
        Read-only ``http(s)://`` repos raise."""
        fs = self._filesystem()
        blob_url = f"{self.base_url}/{name}.msgpack"
        blob, schema = _blob_and_schema(
            name, network_spec, variables, blob, blob_url,
            dataset, model_type, input_shape, layer_names)
        fs.write_bytes(blob_url, blob)            # raises on read-only
        import urllib.error
        try:
            # direct read (fs retries=1): a 404 means "first publish"
            # and must not burn the repo-level retry budget
            idx = json.loads(
                fs.read_bytes(f"{self.base_url}/index.json").decode())
        except (FileNotFoundError, urllib.error.HTTPError) as e:
            # ONLY a missing index means "first publish" — any other
            # failure must abort, or a transient fetch error would
            # silently delist every previously published model
            if isinstance(e, urllib.error.HTTPError) and e.code != 404:
                raise
            idx = {}
        idx[name] = schema.to_json()
        fs.write_bytes(f"{self.base_url}/index.json",
                       json.dumps(idx, indent=1).encode("utf-8"))
        return schema


class ModelDownloader:
    """Fetch models from a repo into a local cache, verifying hashes
    (ref: ModelDownloader.scala:209-280 — downloadModel/downloadByName,
    local caching, retry)."""

    def __init__(self, local_path: str = DEFAULT_CACHE,
                 repo: Optional[LocalRepo] = None):
        self.local = LocalRepo(local_path)
        self.repo = repo

    def list_models(self) -> List[ModelSchema]:
        source = self.repo if self.repo is not None else self.local
        return list(source.list_schemas())

    def download_by_name(self, name: str) -> ModelSchema:
        # cached already?
        try:
            schema = self.local.get_schema(name)
            self.local.read_blob(schema)  # verifies hash
            return schema
        except (KeyError, IOError, FileNotFoundError):
            pass
        if self.repo is None:
            raise KeyError(
                f"model {name!r} not cached and no remote repo configured")
        schema = self.repo.get_schema(name)
        # retry here UNLESS the repo declares it retries internally
        # (HTTPRepo does, in its filesystem layer — wrapping again would
        # multiply attempts; custom repos keep the default 3x backoff)
        if getattr(self.repo, "handles_retries", False):
            blob = self.repo.read_blob(schema)
        else:
            blob = retry_with_backoff(lambda: self.repo.read_blob(schema))
        return self.local.publish(
            name, schema.network_spec, blob=blob,
            dataset=schema.dataset, model_type=schema.model_type,
            input_shape=schema.input_shape, layer_names=schema.layer_names)

    def download_model(self, schema: ModelSchema) -> ModelSchema:
        return self.download_by_name(schema.name)

    def load_variables(self, name: str) -> Any:
        """Blob -> flax variables pytree."""
        from flax import serialization
        schema = self.download_by_name(name)  # verifies the cached blob
        blob = self.local.read_blob(schema, verify=False)
        module = self.build_module(schema)
        import jax
        import jax.numpy as jnp
        shape = [1] + list(schema.input_shape)
        dummy_dtype = jnp.int32 if schema.model_type == "sequence" \
            else jnp.float32
        target = module.init(jax.random.PRNGKey(0),
                             jnp.zeros(shape, dummy_dtype))
        return serialization.from_bytes(target, blob)

    @staticmethod
    def build_module(schema: ModelSchema):
        from mmlspark_tpu.models.networks import build_network
        return build_network(schema.network_spec)
