"""Column UDF helpers (ref: src/udf/src/main/scala/udfs.scala:15-29).

The reference ships two tiny Spark-SQL UDFs — ``to_vector`` (double array
-> dense Vector) and ``get_value_at`` (vector element extraction). Here
they are plain value functions suitable for ``UDFTransformer``'s ``udf``
param, plus eager table-level conveniences.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import numpy as np


def to_vector(value: Any) -> np.ndarray:
    """array-like -> float64 vector (ref: udfs.scala to_vector)."""
    return np.asarray(value, dtype=np.float64)


def get_value_at(i: int) -> Callable[[Any], float]:
    """Vector element extractor for UDFTransformer
    (ref: udfs.scala get_value_at): ``get_value_at(2)`` maps a vector
    column to its third component."""
    def extract(vec: Any) -> float:
        return float(np.asarray(vec)[i])
    return extract


def table_to_vector(table, input_col: str, output_col: str):
    """Eager convenience: coerce an array-valued column to a vector
    column in one call."""
    vals = np.stack([to_vector(v) for v in table[input_col]])
    return table.with_column(output_col, vals)


def table_get_value_at(table, input_col: str, output_col: str, i: int):
    col = table[input_col]
    if isinstance(col, np.ndarray) and col.ndim == 2:
        vals = col[:, i].astype(np.float64)
    else:
        vals = np.asarray([get_value_at(i)(v) for v in col])
    return table.with_column(output_col, vals)
