"""NativeLoader — locate, (re)build, and bind the native runtime library.

Analog of the reference's NativeLoader
(ref: src/core/env/src/main/scala/NativeLoader.java:28,47-68): the
reference extracts per-OS .so files from jar resources to a temp dir and
System.load()s them; here the library lives next to the package (built
once by cmake) and binds through ctypes. Everything that calls into it
falls back to pure numpy when the library is unavailable — native is an
accelerator, never a requirement.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

from mmlspark_tpu.core.logging_utils import get_logger

log = get_logger("native")

_NATIVE_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_NATIVE_DIR, "lib", "libmml_native.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    """One-time cmake build (the packaging-time step; done lazily here
    so source checkouts self-provision)."""
    build_dir = os.path.join(_NATIVE_DIR, "build")
    os.makedirs(build_dir, exist_ok=True)
    try:
        subprocess.run(["cmake", "-S", _NATIVE_DIR, "-B", build_dir,
                        "-DCMAKE_BUILD_TYPE=Release"],
                       check=True, capture_output=True, timeout=120)
        subprocess.run(["cmake", "--build", build_dir, "-j"],
                       check=True, capture_output=True, timeout=300)
        return os.path.exists(_LIB_PATH)
    except (subprocess.CalledProcessError, subprocess.TimeoutExpired,
            FileNotFoundError) as e:
        out = getattr(e, "stderr", b"")
        log.warning("native build failed (%s); using numpy fallbacks: %s",
                    type(e).__name__,
                    out.decode()[-500:] if out else e)
        return False


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.mml_free.argtypes = [ctypes.c_void_p]
    lib.mml_decode_image.argtypes = [
        u8p, ctypes.c_int, ctypes.POINTER(u8p),
        ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_int)]
    lib.mml_decode_image.restype = ctypes.c_int
    lib.mml_resize_bilinear_u8.argtypes = [
        u8p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        u8p, ctypes.c_int, ctypes.c_int]
    lib.mml_resize_bilinear_u8.restype = ctypes.c_int
    lib.mml_unroll_chw.argtypes = [u8p, ctypes.c_int, ctypes.c_int,
                                   ctypes.c_int,
                                   ctypes.POINTER(ctypes.c_double)]
    lib.mml_unroll_chw.restype = ctypes.c_int
    lib.mml_apply_bins.argtypes = [
        ctypes.POINTER(ctypes.c_double), ctypes.c_long, ctypes.c_int,
        ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_long),
        ctypes.POINTER(ctypes.c_int32)]
    lib.mml_apply_bins.restype = ctypes.c_int
    if hasattr(lib, "mml_apply_bins_t_u8"):   # pre-upgrade .so lacks it
        lib.mml_apply_bins_t_u8.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_long, ctypes.c_int,
            ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_long),
            ctypes.POINTER(ctypes.c_uint8)]
        lib.mml_apply_bins_t_u8.restype = ctypes.c_int
    if hasattr(lib, "mml_apply_bins_t_u8_range"):
        lib.mml_apply_bins_t_u8_range.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_long, ctypes.c_int,
            ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_long),
            ctypes.POINTER(ctypes.c_uint8)]
        lib.mml_apply_bins_t_u8_range.restype = ctypes.c_int
    return lib


def get_lib(allow_build: bool = True) -> Optional[ctypes.CDLL]:
    """The loaded library, or None when unavailable. Thread-safe,
    attempts the build exactly once per process."""
    global _lib, _tried
    if _lib is not None:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("MMLSPARK_TPU_NO_NATIVE") == "1":
            return None  # kill-switch: force pure-numpy paths
        if not os.path.exists(_LIB_PATH):
            if not (allow_build and _build()):
                return None
        try:
            _lib = _bind(ctypes.CDLL(_LIB_PATH))
            log.info("native library loaded from %s", _LIB_PATH)
        except OSError as e:
            log.warning("failed to load %s: %s", _LIB_PATH, e)
            _lib = None
    return _lib


def available() -> bool:
    return get_lib() is not None


# ---------------------------------------------------------------------------
# numpy-facing wrappers
# ---------------------------------------------------------------------------

import numpy as np  # noqa: E402


def decode_image(data: bytes) -> Optional[np.ndarray]:
    """JPEG/PNG bytes -> RGB uint8 HWC array, or None if undecodable."""
    lib = get_lib()
    if lib is None:
        return None
    u8p = ctypes.POINTER(ctypes.c_uint8)
    out = u8p()
    h = ctypes.c_int()
    w = ctypes.c_int()
    c = ctypes.c_int()
    buf = (ctypes.c_uint8 * len(data)).from_buffer_copy(data)
    rc = lib.mml_decode_image(buf, len(data), ctypes.byref(out),
                              ctypes.byref(h), ctypes.byref(w),
                              ctypes.byref(c))
    if rc != 0:
        return None
    n = h.value * w.value * c.value
    try:
        arr = np.ctypeslib.as_array(out, shape=(n,)).copy()
    finally:
        lib.mml_free(out)
    return arr.reshape(h.value, w.value, c.value)


def resize_u8(img: np.ndarray, oh: int, ow: int) -> Optional[np.ndarray]:
    lib = get_lib()
    if lib is None:
        return None
    img = np.ascontiguousarray(img, dtype=np.uint8)
    h, w, c = img.shape
    dst = np.empty((oh, ow, c), dtype=np.uint8)
    rc = lib.mml_resize_bilinear_u8(
        img.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), h, w, c,
        dst.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), oh, ow)
    return dst if rc == 0 else None


def unroll_chw(img: np.ndarray) -> Optional[np.ndarray]:
    lib = get_lib()
    if lib is None:
        return None
    img = np.ascontiguousarray(img, dtype=np.uint8)
    h, w, c = img.shape
    dst = np.empty(h * w * c, dtype=np.float64)
    rc = lib.mml_unroll_chw(
        img.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), h, w, c,
        dst.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
    return dst if rc == 0 else None


def apply_bins(X: np.ndarray, upper_bounds: list) -> Optional[np.ndarray]:
    """Parallel per-feature searchsorted (binning.BinMapper.transform
    fast path)."""
    lib = get_lib()
    if lib is None:
        return None
    X = np.ascontiguousarray(X, dtype=np.float64)
    n, f = X.shape
    bounds = (np.concatenate([np.asarray(u, dtype=np.float64)
                              for u in upper_bounds])
              if upper_bounds and any(len(u) for u in upper_bounds)
              else np.zeros(0))
    offsets = np.zeros(f + 1, dtype=np.int64)
    for j, u in enumerate(upper_bounds):
        offsets[j + 1] = offsets[j] + len(u)
    out = np.empty((n, f), dtype=np.int32)
    rc = lib.mml_apply_bins(
        X.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), n, f,
        bounds.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_long)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    return out if rc == 0 else None


def apply_bins_t_u8(X: np.ndarray, upper_bounds: list,
                    feature_range: Optional[tuple] = None,
                    ) -> Optional[np.ndarray]:
    """Fused bin+transpose+narrow: (n, f) f32/f64 features ->
    FEATURES-MAJOR (f, n) uint8 bins in one native pass (the GBDT
    engine's ship layout). ``feature_range=(j0, j1)`` bins only that
    column slice into a (j1-j0, n) block without copying X — the unit
    of the pipelined host-bin/device-ship overlap. Requires every
    feature's bin count <= 256 and the library built after the kernel
    landed (probed via hasattr)."""
    lib = get_lib()
    if lib is None or not hasattr(lib, "mml_apply_bins_t_u8"):
        return None
    if feature_range is not None and not hasattr(
            lib, "mml_apply_bins_t_u8_range"):
        return None
    if any(len(u) + 1 > 256 for u in upper_bounds):
        return None
    X = np.ascontiguousarray(X)
    if X.dtype == np.float32:
        is_f32 = 1
    elif X.dtype == np.float64:
        is_f32 = 0
    else:
        X = np.ascontiguousarray(X, dtype=np.float64)
        is_f32 = 0
    n, f = X.shape
    bounds = (np.concatenate([np.asarray(u, dtype=np.float64)
                              for u in upper_bounds])
              if upper_bounds and any(len(u) for u in upper_bounds)
              else np.zeros(0))
    offsets = np.zeros(f + 1, dtype=np.int64)
    for j, u in enumerate(upper_bounds):
        offsets[j + 1] = offsets[j] + len(u)
    if feature_range is None:
        out = np.empty((f, n), dtype=np.uint8)
        rc = lib.mml_apply_bins_t_u8(
            X.ctypes.data_as(ctypes.c_void_p), is_f32, n, f,
            bounds.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_long)),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
    else:
        j0, j1 = int(feature_range[0]), int(feature_range[1])
        if not 0 <= j0 < j1 <= f:
            raise ValueError(f"feature_range {feature_range} outside "
                             f"[0, {f})")
        out = np.empty((j1 - j0, n), dtype=np.uint8)
        rc = lib.mml_apply_bins_t_u8_range(
            X.ctypes.data_as(ctypes.c_void_p), is_f32, n, f, j0, j1,
            bounds.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_long)),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
    return out if rc == 0 else None
