// Native host-side runtime ops.
//
// The reference ships its hot host-side paths as prebuilt C++ inside jars
// (OpenCV imgcodecs/imgproc for image decode+transform, LightGBM's dataset
// binning — loaded through NativeLoader,
// ref: src/core/env/src/main/scala/NativeLoader.java:28). This library is
// the TPU build's equivalent: the host data path (image decode, resize,
// layout unroll, feature binning) runs native, while all FLOP-heavy math
// stays in XLA on the TPU.
//
// C ABI only — consumed via ctypes (no pybind11 in the image).

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstdio>  // jpeglib.h needs FILE declared first
#include <cstdlib>
#include <cstring>
#include <vector>

#include <jpeglib.h>
#include <png.h>
#include <csetjmp>

extern "C" {

// ---------------------------------------------------------------------------
// memory
// ---------------------------------------------------------------------------

void mml_free(void* p) { std::free(p); }

// ---------------------------------------------------------------------------
// image decode (OpenCV imgcodecs analog)
// ---------------------------------------------------------------------------

struct JpegErr {
  jpeg_error_mgr mgr;
  jmp_buf jump;
};

static void jpeg_err_exit(j_common_ptr cinfo) {
  JpegErr* err = reinterpret_cast<JpegErr*>(cinfo->err);
  longjmp(err->jump, 1);
}

// decode JPEG bytes -> RGB8 buffer (caller frees with mml_free)
static int decode_jpeg(const uint8_t* data, int len, uint8_t** out,
                       int* h, int* w, int* c) {
  jpeg_decompress_struct cinfo;
  JpegErr jerr;
  // volatile: modified between setjmp and longjmp; without it the value
  // read in the error path is indeterminate (UB) on malformed input
  uint8_t* volatile buf = nullptr;
  cinfo.err = jpeg_std_error(&jerr.mgr);
  jerr.mgr.error_exit = jpeg_err_exit;
  if (setjmp(jerr.jump)) {
    std::free(buf);
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, data, static_cast<unsigned long>(len));
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  cinfo.out_color_space = JCS_RGB;
  jpeg_start_decompress(&cinfo);
  const int H = cinfo.output_height, W = cinfo.output_width;
  const int C = cinfo.output_components;  // 3 for JCS_RGB
  buf = static_cast<uint8_t*>(
      std::malloc(static_cast<size_t>(H) * W * C));
  if (!buf) {
    jpeg_destroy_decompress(&cinfo);
    return -2;
  }
  while (cinfo.output_scanline < cinfo.output_height) {
    uint8_t* row = buf + static_cast<size_t>(cinfo.output_scanline) * W * C;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  *out = buf;
  *h = H;
  *w = W;
  *c = C;
  return 0;
}

// decode PNG bytes -> RGB8 (libpng simplified API)
static int decode_png(const uint8_t* data, int len, uint8_t** out,
                      int* h, int* w, int* c) {
  png_image image;
  std::memset(&image, 0, sizeof(image));
  image.version = PNG_IMAGE_VERSION;
  if (!png_image_begin_read_from_memory(&image, data,
                                        static_cast<size_t>(len))) {
    return -1;
  }
  image.format = PNG_FORMAT_RGB;
  const size_t size = PNG_IMAGE_SIZE(image);
  uint8_t* buf = static_cast<uint8_t*>(std::malloc(size));
  if (!buf) {
    png_image_free(&image);
    return -2;
  }
  if (!png_image_finish_read(&image, nullptr, buf, 0, nullptr)) {
    std::free(buf);
    png_image_free(&image);
    return -1;
  }
  *out = buf;
  *h = static_cast<int>(image.height);
  *w = static_cast<int>(image.width);
  *c = 3;
  return 0;
}

// sniff magic bytes, decode jpeg/png -> RGB8
int mml_decode_image(const uint8_t* data, int len, uint8_t** out,
                     int* h, int* w, int* c) {
  if (len >= 3 && data[0] == 0xFF && data[1] == 0xD8 && data[2] == 0xFF) {
    return decode_jpeg(data, len, out, h, w, c);
  }
  if (len >= 8 && data[0] == 0x89 && data[1] == 'P' && data[2] == 'N' &&
      data[3] == 'G') {
    return decode_png(data, len, out, h, w, c);
  }
  return -3;  // unknown format
}

// ---------------------------------------------------------------------------
// image transforms (OpenCV imgproc analog; uint8 HWC buffers)
// ---------------------------------------------------------------------------

// Separable antialiased triangle-kernel resize, matching
// jax.image.resize(method="bilinear", antialias=True) so the native host
// path and the XLA device path produce identical pixels
// (ops/image_ops.resize_host uses jax.image.resize).
int mml_resize_bilinear_u8(const uint8_t* src, int h, int w, int c,
                           uint8_t* dst, int oh, int ow) {
  if (h <= 0 || w <= 0 || oh <= 0 || ow <= 0 || c <= 0) return -1;
  const long n_in = static_cast<long>(h) * w * c;
  double* f64 = static_cast<double*>(std::malloc(sizeof(double) * n_in));
  double* mid = static_cast<double*>(
      std::malloc(sizeof(double) * static_cast<long>(oh) * w * c));
  double* out = static_cast<double*>(
      std::malloc(sizeof(double) * static_cast<long>(oh) * ow * c));
  if (!f64 || !mid || !out) {
    std::free(f64);
    std::free(mid);
    std::free(out);
    return -2;
  }
  for (long i = 0; i < n_in; ++i) f64[i] = src[i];

  // pass 1: H -> OH. Treat src as [h][w*c]; vertical stride = w*c.
  {
    const double scale = static_cast<double>(h) / oh;
    const double s = std::max(scale, 1.0);
    const long row = static_cast<long>(w) * c;
    for (int y = 0; y < oh; ++y) {
      const double center = (y + 0.5) * scale - 0.5;
      const int lo = static_cast<int>(std::ceil(center - s));
      const int hi = static_cast<int>(std::floor(center + s));
      // jax.image.resize drops out-of-range taps and renormalizes
      // over the in-range weight sum (no edge clamping)
      double wsum = 0.0;
      std::vector<double> wgt(hi - lo + 1);
      for (size_t j = 0; j < wgt.size(); ++j) {
        const int idx = lo + static_cast<int>(j);
        const double t = std::abs((idx - center) / s);
        wgt[j] = (idx >= 0 && idx < h && t < 1.0) ? 1.0 - t : 0.0;
        wsum += wgt[j];
      }
      for (long x = 0; x < row; ++x) {
        double acc = 0.0;
        for (size_t j = 0; j < wgt.size(); ++j) {
          if (wgt[j] == 0.0) continue;
          const int idx = lo + static_cast<int>(j);
          acc += wgt[j] * f64[static_cast<long>(idx) * row + x];
        }
        mid[static_cast<long>(y) * row + x] = acc / wsum;
      }
    }
  }
  // pass 2: W -> OW. mid is [oh][w][c].
  {
    const double scale = static_cast<double>(w) / ow;
    const double s = std::max(scale, 1.0);
    for (int x = 0; x < ow; ++x) {
      const double center = (x + 0.5) * scale - 0.5;
      const int lo = static_cast<int>(std::ceil(center - s));
      const int hi = static_cast<int>(std::floor(center + s));
      double wsum = 0.0;
      std::vector<double> wgt(hi - lo + 1);
      for (size_t j = 0; j < wgt.size(); ++j) {
        const int idx = lo + static_cast<int>(j);
        const double t = std::abs((idx - center) / s);
        wgt[j] = (idx >= 0 && idx < w && t < 1.0) ? 1.0 - t : 0.0;
        wsum += wgt[j];
      }
      for (int y = 0; y < oh; ++y) {
        for (int ch = 0; ch < c; ++ch) {
          double acc = 0.0;
          for (size_t j = 0; j < wgt.size(); ++j) {
            if (wgt[j] == 0.0) continue;
            const int idx = lo + static_cast<int>(j);
            acc += wgt[j] *
                   mid[(static_cast<long>(y) * w + idx) * c + ch];
          }
          out[(static_cast<long>(y) * ow + x) * c + ch] = acc / wsum;
        }
      }
    }
  }
  const long n_out = static_cast<long>(oh) * ow * c;
  for (long i = 0; i < n_out; ++i) {
    dst[i] = static_cast<uint8_t>(
        std::lround(std::min(255.0, std::max(0.0, out[i]))));
  }
  std::free(f64);
  std::free(mid);
  std::free(out);
  return 0;
}

// HWC uint8 -> CHW float64 unroll (UnrollImage hot path,
// ref: UnrollImage.scala:18-43; matches
// ops/image_ops.unroll_host's transpose(2,0,1).ravel() order)
int mml_unroll_chw(const uint8_t* src, int h, int w, int c, double* dst) {
  size_t i = 0;
  for (int ch = 0; ch < c; ++ch)
    for (int y = 0; y < h; ++y)
      for (int x = 0; x < w; ++x)
        dst[i++] = src[(static_cast<size_t>(y) * w + x) * c + ch];
  return 0;
}

// ---------------------------------------------------------------------------
// GBDT host binning (LightGBM dataset-construction analog)
// ---------------------------------------------------------------------------

// per-feature searchsorted: bounds is the concatenation of each feature's
// ascending boundaries; offsets[f]..offsets[f+1] delimit feature f.
// NaN maps to bin 0, matching gbdt/binning.py.
int mml_apply_bins(const double* X, long n, int f, const double* bounds,
                   const long* offsets, int32_t* out) {
#pragma omp parallel for schedule(static)
  for (long i = 0; i < n; ++i) {
    for (int j = 0; j < f; ++j) {
      const double v = X[i * f + j];
      const double* lo = bounds + offsets[j];
      const double* hi = bounds + offsets[j + 1];
      if (std::isnan(v)) {
        out[i * f + j] = 0;
        continue;
      }
      out[i * f + j] =
          static_cast<int32_t>(std::lower_bound(lo, hi, v) - lo);
    }
  }
  return 0;
}

// fused bin+transpose+narrow: row-major (n, f) features -> FEATURES-MAJOR
// (f, n) uint8 bins in ONE pass (the layout+dtype the device engine
// ships; separate transform/transpose/astype passes cost three full
// sweeps of a 1M-row matrix). x_is_f32 selects the input dtype — f32
// values widen to double before the boundary compare, which is exact,
// so results match the f64 path bit-for-bit. Requires every feature's
// bin count <= 256 (caller checks). Row-tiled so the strided input
// reads stay within cache while output writes run contiguous.
// feature-RANGE variant: bins only columns [j0, j1) of the full-width
// input into a (j1-j0, n) output block. This is the unit of the
// pipelined ship: the caller bins one feature chunk while the previous
// chunk's host->device transfer is in flight, so host binning and link
// time overlap instead of serializing (offsets/bounds still index the
// FULL feature set; X keeps its full row stride — no column copy).
int mml_apply_bins_t_u8_range(const void* Xv, int x_is_f32, long n,
                              int f, int j0, int j1,
                              const double* bounds, const long* offsets,
                              uint8_t* out) {
  if (j0 < 0 || j1 > f || j0 >= j1) return 1;
  const float* Xf = static_cast<const float*>(Xv);
  const double* Xd = static_cast<const double*>(Xv);
  const long TILE = 8192;
  for (long t0 = 0; t0 < n; t0 += TILE) {
    const long t1 = std::min(n, t0 + TILE);
    for (int j = j0; j < j1; ++j) {
      const double* lo = bounds + offsets[j];
      const double* hi = bounds + offsets[j + 1];
      uint8_t* orow = out + static_cast<size_t>(j - j0) * n;
      for (long i = t0; i < t1; ++i) {
        const double v = x_is_f32 ? static_cast<double>(Xf[i * f + j])
                                  : Xd[i * f + j];
        orow[i] = std::isnan(v)
                      ? 0
                      : static_cast<uint8_t>(
                            std::lower_bound(lo, hi, v) - lo);
      }
    }
  }
  return 0;
}

int mml_apply_bins_t_u8(const void* Xv, int x_is_f32, long n, int f,
                        const double* bounds, const long* offsets,
                        uint8_t* out) {
  return mml_apply_bins_t_u8_range(Xv, x_is_f32, n, f, 0, f, bounds,
                                   offsets, out);
}

}  // extern "C"
