"""TPULearner — minibatch SGD training of zoo networks as an Estimator.

TPU-native replacement for the reference's cntk-train component
(ref: src/cntk-train/src/main/scala/CNTKLearner.scala:88-176): where the
reference writes the dataset to CNTKTextFormat, emits BrainScript configs,
and shells out to ``mpirun cntk`` over ssh with scp'd data and hostfiles
(ref: CommandBuilders.scala:108-267), we build a flax network from a
declarative spec, jit one train step over a named device mesh, and stream
host-sharded minibatches through it:

- **DP**: batch sharded over the ``data`` axis; XLA inserts the gradient
  all-reduce (psum) over ICI — the analog of CNTK's MPI 1-bit SGD ring.
- **FSDP**: optionally shard each param's largest divisible dim over the
  mesh so optimizer state and weights scale past one chip's HBM.
- **bf16 compute / f32 params**: MXU-friendly mixed precision.
- **Masked final batch**: shapes stay static (no recompiles); padded rows
  carry zero loss weight.
- **Checkpoint/resume**: train state snapshots every N steps
  (ref analog: model persistence via ConstructorWritable + LightGBM
  modelString warm-start, SURVEY.md §5).

``fit`` returns a :class:`TPUModel` ready for batched inference — the
same contract as CNTKLearner returning a CNTKModel (:172-175).
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import optax

from mmlspark_tpu.core.logging_utils import get_logger
from mmlspark_tpu.core.params import (
    BoolParam, DictParam, EnumParam, FloatParam, HasFeaturesCol, HasLabelCol,
    IntParam, StringParam, UDFParam,
)
from mmlspark_tpu.core.schema import ImageSchema
from mmlspark_tpu.core.stage import Estimator
from mmlspark_tpu.core.table import DataTable
from mmlspark_tpu.core import serialize as ser
from mmlspark_tpu.models.networks import build_network
from mmlspark_tpu.models.tpu_model import TPUModel
from mmlspark_tpu.parallel import mesh as mesh_lib

logger = get_logger("learner")


# ---------------------------------------------------------------------------
# optimizers / schedules
# ---------------------------------------------------------------------------


def make_optimizer(name: str, lr: float, *, momentum: float = 0.9,
                   weight_decay: float = 0.0, schedule: str = "constant",
                   warmup_steps: int = 0, total_steps: int = 1000
                   ) -> optax.GradientTransformation:
    if schedule == "cosine":
        w = max(warmup_steps, 1)
        sched = optax.warmup_cosine_decay_schedule(
            0.0, lr, w, max(total_steps, w + 1))
    elif schedule == "constant":
        if warmup_steps > 0:
            sched = optax.linear_schedule(0.0, lr, warmup_steps)
        else:
            sched = lr
    else:
        raise ValueError(f"unknown schedule {schedule!r}")
    if name == "sgd":
        return optax.sgd(sched)
    if name == "momentum":
        return optax.sgd(sched, momentum=momentum, nesterov=True)
    if name == "adam":
        return optax.adam(sched)
    if name == "adamw":
        return optax.adamw(sched, weight_decay=weight_decay)
    raise ValueError(f"unknown optimizer {name!r}")


# ---------------------------------------------------------------------------
# sharding rules
# ---------------------------------------------------------------------------


# bf16 peak FLOP/s per chip by device kind — used only to report MFU
# alongside measured throughput (public figures; unknown kinds -> None)
_PEAK_BF16_FLOPS = {
    "TPU v2": 46e12,
    "TPU v3": 123e12,
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}


def peak_flops_per_chip(device_kind: str) -> Optional[float]:
    """Best-effort bf16 peak for MFU reporting; None when unknown."""
    for kind, peak in _PEAK_BF16_FLOPS.items():
        if device_kind.startswith(kind) or kind in device_kind:
            return peak
    return None


def _step_flops(compiled) -> Optional[float]:
    """Per-step FLOPs from XLA's cost analysis of a compiled step."""
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        flops = float(ca.get("flops", 0.0))
        return flops if flops > 0 else None
    except Exception:  # backend without cost analysis
        return None


def fsdp_sharding_rule(mesh: Mesh, axis: str = mesh_lib.FSDP_AXIS
                       ) -> Callable[[jnp.ndarray], NamedSharding]:
    """Shard each leaf's largest dim divisible by the axis size; replicate
    otherwise (simple ZeRO-3-style rule)."""
    size = mesh.shape[axis]

    def rule(leaf) -> NamedSharding:
        shape = getattr(leaf, "shape", ())
        if not shape or size == 1:
            return NamedSharding(mesh, P())
        dims = sorted(range(len(shape)), key=lambda d: -shape[d])
        for d in dims:
            if shape[d] % size == 0 and shape[d] >= size:
                spec = [None] * len(shape)
                spec[d] = axis
                return NamedSharding(mesh, P(*spec))
        return NamedSharding(mesh, P())
    return rule


# ---------------------------------------------------------------------------
# feature extraction from table columns
# ---------------------------------------------------------------------------


def table_to_xy(table: DataTable, features_col: str, label_col: str,
                input_shape: Optional[List[int]] = None
                ) -> Tuple[np.ndarray, np.ndarray]:
    field = table.schema.get(features_col)
    col = table[features_col]
    if field is not None and ImageSchema.is_image(field):
        x = np.stack([np.asarray(r[ImageSchema.DATA]) for r in col]
                     ).astype(np.float32) / 255.0
    elif isinstance(col, np.ndarray):
        x = np.asarray(col, dtype=np.float32)
    else:
        x = np.stack([np.asarray(v) for v in col]).astype(np.float32)
    if input_shape:
        x = x.reshape((x.shape[0],) + tuple(input_shape))
    y = np.asarray(table[label_col])
    return x, y


class TPULearner(Estimator, HasFeaturesCol, HasLabelCol):
    """Train a zoo network on a table; returns a TPUModel."""

    networkSpec = DictParam(
        "declarative network spec, e.g. {'type':'resnet',...} "
        "(BrainScript analog, ref: BrainscriptBuilder.scala:16)", default=None)
    moduleFactory = UDFParam(
        "callable () -> flax Module (alternative to networkSpec)", default=None)
    loss = EnumParam(["cross_entropy", "mse", "token_cross_entropy"],
                     "training loss", default="cross_entropy")
    optimizer = EnumParam(["sgd", "momentum", "adam", "adamw"],
                          "optimizer", default="momentum")
    learningRate = FloatParam("peak learning rate", default=0.1)
    momentum = FloatParam("sgd momentum", default=0.9)
    weightDecay = FloatParam("adamw weight decay", default=1e-4)
    schedule = EnumParam(["constant", "cosine"], "lr schedule",
                         default="cosine")
    warmupSteps = IntParam("lr warmup steps", default=0)
    epochs = IntParam("training epochs", default=1)
    batchSize = IntParam("global batch size", default=128)
    seed = IntParam("rng seed", default=0)
    computeDtype = EnumParam(["float32", "bfloat16"],
                             "device compute dtype", default="bfloat16")
    meshAxes = DictParam("mesh axes, e.g. {'data': -1} or "
                         "{'data': 4, 'fsdp': 2}", default=None)
    paramSharding = EnumParam(["replicated", "fsdp"],
                              "parameter sharding strategy",
                              default="replicated")
    inputShape = UDFParam("reshape flat features to this per-row shape "
                          "(list), e.g. [32,32,3]", default=None)
    checkpointDir = StringParam("checkpoint directory ('' = off)", default="")
    checkpointEvery = IntParam("steps between checkpoints", default=200)
    resume = BoolParam("resume from latest checkpoint if present",
                       default=True)
    logEvery = IntParam("steps between loss logs", default=50)
    dataFeed = EnumParam(
        ["host", "device"],
        "'host' streams minibatches through a prefetch thread; 'device' "
        "places the whole (padded) dataset in HBM once and shuffles on "
        "device per epoch, so the steady-state step consumes only a "
        "scalar index from the host — the MXU-bound mode for datasets "
        "that fit in HBM (single-process, in-memory tables only)",
        default="host")
    profileDir = StringParam(
        "emit a jax.profiler xplane trace of the training loop here "
        "('' = off; SURVEY §5 profiler upgrade)", default="")
    traceAnnotations = BoolParam(
        "wrap each train-step/chunk dispatch in a named "
        "jax.profiler.TraceAnnotation so an on-chip (xplane) profile's "
        "rows correlate 1:1 with the framework's learner.step/chunk "
        "spans (opt-in: annotations cost a TraceMe record per dispatch)",
        default=False)
    memoryStatsEvery = IntParam(
        "steps between device-memory-stats samples (bytes_in_use/peak) "
        "recorded into learner.memory_samples and the fit trace "
        "(0 = off; device-feed mode samples once per chunk)", default=0)

    def _post_init(self):
        self._mesh: Optional[Mesh] = None
        self.history: List[Dict[str, float]] = []

    def set_mesh(self, mesh: Mesh) -> "TPULearner":
        self._mesh = mesh
        return self

    # -- internals ----------------------------------------------------------

    def _build_module(self):
        factory = self.get("moduleFactory")
        if factory is not None:
            return factory()
        spec = self.get("networkSpec")
        if spec is None:
            raise ValueError("set networkSpec or moduleFactory")
        spec = dict(spec)
        if self.get("computeDtype") == "bfloat16":
            spec.setdefault("dtype", "bfloat16")
        return build_network(spec)

    def _loss_fn(self, logits, y, w):
        kind = self.get("loss")
        if kind == "cross_entropy":
            losses = optax.softmax_cross_entropy_with_integer_labels(
                logits.astype(jnp.float32), y)
        elif kind == "token_cross_entropy":
            per_tok = optax.softmax_cross_entropy_with_integer_labels(
                logits.astype(jnp.float32), y)
            losses = per_tok.mean(axis=-1)
        else:  # mse
            pred = logits.astype(jnp.float32)
            if pred.ndim == 2 and pred.shape[-1] == 1:
                pred = pred[:, 0]
            losses = (pred - y.astype(jnp.float32)) ** 2
        return jnp.sum(losses * w) / jnp.maximum(jnp.sum(w), 1.0)

    def fit(self, table) -> TPUModel:
        """``table`` is a DataTable, or — streaming ingestion for data
        that should not live in host RAM at once — a sequence of
        DataTable shards / a zero-arg callable returning an iterable of
        shards (re-invoked each epoch; shuffling is within-shard with
        remainder rows carried across shard boundaries). The HDFS-staged
        feed of the reference (CNTKLearner.scala:123-140) becomes a
        shard iterator."""
        mesh = self._mesh or mesh_lib.make_mesh(self.get("meshAxes"))
        module = self._build_module()
        input_shape = self.get("inputShape")
        fcol, lcol = self.get_features_col(), self.get_label_col()
        y_cast = np.int32 if self.get("loss") != "mse" else np.float32

        streaming = not isinstance(table, DataTable)
        if streaming:
            if not callable(table) and iter(table) is table:
                raise ValueError(
                    "streaming fit() needs to replay shards every epoch: "
                    "pass a sequence of DataTables, an io.ooc."
                    "ChunkedTable, or a zero-arg callable returning a "
                    "fresh iterator, not a one-shot generator")
            factory = table if callable(table) else (lambda: iter(table))
            # one metadata pass: count rows AND grab the first shard for
            # shapes/schema (IO-backed factories pay this pass once, not
            # twice). A ChunkedTable that already knows its row count
            # skips the counting decode pass entirely (spill-aware
            # feed: epochs then replay the chunk stream, each chunk
            # decoding on the prefetch worker while the device steps).
            from mmlspark_tpu.io.ooc import ChunkedTable as _Chunked
            if isinstance(table, _Chunked) and table.num_rows:
                n, first_shard = table.num_rows, table.peek()
            else:
                n, first_shard = 0, None
                for t in factory():
                    if first_shard is None:
                        first_shard = t
                    n += len(t)
            if n == 0:
                raise ValueError("empty shard stream")
            x0, y0 = table_to_xy(first_shard, fcol, lcol, input_shape)
            sample_x, sample_y = x0[:1], y0[:1].astype(y_cast)
            schema_src = first_shard
            x = y = None
        else:
            x, y = table_to_xy(table, fcol, lcol, input_shape)
            y = y.astype(y_cast)
            n = x.shape[0]
            sample_x, sample_y = x[:1], y[:1]
            schema_src = table

        # multi-host: each process feeds its LOCAL rows; the global batch
        # is assembled per-step from every host's slice (the
        # host-partitioned feeding that replaces HDFS staging + scp,
        # ref: CNTKLearner.scala:123-140 / CommandBuilders.scala:207-229).
        # The caller passes this host's shard (see
        # parallel.distributed.shard_table_for_host); shards must be
        # equal-sized across hosts so step counts agree.
        from mmlspark_tpu.parallel import distributed as dist
        proc_count = dist.host_info().process_count
        batch_size = self.get("batchSize")
        if proc_count > 1:
            if batch_size % proc_count:
                raise ValueError(
                    f"batchSize {batch_size} must divide evenly over "
                    f"{proc_count} processes")
            local_batch = batch_size // proc_count
            # agree on a common step count: ragged shards would make one
            # host enter a collective the others never reach. Truncate
            # every host to the global minimum row count — streaming
            # already counted its rows in the metadata pass, so the same
            # agreement covers ragged shard streams (each host caps its
            # per-epoch consumption at n_min; the batching then yields
            # identical step counts and batch shapes on every host).
            from jax.experimental import multihost_utils
            n_all = np.asarray(multihost_utils.process_allgather(
                np.asarray([n])))
            n_min = int(n_all.min())
            if n_min != n:
                logger.warning(
                    "host shards are unequal (%s); truncating to %d "
                    "rows per host so step counts agree",
                    n_all.ravel().tolist(), n_min)
                if not streaming:
                    x, y = x[:n_min], y[:n_min]
                n = n_min
        else:
            local_batch = batch_size
        device_feed = self.get("dataFeed") == "device"
        if device_feed and streaming:
            raise ValueError(
                "dataFeed='device' needs the whole dataset resident in "
                "HBM: pass an in-memory DataTable per process (use "
                "dataFeed='host' for shard streams)")
        steps_per_epoch = max(1, (n + local_batch - 1) // local_batch)
        total_steps = steps_per_epoch * self.get("epochs")

        tx = make_optimizer(
            self.get("optimizer"), self.get("learningRate"),
            momentum=self.get("momentum"),
            weight_decay=self.get("weightDecay"),
            schedule=self.get("schedule"),
            warmup_steps=self.get("warmupSteps"),
            total_steps=total_steps)

        rng = jax.random.PRNGKey(self.get("seed"))
        sample_in = jnp.asarray(sample_x)
        if getattr(module, "int_input", False):
            sample_in = sample_in.astype(jnp.int32)
        variables = module.init(rng, sample_in, train=False)
        params = variables["params"]
        batch_stats = variables.get("batch_stats", {})
        has_bn = bool(batch_stats)

        state = {
            "params": params,
            "opt_state": tx.init(params),
            "batch_stats": batch_stats,
            "step": jnp.zeros((), jnp.int32),
        }

        # shardings: batch over data axis; state replicated or fsdp-sharded
        if (self.get("paramSharding") == "fsdp"
                and mesh_lib.FSDP_AXIS in mesh.shape):
            rule = fsdp_sharding_rule(mesh)
            state_sharding = jax.tree_util.tree_map(rule, state)
        else:
            repl = NamedSharding(mesh, P())
            state_sharding = jax.tree_util.tree_map(
                lambda _: repl, state)
        state = jax.tree_util.tree_map(
            lambda a, s: jax.device_put(jnp.asarray(a), s),
            state, state_sharding)

        data_sharding = {
            "x": NamedSharding(mesh, P(*((mesh_lib.DATA_AXIS,)
                                         + (None,) * (sample_x.ndim - 1)))),
            "y": NamedSharding(mesh, P(*((mesh_lib.DATA_AXIS,)
                                         + (None,) * (sample_y.ndim - 1)))),
            "w": NamedSharding(mesh, P(mesh_lib.DATA_AXIS)),
        }

        loss_kind = self.get("loss")
        is_int_input = bool(getattr(module, "int_input", False))
        dropout_seed = self.get("seed") + 1

        def train_step(st, batch):
            step_rng = jax.random.fold_in(
                jax.random.PRNGKey(dropout_seed), st["step"])

            def loss_of(p):
                inputs = batch["x"].astype(jnp.int32) if is_int_input \
                    else batch["x"]
                var_in = {"params": p}
                if has_bn:
                    var_in["batch_stats"] = st["batch_stats"]
                    out, mut = module.apply(
                        var_in, inputs, train=True,
                        mutable=["batch_stats"],
                        rngs={"dropout": step_rng})
                    new_bs = mut["batch_stats"]
                else:
                    out = module.apply(var_in, inputs, train=True,
                                       rngs={"dropout": step_rng})
                    new_bs = st["batch_stats"]
                loss = self._loss_fn(out, batch["y"], batch["w"])
                return loss, new_bs

            (loss, new_bs), grads = jax.value_and_grad(
                loss_of, has_aux=True)(st["params"])
            updates, new_opt = tx.update(grads, st["opt_state"], st["params"])
            new_params = optax.apply_updates(st["params"], updates)
            return {
                "params": new_params,
                "opt_state": new_opt,
                "batch_stats": new_bs,
                "step": st["step"] + 1,
            }, loss

        jit_step = jax.jit(train_step,
                           in_shardings=(state_sharding, data_sharding),
                           out_shardings=(state_sharding, None),
                           donate_argnums=(0,))

        # checkpoint/resume. A corrupt/truncated checkpoint (a crash
        # mid-save, a filesystem hiccup) must not kill the whole fit:
        # fall back newest -> oldest across the retained checkpoints,
        # then to fresh init — resume is a best-effort accelerator, not
        # a correctness gate (losing a few hundred steps beats losing
        # the run).
        ckpt_dir = self.get("checkpointDir")
        start_step = 0
        if ckpt_dir and self.get("resume"):
            candidates = _checkpoint_candidates(ckpt_dir)
            for candidate in candidates:
                try:
                    loaded = _load_checkpoint_pytree(candidate)
                    # namedtuple containers (optax states) serialize as
                    # plain tuples; rebuild them against the
                    # freshly-built treedef. Unflatten/step parsing can
                    # fail on a truncated file too — same fallback.
                    host_state = jax.tree_util.tree_unflatten(
                        jax.tree_util.tree_structure(state),
                        jax.tree_util.tree_leaves(loaded))
                    cand_step = int(host_state["step"])
                    cand_state = jax.tree_util.tree_map(
                        lambda a, s: jax.device_put(jnp.asarray(a), s),
                        host_state, state_sharding)
                except OSError:
                    # transient I/O (network timeout, 5xx via the
                    # remote filesystems' IOError surface, connection
                    # reset) is NOT corruption: falling back here
                    # would silently restart a run from fresh init
                    # during a store outage — fail loudly instead (the
                    # filesystem layer already retried)
                    raise
                except Exception as e:  # noqa: BLE001 — corrupt ckpt
                    # parse-class failures (truncated npz, bad json,
                    # mismatched tree): genuinely a bad FILE
                    logger.warning(
                        "failed to load checkpoint %s (%s); falling "
                        "back to the previous one", candidate, e)
                    continue
                state = cand_state
                start_step = cand_step
                logger.info("resumed from %s (step %d)", candidate,
                            start_step)
                break
            else:
                if candidates:
                    logger.warning(
                        "no loadable checkpoint in %s; training from "
                        "fresh init", ckpt_dir)
        if proc_count > 1 and ckpt_dir and self.get("resume"):
            # hosts must resume from the SAME step — a host that found
            # no checkpoint (non-shared filesystem) would replay steps
            # the others skip and hang the first collective
            from jax.experimental import multihost_utils
            steps = np.asarray(multihost_utils.process_allgather(
                np.asarray([start_step]))).ravel()
            if len(set(steps.tolist())) > 1:
                raise RuntimeError(
                    f"hosts disagree on the resume step {steps.tolist()}:"
                    f" checkpointDir must be on a filesystem shared by "
                    f"all hosts (or set resume=False)")

        # training loop. Input feed: a background thread slices/pads the
        # next minibatch and device_puts it while the current step runs on
        # the MXU (the CNTK out-of-band reader analog — see utils/prefetch).
        # Logging NEVER syncs the device on the hot path: logged losses stay
        # on device and are flushed one logEvery-interval late, by which
        # time they are ready and float() is free.
        import time as _time
        from mmlspark_tpu.utils.prefetch import make_prefetcher

        self.history = []
        self.timing: Dict[str, float] = {}
        # fit-scoped trace: per-step/chunk dispatch spans + optional
        # device-memory samples, in the same buffer the serving spans
        # land in (span count capped so a long fit can't balloon it)
        from mmlspark_tpu.core.trace import get_tracer
        _tracer = get_tracer()
        fit_trace = _tracer.new_trace("learner.fit") \
            if _tracer.enabled else None
        _SPAN_CAP = 2048
        ann_on = bool(self.get("traceAnnotations"))
        mem_every = int(self.get("memoryStatsEvery") or 0)
        self.memory_samples: List[Dict[str, Any]] = []

        def _emit_span(name, t0, **attrs):
            if fit_trace is not None and \
                    len(fit_trace._spans) < _SPAN_CAP:
                _tracer.emit(name, t0, trace=fit_trace, attrs=attrs)

        def _sample_memory(step_, force=False):
            if not mem_every or (not force and step_ % mem_every):
                return
            from mmlspark_tpu.utils.profiling import device_memory_stats
            stats = device_memory_stats()
            if not stats:
                return
            sample = {"step": int(step_)}
            for key in ("bytes_in_use", "peak_bytes_in_use",
                        "bytes_limit"):
                if key in stats:
                    sample[key] = stats[key]
            self.memory_samples.append(sample)
            _emit_span("memory", _time.perf_counter(), **sample)

        np_rng = np.random.default_rng(self.get("seed"))
        log_every = self.get("logEvery")
        ckpt_every = self.get("checkpointEvery")
        epochs = self.get("epochs")

        def index_stream():
            """(epoch, step, bx, by) numpy batches. In-memory mode
            shuffles globally per epoch; streaming mode re-reads the
            shard factory each epoch, shuffles within shards, and
            carries remainder rows across shard boundaries."""
            step = 0
            for epoch in range(epochs):
                if not streaming:
                    order = np_rng.permutation(n)
                    for bstart in range(0, n, local_batch):
                        step += 1
                        if step <= start_step:
                            continue  # fast-forward post-resume
                        idx = order[bstart:bstart + local_batch]
                        yield epoch, step, x[idx], y[idx]
                    continue
                carry_x = carry_y = None
                consumed = 0   # rows taken this epoch; capped at n so
                #                multi-host ragged streams stay in step
                for shard in factory():
                    if consumed >= n:
                        break
                    xs, ys = table_to_xy(shard, fcol, lcol, input_shape)
                    ys = ys.astype(y_cast)
                    take = min(len(xs), n - consumed)
                    if take < len(xs):
                        xs, ys = xs[:take], ys[:take]
                    consumed += take
                    perm = np_rng.permutation(len(xs))
                    xs, ys = xs[perm], ys[perm]
                    if carry_x is not None:
                        xs = np.concatenate([carry_x, xs])
                        ys = np.concatenate([carry_y, ys])
                    n_full = len(xs) // local_batch
                    for i in range(n_full):
                        step += 1
                        if step <= start_step:
                            continue
                        sl = slice(i * local_batch, (i + 1) * local_batch)
                        yield epoch, step, xs[sl], ys[sl]
                    rest = len(xs) - n_full * local_batch
                    carry_x = xs[-rest:] if rest else None
                    carry_y = ys[-rest:] if rest else None
                if carry_x is not None:
                    step += 1
                    if step > start_step:
                        yield epoch, step, carry_x, carry_y

        def _to_global(arr, sharding):
            """Local slice -> global device array. Single-process:
            plain device_put; multi-process: every host contributes its
            slice of the global batch."""
            if proc_count > 1:
                return jax.make_array_from_process_local_data(
                    sharding, arr)
            return jax.device_put(arr, sharding)

        def make_batch(item):
            epoch, step, bx_np, by_np = item
            bx, true_len = mesh_lib.pad_to_multiple(
                bx_np, local_batch, axis=0)
            by, _ = mesh_lib.pad_to_multiple(by_np, local_batch, axis=0)
            w = (np.arange(local_batch) < true_len).astype(np.float32)
            return epoch, step, true_len * proc_count, {
                "x": _to_global(bx, data_sharding["x"]),
                "y": _to_global(by, data_sharding["y"]),
                "w": _to_global(w, data_sharding["w"]),
            }

        pending: List[Tuple[int, int, Any, float]] = []  # deferred log queue

        def flush_logs(final: bool = False) -> None:
            # flush entries whose device value is (almost surely) ready:
            # everything but the newest, or everything when final
            keep = 0 if final else 1
            while len(pending) > keep:
                step_, epoch_, dev_loss, t = pending.pop(0)
                if isinstance(dev_loss, tuple):
                    # device-feed chunks log (loss_vector, index); resolve
                    # via a plain transfer — indexing with jnp would
                    # compile an eager gather mid-loop
                    arr, j = dev_loss
                    lv = float(np.asarray(arr)[j])
                else:
                    lv = float(dev_loss)
                self.history.append({"step": step_, "loss": lv,
                                     "epoch": epoch_, "time": t})
                logger.info("step %d/%d loss %.4f", step_, total_steps, lv)

        from mmlspark_tpu.utils.profiling import maybe_trace

        global_step = start_step
        t_first = None
        t_loop_start = _time.time()
        first_timed_step = start_step
        examples_timed = 0   # true (unpadded) rows after the warmup step
        flops_per_step: Optional[float] = None
        # CPU backend: async dispatch racing ahead starves XLA's
        # in-process collective rendezvous on small hosts (7/8 devices
        # join, the 8th's thunk never gets a pool thread -> fatal
        # timeout). Serialize steps there; TPU keeps async dispatch.
        sync_each_step = jax.default_backend() == "cpu"

        def step_bookkeeping(loss, true_rows, epoch):
            """Per-step timing/logging/checkpoint shared by both feed
            modes (reads global_step/state from the enclosing scope)."""
            nonlocal t_first, first_timed_step, examples_timed
            if sync_each_step:
                loss.block_until_ready()
            if t_first is None:
                # sync the compile+first step via value transfer (the
                # tunnel backend's readiness can run ahead of execution)
                float(loss)
                t_first = _time.time()
                first_timed_step = global_step
            else:
                examples_timed += true_rows
            if global_step % log_every == 0 or global_step == total_steps:
                pending.append((global_step, epoch, loss, _time.time()))
                flush_logs()
            if ckpt_dir and global_step % ckpt_every == 0:
                _save_checkpoint(ckpt_dir, global_step, state)

        if device_feed:
            # Pad once to full batches; per-epoch shuffle happens ON
            # DEVICE: a permutation derived on device from the (shared)
            # seed key gathers the padded dataset into an
            # (steps, batch, ...) epoch tensor, and each step then reads
            # only a scalar batch index from the host — the steady state
            # is chip-bound, not feed-bound. Multi-host: every process
            # contributes its LOCAL padded shard to a row-sharded global
            # array; the permutation key is seed-derived in-program so
            # hosts agree without communicating, and the global gather's
            # cross-device row movement rides the mesh interconnect
            # (ref: CommandBuilders.scala:108-267 — distributed training
            # is the product, not a mode).
            n_pad_local = steps_per_epoch * local_batch
            pad = n_pad_local - n
            if pad:
                x_p = np.concatenate(
                    [x, np.zeros((pad,) + x.shape[1:], x.dtype)])
                y_p = np.concatenate(
                    [y, np.zeros((pad,) + y.shape[1:], y.dtype)])
            else:
                x_p, y_p = x, y
            w_p = (np.arange(n_pad_local) < n).astype(np.float32)
            n_pad = n_pad_local * proc_count     # GLOBAL padded rows
            global_batch = local_batch * proc_count
            try:
                stats = jax.devices()[0].memory_stats() or {}
                hbm_limit = stats.get("bytes_limit")
            except Exception:
                hbm_limit = None
            # resident twice: the row-major copy + the epoch tensor. Only
            # the data axis shards the rows — other mesh axes replicate
            # them, so per-chip residency divides by the data size alone.
            want = 2 * proc_count * (x_p.nbytes + y_p.nbytes + w_p.nbytes)
            per_chip = want / mesh.shape.get(mesh_lib.DATA_AXIS, 1)
            if hbm_limit and per_chip > 0.6 * hbm_limit:
                logger.warning(
                    "dataFeed='device' will hold ~%.1f GB per chip in HBM "
                    "(limit %.1f GB/chip); consider dataFeed='host'",
                    per_chip / 2**30, hbm_limit / 2**30)
            repl = NamedSharding(mesh, P())

            def _row_sh(nd):
                return NamedSharding(mesh, P(*((mesh_lib.DATA_AXIS,)
                                               + (None,) * (nd - 1))))

            x_dev = _to_global(x_p, _row_sh(x_p.ndim))
            y_dev = _to_global(y_p, _row_sh(y_p.ndim))
            w_dev = _to_global(w_p, _row_sh(1))
            row_shardings = (_row_sh(x_p.ndim), _row_sh(y_p.ndim),
                             _row_sh(1))
            base_key = jax.random.PRNGKey(self.get("seed") + 17)

            def run_chunk(st, xf, yf, wf, epoch_s, start, length):
                """``length`` consecutive steps as ONE device program:
                the epoch permutation is derived on device from the epoch
                index (fold_in — deterministic, so resume replays it),
                the shuffled epoch tensors never exist on the host, and
                the scan body reads one batch per step. The host
                dispatches once per chunk with two scalars; nothing else
                crosses the tunnel, so tiny step times can't become
                host-dispatch-bound (one-time eager-op compiles cost
                ~0.7 s each through the remote backend — the loop must
                not contain any)."""
                perm = jax.random.permutation(
                    jax.random.fold_in(base_key, epoch_s), n_pad)
                # gather ONLY this chunk's rows (checkpoint-segmented
                # chunks would otherwise re-gather the full epoch tensor
                # once per segment)
                sel = jax.lax.dynamic_slice_in_dim(
                    perm, start * global_batch, length * global_batch)

                def g(a):
                    return a[sel].reshape(
                        (length, global_batch) + a.shape[1:])
                xs, ys, ws = g(xf), g(yf), g(wf)

                def body(carry, b):
                    batch = {"x": xs[b], "y": ys[b], "w": ws[b]}
                    return train_step(carry, batch)
                st, losses = jax.lax.scan(
                    body, st, jnp.arange(length))
                # true (unpadded) rows this chunk — padding carries w=0
                cnt = (ws > 0).sum()
                return st, losses, cnt

            chunk_fns: Dict[int, Any] = {}   # scan length -> jitted fn

            def get_chunk_fn(length):
                if length not in chunk_fns:
                    def f(st, xf, yf, wf, e, s0, _len=length):
                        return run_chunk(st, xf, yf, wf, e, s0, _len)
                    chunk_fns[length] = jax.jit(
                        f,
                        in_shardings=(state_sharding,) + row_shardings
                        + (None, None),
                        out_shardings=(state_sharding, None, None),
                        donate_argnums=(0,))
                return chunk_fns[length]

            # (device count scalar, counted-in-steady-state?) per chunk;
            # resolved after the clock stops
            chunk_counts: List[Tuple[Any, bool]] = []

            def chunk_bookkeeping(losses, cnt, length, epoch):
                """Chunk analog of step_bookkeeping. All values stay on
                device; the only host interaction is np.asarray transfers
                (never eager jnp ops, which would compile mid-loop)."""
                nonlocal t_first, first_timed_step
                if sync_each_step or t_first is None:
                    # sync via VALUE TRANSFER, not block_until_ready: the
                    # experimental tunnel backend has been observed to
                    # report readiness before remote execution completes,
                    # but the loss bytes cannot arrive early
                    np.asarray(losses)
                chunk_counts.append((cnt, t_first is not None))
                if t_first is None:
                    # timing starts after the compile+first chunk
                    t_first = _time.time()
                    first_timed_step = global_step
                base = global_step - length
                for j in range(length):
                    gs = base + j + 1
                    if gs % log_every == 0 or gs == total_steps:
                        pending.append(
                            (gs, epoch, (losses, j), _time.time()))
                flush_logs()
                if ckpt_dir and global_step % ckpt_every == 0:
                    _save_checkpoint(ckpt_dir, global_step, state)

            with maybe_trace(self.get("profileDir")):
                for epoch in range(epochs):
                    if (epoch + 1) * steps_per_epoch <= start_step:
                        global_step = (epoch + 1) * steps_per_epoch
                        continue
                    base = epoch * steps_per_epoch
                    i = max(0, start_step - base)   # resume mid-epoch
                    while i < steps_per_epoch:
                        seg_end = steps_per_epoch
                        if ckpt_dir:
                            # segment at checkpoint boundaries so saves
                            # land exactly every checkpointEvery steps
                            cur = base + i
                            nxt = (cur // ckpt_every + 1) * ckpt_every
                            seg_end = min(seg_end, nxt - base)
                        length = seg_end - i
                        fn = get_chunk_fn(length)
                        if flops_per_step is None:
                            # cost-analyze ONE bare train_step (XLA's
                            # analysis counts a scan body once, so
                            # analyzing the chunk would under-report by
                            # the scan length); lowered from avals, one
                            # extra compile before timing starts
                            batch_sds = {
                                "x": jax.ShapeDtypeStruct(
                                    (global_batch,) + x_p.shape[1:],
                                    x_p.dtype),
                                "y": jax.ShapeDtypeStruct(
                                    (global_batch,) + y_p.shape[1:],
                                    y_p.dtype),
                                "w": jax.ShapeDtypeStruct(
                                    (global_batch,), jnp.float32),
                            }
                            probe = jax.jit(
                                train_step,
                                in_shardings=(state_sharding,
                                              data_sharding),
                                out_shardings=(state_sharding, None))
                            flops_per_step = _step_flops(
                                probe.lower(state, batch_sds).compile())
                            flops_per_step = flops_per_step or -1.0
                        from mmlspark_tpu.utils.profiling import annotate
                        t_chunk = _time.perf_counter()
                        if ann_on:
                            with annotate("learner_chunk"):
                                state, losses, cnt = fn(
                                    state, x_dev, y_dev, w_dev,
                                    np.int32(epoch), np.int32(i))
                        else:
                            state, losses, cnt = fn(
                                state, x_dev, y_dev, w_dev,
                                np.int32(epoch), np.int32(i))
                        global_step = base + seg_end
                        chunk_bookkeeping(losses, cnt, length, epoch)
                        _emit_span("learner.chunk", t_chunk,
                                   step=global_step, epoch=epoch,
                                   length=length)
                        _sample_memory(global_step, force=bool(mem_every))
                        i = seg_end
        else:
            from mmlspark_tpu.utils.profiling import annotate
            feed = make_prefetcher(index_stream(), make_batch, depth=2)
            try:
                with maybe_trace(self.get("profileDir")):
                    for epoch, global_step, true_len, batch in feed:
                        t_step = _time.perf_counter()
                        if ann_on:
                            with annotate("learner_step"):
                                state, loss = jit_step(state, batch)
                        else:
                            state, loss = jit_step(state, batch)
                        # dispatch-enqueue wall (steps run async): the
                        # span shows host-side stalls, the xplane
                        # annotation shows the on-chip time
                        _emit_span("learner.step", t_step,
                                   step=global_step, epoch=epoch)
                        step_bookkeeping(loss, true_len, epoch)
                        _sample_memory(global_step)
            finally:
                # abnormal exit must not leave the worker blocked in put()
                # pinning prefetched batches in HBM
                feed.close()
        state = jax.block_until_ready(state)
        # belt-and-braces completion barrier: fetch a real VALUE from the
        # final state (see chunk_bookkeeping — the tunnel backend's
        # readiness signal has been observed to run ahead of execution;
        # transferred bytes cannot)
        np.asarray(state["step"])
        t_end = _time.time()
        if device_feed:
            # resolve the deferred per-chunk row counts (transfers only,
            # after the clock stops so they can't skew the measurement).
            # Counts are GLOBAL already — the chunk's w spans every
            # host's rows — so no per-process multiplier.
            examples_timed = int(sum(
                float(np.asarray(c)) for c, timed in chunk_counts
                if timed))
            if t_first is not None and global_step == first_timed_step:
                # single-chunk run: the whole fit was "warmup", so report
                # the full wall including the first chunk (compile time
                # excluded is impossible here — flag it)
                examples_timed = int(sum(
                    float(np.asarray(c)) for c, _ in chunk_counts))
                first_timed_step = start_step
                t_first = t_loop_start
                self_timing_includes_compile = True
            else:
                self_timing_includes_compile = False
        else:
            self_timing_includes_compile = False
        flush_logs(final=True)
        steps_timed = global_step - (first_timed_step if t_first else 0)
        if t_first is not None and steps_timed > 0:
            wall = t_end - t_first
            self.timing = {
                "steps_timed": steps_timed,
                "wall_s": wall,
                # true rows only — padding of partial batches is masked
                # compute, counting it would inflate the metric
                "examples_per_sec": examples_timed / max(wall, 1e-9),
            }
            if self_timing_includes_compile:
                self.timing["includes_compile"] = True
            if flops_per_step and flops_per_step > 0:
                # XLA cost analysis reports the PER-DEVICE cost of the
                # SPMD-partitioned module (verified empirically on a
                # data-sharded matmul), so per-chip rates need no further
                # division by chip count
                tflops = flops_per_step * steps_timed / max(wall, 1e-9) / 1e12
                self.timing["flops_per_step_per_chip"] = flops_per_step
                self.timing["model_flops_per_step"] = (
                    flops_per_step * int(mesh.devices.size))
                self.timing["tflops_per_sec_per_chip"] = tflops
                peak = peak_flops_per_chip(jax.devices()[0].device_kind)
                if peak:
                    self.timing["mfu"] = tflops * 1e12 / peak
        if ckpt_dir:
            _save_checkpoint(ckpt_dir, global_step, state)
        if fit_trace is not None:
            fit_trace.root.set("steps", int(global_step))
            fit_trace.root.set("feed",
                               "device" if device_feed else "host")
            if self.timing:
                fit_trace.root.set(
                    "examples_per_sec",
                    round(self.timing.get("examples_per_sec", 0.0), 1))
            _tracer.finish(fit_trace)

        host_params = jax.device_get(state["params"])
        host_bs = jax.device_get(state["batch_stats"])
        weights = {"params": host_params}
        if has_bn:
            weights["batch_stats"] = host_bs
        field = schema_src.schema.get(self.get_features_col())
        img_scale = (1.0 / 255.0) if (field is not None
                                      and ImageSchema.is_image(field)) else 1.0
        model = TPUModel(
            modelFn=_InferApply(module, is_int_input, img_scale, input_shape),
            weights=weights,
            inputCol=self.get_features_col(),
            outputCol="scores",
            batchSize=batch_size,
            computeDtype="float32")
        model.set_mesh(mesh)
        return model


class _InferApply:
    """Picklable inference apply for trained modules (handles batch_stats
    and integer-token inputs)."""

    def __init__(self, module, int_input: bool = False, scale: float = 1.0,
                 input_shape=None):
        self.module = module
        self.int_input = int_input
        self.scale = scale
        self.input_shape = input_shape

    def __call__(self, weights, inputs):
        x = list(inputs.values())[0]
        if self.input_shape:
            x = x.reshape((x.shape[0],) + tuple(self.input_shape))
        if self.int_input:
            x = x.astype(jnp.int32)
        elif self.scale != 1.0:
            x = x.astype(jnp.float32) * self.scale
        variables = {"params": weights["params"]}
        if "batch_stats" in weights and weights["batch_stats"]:
            variables["batch_stats"] = weights["batch_stats"]
        return self.module.apply(variables, x, train=False)


def _is_remote(path: str) -> bool:
    from mmlspark_tpu.utils import filesystem as fslib
    return fslib.scheme_of(path) != "file"


def _remote_steps(ckpt_dir: str) -> List[str]:
    """Sorted step_XXXXXXXX names that have a COMPLETE checkpoint
    (treedef.json is uploaded last, so its presence marks done)."""
    import re
    from mmlspark_tpu.utils import filesystem as fslib
    fs = fslib.get_filesystem(ckpt_dir)
    steps = set()
    for f in fs.list_files(ckpt_dir.rstrip("/"), recursive=True):
        m = re.search(r"(step_\d{8})/treedef\.json$", f)
        if m:
            steps.add(m.group(1))
    return sorted(steps)


def _save_checkpoint(ckpt_dir: str, step: int, state) -> None:
    # multi-host: only the coordinator writes (hosts share the FS —
    # which may be a remote scheme like webdav://, the HDFS-staging
    # analog of CNTKLearner.scala:18-67 dataTransfer=hdfs)
    if jax.process_index() != 0:
        return
    host = jax.device_get(state)
    if _is_remote(ckpt_dir):
        import tempfile
        from mmlspark_tpu.utils import filesystem as fslib
        fs = fslib.get_filesystem(ckpt_dir)
        base = f"{ckpt_dir.rstrip('/')}/step_{step:08d}"
        with tempfile.TemporaryDirectory() as td:
            ser._save_pytree(host, td)
            # treedef.json LAST: it is the completeness marker that
            # _remote_steps / resume key on
            names = sorted(os.listdir(td),
                           key=lambda n: n == "treedef.json")
            for fn in names:
                with open(os.path.join(td, fn), "rb") as f:
                    fs.write_bytes(f"{base}/{fn}", f.read())
        try:
            stales = _remote_steps(ckpt_dir)[:-3]
            for stale in stales:
                fs.delete_path(f"{ckpt_dir.rstrip('/')}/{stale}/")
        except (IOError, OSError, NotImplementedError):
            pass                       # pruning (incl. listing) is
            #                            best-effort — the save landed
        return
    path = os.path.join(ckpt_dir, f"step_{step:08d}")
    os.makedirs(path, exist_ok=True)
    ser._save_pytree(host, path)
    # keep only the 3 latest
    all_ckpts = sorted(d for d in os.listdir(ckpt_dir)
                       if d.startswith("step_"))
    for stale in all_ckpts[:-3]:
        import shutil
        shutil.rmtree(os.path.join(ckpt_dir, stale), ignore_errors=True)


def _checkpoint_candidates(ckpt_dir: str) -> List[str]:
    """All retained checkpoint paths, NEWEST first — the corrupt-
    checkpoint fallback order (resume tries each until one loads). A
    remote LISTING failure propagates (the filesystem layer already
    retries): an unreachable store must fail loudly, not silently
    restart training from scratch — only corrupt checkpoint FILES get
    the fallback treatment."""
    if _is_remote(ckpt_dir):
        steps = _remote_steps(ckpt_dir)
        return [f"{ckpt_dir.rstrip('/')}/{s}" for s in reversed(steps)]
    if not os.path.isdir(ckpt_dir):
        return []
    ckpts = sorted((d for d in os.listdir(ckpt_dir)
                    if d.startswith("step_")), reverse=True)
    return [os.path.join(ckpt_dir, d) for d in ckpts]


def _latest_checkpoint(ckpt_dir: str) -> Optional[str]:
    candidates = _checkpoint_candidates(ckpt_dir)
    return candidates[0] if candidates else None


def _load_checkpoint_pytree(path: str):
    """ser._load_pytree from a local OR remote checkpoint directory."""
    if not _is_remote(path):
        return ser._load_pytree(path)
    import tempfile
    from mmlspark_tpu.utils import filesystem as fslib
    fs = fslib.get_filesystem(path)
    with tempfile.TemporaryDirectory() as td:
        for fn in ("leaves.npz", "treedef.json"):
            data = fs.read_bytes(f"{path.rstrip('/')}/{fn}")
            with open(os.path.join(td, fn), "wb") as f:
                f.write(data)
        return ser._load_pytree(td)
