"""TPULearner — minibatch SGD training of zoo networks as an Estimator.

TPU-native replacement for the reference's cntk-train component
(ref: src/cntk-train/src/main/scala/CNTKLearner.scala:88-176): where the
reference writes the dataset to CNTKTextFormat, emits BrainScript configs,
and shells out to ``mpirun cntk`` over ssh with scp'd data and hostfiles
(ref: CommandBuilders.scala:108-267), we build a flax network from a
declarative spec, jit one train step over a named device mesh, and stream
host-sharded minibatches through it:

- **DP**: batch sharded over the ``data`` axis; XLA inserts the gradient
  all-reduce (psum) over ICI — the analog of CNTK's MPI 1-bit SGD ring.
- **FSDP**: optionally shard each param's largest divisible dim over the
  mesh so optimizer state and weights scale past one chip's HBM.
- **bf16 compute / f32 params**: MXU-friendly mixed precision.
- **Masked final batch**: shapes stay static (no recompiles); padded rows
  carry zero loss weight.
- **Checkpoint/resume**: train state snapshots every N steps
  (ref analog: model persistence via ConstructorWritable + LightGBM
  modelString warm-start, SURVEY.md §5).

``fit`` returns a :class:`TPUModel` ready for batched inference — the
same contract as CNTKLearner returning a CNTKModel (:172-175).
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import optax

from mmlspark_tpu.core.logging_utils import get_logger
from mmlspark_tpu.core.params import (
    BoolParam, DictParam, EnumParam, FloatParam, HasFeaturesCol, HasLabelCol,
    IntParam, StringParam, UDFParam,
)
from mmlspark_tpu.core.schema import ImageSchema
from mmlspark_tpu.core.stage import Estimator
from mmlspark_tpu.core.table import DataTable
from mmlspark_tpu.core import serialize as ser
from mmlspark_tpu.models.networks import build_network
from mmlspark_tpu.models.tpu_model import TPUModel
from mmlspark_tpu.parallel import mesh as mesh_lib

logger = get_logger("learner")


# ---------------------------------------------------------------------------
# optimizers / schedules
# ---------------------------------------------------------------------------


def make_optimizer(name: str, lr: float, *, momentum: float = 0.9,
                   weight_decay: float = 0.0, schedule: str = "constant",
                   warmup_steps: int = 0, total_steps: int = 1000
                   ) -> optax.GradientTransformation:
    if schedule == "cosine":
        w = max(warmup_steps, 1)
        sched = optax.warmup_cosine_decay_schedule(
            0.0, lr, w, max(total_steps, w + 1))
    elif schedule == "constant":
        if warmup_steps > 0:
            sched = optax.linear_schedule(0.0, lr, warmup_steps)
        else:
            sched = lr
    else:
        raise ValueError(f"unknown schedule {schedule!r}")
    if name == "sgd":
        return optax.sgd(sched)
    if name == "momentum":
        return optax.sgd(sched, momentum=momentum, nesterov=True)
    if name == "adam":
        return optax.adam(sched)
    if name == "adamw":
        return optax.adamw(sched, weight_decay=weight_decay)
    raise ValueError(f"unknown optimizer {name!r}")


# ---------------------------------------------------------------------------
# sharding rules
# ---------------------------------------------------------------------------


def fsdp_sharding_rule(mesh: Mesh, axis: str = mesh_lib.FSDP_AXIS
                       ) -> Callable[[jnp.ndarray], NamedSharding]:
    """Shard each leaf's largest dim divisible by the axis size; replicate
    otherwise (simple ZeRO-3-style rule)."""
    size = mesh.shape[axis]

    def rule(leaf) -> NamedSharding:
        shape = getattr(leaf, "shape", ())
        if not shape or size == 1:
            return NamedSharding(mesh, P())
        dims = sorted(range(len(shape)), key=lambda d: -shape[d])
        for d in dims:
            if shape[d] % size == 0 and shape[d] >= size:
                spec = [None] * len(shape)
                spec[d] = axis
                return NamedSharding(mesh, P(*spec))
        return NamedSharding(mesh, P())
    return rule


# ---------------------------------------------------------------------------
# feature extraction from table columns
# ---------------------------------------------------------------------------


def table_to_xy(table: DataTable, features_col: str, label_col: str,
                input_shape: Optional[List[int]] = None
                ) -> Tuple[np.ndarray, np.ndarray]:
    field = table.schema.get(features_col)
    col = table[features_col]
    if field is not None and ImageSchema.is_image(field):
        x = np.stack([np.asarray(r[ImageSchema.DATA]) for r in col]
                     ).astype(np.float32) / 255.0
    elif isinstance(col, np.ndarray):
        x = np.asarray(col, dtype=np.float32)
    else:
        x = np.stack([np.asarray(v) for v in col]).astype(np.float32)
    if input_shape:
        x = x.reshape((x.shape[0],) + tuple(input_shape))
    y = np.asarray(table[label_col])
    return x, y


class TPULearner(Estimator, HasFeaturesCol, HasLabelCol):
    """Train a zoo network on a table; returns a TPUModel."""

    networkSpec = DictParam(
        "declarative network spec, e.g. {'type':'resnet',...} "
        "(BrainScript analog, ref: BrainscriptBuilder.scala:16)", default=None)
    moduleFactory = UDFParam(
        "callable () -> flax Module (alternative to networkSpec)", default=None)
    loss = EnumParam(["cross_entropy", "mse", "token_cross_entropy"],
                     "training loss", default="cross_entropy")
    optimizer = EnumParam(["sgd", "momentum", "adam", "adamw"],
                          "optimizer", default="momentum")
    learningRate = FloatParam("peak learning rate", default=0.1)
    momentum = FloatParam("sgd momentum", default=0.9)
    weightDecay = FloatParam("adamw weight decay", default=1e-4)
    schedule = EnumParam(["constant", "cosine"], "lr schedule",
                         default="cosine")
    warmupSteps = IntParam("lr warmup steps", default=0)
    epochs = IntParam("training epochs", default=1)
    batchSize = IntParam("global batch size", default=128)
    seed = IntParam("rng seed", default=0)
    computeDtype = EnumParam(["float32", "bfloat16"],
                             "device compute dtype", default="bfloat16")
    meshAxes = DictParam("mesh axes, e.g. {'data': -1} or "
                         "{'data': 4, 'fsdp': 2}", default=None)
    paramSharding = EnumParam(["replicated", "fsdp"],
                              "parameter sharding strategy",
                              default="replicated")
    inputShape = UDFParam("reshape flat features to this per-row shape "
                          "(list), e.g. [32,32,3]", default=None)
    checkpointDir = StringParam("checkpoint directory ('' = off)", default="")
    checkpointEvery = IntParam("steps between checkpoints", default=200)
    resume = BoolParam("resume from latest checkpoint if present",
                       default=True)
    logEvery = IntParam("steps between loss logs", default=50)
    profileDir = StringParam(
        "emit a jax.profiler xplane trace of the training loop here "
        "('' = off; SURVEY §5 profiler upgrade)", default="")

    def _post_init(self):
        self._mesh: Optional[Mesh] = None
        self.history: List[Dict[str, float]] = []

    def set_mesh(self, mesh: Mesh) -> "TPULearner":
        self._mesh = mesh
        return self

    # -- internals ----------------------------------------------------------

    def _build_module(self):
        factory = self.get("moduleFactory")
        if factory is not None:
            return factory()
        spec = self.get("networkSpec")
        if spec is None:
            raise ValueError("set networkSpec or moduleFactory")
        spec = dict(spec)
        if self.get("computeDtype") == "bfloat16":
            spec.setdefault("dtype", "bfloat16")
        return build_network(spec)

    def _loss_fn(self, logits, y, w):
        kind = self.get("loss")
        if kind == "cross_entropy":
            losses = optax.softmax_cross_entropy_with_integer_labels(
                logits.astype(jnp.float32), y)
        elif kind == "token_cross_entropy":
            per_tok = optax.softmax_cross_entropy_with_integer_labels(
                logits.astype(jnp.float32), y)
            losses = per_tok.mean(axis=-1)
        else:  # mse
            pred = logits.astype(jnp.float32)
            if pred.ndim == 2 and pred.shape[-1] == 1:
                pred = pred[:, 0]
            losses = (pred - y.astype(jnp.float32)) ** 2
        return jnp.sum(losses * w) / jnp.maximum(jnp.sum(w), 1.0)

    def fit(self, table) -> TPUModel:
        """``table`` is a DataTable, or — streaming ingestion for data
        that should not live in host RAM at once — a sequence of
        DataTable shards / a zero-arg callable returning an iterable of
        shards (re-invoked each epoch; shuffling is within-shard with
        remainder rows carried across shard boundaries). The HDFS-staged
        feed of the reference (CNTKLearner.scala:123-140) becomes a
        shard iterator."""
        mesh = self._mesh or mesh_lib.make_mesh(self.get("meshAxes"))
        module = self._build_module()
        input_shape = self.get("inputShape")
        fcol, lcol = self.get_features_col(), self.get_label_col()
        y_cast = np.int32 if self.get("loss") != "mse" else np.float32

        streaming = not isinstance(table, DataTable)
        if streaming:
            if not callable(table) and iter(table) is table:
                raise ValueError(
                    "streaming fit() needs to replay shards every epoch: "
                    "pass a sequence of DataTables or a zero-arg callable "
                    "returning a fresh iterator, not a one-shot generator")
            factory = table if callable(table) else (lambda: iter(table))
            # one metadata pass: count rows AND grab the first shard for
            # shapes/schema (IO-backed factories pay this pass once, not
            # twice)
            n, first_shard = 0, None
            for t in factory():
                if first_shard is None:
                    first_shard = t
                n += len(t)
            if n == 0:
                raise ValueError("empty shard stream")
            x0, y0 = table_to_xy(first_shard, fcol, lcol, input_shape)
            sample_x, sample_y = x0[:1], y0[:1].astype(y_cast)
            schema_src = first_shard
            x = y = None
        else:
            x, y = table_to_xy(table, fcol, lcol, input_shape)
            y = y.astype(y_cast)
            n = x.shape[0]
            sample_x, sample_y = x[:1], y[:1]
            schema_src = table

        # multi-host: each process feeds its LOCAL rows; the global batch
        # is assembled per-step from every host's slice (the
        # host-partitioned feeding that replaces HDFS staging + scp,
        # ref: CNTKLearner.scala:123-140 / CommandBuilders.scala:207-229).
        # The caller passes this host's shard (see
        # parallel.distributed.shard_table_for_host); shards must be
        # equal-sized across hosts so step counts agree.
        from mmlspark_tpu.parallel import distributed as dist
        proc_count = dist.host_info().process_count
        batch_size = self.get("batchSize")
        if proc_count > 1:
            if batch_size % proc_count:
                raise ValueError(
                    f"batchSize {batch_size} must divide evenly over "
                    f"{proc_count} processes")
            local_batch = batch_size // proc_count
            if streaming:
                raise NotImplementedError(
                    "streaming shard ingestion is single-host for now: "
                    "hosts cannot agree on step counts without knowing "
                    "every shard's size up front (ragged streams would "
                    "deadlock the global-batch collectives)")
            # agree on a common step count: ragged shards would make one
            # host enter a collective the others never reach. Truncate
            # every host to the global minimum row count.
            from jax.experimental import multihost_utils
            n_all = np.asarray(multihost_utils.process_allgather(
                np.asarray([n])))
            n_min = int(n_all.min())
            if n_min != n:
                logger.warning(
                    "host shards are unequal (%s); truncating to %d "
                    "rows per host so step counts agree",
                    n_all.ravel().tolist(), n_min)
                x, y = x[:n_min], y[:n_min]
                n = n_min
        else:
            local_batch = batch_size
        steps_per_epoch = max(1, (n + local_batch - 1) // local_batch)
        total_steps = steps_per_epoch * self.get("epochs")

        tx = make_optimizer(
            self.get("optimizer"), self.get("learningRate"),
            momentum=self.get("momentum"),
            weight_decay=self.get("weightDecay"),
            schedule=self.get("schedule"),
            warmup_steps=self.get("warmupSteps"),
            total_steps=total_steps)

        rng = jax.random.PRNGKey(self.get("seed"))
        sample_in = jnp.asarray(sample_x)
        if getattr(module, "int_input", False):
            sample_in = sample_in.astype(jnp.int32)
        variables = module.init(rng, sample_in, train=False)
        params = variables["params"]
        batch_stats = variables.get("batch_stats", {})
        has_bn = bool(batch_stats)

        state = {
            "params": params,
            "opt_state": tx.init(params),
            "batch_stats": batch_stats,
            "step": jnp.zeros((), jnp.int32),
        }

        # shardings: batch over data axis; state replicated or fsdp-sharded
        if (self.get("paramSharding") == "fsdp"
                and mesh_lib.FSDP_AXIS in mesh.shape):
            rule = fsdp_sharding_rule(mesh)
            state_sharding = jax.tree_util.tree_map(rule, state)
        else:
            repl = NamedSharding(mesh, P())
            state_sharding = jax.tree_util.tree_map(
                lambda _: repl, state)
        state = jax.tree_util.tree_map(
            lambda a, s: jax.device_put(jnp.asarray(a), s),
            state, state_sharding)

        data_sharding = {
            "x": NamedSharding(mesh, P(*((mesh_lib.DATA_AXIS,)
                                         + (None,) * (sample_x.ndim - 1)))),
            "y": NamedSharding(mesh, P(*((mesh_lib.DATA_AXIS,)
                                         + (None,) * (sample_y.ndim - 1)))),
            "w": NamedSharding(mesh, P(mesh_lib.DATA_AXIS)),
        }

        loss_kind = self.get("loss")
        is_int_input = bool(getattr(module, "int_input", False))
        dropout_seed = self.get("seed") + 1

        def train_step(st, batch):
            step_rng = jax.random.fold_in(
                jax.random.PRNGKey(dropout_seed), st["step"])

            def loss_of(p):
                inputs = batch["x"].astype(jnp.int32) if is_int_input \
                    else batch["x"]
                var_in = {"params": p}
                if has_bn:
                    var_in["batch_stats"] = st["batch_stats"]
                    out, mut = module.apply(
                        var_in, inputs, train=True,
                        mutable=["batch_stats"],
                        rngs={"dropout": step_rng})
                    new_bs = mut["batch_stats"]
                else:
                    out = module.apply(var_in, inputs, train=True,
                                       rngs={"dropout": step_rng})
                    new_bs = st["batch_stats"]
                loss = self._loss_fn(out, batch["y"], batch["w"])
                return loss, new_bs

            (loss, new_bs), grads = jax.value_and_grad(
                loss_of, has_aux=True)(st["params"])
            updates, new_opt = tx.update(grads, st["opt_state"], st["params"])
            new_params = optax.apply_updates(st["params"], updates)
            return {
                "params": new_params,
                "opt_state": new_opt,
                "batch_stats": new_bs,
                "step": st["step"] + 1,
            }, loss

        jit_step = jax.jit(train_step,
                           in_shardings=(state_sharding, data_sharding),
                           out_shardings=(state_sharding, None),
                           donate_argnums=(0,))

        # checkpoint/resume
        ckpt_dir = self.get("checkpointDir")
        start_step = 0
        if ckpt_dir and self.get("resume"):
            latest = _latest_checkpoint(ckpt_dir)
            if latest is not None:
                try:
                    loaded = ser._load_pytree(latest)
                except Exception as e:
                    raise RuntimeError(
                        f"failed to load checkpoint {latest!r}: {e}. "
                        f"Delete it (or set resume=False) to retrain "
                        f"from scratch.") from e
                # namedtuple containers (optax states) serialize as plain
                # tuples; rebuild them against the freshly-built treedef
                host_state = jax.tree_util.tree_unflatten(
                    jax.tree_util.tree_structure(state),
                    jax.tree_util.tree_leaves(loaded))
                start_step = int(host_state["step"])
                state = jax.tree_util.tree_map(
                    lambda a, s: jax.device_put(jnp.asarray(a), s),
                    host_state, state_sharding)
                logger.info("resumed from %s (step %d)", latest, start_step)
        if proc_count > 1 and ckpt_dir and self.get("resume"):
            # hosts must resume from the SAME step — a host that found
            # no checkpoint (non-shared filesystem) would replay steps
            # the others skip and hang the first collective
            from jax.experimental import multihost_utils
            steps = np.asarray(multihost_utils.process_allgather(
                np.asarray([start_step]))).ravel()
            if len(set(steps.tolist())) > 1:
                raise RuntimeError(
                    f"hosts disagree on the resume step {steps.tolist()}:"
                    f" checkpointDir must be on a filesystem shared by "
                    f"all hosts (or set resume=False)")

        # training loop. Input feed: a background thread slices/pads the
        # next minibatch and device_puts it while the current step runs on
        # the MXU (the CNTK out-of-band reader analog — see utils/prefetch).
        # Logging NEVER syncs the device on the hot path: logged losses stay
        # on device and are flushed one logEvery-interval late, by which
        # time they are ready and float() is free.
        import time as _time
        from mmlspark_tpu.utils.prefetch import make_prefetcher

        self.history = []
        self.timing: Dict[str, float] = {}
        np_rng = np.random.default_rng(self.get("seed"))
        log_every = self.get("logEvery")
        ckpt_every = self.get("checkpointEvery")
        epochs = self.get("epochs")

        def index_stream():
            """(epoch, step, bx, by) numpy batches. In-memory mode
            shuffles globally per epoch; streaming mode re-reads the
            shard factory each epoch, shuffles within shards, and
            carries remainder rows across shard boundaries."""
            step = 0
            for epoch in range(epochs):
                if not streaming:
                    order = np_rng.permutation(n)
                    for bstart in range(0, n, local_batch):
                        step += 1
                        if step <= start_step:
                            continue  # fast-forward post-resume
                        idx = order[bstart:bstart + local_batch]
                        yield epoch, step, x[idx], y[idx]
                    continue
                carry_x = carry_y = None
                for shard in factory():
                    xs, ys = table_to_xy(shard, fcol, lcol, input_shape)
                    ys = ys.astype(y_cast)
                    perm = np_rng.permutation(len(xs))
                    xs, ys = xs[perm], ys[perm]
                    if carry_x is not None:
                        xs = np.concatenate([carry_x, xs])
                        ys = np.concatenate([carry_y, ys])
                    n_full = len(xs) // local_batch
                    for i in range(n_full):
                        step += 1
                        if step <= start_step:
                            continue
                        sl = slice(i * local_batch, (i + 1) * local_batch)
                        yield epoch, step, xs[sl], ys[sl]
                    rest = len(xs) - n_full * local_batch
                    carry_x = xs[-rest:] if rest else None
                    carry_y = ys[-rest:] if rest else None
                if carry_x is not None:
                    step += 1
                    if step > start_step:
                        yield epoch, step, carry_x, carry_y

        def _to_global(arr, sharding):
            """Local slice -> global device array. Single-process:
            plain device_put; multi-process: every host contributes its
            slice of the global batch."""
            if proc_count > 1:
                return jax.make_array_from_process_local_data(
                    sharding, arr)
            return jax.device_put(arr, sharding)

        def make_batch(item):
            epoch, step, bx_np, by_np = item
            bx, true_len = mesh_lib.pad_to_multiple(
                bx_np, local_batch, axis=0)
            by, _ = mesh_lib.pad_to_multiple(by_np, local_batch, axis=0)
            w = (np.arange(local_batch) < true_len).astype(np.float32)
            return epoch, step, true_len * proc_count, {
                "x": _to_global(bx, data_sharding["x"]),
                "y": _to_global(by, data_sharding["y"]),
                "w": _to_global(w, data_sharding["w"]),
            }

        pending: List[Tuple[int, int, Any, float]] = []  # deferred log queue

        def flush_logs(final: bool = False) -> None:
            # flush entries whose device value is (almost surely) ready:
            # everything but the newest, or everything when final
            keep = 0 if final else 1
            while len(pending) > keep:
                step_, epoch_, dev_loss, t = pending.pop(0)
                lv = float(dev_loss)
                self.history.append({"step": step_, "loss": lv,
                                     "epoch": epoch_, "time": t})
                logger.info("step %d/%d loss %.4f", step_, total_steps, lv)

        from mmlspark_tpu.utils.profiling import maybe_trace

        global_step = start_step
        t_first = None
        examples_timed = 0   # true (unpadded) rows after the warmup step
        # CPU backend: async dispatch racing ahead starves XLA's
        # in-process collective rendezvous on small hosts (7/8 devices
        # join, the 8th's thunk never gets a pool thread -> fatal
        # timeout). Serialize steps there; TPU keeps async dispatch.
        sync_each_step = jax.default_backend() == "cpu"
        feed = make_prefetcher(index_stream(), make_batch, depth=2)
        try:
            with maybe_trace(self.get("profileDir")):
                for epoch, global_step, true_len, batch in feed:
                    state, loss = jit_step(state, batch)
                    if sync_each_step:
                        loss.block_until_ready()
                    if t_first is None:
                        # block on the compile+first step so steady-state
                        # timing starts after warmup
                        loss.block_until_ready()
                        t_first = _time.time()
                        first_timed_step = global_step
                    else:
                        examples_timed += true_len
                    if global_step % log_every == 0 or \
                            global_step == total_steps:
                        pending.append(
                            (global_step, epoch, loss, _time.time()))
                        flush_logs()
                    if ckpt_dir and global_step % ckpt_every == 0:
                        _save_checkpoint(ckpt_dir, global_step, state)
        finally:
            # abnormal exit must not leave the worker blocked in put()
            # pinning prefetched batches in HBM
            feed.close()
        state = jax.block_until_ready(state)
        t_end = _time.time()
        flush_logs(final=True)
        steps_timed = global_step - (first_timed_step if t_first else 0)
        if t_first is not None and steps_timed > 0:
            self.timing = {
                "steps_timed": steps_timed,
                "wall_s": t_end - t_first,
                # true rows only — padding of partial batches is masked
                # compute, counting it would inflate the metric
                "examples_per_sec":
                    examples_timed / max(t_end - t_first, 1e-9),
            }
        if ckpt_dir:
            _save_checkpoint(ckpt_dir, global_step, state)

        host_params = jax.device_get(state["params"])
        host_bs = jax.device_get(state["batch_stats"])
        weights = {"params": host_params}
        if has_bn:
            weights["batch_stats"] = host_bs
        field = schema_src.schema.get(self.get_features_col())
        img_scale = (1.0 / 255.0) if (field is not None
                                      and ImageSchema.is_image(field)) else 1.0
        model = TPUModel(
            modelFn=_InferApply(module, is_int_input, img_scale, input_shape),
            weights=weights,
            inputCol=self.get_features_col(),
            outputCol="scores",
            batchSize=batch_size,
            computeDtype="float32")
        model.set_mesh(mesh)
        return model


class _InferApply:
    """Picklable inference apply for trained modules (handles batch_stats
    and integer-token inputs)."""

    def __init__(self, module, int_input: bool = False, scale: float = 1.0,
                 input_shape=None):
        self.module = module
        self.int_input = int_input
        self.scale = scale
        self.input_shape = input_shape

    def __call__(self, weights, inputs):
        x = list(inputs.values())[0]
        if self.input_shape:
            x = x.reshape((x.shape[0],) + tuple(self.input_shape))
        if self.int_input:
            x = x.astype(jnp.int32)
        elif self.scale != 1.0:
            x = x.astype(jnp.float32) * self.scale
        variables = {"params": weights["params"]}
        if "batch_stats" in weights and weights["batch_stats"]:
            variables["batch_stats"] = weights["batch_stats"]
        return self.module.apply(variables, x, train=False)


def _save_checkpoint(ckpt_dir: str, step: int, state) -> None:
    # multi-host: only the coordinator writes (hosts may share the FS)
    if jax.process_index() != 0:
        return
    host = jax.device_get(state)
    path = os.path.join(ckpt_dir, f"step_{step:08d}")
    os.makedirs(path, exist_ok=True)
    ser._save_pytree(host, path)
    # keep only the 3 latest
    all_ckpts = sorted(d for d in os.listdir(ckpt_dir)
                       if d.startswith("step_"))
    for stale in all_ckpts[:-3]:
        import shutil
        shutil.rmtree(os.path.join(ckpt_dir, stale), ignore_errors=True)


def _latest_checkpoint(ckpt_dir: str) -> Optional[str]:
    if not os.path.isdir(ckpt_dir):
        return None
    ckpts = sorted(d for d in os.listdir(ckpt_dir) if d.startswith("step_"))
    return os.path.join(ckpt_dir, ckpts[-1]) if ckpts else None
